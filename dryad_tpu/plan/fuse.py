"""Whole-DAG SPMD fusion: one compiled program per multi-stage plan.

Phase-2 lowering (``plan/lower.py``) already fuses maximal *operator
chains* into stages, but the executor still dispatches every stage as
its own compiled program with the driver mediating each boundary — one
compile key, one dispatch latency, and (through a TPU tunnel) one
control round-trip per stage.  The reference Dryad pays a process +
channel boundary between every stage pair (N*M file/HTTP channels per
exchange, ``channelinterface.h``); our intra-stage shuffles are already
on-device ``all_to_all`` ops (``ops/shuffle.py``), so the remaining
lever is the *inter-stage* boundary.

This pass stitches a maximal run of consecutive device-eligible stages
— including their hash/range exchanges — into a single
:class:`FusedStage` whose body chains the per-stage kernels inside ONE
``shard_map`` region (``exec.kernels.build_fused_fn`` /
``parallel.stage.compile_fused``), compiled once and dispatched once.
Intermediates stay in HBM for the whole region; exchanges at the seams
ride the same mesh collectives as intra-stage exchanges (hybrid-mesh
plans keep the ICI-hop -> combine -> one-DCN-hop tree decomposition of
PAPERS.md arxiv 2112.01075 through the per-member tree kernels).

Fusion eligibility (a seam BREAKS, with a recorded
``fuse_break_reason``, when any rule fails):

- every op in the run must be a device kernel from :data:`FUSABLE_OPS`
  (``apply_host`` / ``do_while`` stages are driver-evaluated host
  boundaries — ``host_boundary:*``);
- a stage shaped for observed-volume width adaptation (all ops
  width-insensitive, a full-width exchange, statically-unbounded
  non-plan inputs, and a shrinking producer) stays UNFUSED so the
  executor's runtime re-widthing (``DrDynamicRangeDistributor.cpp:54``
  semantics) still applies — fusing it would pin the region to the
  static width (``width_adapt:*``).

Overflow contract: any member's bucket-overflow flag retries the WHOLE
region at the next palette capacity — the same bounded-palette shape
contract as the single-stage path, so a fused plan stays byte-identical
to the staged baseline (the ``plan_fuse=False`` differential).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from dryad_tpu.plan.lower import Stage, StageGraph, _stage_ids

# Stage-op kinds the fuser admits into a fused region.  Every entry
# MUST have a registered device kernel (``exec.kernels._KERNELS``) —
# the AST lint ``tests/test_fuse_lint.py`` enforces the subset relation
# in both directions, so a new device kernel extends fusion coverage
# (or is consciously excluded here) instead of silently rotting.
FUSABLE_OPS = frozenset({
    "select", "where", "project", "seed", "select_many", "apply",
    "exchange_hash", "exchange_range", "resize",
    "group_reduce", "group_reduce_dense", "string_code",
    "group_combine", "distinct", "local_sort", "topk",
    "join", "semi", "concat", "take", "with_rank", "skip", "tail",
    "take_while", "skip_while", "reverse", "default_if_empty",
    "scalar_agg", "fork", "group_join_count", "join_ranked", "zip",
    "sliding_window",
})

# Driver-evaluated stages: hard host boundaries no region may cross.
DRIVER_OPS = frozenset({"do_while", "apply_host"})

# Op kinds proven width-insensitive — the observed-volume width
# adapter may re-dispatch a stage of only these at a reduced fan
# (``exec.executor`` consumes this set; ONE definition for the pass's
# adapt-seam rule and the executor's runtime gate).
ADAPT_OK_OPS = frozenset({
    "select", "where", "project", "exchange_hash", "exchange_range",
    "resize", "group_reduce", "group_reduce_dense", "local_sort",
    "join", "scalar_agg", "string_code",
})

# Aggregation-shaped ops that shrink data by orders of magnitude — the
# producers whose observed output makes width adaptation worth a sync.
SHRINKING_OPS = frozenset({
    "group_reduce", "group_reduce_dense", "distinct", "scalar_agg",
    "topk",
})


class FusedStage:
    """A run of stages compiled and dispatched as ONE SPMD program.

    Duck-types the :class:`~dryad_tpu.plan.lower.Stage` surface the
    executor consumes (``id``/``name``/``input_refs``/``ops``/
    ``out_slots``/``growth``) plus the region structure:

    - ``members``: the fused stages, in topological (list) order;
    - ``wiring``: per member, one entry per member input ref —
      ``("ext", j)`` binds the region's external input ``j``,
      ``("mem", mi, oi)`` binds output ``oi`` of ``members[mi]``;
    - ``exports``: ``(member_index, out_index)`` pairs, in region
      output order — the member outputs consumed outside the region
      (or by the plan's roots).

    ``ops`` chains the member ops so structural scans (overflow
    capability, miss guards, operand enumeration, fault-name tokens)
    see the whole region; member-local slot numbers overlap, so any
    *identity* derivation (compile keys, checkpoint fingerprints) must
    also fold ``wiring``/``exports``/member boundaries — see
    ``fingerprint_extra`` and the executor's fused ``_stage_key``.
    """

    def __init__(
        self,
        members: List[Stage],
        input_refs: List[Tuple[Any, int]],
        wiring: List[Tuple[Tuple, ...]],
        exports: List[Tuple[int, int]],
    ):
        self.id = next(_stage_ids)
        # "+"-token name so fault injection (exec.faults token match),
        # stage statistics, and metric labels keep working per op kind
        seen: Dict[str, None] = {}
        for m in members:
            for tok in m.name.split("+"):
                seen.setdefault(tok)
        self.name = "+".join(seen)
        self.members = members
        self.input_refs = input_refs
        self.wiring = wiring
        self.exports = exports
        self.out_slots = list(range(len(exports)))
        self.growth = max((m.growth for m in members), default=1.0)
        self.ops = [op for m in members for op in m.ops]

    @property
    def fingerprint_extra(self) -> str:
        """Region structure for the checkpoint identity: chained op
        params alone cannot distinguish two regions that partition the
        same op sequence differently or wire members differently."""
        return (
            f"fused:members={[(len(m.ops), tuple(m.out_slots)) for m in self.members]!r}"
            f":wiring={self.wiring!r}:exports={self.exports!r}"
        )

    def __repr__(self) -> str:
        return (
            f"FusedStage(id={self.id}, members="
            f"{[m.id for m in self.members]}, exports={self.exports})"
        )


@dataclasses.dataclass
class FuseReport:
    """What fused and why seams broke — the explain/debug surface."""

    enabled: bool
    # one entry per dispatch unit, in dispatch order:
    # {"id", "members": [stage ids], "names": [...], "fused": bool,
    #  "reason": Optional[str]}  (reason set on unfused singletons)
    regions: List[Dict[str, Any]]
    # {"after": stage id, "before": stage id, "reason": str} per
    # consecutive-stage boundary that did NOT fuse
    breaks: List[Dict[str, Any]]
    n_stages: int
    n_dispatch_units: int


def _ineligible_reason(stage: Stage) -> Optional[str]:
    """None when every op is fusable; else the seam-break reason."""
    for op in stage.ops:
        if op.kind in DRIVER_OPS:
            return f"host_boundary:{op.kind}"
        if op.kind not in FUSABLE_OPS:
            return f"unsupported_op:{op.kind}"
    return None


def _is_shrinker(stage: Stage) -> bool:
    return any(op.kind in SHRINKING_OPS for op in stage.ops)


def _adaptable_shape(stage: Stage) -> bool:
    """Mirror of the executor's ``_adaptable``: all ops
    width-insensitive and at least one full-width exchange."""
    return all(op.kind in ADAPT_OK_OPS for op in stage.ops) and any(
        op.kind in ("exchange_hash", "exchange_range")
        and not op.params.get("nparts")
        for op in stage.ops
    )


def _adapt_candidate(
    stage: Stage, by_id: Dict[int, Stage], config, single_axis: bool
) -> bool:
    """True when the staged executor could re-dispatch ``stage`` at an
    observed-volume-reduced width: fusing it into any region would pin
    it to the static full width, so the pass leaves it alone (seam
    reason ``width_adapt``)."""
    if not single_axis or not getattr(config, "tail_fanout_rows", 0):
        return False
    if not _adaptable_shape(stage):
        return False
    producers = []
    for ref, _idx in stage.input_refs:
        if ref == "plan_input":
            return False  # static bindings: lowering already decided
        p = by_id.get(ref)
        if p is None:
            return False
        producers.append(p)
    return any(_is_shrinker(p) for p in producers)


def fuse(
    graph: StageGraph, config, single_axis: bool = True
) -> Tuple[StageGraph, FuseReport]:
    """Group maximal runs of consecutive device-eligible stages into
    :class:`FusedStage` regions and rewire the graph.

    Stages appear in ``graph.stages`` in topological order (lowering
    materializes producers before consumers), so ANY contiguous run is
    a valid region: every external input is produced before the region
    dispatches and every external consumer runs after it.

    Returns the (possibly) rewired graph plus a :class:`FuseReport`;
    with fewer than two fusable neighbors the graph passes through
    untouched.
    """
    by_id = {s.id: s for s in graph.stages}
    # classify: None = fusable; a string = unfused singleton + reason
    cls: Dict[int, Optional[str]] = {}
    for s in graph.stages:
        reason = _ineligible_reason(s)
        if reason is None and _adapt_candidate(s, by_id, config, single_axis):
            reason = "width_adapt:observed-volume adaptation opportunity"
        cls[s.id] = reason

    # group consecutive unclassified stages into runs
    runs: List[List[Stage]] = []
    cur: List[Stage] = []
    for s in graph.stages:
        if cls[s.id] is None:
            cur.append(s)
        else:
            if cur:
                runs.append(cur)
                cur = []
            runs.append([s])
    if cur:
        runs.append(cur)

    breaks: List[Dict[str, Any]] = []
    for a, b in zip(graph.stages, graph.stages[1:]):
        if cls[a.id] is None and cls[b.id] is None:
            continue  # same run — fused together (or lone pair edge)
        breaks.append({
            "after": a.id,
            "before": b.id,
            "reason": cls[b.id] or cls[a.id] or "single_stage",
        })

    # (producer sid, out idx) pairs consumed by the plan roots
    root_refs = set(graph.outputs.values())

    new_stages: List[Any] = []
    regions: List[Dict[str, Any]] = []
    # (old sid, out idx) -> (new sid, new out idx) for fused members
    remap: Dict[Tuple[int, int], Tuple[int, int]] = {}

    def _remap_ref(ref, idx):
        if ref == "plan_input":
            return (ref, idx)
        return remap.get((ref, idx), (ref, idx))

    for run in runs:
        if len(run) < 2 or cls[run[0].id] is not None:
            for s in run:
                if any((r, i) in remap for r, i in s.input_refs if r != "plan_input"):
                    s = Stage(
                        s.id, s.name,
                        [_remap_ref(r, i) for r, i in s.input_refs],
                        ops=s.ops, out_slots=s.out_slots, growth=s.growth,
                    )
                new_stages.append(s)
                regions.append({
                    "id": s.id, "members": [s.id], "names": [s.name],
                    "fused": False, "reason": cls[s.id],
                })
            continue

        member_pos = {m.id: i for i, m in enumerate(run)}
        member_set = set(member_pos)
        ext_refs: List[Tuple[Any, int]] = []
        ext_index: Dict[Tuple[Any, int], int] = {}
        wiring: List[Tuple[Tuple, ...]] = []
        for m in run:
            w: List[Tuple] = []
            for ref, idx in m.input_refs:
                if ref != "plan_input" and ref in member_set:
                    w.append(("mem", member_pos[ref], idx))
                    continue
                key = _remap_ref(ref, idx)
                if key not in ext_index:
                    ext_index[key] = len(ext_refs)
                    ext_refs.append(key)
                w.append(("ext", ext_index[key]))
            wiring.append(tuple(w))

        consumed_outside = set()
        for s in graph.stages:
            if s.id in member_set:
                continue
            for ref, idx in s.input_refs:
                if ref != "plan_input" and ref in member_set:
                    consumed_outside.add((ref, idx))
        exports: List[Tuple[int, int]] = []
        for mi, m in enumerate(run):
            for oi in range(len(m.out_slots)):
                if (m.id, oi) in consumed_outside or (m.id, oi) in root_refs:
                    exports.append((mi, oi))
        if not exports:  # defensive: a dead-tail region still yields
            exports = [
                (len(run) - 1, oi)
                for oi in range(len(run[-1].out_slots))
            ]

        fused = FusedStage(run, ext_refs, wiring, exports)
        for pos, (mi, oi) in enumerate(exports):
            remap[(run[mi].id, oi)] = (fused.id, pos)
        new_stages.append(fused)
        regions.append({
            "id": fused.id, "members": [m.id for m in run],
            "names": [m.name for m in run], "fused": True, "reason": None,
        })

    outputs = {
        nid: _remap_ref(ref, idx) for nid, (ref, idx) in graph.outputs.items()
    }
    report = FuseReport(
        enabled=True, regions=regions, breaks=breaks,
        n_stages=len(graph.stages), n_dispatch_units=len(new_stages),
    )
    return StageGraph(new_stages, outputs, graph.inputs), report
