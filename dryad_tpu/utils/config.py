"""Config / flag system.

Mirrors the reference's two-level config: per-context knobs on
``DryadLinqContext`` (reference ``LinqToDryad/DryadLinqContext.cs:577-1107``)
and process-wide compile-time defaults in ``StaticConfig``
(reference ``LinqToDryad/DryadLinqGlobals.cs:36-74``), with environment
variable overrides (reference env plumbing ``LocalJobSubmission.cs:169``).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    return int(v) if v not in (None, "") else default


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    return float(v) if v not in (None, "") else default


def _env_bool(name: str, default: bool) -> bool:
    v = os.environ.get(name)
    if v in (None, ""):
        return default
    return v.lower() in ("1", "true", "yes", "on")


class StaticConfig:
    """Process-wide defaults (reference ``DryadLinqGlobals.cs:36-74``).

    Values are read once at import; env vars named ``DRYAD_TPU_*`` override.
    """

    # Reference: StaticConfig.DefaultPartitionCount = 8.
    default_partition_count: int = _env_int("DRYAD_TPU_DEFAULT_PARTITIONS", 8)
    # Reference: StaticConfig.MaxPartitionCount = 20000.
    max_partition_count: int = _env_int("DRYAD_TPU_MAX_PARTITIONS", 20000)
    # Analog of UseMemoryFIFO: keep data in HBM between fused stages.
    use_hbm_channels: bool = _env_bool("DRYAD_TPU_USE_HBM_CHANNELS", True)
    # Per-(src,dst) shuffle bucket slack over the uniform expectation.
    shuffle_slack: float = _env_float("DRYAD_TPU_SHUFFLE_SLACK", 2.0)
    # Logging level name for the framework logger.
    logging_level: str = os.environ.get("DRYAD_TPU_LOGGING_LEVEL", "INFO")


@dataclasses.dataclass
class DryadConfig:
    """Per-context configuration (reference ``DryadLinqContext`` properties).

    Attributes map to reference context knobs:
    - ``partition_count``: default output partitioning (``DefaultPartitionCount``).
    - ``enable_speculative_duplication``: ``DryadLinqContext.cs:959``.
    - ``max_stage_failures``: GM failure budget (``DrGraph.h:42``
      ``m_maxActiveFailureCount``).
    - ``shuffle_slack`` / ``max_shuffle_retries``: padded-bucket shuffle
      capacity slack and the bounded shape palette for overflow retries
      (the adaptive-execution analog of ``DrDynamicDistributor.h:26``).
    - ``intermediate_compression``: channel compression transform
      (``dryadvertex.h:33-48`` TransformType).
    - ``sample_rate``: range-partition sampler rate (reference 0.1%%,
      ``DryadLinqSampler.cs:38-42``).
    """

    partition_count: int = StaticConfig.default_partition_count
    enable_speculative_duplication: bool = True
    max_stage_failures: int = 3
    shuffle_slack: float = StaticConfig.shuffle_slack
    max_shuffle_retries: int = 3
    intermediate_compression: Optional[str] = None  # None | "zlib"
    sample_rate: float = 0.001
    # Event log directory (Calypso analog); None disables.
    event_log_dir: Optional[str] = None
    # XLA/JAX profiler output directory (SURVEY 5.1: profiler traces +
    # per-stage step markers); None disables tracing.
    profile_dir: Optional[str] = None
    # Stage-output checkpoint directory (durable DCT_File channel
    # analog, SURVEY §5.4); None disables checkpoint/resume.
    checkpoint_dir: Optional[str] = None
    # Checkpoint retention lease in seconds (channel-file
    # retain/lease-grace analog, DrProcess.h:80-89); None keeps forever.
    checkpoint_retain_seconds: Optional[float] = None
    # Thread count for host-side IO (DRYAD_THREADS_PER_WORKER analog).
    io_threads: int = _env_int("DRYAD_TPU_IO_THREADS", 4)
    # Outlier threshold in sigmas for speculative duplication
    # (reference DrStageStatistics.cpp:24-25: 3 sigma).
    outlier_sigmas: float = 3.0
    # Straggler-threshold floor (exec.stats.StageStatistics): with few
    # completed samples the trimmed-sigma fit degenerates (variance ~0
    # flags EVERY later attempt an outlier); the threshold is clamped
    # to floor_ratio x the trimmed mean.
    straggler_floor_ratio: float = _env_float(
        "DRYAD_TPU_STRAGGLER_FLOOR", 1.5
    )
    # Coded stage redundancy (dryad_tpu.redundancy): a partitioned
    # aggregation whose combiner is LINEAR (sum/count/mean partials, or
    # Decomposable(linear=True)) runs as k systematic + up to r parity
    # coded vertices — ANY k of the k+r completions reconstruct the
    # stage output (exactly for integer accumulators), so stragglers
    # need no identification and killed vertices no re-execution.
    # Non-linear combiners keep the duplicate-on-straggle path.
    coded_redundancy: bool = _env_bool("DRYAD_TPU_CODED_REDUNDANCY", True)
    coded_parity_tasks: int = _env_int("DRYAD_TPU_CODED_PARITY", 2)
    # Float decode guard: refuse coded subsets whose combination-weight
    # L1 norm would amplify rounding noise beyond this factor.
    coded_max_amplification: float = _env_float(
        "DRYAD_TPU_CODED_MAX_AMP", 1e6
    )
    # Retry backoff (exec.failure.RetryPolicy): transient stage/vertex
    # failures wait base * 2^(failures-1) seconds (capped at max) plus
    # seeded jitter before re-executing — a crashing dependency gets
    # breathing room instead of an immediate retry storm.
    retry_backoff_base: float = _env_float("DRYAD_TPU_RETRY_BACKOFF", 0.05)
    retry_backoff_max: float = 2.0
    retry_jitter: float = 0.5  # backoff *= 1 + jitter * U(0,1), seeded
    retry_seed: int = _env_int("DRYAD_TPU_RETRY_SEED", 0)
    # Broadcast-join threshold: with strategy='auto', a right side whose
    # TOTAL row capacity (per-partition capacity x P) is at or below this
    # is replicated via all_gather instead of co-hash-partitioned (the
    # dynamic broadcast decision of DynamicManager.cs:51 /
    # DrDynamicBroadcast.h:23, made trace-time from static capacities).
    broadcast_limit: int = _env_int("DRYAD_TPU_BROADCAST_LIMIT", 1 << 16)
    # order_by+take(n) fuses into a shuffle-free distributed top-k when
    # n is at or below this (each partition gathers P*n head rows);
    # larger takes keep the full range-exchange sort.
    topk_limit: int = _env_int("DRYAD_TPU_TOPK_LIMIT", 1024)
    # Auto-dense STRING group_by: a single-STRING-key group_by with
    # sum/count/mean aggs lowers to the MXU bucket path keyed on dense
    # dictionary codes (ops/stringcode.py) when the context dictionary
    # holds at most auto_dense_limit distinct strings — no shuffle at
    # all, vs the reference's full hash repartition for the same query.
    auto_dense_strings: bool = True
    # Int twin: a plain group_by over one INT32 key whose INGEST-time
    # range is [0, K), K <= auto_dense_limit, rides the same MXU bucket
    # path (with a range-miss guard for post-ingest fabrication).
    auto_dense_ints: bool = True
    auto_dense_limit: int = _env_int("DRYAD_TPU_AUTO_DENSE_LIMIT", 1 << 17)
    # Compile-once dictionary coding (static-vs-operand param split):
    # the string CodeTable/DecodeTable arrays ride the compiled program
    # as call-time DEVICE OPERANDS on a power-of-two shape palette —
    # the compile cache keys on the palette tier, a widening vocabulary
    # pays O(log vocab) compiles instead of O(widenings), and the
    # executor's operand pool scatters only the widened table delta to
    # the device.  Off = the legacy baked-constant path (each table
    # content is its own compile-cache key) kept as the differential
    # baseline.
    stringcode_runtime_tables: bool = _env_bool(
        "DRYAD_TPU_STRINGCODE_RUNTIME_TABLES", True
    )
    # Device-resident input cache budget in bytes (0 disables): ingested
    # host/store tables stay sharded in HBM across submits, LRU-evicted
    # by size — the on-device analog of the ProcessService LRU block
    # cache (Cache.cs:32) applied to ingest instead of channel files.
    # Repeated queries over one table skip the host->device transfer
    # (through a tunneled chip that transfer dominates end-to-end time).
    device_cache_bytes: int = _env_int(
        "DRYAD_TPU_DEVICE_CACHE", 2 * 1024 * 1024 * 1024
    )
    # Target rows per independent vertex task: when a partitioned
    # submission doesn't pin nparts, the fan-out is computed from the
    # OBSERVED input size (the data-size-driven consumer-count
    # recomputation of DrDynamicRangeDistributor.cpp:54-110:
    # copies = sampledSize / dataPerVertex).
    rows_per_vertex: int = _env_int("DRYAD_TPU_ROWS_PER_VERTEX", 1 << 18)
    # Whole-DAG SPMD fusion (plan.fuse): maximal runs of consecutive
    # device-eligible stages — including their hash/range exchanges —
    # compile and dispatch as ONE shard_map region, dropping dispatches
    # per plan from O(stages) to O(fused regions) and keeping every
    # inter-stage intermediate in HBM.  Any seam's bucket-overflow flag
    # retries the WHOLE region at the next palette capacity (same
    # bounded-palette contract as single-stage overflow).  Off = the
    # driver-mediated per-stage path, kept as the differential baseline.
    plan_fuse: bool = _env_bool("DRYAD_TPU_PLAN_FUSE", True)
    # How many overflow-capable stages may be DISPATCHED speculatively
    # before the driver syncs their overflow flags in one batched
    # readback (the GM pump's concurrent vertex management,
    # DrMessagePump.h:116-180).  Through a ~70ms/dispatch tunnel a
    # 5-shuffle pipeline pays one control round-trip instead of five;
    # an overflow re-runs the affected suffix at a larger boost.
    # 1 = legacy per-stage sync.
    overflow_sync_depth: int = _env_int("DRYAD_TPU_OVERFLOW_SYNC_DEPTH", 4)
    # Memory-bounded staged exchange (plan.xchgplan): hash/range/join
    # repartitions decompose into ppermute rounds shipping at most this
    # many destination buckets each, so peak extra HBM per device is
    # O(window * B) instead of the flat all_to_all's O(P * B) — ICI
    # hops staged first, all DCN-crossing traffic batched into one
    # round per remote slice (arxiv 2112.01075's decomposition over the
    # combinetree mesh model).  0 = the flat single-collective path,
    # kept as the differential baseline; -1 = auto policy — the
    # executor picks flat while the estimated all_to_all footprint
    # fits exchange_hbm_budget_mb, else the widest window that does
    # (plan.xchgplan.resolve_window; the runtime rewriter can pin the
    # auto choice via RewriteController.retune_exchange).
    exchange_window: int = _env_int("DRYAD_TPU_EXCHANGE_WINDOW", 0)
    # HBM the auto exchange-window policy may spend on one exchange's
    # staging buffers (only read when exchange_window == -1).
    exchange_hbm_budget_mb: int = _env_int(
        "DRYAD_TPU_EXCHANGE_HBM_BUDGET_MB", 256
    )
    # Stage-level fan-out adaptation (DrDynamicRangeDistributor.cpp:
    # 54-110: consumer copies = observed size / data-per-vertex): when a
    # stage's input row count is STATICALLY bounded at or below
    # tail_fanout_rows (post-aggregation tails, take(n) heads, dense-K
    # domains), its exchange concentrates rows onto
    # ceil(rows / tail_rows_per_partition) partitions instead of all P —
    # the remaining partitions run empty (masked) and per-partition
    # padding shrinks.  0 disables.
    tail_fanout_rows: int = _env_int("DRYAD_TPU_TAIL_FANOUT_ROWS", 4096)
    tail_rows_per_partition: int = _env_int(
        "DRYAD_TPU_TAIL_ROWS_PER_PARTITION", 512
    )
    # Out-of-core streaming (exec.outofcore; reference streaming channel
    # stack channelinterface.h:212): max rows a phase-2 bucket may hold
    # before it re-splits from observed volume, the partial-accumulator
    # compaction threshold, and the phase-1 spill fan-out.
    stream_bucket_rows: int = _env_int("DRYAD_TPU_STREAM_BUCKET_ROWS", 1 << 21)
    stream_combine_rows: int = _env_int(
        "DRYAD_TPU_STREAM_COMBINE_ROWS", 1 << 20
    )
    stream_buckets: int = _env_int("DRYAD_TPU_STREAM_BUCKETS", 32)
    # Spill directory for streaming buckets (None: a fresh tempdir).
    stream_spill_dir: Optional[str] = os.environ.get(
        "DRYAD_TPU_STREAM_SPILL_DIR"
    ) or None
    # Chunk pipeline depth (exec.pipeline): how many chunks may be in
    # flight at once across ingest / device compute / readback — the
    # RChannelReader read-ahead budget (channelinterface.h:212).
    # 1 = the serial legacy driver (no prefetch thread, no background
    # spill writer, per-chunk host readback of partials).
    stream_pipeline_depth: int = _env_int(
        "DRYAD_TPU_STREAM_PIPELINE_DEPTH", 4
    )
    # Bounded buffer of the background spill writer, in queued pieces
    # (exec.spill.SpillWriter): backpressure for the scatter phase.
    stream_writer_queue: int = _env_int("DRYAD_TPU_STREAM_WRITER_QUEUE", 8)
    # Topology- and distribution-aware combine trees (exec.combinetree):
    # streaming group_by partials accumulate into similarity-placed tree
    # groups whose level-0 merges ELIDE the hash exchange (partials are
    # already co-hash-partitioned, so equal keys are colocated and one
    # local reduce merges them — zero collective bytes), and only the
    # final fold pays a full exchange (on a hybrid mesh: one ICI hop +
    # exactly one DCN hop via the tree exchange).  Off = the flat
    # N-ary-merge combiner, kept as the differential baseline.
    combine_tree: bool = _env_bool("DRYAD_TPU_COMBINE_TREE", True)
    # Max batches one tree-group flush folds in a single program
    # (stable fan-in -> stable shapes -> compile reuse).
    combine_tree_fan: int = _env_int("DRYAD_TPU_COMBINE_TREE_FAN", 16)
    # Coarse key-range resolution of the placement/degrade histograms
    # (obs.metrics.KeyRangeHistogram): key hashes fold into this many
    # ranges; placement reads per-range counts, degrade reads per-range
    # distinct-occupancy estimates.  Power of two.
    combine_tree_ranges: int = _env_int("DRYAD_TPU_COMBINE_TREE_RANGES", 64)
    # Tree groups (level-0 accumulators).  0 = auto: the DCN slice
    # count on a hybrid mesh, else 4.
    combine_tree_groups: int = _env_int("DRYAD_TPU_COMBINE_TREE_GROUPS", 0)
    # Per-key-range host degrade threshold: a range whose estimated
    # distinct-key fraction (est. distinct / rows seen) stays at or
    # above this stops reducing on device and streams to host
    # accumulation; hot (reducing) ranges stay in the tree.
    combine_tree_degrade_ratio: float = _env_float(
        "DRYAD_TPU_COMBINE_TREE_DEGRADE_RATIO", 0.75
    )
    # Host-degrade re-probe (flat combiner): after this many CONSECUTIVE
    # host combines that DO reduce below the device capacity check, the
    # device path is retried (the degrade decision is no longer sticky).
    # 0 disables re-probing.
    stream_host_reprobe: int = _env_int("DRYAD_TPU_STREAM_HOST_REPROBE", 2)
    # Ring-buffer cap for the context EventLog's in-memory mirror
    # (exec.events): long out-of-core jobs emit per-chunk/span events
    # without bound; the file sink (event_log_dir) keeps the full
    # stream.  0 = unbounded (legacy behavior).
    obs_events_mem_cap: int = _env_int("DRYAD_TPU_OBS_EVENTS_MEM_CAP", 1 << 16)
    # Flight recorder (obs.flightrec): always-on bounded ring of recent
    # events + periodic health microsnapshots in every process, dumped
    # atomically to blackbox-<pid>.json on JobFailedError, unhandled
    # exceptions, and worker death (incl. the chaos os._exit path) —
    # crash forensics that survive the process.  Off = no ring, no
    # dump hooks.
    obs_flight_recorder: bool = _env_bool("DRYAD_TPU_FLIGHT_RECORDER", True)
    # Flight-recorder ring capacity in events and the minimum seconds
    # between health microsnapshots (RSS, in-flight dispatches,
    # pipeline occupancy, operand-pool residency; sampled
    # opportunistically on record — no background thread).
    flightrec_events: int = _env_int("DRYAD_TPU_FLIGHTREC_EVENTS", 2048)
    flightrec_snapshot_s: float = _env_float(
        "DRYAD_TPU_FLIGHTREC_SNAPSHOT_S", 1.0
    )
    # Blackbox dump directory; None = the event_log_dir when set, else
    # the process working directory.
    flightrec_dir: Optional[str] = os.environ.get(
        "DRYAD_TPU_FLIGHTREC_DIR"
    ) or None
    # Online diagnosis engine (obs.diagnose): streaming folds over the
    # live event stream that detect named pathologies (recompile storm,
    # straggler, partition skew, stall dominance, quarantine churn,
    # combine-tree thrash, overflow loops) and emit schema-registered
    # ``diagnosis`` events; the straggler diagnosis seeds coded-spare
    # pre-launch.  Off = record-only observability (PR 3 behavior).
    obs_diagnosis: bool = _env_bool("DRYAD_TPU_OBS_DIAGNOSIS", True)
    # Partition-skew trigger: max/mean per-partition (or per-range) row
    # ratio at or above this diagnoses ``partition_skew``.
    diagnose_skew_ratio: float = _env_float(
        "DRYAD_TPU_DIAGNOSE_SKEW_RATIO", 4.0
    )
    # Recompile-storm trigger: this many xla_compile events for ONE
    # lowering tier within the sliding window diagnoses a storm (the
    # palette exists precisely so tiers compile once).
    diagnose_recompile_burst: int = _env_int(
        "DRYAD_TPU_DIAGNOSE_RECOMPILE_BURST", 4
    )
    # Per-(rule, subject) re-diagnosis cooldown in seconds: a persistent
    # pathology re-announces at most this often instead of flooding the
    # stream it is diagnosing.
    diagnose_cooldown_s: float = _env_float(
        "DRYAD_TPU_DIAGNOSE_COOLDOWN_S", 5.0
    )
    # Async device-paced dispatch (exec.pipeline.DispatchWindow): how
    # many out-of-core chunk dispatches may be in flight before the
    # streaming driver blocks on its oldest readback.  The driver
    # thread only FEEDS (dispatch returns immediately); a background
    # collector thread drains readbacks strictly in submit order, so
    # chunk commit order — and therefore float accumulation order —
    # is identical to the serial loop and results stay byte-identical.
    # Overflow retries are detected at drain time and the retried
    # chunk re-enters the window.  1 = the serial dispatch-then-drain
    # legacy driver, kept as the differential baseline.
    dispatch_depth: int = _env_int("DRYAD_TPU_DISPATCH_DEPTH", 2)
    # Cross-chunk plan fusion: the streaming driver lowers up to this
    # many chunk partial-plans as ONE multi-root program per dispatch
    # (api.context.DryadContext.run_many_to_host_async) — the chunk
    # chains land consecutively in the stage graph, so plan_fuse folds
    # them into a single dispatched region and K chunk round trips
    # collapse into one.  Each chunk remains its own computation inside
    # the region (per-chunk reduction order unchanged -> byte
    # identical).  1 = one chunk per dispatch (legacy).
    chunk_fuse: int = _env_int("DRYAD_TPU_CHUNK_FUSE", 1)
    # Device-side do_while routing: attempt the lax.while_loop lowering
    # for EVERY fixed-point stage (not only device=True plans), keeping
    # iteration on the chip instead of paying one dispatch round trip
    # per driver-loop iteration; lowering refusals fall back to the
    # driver loop exactly as the explicit device path does.
    do_while_device_auto: bool = _env_bool(
        "DRYAD_TPU_DO_WHILE_DEVICE_AUTO", True
    )
    # Batched worker command streams (cluster.localjob/worker): up to
    # this many gang run commands ship per worker as ONE ``runbatch``
    # mailbox command with one aggregated status round trip (per-
    # command fault classification preserved in the aggregate).
    # 0 disables batching (one mailbox round trip per command).
    command_batch: int = _env_int("DRYAD_TPU_COMMAND_BATCH", 8)
    # Worker-side combine, the gang tree's level -1 (cluster.localjob
    # submit_partitioned + cluster.worker ``combineparts``): after the
    # vertex wave, each gang worker pre-merges the un-finalized partial
    # state of the parts IT won (``exec.partial.merge_state_rows``) and
    # ships ONE folded partial plus its KeyRangeHistogram snapshot, so
    # driver ingress drops by the per-worker vertex fan-in and the
    # driver's level-0/1 tree merges per-WORKER partials.  Off = flat
    # per-vertex assembly, kept as the differential oracle.
    gang_combine_tree: bool = _env_bool(
        "DRYAD_TPU_GANG_COMBINE_TREE", False
    )
    # Overlapped gang command streams (cluster.gangwindow): how many
    # ``runbatch`` envelopes may be in flight per worker before
    # ``submit_many`` blocks on its oldest aggregated status.  The
    # driver only FEEDS; a collector drains statuses strictly in
    # submit order, so batch commit order is identical to the serial
    # loop.  1 = one blocking round trip per batch (the differential
    # baseline).
    gang_batch_depth: int = _env_int("DRYAD_TPU_GANG_BATCH_DEPTH", 1)
    # Per-worker gang partition cache budget in host bytes
    # (cluster.partcache.PartitionCache): a worker keeps the result
    # partitions it wrote, content-fingerprint-keyed, so a later
    # sub-command referencing them (level -1 ``combineparts``) reads
    # from memory instead of the job root; entries LRU-evict by size
    # with spill-to-file (spilled entries stay servable).  0 disables.
    gang_partition_cache_bytes: int = _env_int(
        "DRYAD_TPU_GANG_PARTITION_CACHE", 64 * 1024 * 1024
    )
    # Serving tier (dryad_tpu.serve.QueryService): default per-tenant
    # admission quotas — max queries a tenant may have admitted-and-
    # unresolved at once, and the summed host-input bytes those admitted
    # queries may bind (0 = no byte budget).  Both are per-TENANT
    # defaults a session() call can override; admission past either
    # fails fast with a structured QueryRejected.
    serve_max_inflight: int = _env_int("DRYAD_TPU_SERVE_MAX_INFLIGHT", 32)
    serve_max_bytes: int = _env_int(
        "DRYAD_TPU_SERVE_MAX_BYTES", 1 << 30
    )
    # Plan-fingerprint result cache budget in host bytes (0 disables):
    # repeat queries whose lowered stage keys AND ingest binding
    # fingerprints match a resident entry resolve with ZERO device
    # dispatches; entries LRU-evict by size and invalidate on the
    # owning session's ingest-epoch bump.
    serve_result_cache_bytes: int = _env_int(
        "DRYAD_TPU_SERVE_CACHE_BYTES", 256 * 1024 * 1024
    )
    # Weighted deficit-round-robin cost quantum: one scheduling cost
    # unit per this many host-input bytes (a query always costs at
    # least one unit; each visit refills weight units), so a heavy
    # tenant's big-input queries consume deficit proportionally and
    # cannot starve a light tenant.
    serve_drr_quantum_bytes: int = _env_int(
        "DRYAD_TPU_SERVE_DRR_QUANTUM", 1 << 22
    )
    # Result-cache admission policy: "cost" admits an entry only when
    # its observed recompute time amortizes its bytes (at least
    # serve_cache_min_sec_per_gb seconds of saved work per cached GB),
    # so cheap-but-large results cannot evict expensive ones; "all" is
    # the legacy unconditional insert.
    serve_cache_admission: str = os.environ.get(
        "DRYAD_TPU_SERVE_CACHE_ADMISSION", "cost"
    )
    serve_cache_min_sec_per_gb: float = _env_float(
        "DRYAD_TPU_SERVE_CACHE_MIN_SEC_PER_GB", 0.5
    )
    # Runtime plan rewriting (dryad_tpu.rewrite): the controller taps
    # the event stream, folds diagnosis events into RewriteActions,
    # and the drivers apply them at chunk/window boundaries.  Requires
    # obs_diagnosis; every rewrite is byte-identity-preserving (the
    # fuzz-differential suite runs this knob on vs off).
    plan_rewrite: bool = _env_bool("DRYAD_TPU_PLAN_REWRITE", True)
    # Continuous telemetry plane (dryad_tpu.obs.telemetry): a
    # ResourceMonitor taps the event stream and samples device HBM /
    # host RSS plus every shared flightrec probe on an interval,
    # feeding resource_sample events, rolling gauges, and the measured
    # HeadroomProvider that the adaptive exchange-window and
    # dispatch-depth policies consult.  Off = no sampler, adaptive
    # knobs fall back to configured budgets/defaults.
    obs_telemetry: bool = _env_bool("DRYAD_TPU_OBS_TELEMETRY", True)
    # Min seconds between resource samples (tap-paced; a background
    # thread in resident processes uses the same interval).
    telemetry_sample_s: float = _env_float(
        "DRYAD_TPU_TELEMETRY_SAMPLE_S", 1.0
    )
    # Rolling-window width for the telemetry metric store — counter
    # totals and SLO latency percentiles read over this horizon.
    telemetry_window_s: float = _env_float(
        "DRYAD_TPU_TELEMETRY_WINDOW_S", 60.0
    )
    # Query-scoped trace propagation (obs.tracectx): run_* entry
    # points mint a TraceContext so every span / exchange_round /
    # dispatch_gap / gang_window / diagnosis event is attributable to
    # one query (obs.critpath folds them into a critical-path
    # breakdown).  Off = events still flow, unstamped — no per-query
    # attribution; the bench --obs-overhead A/B flips this.
    query_trace: bool = _env_bool("DRYAD_TPU_QUERY_TRACE", True)

    def __post_init__(self) -> None:
        self.validate()

    def validate(self) -> None:
        if self.partition_count < 1:
            raise ValueError("partition_count must be >= 1")
        if self.partition_count > StaticConfig.max_partition_count:
            raise ValueError(
                f"partition_count {self.partition_count} exceeds "
                f"max {StaticConfig.max_partition_count}"
            )
        if self.shuffle_slack < 1.0:
            raise ValueError("shuffle_slack must be >= 1.0")
        if self.intermediate_compression not in (None, "zlib"):
            raise ValueError("intermediate_compression must be None or 'zlib'")
        if not 0.0 < self.sample_rate <= 1.0:
            raise ValueError("sample_rate must be in (0, 1]")
        if self.max_shuffle_retries < 0:
            raise ValueError("max_shuffle_retries must be >= 0")
        if self.max_stage_failures < 1:
            raise ValueError("max_stage_failures must be >= 1")
        if self.outlier_sigmas <= 0:
            raise ValueError("outlier_sigmas must be > 0")
        if self.straggler_floor_ratio < 1.0:
            raise ValueError("straggler_floor_ratio must be >= 1.0")
        if self.coded_parity_tasks < 1:
            raise ValueError("coded_parity_tasks must be >= 1")
        if self.coded_max_amplification <= 0:
            raise ValueError("coded_max_amplification must be > 0")
        if self.retry_backoff_base < 0:
            raise ValueError("retry_backoff_base must be >= 0")
        if self.retry_backoff_max < self.retry_backoff_base:
            raise ValueError(
                "retry_backoff_max must be >= retry_backoff_base"
            )
        if self.retry_jitter < 0:
            raise ValueError("retry_jitter must be >= 0")
        if self.io_threads < 1:
            raise ValueError("io_threads must be >= 1")
        if self.rows_per_vertex < 1:
            raise ValueError("rows_per_vertex must be >= 1")
        if self.device_cache_bytes < 0:
            raise ValueError("device_cache_bytes must be >= 0")
        if self.overflow_sync_depth < 1:
            raise ValueError("overflow_sync_depth must be >= 1")
        if self.exchange_window < -1:
            raise ValueError(
                "exchange_window must be >= 0, or -1 for the auto policy"
            )
        if self.exchange_hbm_budget_mb < 1:
            raise ValueError("exchange_hbm_budget_mb must be >= 1")
        if self.tail_fanout_rows < 0:
            raise ValueError("tail_fanout_rows must be >= 0")
        if self.tail_rows_per_partition < 1:
            raise ValueError("tail_rows_per_partition must be >= 1")
        if self.stream_bucket_rows < 1:
            raise ValueError("stream_bucket_rows must be >= 1")
        if self.stream_combine_rows < 1:
            raise ValueError("stream_combine_rows must be >= 1")
        if self.stream_buckets < 2:
            raise ValueError("stream_buckets must be >= 2")
        if self.stream_pipeline_depth < 1:
            raise ValueError("stream_pipeline_depth must be >= 1")
        if self.stream_writer_queue < 1:
            raise ValueError("stream_writer_queue must be >= 1")
        if self.obs_events_mem_cap < 0:
            raise ValueError("obs_events_mem_cap must be >= 0")
        if self.flightrec_events < 16:
            raise ValueError("flightrec_events must be >= 16")
        if self.flightrec_snapshot_s <= 0:
            raise ValueError("flightrec_snapshot_s must be > 0")
        if self.diagnose_skew_ratio < 1.0:
            raise ValueError("diagnose_skew_ratio must be >= 1.0")
        if self.diagnose_recompile_burst < 2:
            raise ValueError("diagnose_recompile_burst must be >= 2")
        if self.diagnose_cooldown_s < 0:
            raise ValueError("diagnose_cooldown_s must be >= 0")
        if self.combine_tree_fan < 2:
            raise ValueError("combine_tree_fan must be >= 2")
        if self.combine_tree_ranges < 2 or (
            self.combine_tree_ranges & (self.combine_tree_ranges - 1)
        ):
            raise ValueError(
                "combine_tree_ranges must be a power of two >= 2"
            )
        if self.combine_tree_groups < 0:
            raise ValueError("combine_tree_groups must be >= 0")
        if not 0.0 < self.combine_tree_degrade_ratio <= 1.0:
            raise ValueError(
                "combine_tree_degrade_ratio must be in (0, 1]"
            )
        if self.stream_host_reprobe < 0:
            raise ValueError("stream_host_reprobe must be >= 0")
        if self.dispatch_depth != -1 and self.dispatch_depth < 1:
            raise ValueError(
                "dispatch_depth must be >= 1, or -1 for the adaptive "
                "headroom policy"
            )
        if self.chunk_fuse < 1:
            raise ValueError("chunk_fuse must be >= 1")
        if self.command_batch < 0:
            raise ValueError("command_batch must be >= 0")
        if self.gang_batch_depth < 1:
            raise ValueError("gang_batch_depth must be >= 1")
        if self.gang_partition_cache_bytes < 0:
            raise ValueError("gang_partition_cache_bytes must be >= 0")
        if self.serve_max_inflight < 1:
            raise ValueError("serve_max_inflight must be >= 1")
        if self.serve_max_bytes < 0:
            raise ValueError("serve_max_bytes must be >= 0")
        if self.serve_result_cache_bytes < 0:
            raise ValueError("serve_result_cache_bytes must be >= 0")
        if self.serve_drr_quantum_bytes < 1:
            raise ValueError("serve_drr_quantum_bytes must be >= 1")
        if self.serve_cache_admission not in ("cost", "all"):
            raise ValueError(
                "serve_cache_admission must be 'cost' or 'all'"
            )
        if self.serve_cache_min_sec_per_gb < 0:
            raise ValueError("serve_cache_min_sec_per_gb must be >= 0")
        if self.telemetry_sample_s <= 0:
            raise ValueError("telemetry_sample_s must be > 0")
        if self.telemetry_window_s <= 0:
            raise ValueError("telemetry_window_s must be > 0")


# Every ``DryadConfig`` field, one line each — THE documented key
# table.  The graftlint ``config-key`` rule cross-references this dict
# against the dataclass fields (both directions: every field is
# documented here; every documented key is a real field) AND against
# every ``config.<attr>`` / ``getattr(config, "attr", ...)`` use in the
# package, so a renamed or misspelled knob cannot silently read a
# default.
CONFIG_KEYS = {
    "partition_count": "default output partitioning (DefaultPartitionCount)",
    "enable_speculative_duplication":
        "duplicate straggling vertex tasks (DryadLinqContext.cs:959)",
    "max_stage_failures": "GM failure budget per stage before job failure",
    "shuffle_slack": "padded shuffle-bucket slack over uniform expectation",
    "max_shuffle_retries": "bounded shape palette for overflow retries",
    "intermediate_compression": "channel compression: None or 'zlib'",
    "sample_rate": "range-partition sampler rate (reference 0.1%)",
    "event_log_dir": "JSONL event-log directory (Calypso); None disables",
    "profile_dir": "XLA/JAX profiler output directory; None disables",
    "checkpoint_dir": "stage-output checkpoint directory; None disables",
    "checkpoint_retain_seconds": "checkpoint retention lease; None keeps",
    "io_threads": "host-side IO thread count (DRYAD_THREADS_PER_WORKER)",
    "outlier_sigmas": "speculative-duplication outlier threshold (sigmas)",
    "straggler_floor_ratio": "straggler-threshold floor over trimmed mean",
    "coded_redundancy": "k-of-n coded spares for linear partial aggregates",
    "coded_parity_tasks": "max parity spares r per coded stage",
    "coded_max_amplification": "float-decode rounding amplification guard",
    "retry_backoff_base": "transient-retry backoff base seconds",
    "retry_backoff_max": "transient-retry backoff cap seconds",
    "retry_jitter": "seeded retry-backoff jitter fraction",
    "retry_seed": "retry-jitter RNG seed",
    "broadcast_limit": "broadcast-join max replicated right-side rows",
    "topk_limit": "order_by+take fuses to shuffle-free top-k at or below",
    "auto_dense_strings": "single-STRING-key group_by lowers to MXU buckets",
    "auto_dense_ints": "bounded-INT32-key group_by rides the dense path",
    "auto_dense_limit": "dense-key domain cap for the MXU bucket path",
    "stringcode_runtime_tables": "code tables ship as palette operands",
    "device_cache_bytes": "device-resident ingest cache budget; 0 off",
    "rows_per_vertex": "target rows per independent vertex task",
    "plan_fuse": "whole-DAG SPMD fusion into one dispatched program",
    "overflow_sync_depth": "speculative dispatches per overflow readback",
    "exchange_window":
        "staged-exchange buckets per round (0 = flat, -1 = auto policy)",
    "exchange_hbm_budget_mb":
        "staging-buffer HBM budget for the auto exchange-window policy",
    "tail_fanout_rows": "static row bound enabling tail fan-out; 0 off",
    "tail_rows_per_partition": "rows per partition after tail fan-out",
    "stream_bucket_rows": "max rows per phase-2 bucket before re-split",
    "stream_combine_rows": "partial-accumulator compaction threshold",
    "stream_buckets": "phase-1 spill fan-out (bucket count)",
    "stream_spill_dir": "spill directory; None = fresh tempdir",
    "stream_pipeline_depth": "chunks in flight across the ooc pipeline",
    "stream_writer_queue": "background spill-writer queue, in pieces",
    "combine_tree": "topology-aware hierarchical streaming combines",
    "combine_tree_fan": "max batches folded per tree-group flush",
    "combine_tree_ranges": "key-range histogram resolution (power of two)",
    "combine_tree_groups": "level-0 tree groups; 0 = auto from topology",
    "combine_tree_degrade_ratio": "per-range host-degrade distinct ratio",
    "stream_host_reprobe": "reducing host combines before device re-probe",
    "obs_events_mem_cap": "EventLog in-memory ring cap; 0 unbounded",
    "obs_flight_recorder": "crash-forensics ring + blackbox dump hooks",
    "flightrec_events": "flight-recorder ring capacity in events",
    "flightrec_snapshot_s": "min seconds between health microsnapshots",
    "flightrec_dir": "blackbox dump dir; None = event_log_dir or cwd",
    "obs_diagnosis": "online pathology detection over the live stream",
    "diagnose_skew_ratio": "partition-skew max/mean row-ratio trigger",
    "diagnose_recompile_burst": "per-tier compiles in window = storm",
    "diagnose_cooldown_s": "per-(rule, subject) re-diagnosis cooldown",
    "dispatch_depth": "ooc chunk dispatches in flight; 1 = serial "
                      "driver, -1 = adaptive from measured headroom",
    "chunk_fuse": "chunk partial-plans lowered per dispatch; 1 = legacy",
    "do_while_device_auto": "try lax.while_loop for every fixed point",
    "command_batch": "gang run commands per runbatch round trip; 0 off",
    "gang_combine_tree": "worker-side level -1 partial pre-merge",
    "gang_batch_depth": "runbatch envelopes in flight per worker; 1 serial",
    "gang_partition_cache_bytes": "worker partition cache budget; 0 off",
    "serve_max_inflight": "per-tenant admitted-query cap (QueryRejected)",
    "serve_max_bytes": "per-tenant admitted host-input byte budget; 0 off",
    "serve_result_cache_bytes": "plan-fingerprint result cache; 0 off",
    "serve_drr_quantum_bytes": "input bytes per fair-share cost unit",
    "serve_cache_admission":
        "result-cache admission: 'cost' (amortizing only) or 'all'",
    "serve_cache_min_sec_per_gb":
        "cost admission floor: saved seconds per cached GB",
    "plan_rewrite": "runtime plan rewriter (dryad_tpu.rewrite); "
                    "diagnosis-driven, byte-identity-preserving",
    "obs_telemetry": "continuous resource sampler + measured headroom",
    "telemetry_sample_s": "min seconds between resource samples",
    "telemetry_window_s": "rolling metric window for SLO readouts",
    "query_trace": "query-scoped trace propagation (obs.tracectx); "
                   "qid-stamps events for critical-path attribution",
}
