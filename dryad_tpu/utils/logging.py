"""Structured leveled logging (reference ``GraphManager/shared/DrLogging.h:23-34``).

The reference captures file/function/line with ``DrLogD/I/W/E/A`` macros and
reads the level from ``DRYAD_LOGGING_LEVEL``; here we configure a stdlib
logger namespace ``dryad_tpu`` once, with level from ``DRYAD_TPU_LOGGING_LEVEL``.
"""

from __future__ import annotations

import logging
import sys

from dryad_tpu.utils.config import StaticConfig

_CONFIGURED = False


def get_logger(name: str = "dryad_tpu") -> logging.Logger:
    global _CONFIGURED
    if not _CONFIGURED:
        root = logging.getLogger("dryad_tpu")
        if not root.handlers:
            handler = logging.StreamHandler(sys.stderr)
            handler.setFormatter(
                logging.Formatter(
                    "%(asctime)s %(levelname).1s %(name)s "
                    "[%(filename)s:%(lineno)d] %(message)s"
                )
            )
            root.addHandler(handler)
        root.setLevel(getattr(logging, StaticConfig.logging_level.upper(), logging.INFO))
        root.propagate = False
        _CONFIGURED = True
    return logging.getLogger(name)
