"""Query — the lazy table handle and operator surface.

The analog of ``DryadLinqQuery<T>`` + the ``DryadLinqQueryable``
extension-method surface (``LinqToDryad/DryadLinqQuery.cs:299``,
``DryadLinqQueryable.cs:39``): a Query wraps a logical plan node;
operators build new nodes; ``collect``/``submit`` trigger lowering and
execution through the context.  Operator parity map (reference op ->
here): Select->select, Where->where, SelectMany->select_many,
GroupBy->group_by, Join/GroupJoin->join/group_join_count,
OrderBy/ThenBy->order_by, Distinct->distinct, Concat->concat,
Union/Intersect/Except->union/intersect/except_, HashPartition->
hash_partition, RangePartition->range_partition, Apply/
ApplyPerPartition->apply, ApplyWithPartitionIndex->apply(with_index),
Fork->fork, DoWhile->do_while, Take->take, Count/Sum/Min/Max/Average->
count/sum_/min_/max_/mean (+ *_as_query lazy forms), Zip->zip_,
SlidingWindow->sliding_window, Assume{Hash,Range}Partition->
assume_hash_partition/assume_range_partition, ToStore/Submit->
to_store/submit/collect.
"""

from __future__ import annotations

import operator
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from dryad_tpu.api.decomposable import Decomposable
from dryad_tpu.columnar.schema import ColumnType, Schema
from dryad_tpu.plan import infer
from dryad_tpu.plan.nodes import Node, PartitionInfo

KeyArg = Union[str, Sequence[str]]
OrderArg = Union[str, Tuple[str, Union[bool, str]]]  # bool True / "desc" = descending

JOIN_STRATEGIES = ("shuffle", "broadcast", "auto")


def _check_strategy(strategy: str) -> None:
    if strategy not in JOIN_STRATEGIES:
        raise ValueError(
            f"unknown join strategy {strategy!r}; expected one of {JOIN_STRATEGIES}"
        )

_AGG_TYPE_RULES = {
    "count": lambda ct: ColumnType.INT32,
    "sum": lambda ct: ct,
    "min": lambda ct: ct,
    "max": lambda ct: ct,
    "first": lambda ct: ct,
    "mean": lambda ct: ColumnType.FLOAT32,
    "any": lambda ct: ColumnType.BOOL,
    "all": lambda ct: ColumnType.BOOL,
}


def _keys(k: KeyArg) -> List[str]:
    return [k] if isinstance(k, str) else list(k)


def _order_keys(keys: Sequence[OrderArg]) -> List[Tuple[str, bool]]:
    out: List[Tuple[str, bool]] = []
    for k in keys:
        if isinstance(k, str):
            out.append((k, False))
            continue
        name, d = k[0], k[1]
        # accept "asc"/"desc" strings: a bare bool(...) would read the
        # truthy string "asc" as DESCENDING — a silent wrong order.
        if isinstance(d, str):
            if d not in ("asc", "desc"):
                raise ValueError(
                    f"order direction for {name!r} must be 'asc', 'desc' "
                    f"or a bool (True=descending), got {d!r}")
            d = d == "desc"
        out.append((name, bool(d)))
    return out


class _Project:
    """Name-projection row fn: picklable for job packages, VALUE-equal
    so re-lowering a rebuilt query hits the compiled-stage cache."""

    def __init__(self, phys: List[str]):
        self.phys = tuple(phys)

    def __eq__(self, other) -> bool:
        return type(other) is _Project and other.phys == self.phys

    def __hash__(self) -> int:
        return hash(("_Project", self.phys))

    def __call__(self, cols: Dict) -> Dict:
        return {c: cols[c] for c in self.phys}


_VOCAB_PRESERVING = frozenset({
    "where", "take", "skip", "tail", "reverse", "order_by",
    "hash_partition", "range_partition", "assume_partition", "tee",
    "with_rank", "take_while", "skip_while", "distinct",
})


def static_str_vocab(node, col):
    """Static hash-vocabulary bound for a STRING column, walked back to
    ingest through value-preserving nodes (the string twin of the
    INT32 range walk): the union of the reaching ingests' per-column
    hash sets, or None when something could fabricate values
    (select/apply/join/default_if_empty).  Shared by the API gate and
    the lowering's subset-table build."""
    import numpy as np

    if node.kind == "input":
        return (node.params.get("str_vocab") or {}).get(col)
    if node.kind == "concat":
        vs = [static_str_vocab(i, col) for i in node.inputs]
        if any(v is None for v in vs):
            return None
        return np.unique(np.concatenate(vs)) if vs else None
    if node.kind == "select" and isinstance(node.params.get("fn"), _Project):
        return static_str_vocab(node.inputs[0], col)
    if node.kind in _VOCAB_PRESERVING and node.inputs:
        return static_str_vocab(node.inputs[0], col)
    return None


class Query:
    """Lazy distributed table: a logical plan node plus its context."""

    def __init__(self, ctx, node: Node):
        self.ctx = ctx
        self.node = node

    @property
    def schema(self) -> Schema:
        return self.node.schema

    def _require_cols(self, names: Sequence[str], where: str = "") -> None:
        missing = [n for n in names if n not in self.schema]
        if missing:
            raise ValueError(
                f"unknown column(s) {missing} {where}; have {self.schema.names}"
            )

    # -- row-wise operators -----------------------------------------------
    def select(self, fn: Callable[[Dict], Dict], schema: Optional[Schema] = None) -> "Query":
        """Projection/map over physical columns (reference Select).

        Partition metadata is dropped: ``fn`` may rewrite key *values*
        even when the key *name* survives, which would make shuffle
        elision silently wrong.  Use ``project`` (name-only projection)
        or ``assume_*_partition`` to retain metadata.
        """
        out_schema = schema or infer.infer_select_schema(self.schema, fn)
        node = Node("select", [self.node], out_schema, PartitionInfo(), fn=fn)
        return Query(self.ctx, node)

    def project(self, names: KeyArg) -> "Query":
        """Column projection by name."""
        names = _keys(names)
        out_schema = self.schema.select(names)
        # a picklable callable (not a closure): projections must survive
        # job packaging (exec.jobpackage)
        fn = _Project(out_schema.device_names())
        keep = self.node.partition
        if keep.keys and not all(k in out_schema for k in keep.keys):
            keep = PartitionInfo()
        return Query(self.ctx, Node("select", [self.node], out_schema, keep, fn=fn))

    def where(self, fn: Callable[[Dict], Any]) -> "Query":
        node = Node("where", [self.node], self.schema, self.node.partition, fn=fn)
        return Query(self.ctx, node)

    def select_many(
        self,
        fn: Callable[[Dict], Tuple[Dict, Any]],
        factor: int,
        schema: Optional[Schema] = None,
    ) -> "Query":
        """Flat-map: fn maps each row to ``factor`` rows.

        fn(cols) -> (out_cols each shaped (n, factor, ...), valid (n, factor)).
        """
        out_schema = schema or infer.infer_select_many_schema(self.schema, fn, factor)
        node = Node(
            "select_many", [self.node], out_schema, PartitionInfo(),
            fn=fn, factor=int(factor),
        )
        return Query(self.ctx, node)

    # -- grouping / aggregation -------------------------------------------
    def group_by(
        self,
        keys: KeyArg,
        aggs: Optional[Dict[str, Tuple[str, Optional[str]]]] = None,
        decomposable: Optional[Decomposable] = None,
        dense: Optional[int] = None,
        salt: Optional[int] = None,
    ) -> "Query":
        """GroupBy with builtin aggregates or a Decomposable.

        ``aggs``: out_name -> (op, col) with op in
        sum|count|min|max|mean|first|any|all (col None for count).
        int64 columns aggregate exactly with 64-bit arithmetic;
        sum/mean WRAP mod 2^64 when a group's true total exceeds the
        int64 range (numpy int64 semantics — C# long Average instead
        throws OverflowException there).  float64 supports
        min/max/first (totalOrder); cast to float32 for sums.

        ``salt=S`` spreads each key over S shuffle destinations
        (partial-reduce on (key, salt), exchange, reduce, then exchange
        on the key alone) — the skew escape hatch for heavy-hitter keys,
        the analog of the reference's data-size-driven hash
        redistribution (``DrDynamicDistributor.h:26,79``).  Costs a
        second shuffle; use when one key dominates.

        ``dense=K`` declares the single INT32 key lies in [0, K): the
        engine then skips the sort+shuffle pipeline and reduces on the
        MXU via one-hot matmul buckets (Pallas kernel on TPU) followed
        by one ``psum_scatter`` — the aggregation-tree fast path.  Only
        sum/count/mean aggregates; rows with keys outside [0, K) are
        dropped.  Output is range-partitioned and ordered by the key.

        Dense-path precision: counts are exact (int32 across the mesh;
        per-partition capacity is guarded at 2^24).  SUM columns
        accumulate on the MXU via split-bf16 terms
        (``ops/pallas_bucket.py``): integer values use 3 terms and stay
        EXACT up to 2^24 per value (totals still accumulate in f32, so
        an integer sum loses exactness once a per-bucket total exceeds
        2^24 — use the default sort-based path when exact large integer
        sums matter); float values use 2 terms (~2^-16 per-element
        representation error, amplified by cancellation in near-zero
        groups).
        """
        keys = _keys(keys)
        if salt is not None:
            if salt < 2:
                raise ValueError("salt must be >= 2")
            if dense is not None or decomposable is not None:
                raise ValueError("salt applies to builtin-agg group_by only")
        if dense is not None:
            if decomposable is not None:
                raise ValueError("dense group_by takes builtin aggs only")
            if len(keys) != 1:
                raise ValueError("dense group_by requires exactly one key")
            if self.schema.field(keys[0]).ctype != ColumnType.INT32:
                raise ValueError("dense group_by key must be INT32")
            if dense < 1:
                raise ValueError("dense bucket count must be >= 1")
            bad = [
                op for op, _c, _o in (
                    (op, c, o) for o, (op, c) in (aggs or {}).items()
                ) if op not in ("sum", "count", "mean")
            ]
            if not aggs:
                raise ValueError("group_by needs aggs")
            if bad:
                raise ValueError(
                    f"dense group_by supports sum/count/mean, got {bad}"
                )
            wide = [
                c for _o, (_op, c) in aggs.items()
                if c is not None and self.schema.field(c).ctype.is_split
            ]
            if wide:
                raise ValueError(
                    f"dense group_by aggregates f32 on the MXU; columns "
                    f"{wide} are 64-bit/split types — use the default "
                    f"sort-based path"
                )
        fields: List[Tuple[str, ColumnType]] = [
            (k, self.schema.field(k).ctype) for k in keys
        ]
        if decomposable is not None:
            fields += list(decomposable.out_fields)
            node = Node(
                "group_by", [self.node], Schema(fields),
                PartitionInfo.hashed(keys), keys=keys, decomposable=decomposable,
            )
            return Query(self.ctx, node)
        if not aggs:
            raise ValueError("group_by needs aggs or a decomposable")
        agg_list = []
        for out_name, (op, col) in aggs.items():
            if op not in _AGG_TYPE_RULES:
                raise ValueError(f"unknown aggregate {op!r}")
            ct = self.schema.field(col).ctype if col is not None else ColumnType.INT32
            fields.append((out_name, _AGG_TYPE_RULES[op](ct)))
            agg_list.append((op, col, out_name))
        if dense is not None:
            part = PartitionInfo.ranged(
                [(keys[0], False)], ordered=[(keys[0], False)]
            )
            node = Node(
                "group_by", [self.node], Schema(fields), part,
                keys=keys, aggs=agg_list, dense=int(dense),
            )
        elif (k_int := self._auto_dense_int(keys, agg_list, salt)) is not None:
            # int auto-dense: ingest-bounded [0, K) key domain rides the
            # MXU bucket path with a range-miss guard (sort/shuffle
            # path and its 12x-slower segmented reduce skipped entirely)
            part = PartitionInfo.ranged(
                [(keys[0], False)], ordered=[(keys[0], False)]
            )
            node = Node(
                "group_by", [self.node], Schema(fields), part,
                keys=keys, aggs=agg_list, dense=k_int, guard_range=True,
            )
        else:
            auto = self._auto_dense_eligible(keys, agg_list, salt)
            # The auto-dense path physically partitions output by
            # dictionary CODE range, which matches neither a hash nor a
            # key-order range claim — so the node claims NOTHING and
            # downstream consumers re-exchange (a stale hashed claim
            # would elide a join's left exchange and drop matches).
            part = (
                PartitionInfo() if auto else PartitionInfo.hashed(keys)
            )
            node = Node(
                "group_by", [self.node], Schema(fields), part,
                keys=keys, aggs=agg_list, salt=salt, auto_dense=auto,
            )
        return Query(self.ctx, node)

    # node kinds that pass column VALUES through unchanged, so an
    # ingest-time range bound on a column still holds at their output.
    # default_if_empty is NOT here: its defaults dict can fabricate a
    # key outside the ingest range (code-review r4).
    _VALUE_PRESERVING = frozenset({
        "where", "take", "skip", "tail", "reverse",
        "order_by", "hash_partition", "range_partition",
        "assume_partition", "tee", "with_rank", "take_while",
        "skip_while", "distinct",
    })

    def _int_key_range(self, node, col) -> Optional[Tuple[int, int]]:
        """Static (min, max) bound for an INT32 column, walked back to
        ingest through value-preserving nodes only (select/apply/join
        may fabricate values, so they break the bound; project() lowers
        to a "select" with a recognizable name-only _Project fn)."""
        if node.kind == "input":
            return (node.params.get("col_stats") or {}).get(col)
        if node.kind == "concat":
            rs = [self._int_key_range(i, col) for i in node.inputs]
            if any(r is None for r in rs):
                return None
            return (min(r[0] for r in rs), max(r[1] for r in rs))
        if node.kind == "select" and isinstance(
            node.params.get("fn"), _Project
        ):
            return self._int_key_range(node.inputs[0], col)
        if node.kind in self._VALUE_PRESERVING and node.inputs:
            return self._int_key_range(node.inputs[0], col)
        return None

    def _auto_dense_int(self, keys, agg_list, salt) -> Optional[int]:
        """Int auto-dense gate (the integer twin of the STRING rewrite):
        a plain group_by over ONE INT32 key whose ingest-time range is
        [0, K) with K <= auto_dense_limit rides the MXU bucket path —
        no sort, no shuffle.  Returns K or None.  Unlike the explicit
        ``dense=`` API (which documents dropping out-of-range rows),
        this rewrite adds a range-miss guard: values fabricated after
        ingest fail loudly instead of silently vanishing."""
        cfg = self.ctx.config
        if salt or not getattr(cfg, "auto_dense_ints", True):
            return None
        if len(keys) != 1:
            return None
        if self.schema.field(keys[0]).ctype is not ColumnType.INT32:
            return None
        plain = (
            ColumnType.INT32, ColumnType.UINT32,
            ColumnType.FLOAT32, ColumnType.BOOL,
        )
        for op, col, _name in agg_list:
            if op not in ("sum", "count", "mean"):
                return None
            if col is not None and self.schema.field(col).ctype not in plain:
                return None
        rng = self._int_key_range(self.node, keys[0])
        limit = getattr(cfg, "auto_dense_limit", 1 << 17)
        # 0-based domains only (the common categorical-code shape);
        # negative or offset ranges keep the sort path
        if rng is None or rng[0] < 0 or rng[1] + 1 > limit:
            return None
        return rng[1] + 1

    def _auto_dense_eligible(self, keys, agg_list, salt) -> bool:
        """Build-time gate for the auto-dense STRING group_by lowering
        (``plan/lower.py`` re-checks at lowering; a vocabulary grown
        past the limit falls back to the sort path, which the
        claim-free partition metadata keeps correct).

        The vocabulary bound is PER-INGEST when provenance allows
        (``static_str_vocab``): a context that once ingested a huge
        unrelated vocabulary no longer disables the fast path for every
        later query — only the key column's own domain matters (and the
        coding tables shrink to it)."""
        cfg = self.ctx.config
        if salt or not getattr(cfg, "auto_dense_strings", True):
            return False
        d = getattr(self.ctx, "dictionary", None)
        limit = getattr(cfg, "auto_dense_limit", 1 << 17)
        if d is None or len(d) == 0:
            return False
        if len(keys) != 1:
            return False
        vocab = static_str_vocab(self.node, keys[0])
        bound = len(vocab) if vocab is not None else len(d)
        if not 0 < bound <= limit:
            return False
        if self.schema.field(keys[0]).ctype is not ColumnType.STRING:
            return False
        plain = (
            ColumnType.INT32, ColumnType.UINT32,
            ColumnType.FLOAT32, ColumnType.BOOL,
        )
        for op, col, _name in agg_list:
            if op not in ("sum", "count", "mean"):
                return False
            if col is not None and self.schema.field(col).ctype not in plain:
                return False
        return True

    def distinct(self, keys: Optional[KeyArg] = None) -> "Query":
        keys = _keys(keys) if keys is not None else self.schema.names
        # Distinct over exactly one STRING column (the whole schema) is
        # the vocabulary query — the auto-dense rewrite computes it as a
        # shuffle-free bucket count>0 + decode; like auto-dense group_by
        # the output is code-range partitioned, so the node claims
        # nothing (see _auto_dense_eligible).
        auto = (
            self.schema.names == list(keys)
            and self._auto_dense_eligible(keys, [("count", None, "#c")], None)
        )
        node = Node(
            "distinct", [self.node], self.schema,
            PartitionInfo() if auto else PartitionInfo.hashed(keys),
            keys=keys, auto_dense=auto,
        )
        return Query(self.ctx, node)

    # -- joins --------------------------------------------------------------
    def _join_partition_info(self, lk: List[str], strategy: str) -> PartitionInfo:
        """Output placement depends on strategy: a broadcast join leaves
        the left side where it is; a shuffle join co-hash-partitions;
        'auto' is decided at trace time, so nothing can be assumed."""
        if strategy == "broadcast":
            return self.node.partition
        if strategy == "auto":
            return PartitionInfo()
        return PartitionInfo.hashed(lk)

    def join(
        self,
        other: "Query",
        left_keys: KeyArg,
        right_keys: Optional[KeyArg] = None,
        expansion: float = 4.0,
        suffix: str = "_r",
        strategy: str = "auto",
    ) -> "Query":
        """Inner equi-join (reference Join): co-hash-partition + local
        join, or replicate a small right side (``strategy`` in
        shuffle|broadcast|auto; broadcast is the
        ``DrDynamicBroadcastManager`` copy-tree as one ``all_gather``)."""
        _check_strategy(strategy)
        lk = _keys(left_keys)
        rk = _keys(right_keys) if right_keys is not None else lk
        self._require_cols(lk, "in join left keys")
        other._require_cols(rk, "in join right keys")
        fields = [(f.name, f.ctype) for f in self.schema.fields]
        lnames = {f.name for f in self.schema.fields}
        for f in other.schema.fields:
            if f.name in rk:
                continue
            name = f.name if f.name not in lnames else f"{f.name}{suffix}"
            fields.append((name, f.ctype))
        node = Node(
            "join", [self.node, other.node], Schema(fields),
            self._join_partition_info(lk, strategy),
            left_keys=lk, right_keys=rk, join_kind="inner",
            expansion=expansion, suffix=suffix, strategy=strategy,
        )
        return Query(self.ctx, node)

    def semi_join(
        self, other: "Query", left_keys: KeyArg,
        right_keys: Optional[KeyArg] = None, expansion: float = 4.0,
        strategy: str = "auto",
    ) -> "Query":
        return self._semi(other, left_keys, right_keys, expansion, False, strategy)

    def anti_join(
        self, other: "Query", left_keys: KeyArg,
        right_keys: Optional[KeyArg] = None, expansion: float = 4.0,
        strategy: str = "auto",
    ) -> "Query":
        return self._semi(other, left_keys, right_keys, expansion, True, strategy)

    def _semi(self, other, left_keys, right_keys, expansion, anti, strategy="shuffle") -> "Query":
        _check_strategy(strategy)
        lk = _keys(left_keys)
        rk = _keys(right_keys) if right_keys is not None else lk
        self._require_cols(lk, "in join left keys")
        other._require_cols(rk, "in join right keys")
        node = Node(
            "join", [self.node, other.node], self.schema,
            self._join_partition_info(lk, strategy),
            left_keys=lk, right_keys=rk,
            join_kind="anti" if anti else "semi", expansion=expansion,
            strategy=strategy,
        )
        return Query(self.ctx, node)

    # -- set operations (reference Union/Intersect/Except) -------------------
    def concat(self, *others: "Query") -> "Query":
        for o in others:
            if o.schema.names != self.schema.names:
                raise ValueError("concat requires identical schemas")
        node = Node(
            "concat", [self.node] + [o.node for o in others], self.schema,
            PartitionInfo(),
        )
        return Query(self.ctx, node)

    def union(self, other: "Query") -> "Query":
        return self.concat(other).distinct()

    def intersect(self, other: "Query") -> "Query":
        return self.distinct().semi_join(other, self.schema.names)

    def except_(self, other: "Query") -> "Query":
        return self.distinct().anti_join(other, self.schema.names)

    # -- partitioning -------------------------------------------------------
    def hash_partition(self, keys: KeyArg) -> "Query":
        keys = _keys(keys)
        node = Node(
            "hash_partition", [self.node], self.schema,
            PartitionInfo.hashed(keys), keys=keys,
        )
        return Query(self.ctx, node)

    def range_partition(self, keys: KeyArg) -> "Query":
        ks = _order_keys(_keys(keys))
        self._require_cols([n for n, _ in ks], "in range_partition")
        node = Node(
            "range_partition", [self.node], self.schema,
            PartitionInfo.ranged(ks), keys=ks,
        )
        return Query(self.ctx, node)

    def assume_hash_partition(self, keys: KeyArg) -> "Query":
        node = Node(
            "assume_partition", [self.node], self.schema,
            PartitionInfo.hashed(_keys(keys)),
        )
        return Query(self.ctx, node)

    def assume_range_partition(self, keys: KeyArg) -> "Query":
        node = Node(
            "assume_partition", [self.node], self.schema,
            PartitionInfo.ranged(_order_keys(_keys(keys))),
        )
        return Query(self.ctx, node)

    def assume_order_by(self, keys: Sequence[OrderArg]) -> "Query":
        ks = _order_keys(keys)
        node = Node(
            "assume_partition", [self.node], self.schema,
            PartitionInfo.ranged(ks, ks),
        )
        return Query(self.ctx, node)

    # -- ordering -----------------------------------------------------------
    def order_by(self, keys: Sequence[OrderArg]) -> "Query":
        """Global sort: range partition + local sort (reference
        OrderBy/ThenBy chain collapses into one keys list)."""
        ks = _order_keys(keys)
        self._require_cols([n for n, _ in ks], "in order_by")
        node = Node(
            "order_by", [self.node], self.schema,
            # spread: the skew-proof exchange may split equal keys
            # across a partition boundary (plan/nodes.py PartitionInfo)
            PartitionInfo.ranged(ks, ks, spread=True), keys=ks,
        )
        return Query(self.ctx, node)

    def with_rank(self, out: str = "rank") -> "Query":
        """Attach each row's global engine-order position as an INT32
        column — the indexed-operator primitive (reference LongSelect /
        indexed Select/Where overloads): ``q.with_rank().select(...)``
        gives every row its index."""
        if out in self.schema.names:
            raise ValueError(f"column {out!r} already exists")
        node = Node(
            "with_rank", [self.node],
            self.schema.with_field(out, ColumnType.INT32),
            self.node.partition, out=out,
        )
        return Query(self.ctx, node)

    def take(self, n: int) -> "Query":
        # LINQ Take clamps negative counts to an empty sequence; the
        # kernel compares uint32 ranks, so a raw negative would wrap.
        node = Node(
            "take", [self.node], self.schema, self.node.partition,
            n=max(0, int(n)),
        )
        return Query(self.ctx, node)

    def skip(self, n: int) -> "Query":
        """Drop the first n rows of global engine order (reference Skip)."""
        node = Node(
            "skip", [self.node], self.schema, self.node.partition,
            n=max(0, int(n)),
        )
        return Query(self.ctx, node)

    def tail(self, n: int) -> "Query":
        """Keep the last n rows of global engine order (the Last /
        TakeLast shape of the reference dispatch)."""
        node = Node(
            "tail", [self.node], self.schema, self.node.partition,
            n=max(0, int(n)),
        )
        return Query(self.ctx, node)

    def take_while(self, fn: Callable[[Dict], Any]) -> "Query":
        """Rows strictly before the first predicate failure in global
        engine order (reference TakeWhile)."""
        node = Node(
            "take_while", [self.node], self.schema, self.node.partition, fn=fn
        )
        return Query(self.ctx, node)

    def skip_while(self, fn: Callable[[Dict], Any]) -> "Query":
        """Rows from the first predicate failure onward (SkipWhile)."""
        node = Node(
            "skip_while", [self.node], self.schema, self.node.partition, fn=fn
        )
        return Query(self.ctx, node)

    def reverse(self) -> "Query":
        """Globally reverse row order (reference Reverse,
        ``DryadLinqQueryGen.cs:2731``)."""
        node = Node("reverse", [self.node], self.schema, PartitionInfo())
        return Query(self.ctx, node)

    def default_if_empty(self, defaults: Optional[Dict[str, Any]] = None) -> "Query":
        """If empty, a single default row (reference DefaultIfEmpty).

        ``defaults``: logical column -> value; unlisted columns default
        to zero / empty string."""
        # The default row materializes on partition 0, which breaks any
        # inherited hash/range placement — downstream shuffles must not
        # be elided.
        node = Node(
            "default_if_empty", [self.node], self.schema, PartitionInfo(),
            defaults=self._physical_row(defaults or {}),
        )
        return Query(self.ctx, node)

    def of_type(self, tag_col: str, value: Any) -> "Query":
        """Keep rows whose type-tag column equals ``value`` (reference
        OfType; a columnar engine models subtype unions as a tag
        column, so OfType is tag equality)."""
        self._require_cols([tag_col], "in of_type")
        f = self.schema.field(tag_col)
        if f.ctype.is_split:
            phys = self._physical_row({tag_col: value})
            h0 = phys[f"{tag_col}#h0"]
            h1 = phys[f"{tag_col}#h1"]

            def fn(cols):
                return (cols[f"{tag_col}#h0"] == h0) & (
                    cols[f"{tag_col}#h1"] == h1
                )
        else:
            def fn(cols):
                return cols[tag_col] == value
        return self.where(fn)

    # -- element access (eager, reference First/Last/Single/ElementAt) ------
    def _one_row(self, q: "Query") -> Optional[Dict[str, Any]]:
        table = q.collect()
        n = len(next(iter(table.values()), []))
        if n == 0:
            return None
        return {k: v[0] if np.asarray(v).ndim else v for k, v in table.items()}

    def first(self) -> Dict[str, Any]:
        row = self._one_row(self.take(1))
        if row is None:
            raise ValueError("first() on an empty sequence")
        return row

    def first_or_default(self) -> Optional[Dict[str, Any]]:
        return self._one_row(self.take(1))

    def last(self) -> Dict[str, Any]:
        row = self._one_row(self.tail(1))
        if row is None:
            raise ValueError("last() on an empty sequence")
        return row

    def last_or_default(self) -> Optional[Dict[str, Any]]:
        return self._one_row(self.tail(1))

    def single(self) -> Dict[str, Any]:
        table = self.take(2).collect()
        n = len(next(iter(table.values()), []))
        if n == 0:
            raise ValueError("single() on an empty sequence")
        if n > 1:
            raise ValueError("single() on a sequence with more than one row")
        return {k: v[0] for k, v in table.items()}

    def single_or_default(self) -> Optional[Dict[str, Any]]:
        table = self.take(2).collect()
        n = len(next(iter(table.values()), []))
        if n > 1:
            raise ValueError("single_or_default() on a sequence with more than one row")
        return {k: v[0] for k, v in table.items()} if n else None

    def element_at(self, n: int) -> Dict[str, Any]:
        if n < 0:
            raise IndexError(f"element_at({n}) out of range")
        row = self._one_row(self.skip(n).take(1))
        if row is None:
            raise IndexError(f"element_at({n}) out of range")
        return row

    def element_at_or_default(self, n: int) -> Optional[Dict[str, Any]]:
        if n < 0:
            return None
        return self._one_row(self.skip(n).take(1))

    def contains(self, row: Dict[str, Any]) -> bool:
        """Whole-row membership (reference Contains)."""
        if set(row) != set(self.schema.names):
            raise ValueError(
                f"contains() row must bind every column {self.schema.names}"
            )
        arrays = {k: np.asarray([v]) for k, v in row.items()}
        one = self.ctx.from_arrays(arrays, schema=self.schema)
        # One-row probe: broadcast it instead of shuffling the table.
        return (
            self.semi_join(one, self.schema.names, strategy="broadcast").count()
            > 0
        )

    def sequence_equal(self, other: "Query") -> bool:
        """Element-wise equality of two sequences in global engine order
        (reference SequenceEqual)."""
        if [
            (f.name, f.ctype) for f in self.schema.fields
        ] != [(f.name, f.ctype) for f in other.schema.fields]:
            return False
        n1, n2 = self.count(), other.count()
        if n1 != n2:
            return False
        if n1 == 0:
            return True
        from dryad_tpu.ops.join import _suffixed
        from dryad_tpu.plan import keys as K

        suffix = "__sq"
        z = self.zip_(other, suffix=suffix)
        lcols = K.equality_cols(self.schema, self.schema.names)
        rcols = [_suffixed(c, suffix) for c in lcols]

        def fn(cols):
            m = None
            for l, r in zip(lcols, rcols):
                e = cols[l] == cols[r]
                m = e if m is None else (m & e)
            return {"eq": m}

        eq = z.select(fn, schema=Schema([("eq", ColumnType.BOOL)]))
        return bool(eq.all_("eq"))

    # -- outer joins / group-join --------------------------------------------
    def left_join(
        self,
        other: "Query",
        left_keys: KeyArg,
        right_keys: Optional[KeyArg] = None,
        right_defaults: Optional[Dict[str, Any]] = None,
        expansion: float = 4.0,
        suffix: str = "_r",
        strategy: str = "auto",
    ) -> "Query":
        """Left-outer equi-join: unmatched left rows survive with
        default-valued right columns (the GroupJoin + DefaultIfEmpty
        left-outer idiom of the reference)."""
        _check_strategy(strategy)
        lk = _keys(left_keys)
        rk = _keys(right_keys) if right_keys is not None else lk
        self._require_cols(lk, "in join left keys")
        other._require_cols(rk, "in join right keys")
        fields = [(f.name, f.ctype) for f in self.schema.fields]
        lnames = {f.name for f in self.schema.fields}
        for f in other.schema.fields:
            if f.name in rk:
                continue
            name = f.name if f.name not in lnames else f"{f.name}{suffix}"
            fields.append((name, f.ctype))
        phys_defaults = other._physical_row(right_defaults or {})
        node = Node(
            "join", [self.node, other.node], Schema(fields),
            self._join_partition_info(lk, strategy),
            left_keys=lk, right_keys=rk, join_kind="left",
            expansion=expansion, suffix=suffix,
            right_defaults=phys_defaults, strategy=strategy,
        )
        return Query(self.ctx, node)

    def group_join(
        self,
        other: "Query",
        left_keys: KeyArg,
        right_keys: Optional[KeyArg] = None,
        aggs: Optional[Dict[str, Tuple[str, Optional[str]]]] = None,
        defaults: Optional[Dict[str, Any]] = None,
        expansion: float = 4.0,
        strategy: str = "auto",
        selector: Optional[Callable[["Query"], "Query"]] = None,
        order: Optional[Sequence[OrderArg]] = None,
        rank_limit: Optional[int] = None,
        lid_col: str = "gj_lid",
        rank_col: str = "gj_rank",
        suffix: str = "_r",
    ) -> "Query":
        """GroupJoin (reference ``DryadLinqQueryable.cs`` GroupJoin
        overloads; dispatch ``DryadLinqQueryGen.cs:3439ff``): per left
        row, the group of exactly-matching right rows.  Three shapes:

        - neither ``aggs`` nor ``selector``: match count per left row
          (``group_join_count``).
        - ``aggs``: aggregates over the matched group via right-side
          pre-aggregation; unmatched lefts survive with ``defaults``
          (count-like aggregates default to 0).
        - ``selector``: the FULL result-selector form.  ``selector``
          receives the expanded (left x matching-right) pairs as a
          Query carrying every left column, the right non-key columns
          (clashes suffixed), plus ``lid_col`` (INT32 global left-row
          id) and ``rank_col`` (INT32 group-local position of the
          match).  It returns a Query that keeps ``lid_col``,
          typically one row per group — e.g.
          ``lambda p: p.where(lambda c: c["gj_rank"] < 3)
          .group_by("gj_lid", {"top3_sum": ("sum", "v")})`` for
          top-k-per-key, or rank-pivot selects for concat-style
          results.  The selector output is left-outer-joined back onto
          the left rows, so unmatched lefts survive with ``defaults``
          (the GroupJoin + DefaultIfEmpty composition); selector
          columns clashing with left names get ``"_s"``.

          With ``order`` (an ``order_by``-style key list over RIGHT
          columns), ranks follow that value order within each group —
          deterministic under any partitioning.  Without it they
          follow the right side's engine order.

          ``rank_limit=k`` bounds each group to its first k matches
          BEFORE pair expansion, so hot keys stop multiplying pair
          counts quadratically: top-k-per-key runs at ~k x left-rows
          memory regardless of skew (a selector filtering
          ``gj_rank < k`` sees identical pairs either way; matches
          past rank k-1 are simply absent).  Without it, a key with m
          left x m right occurrences expands m^2 pairs and a skewed
          input can exceed every capacity boost.
        """
        lk = _keys(left_keys)
        rk = _keys(right_keys) if right_keys is not None else lk
        if rank_limit is not None and selector is None:
            raise ValueError(
                "group_join: rank_limit only applies to the selector form"
            )
        if selector is not None:
            if aggs:
                raise ValueError("group_join: pass aggs OR selector, not both")
            for c in (lid_col, rank_col):
                # a right column with the helper name would be silently
                # clobbered by the rank output, so reject both sides
                if c in self.schema.names or c in other.schema.names:
                    raise ValueError(
                        f"group_join helper column {c!r} clashes with an "
                        "input column; rename via lid_col=/rank_col="
                    )
            left2 = self.with_rank(lid_col)
            pairs = left2._ranked_join(
                other, lk, rk, rank_out=rank_col, order=order,
                expansion=expansion, suffix=suffix, strategy=strategy,
                rank_limit=rank_limit,
            )
            sel = selector(pairs)
            if lid_col not in sel.schema.names:
                raise ValueError(
                    f"group_join selector result must keep the {lid_col!r} "
                    "column (one row per left-row group)"
                )
            out = left2.left_join(
                sel, [lid_col], right_defaults=defaults, expansion=2.0,
                suffix="_s", strategy=strategy,
            )
            keep = [
                c for c in out.schema.names if c not in (lid_col, rank_col)
            ]
            return out.project(keep)
        if not aggs:
            return self.group_join_count(
                other, lk, rk, expansion=expansion, strategy=strategy
            )
        right_agg = other.group_by(rk, aggs)
        dflt = dict(defaults or {})
        for out_name, (op, _col) in aggs.items():
            if op == "count" and out_name not in dflt:
                dflt[out_name] = 0
        return self.left_join(
            right_agg, lk, rk, right_defaults=dflt, expansion=expansion,
            strategy=strategy,
        )

    def _ranked_join(
        self,
        other: "Query",
        left_keys: List[str],
        right_keys: List[str],
        rank_out: str,
        order: Optional[Sequence[OrderArg]] = None,
        expansion: float = 4.0,
        suffix: str = "_r",
        strategy: str = "auto",
        rank_limit: Optional[int] = None,
    ) -> "Query":
        """Inner equi-join that also emits each pair's group-local match
        rank (full GroupJoin's enumerable group).  ``rank_limit=k``
        bounds each group to its first k matches before expansion —
        see :meth:`group_join`."""
        _check_strategy(strategy)
        if rank_limit is not None:
            try:  # accept any integral type (np.int32 etc.), reject bool
                if isinstance(rank_limit, (bool, np.bool_)):
                    raise TypeError
                rank_limit = operator.index(rank_limit)
            except TypeError:
                raise ValueError(
                    f"rank_limit must be a positive int, got {rank_limit!r}"
                ) from None
            if rank_limit < 1:
                raise ValueError(
                    f"rank_limit must be a positive int, got {rank_limit!r}"
                )
        self._require_cols(left_keys, "in group_join left keys")
        other._require_cols(right_keys, "in group_join right keys")
        ks = _order_keys(order) if order is not None else None
        if ks is not None:
            other._require_cols([n for n, _ in ks], "in group_join order")
        fields = [(f.name, f.ctype) for f in self.schema.fields]
        lnames = {f.name for f in self.schema.fields}
        for f in other.schema.fields:
            if f.name in right_keys:
                continue
            name = f.name if f.name not in lnames else f"{f.name}{suffix}"
            fields.append((name, f.ctype))
        fields.append((rank_out, ColumnType.INT32))
        node = Node(
            "join", [self.node, other.node], Schema(fields),
            self._join_partition_info(left_keys, strategy),
            left_keys=left_keys, right_keys=right_keys, join_kind="ranked",
            rank_out=rank_out, order=ks, expansion=expansion, suffix=suffix,
            strategy=strategy, rank_limit=rank_limit,
        )
        return Query(self.ctx, node)

    def _physical_row(self, values: Dict[str, Any]) -> Dict[str, Any]:
        """Encode one logical row (missing columns -> zero/empty) into
        physical column scalars, registering strings in the context
        dictionary."""
        from dryad_tpu.columnar.batch import ColumnBatch

        arrays = {}
        for f in self.schema.fields:
            v = values.get(f.name)
            if v is None:
                v = "" if f.ctype == ColumnType.STRING else 0
            arrays[f.name] = np.asarray([v])
        b = ColumnBatch.from_numpy(
            self.schema, arrays, capacity=1, dictionary=self.ctx.dictionary
        )
        return {k: np.asarray(v)[0] for k, v in b.data.items()}

    def aggregate_decomposable(self, dec: "Decomposable") -> Dict[str, Any]:
        """Whole-table custom aggregate (reference Aggregate with a
        decomposable combiner): one-group group_by, returns the single
        result row."""
        phys = self.schema.device_names()

        def add_key(cols):
            import jax.numpy as jnp

            out = {c: cols[c] for c in phys}
            out["__g"] = jnp.zeros_like(
                next(iter(cols.values())), dtype=jnp.int32
            )
            return out

        keyed = self.select(
            add_key, schema=self.schema.with_field("__g", ColumnType.INT32)
        )
        g = keyed.group_by("__g", decomposable=dec)
        out_names = [n for n, _ in dec.out_fields]
        table = g.project(out_names).collect()
        return {k: (v[0] if len(v) else None) for k, v in table.items()}

    def group_join_count(
        self,
        other: "Query",
        left_keys: KeyArg,
        right_keys: Optional[KeyArg] = None,
        out: str = "match_count",
        expansion: float = 4.0,
        strategy: str = "auto",
    ) -> "Query":
        """GroupJoin's aggregate shape (reference GroupJoin): per left
        row, the count of matching right rows as a new INT32 column.
        Richer group aggregations compose via join + group_by."""
        _check_strategy(strategy)
        lk = _keys(left_keys)
        rk = _keys(right_keys) if right_keys is not None else lk
        self._require_cols(lk, "in group_join left keys")
        other._require_cols(rk, "in group_join right keys")
        fields = [(f.name, f.ctype) for f in self.schema.fields]
        fields.append((out, ColumnType.INT32))
        node = Node(
            "join", [self.node, other.node], Schema(fields),
            self._join_partition_info(lk, strategy),
            left_keys=lk, right_keys=rk, join_kind="count",
            expansion=expansion, out=out, strategy=strategy,
        )
        return Query(self.ctx, node)

    def zip_(self, other: "Query", suffix: str = "_r") -> "Query":
        """Pair rows by global position (reference Zip,
        ``DryadLinqQueryGen.cs`` Zip dispatch): result length is the
        shorter input's length (LINQ Zip semantics)."""
        fields = [(f.name, f.ctype) for f in self.schema.fields]
        lnames = {f.name for f in self.schema.fields}
        for f in other.schema.fields:
            name = f.name if f.name not in lnames else f"{f.name}{suffix}"
            fields.append((name, f.ctype))
        node = Node(
            "zip", [self.node, other.node], Schema(fields), PartitionInfo(),
            suffix=suffix,
        )
        return Query(self.ctx, node)

    def sliding_window(self, size: int, cols: Optional[KeyArg] = None) -> "Query":
        """Sliding windows over the global row sequence (reference
        SlidingWindow, ``DryadLinqQueryable.cs:1318``): for each window
        of ``size`` consecutive rows, emit columns ``{c}_w{j}`` (j-th
        row of the window).  Restricted to non-split (numeric/bool)
        columns; yields n-size+1 windows.
        """
        cols = _keys(cols) if cols is not None else self.schema.names
        self._require_cols(cols, "in sliding_window")
        fields: List[Tuple[str, ColumnType]] = []
        for c in cols:
            ct = self.schema.field(c).ctype
            if ct.is_split:
                raise ValueError(
                    f"sliding_window unsupported on {ct.value} column {c!r}"
                )
            for j in range(size):
                fields.append((f"{c}_w{j}", ct))
        node = Node(
            "sliding_window", [self.node], Schema(fields), PartitionInfo(),
            size=int(size), cols=cols,
        )
        return Query(self.ctx, node)

    # -- escape hatches ------------------------------------------------------
    def apply(
        self,
        fn: Callable,
        schema: Optional[Schema] = None,
        cap_factor: float = 1.0,
        with_index: bool = False,
    ) -> "Query":
        """Per-partition user function over a ColumnBatch (reference
        Apply/ApplyPerPartition; with_index = ApplyWithPartitionIndex)."""
        node = Node(
            "apply", [self.node], schema or self.schema, PartitionInfo(),
            fn=fn, cap_factor=cap_factor, with_index=with_index,
        )
        return Query(self.ctx, node)

    def apply_host(
        self,
        fn: Callable,
        schema: Optional[Schema] = None,
    ) -> "Query":
        """Per-partition HOST callback: fn(cols: dict[str, np.ndarray],
        partition_index) -> dict of equal-length arrays — the arbitrary
        user-code escape hatch (reference Apply runs arbitrary .NET
        lambdas; jittable fns should use ``apply``).  Each job costs a
        device->host->device round-trip: the documented perf cliff
        (SURVEY 7.3).

        The fn sees *physical* columns: STRING columns arrive as their
        encoded hash/prefix word columns (``s#h0``..``s#r1``), and a
        STRING output column must be produced the same way.  Output is
        validated against ``schema`` (names + dtypes) and cast."""
        node = Node(
            "apply_host", [self.node], schema or self.schema,
            PartitionInfo(), fn=fn,
        )
        return Query(self.ctx, node)

    def fork(self, fn: Callable, out_schemas: Sequence[Schema]) -> Tuple["Query", ...]:
        """Multi-output per-partition function (reference Fork,
        ``DryadLinqQueryable.cs:3717``): fn(batch) -> tuple of batches."""
        fork_node = Node(
            "fork", [self.node], self.schema, PartitionInfo(),
            fn=fn, out_schemas=list(out_schemas),
        )
        outs = []
        for i, s in enumerate(out_schemas):
            branch = Node(
                "fork_branch", [fork_node], s, PartitionInfo(), index=i
            )
            outs.append(Query(self.ctx, branch))
        return tuple(outs)

    def do_while(
        self,
        body: Callable[["Query"], "Query"],
        cond: Callable[["Query"], "Query"],
        max_iter: int = 100,
        device: bool = False,
    ) -> "Query":
        """Iterate body until cond yields False (reference DoWhile,
        ``DryadLinqQueryable.cs:1281``). ``cond`` maps the current
        dataset to a 1-row bool query (e.g. via count_as_query + select).

        ``device=True`` compiles the WHOLE loop as one on-device
        ``lax.while_loop`` (no host round-trip per iteration) when body
        and cond each lower to a single fused stage and the body
        preserves batch structure; otherwise it falls back to the
        driver loop (a ``do_while_device_fallback`` event is logged)."""
        node = Node(
            "do_while", [self.node], self.schema, PartitionInfo(),
            body=body, cond=cond, max_iter=max_iter, device=device,
        )
        return Query(self.ctx, node)

    # -- scalar aggregates ---------------------------------------------------
    def _aggregate_node(self, aggs: List[Tuple[str, Optional[str], str]]) -> Node:
        fields = []
        for op, col, out in aggs:
            ct = self.schema.field(col).ctype if col else ColumnType.INT32
            fields.append((out, _AGG_TYPE_RULES[op](ct)))
        return Node(
            "aggregate", [self.node], Schema(fields), PartitionInfo(), aggs=aggs
        )

    def aggregate_as_query(self, aggs: Dict[str, Tuple[str, Optional[str]]]) -> "Query":
        lst = [(op, col, out) for out, (op, col) in aggs.items()]
        return Query(self.ctx, self._aggregate_node(lst))

    def count_as_query(self) -> "Query":
        return self.aggregate_as_query({"count": ("count", None)})

    def _scalar(self, op: str, col: Optional[str]):
        # min/max/mean/any/all on an empty table would otherwise surface
        # the reduction's dtype sentinel; count alongside guards it.
        q = self.aggregate_as_query({"v": (op, col), "n": ("count", None)})
        table = q.collect()
        if op not in ("count", "sum") and int(table["n"][0]) == 0:
            return None
        return table["v"][0].item()

    def count(self) -> int:
        return int(self._scalar("count", None))

    def sum_(self, col: str):
        return self._scalar("sum", col)

    def min_(self, col: str):
        return self._scalar("min", col)

    def max_(self, col: str):
        return self._scalar("max", col)

    def mean(self, col: str) -> float:
        return float(self._scalar("mean", col))

    def any_(self, col: str) -> bool:
        return bool(self._scalar("any", col))

    def all_(self, col: str) -> bool:
        return bool(self._scalar("all", col))

    # -- materialization -----------------------------------------------------
    def explain(self, analyze: bool = False) -> str:
        """Pretty-print the logical plan and fused stage graph
        (``DryadLinqQueryExplain.cs`` analog).  ``analyze=True``
        EXECUTES the query first and appends the runtime-diagnosis
        panel — phase attribution plus any pathologies the online
        engine (``obs.diagnose``) caught during the run."""
        from dryad_tpu.obs import critpath, tracectx
        from dryad_tpu.tools.explain import explain, explain_diagnoses

        text = explain(self)
        if analyze:
            # mint (or adopt) a trace context so the run's events are
            # qid-stamped, then fold them into the critical-path panel
            tctx = tracectx.current() or tracectx.mint()
            with tracectx.activate(tctx):
                self.collect()
            text += "\n\n" + explain_diagnoses(self.ctx)
            bd = critpath.fold_query(self.ctx.events.events(), tctx.qid)
            if bd is not None and bd.phases:
                text += "\n\n-- critical path --\n" + bd.format()
        return text

    def collect(self) -> Dict[str, np.ndarray]:
        """Execute and fetch host logical columns (reference
        Submit+enumerate path, ``DryadLinqQuery.cs:608``)."""
        return self.ctx.run_to_host(self)

    def collect_stream(self):
        """Execute an out-of-core (``from_stream``) plan and yield
        host tables one bounded piece at a time — the result-side
        counterpart of chunked ingest, for outputs larger than host
        memory (reference: enumerating a query streams the output
        table, ``DryadLinqQuery.cs:608-647``).  Plans without a stream
        input yield their whole result once."""
        from dryad_tpu.exec.outofcore import (
            StreamExecutor,
            has_stream_input,
        )

        if not has_stream_input(self.ctx, self.node):
            yield self.collect()
            return
        if self.ctx.local_debug:
            raise RuntimeError(
                "from_stream inputs are not supported in local_debug mode"
            )
        from dryad_tpu.obs import tracectx

        # one trace context covers the whole streamed run: the chunk
        # pipeline captures it at construction, so producer/consumer
        # spans across every yielded piece share one qid
        with tracectx.activate(self.ctx._trace_ctx()):
            _schema, tables = StreamExecutor(self.ctx).run_stream(self.node)
            yield from tables

    def __iter__(self):
        """Enumerating a query triggers execution and yields row dicts
        (reference TableEnumerator, ``DryadLinqQuery.cs:608-647``:
        foreach on a query submits the job and streams the output)."""
        table = self.collect()
        names = list(table.keys())
        n = len(table[names[0]]) if names else 0
        for i in range(n):
            yield {c: table[c][i] for c in names}

    def submit(self) -> "JobHandle":
        return self.ctx.submit(self)

    def to_store(self, path: str) -> "JobHandle":
        """Execute and persist as a partitioned store (reference ToStore,
        ``DryadLinqQueryable.cs:3909``)."""
        return self.ctx.to_store(self, path)

    def cache(self) -> "Query":
        """Execute now and return a query over the DEVICE-RESIDENT
        result: downstream queries branch from the materialized batch
        instead of recomputing this pipeline (the reference's temp-table
        materialization — ``ToStoreInternal`` isTemp,
        ``DryadLinqQueryable.cs:3948`` — kept in HBM instead of DFS).
        The cached table carries this query's partition claim, so a
        downstream consumer with matching keys elides its exchange.
        It does not survive ``rebuild_mesh`` (clear error on use);
        ``ctx.release(cached)`` drops the HBM pin explicitly."""
        if self.ctx.local_debug:
            out = self.ctx.run_to_host(self)
            q = self.ctx.from_arrays(out, schema=self.schema)
            # mark so release() honors the documented contract in the
            # debug interpreter too (there is no HBM pin to drop)
            q.node.params["cached"] = True
            return q
        batch = self.ctx._execute_device(self)
        return self.ctx._from_device_batch(
            batch, self.schema, partition=self.node.partition
        )


class JobHandle:
    """Completed-job handle (reference SubmitAndWait returns job info)."""

    def __init__(self, table: Dict[str, np.ndarray], path: Optional[str] = None):
        self.table = table
        self.path = path

    def result(self) -> Dict[str, np.ndarray]:
        return self.table
