"""User-defined decomposable aggregations.

The analog of the reference's ``IDecomposable`` contract
(``LinqToDryad/IDecomposable.cs:35-71``): an aggregation splits into
Seed (per-row initial accumulator), Accumulate/RecursiveAccumulate
(associative merge of accumulators — one fn here since accumulators are
columns), and FinalReduce (finalize).  The optimizer uses this to build
the partial-aggregation tree: local combine before the shuffle, final
combine after (``DryadLinqDecomposition.cs:34``;
``DrDynamicAggregateManager.h:117-168``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from dryad_tpu.columnar.schema import ColumnType


@dataclasses.dataclass
class Decomposable:
    """seed: cols -> state cols (vectorized over rows).
    merge: (state_a, state_b) -> state (associative, vectorized).
    finalize: cols -> cols (optional; runs after the final combine).
    state_cols: physical state column names produced by ``seed``.
    out_fields: logical (name, ColumnType) list for the final output
    columns (after ``finalize`` if present, else the state columns).
    state_fields: OPTIONAL logical (name, ColumnType) list typing the
    state columns themselves.  When given, a terminal
    ``group_by(decomposable=...)`` additionally qualifies for
    independent-vertex submission (``LocalJobSubmission
    .submit_partitioned``): each vertex reduces its partition to typed
    state rows, the driver merges the assembled partials with ``merge``
    and runs ``finalize`` once — the reference's machine-level partial
    aggregation applied to custom combiners
    (``DrDynamicAggregateManager``).  Without it, decomposable plans
    keep the gang path (state dtypes are unknown until trace).

    **Linearity** (coded stage redundancy, ``dryad_tpu.redundancy``):
    ``linear=True`` declares that ``merge`` is ELEMENTWISE ADDITION of
    the state columns and ``identity`` is their additive zero — the
    contract that lets the scheduler encode the k per-partition
    partials as n = k + r coded vertices and reconstruct the stage
    output from ANY k completions (finalize may still be arbitrary;
    only the state merge must be linear).  Declaring ``linear=True``
    REQUIRES registering the identity element — one zero per state
    column — enforced here and by the AST lint in
    ``tests/test_coded_lint.py``.
    """

    seed: Callable[[Dict], Dict]
    merge: Callable[[Dict, Dict], Dict]
    state_cols: Sequence[str]
    out_fields: Sequence[Tuple[str, ColumnType]]
    finalize: Optional[Callable[[Dict], Dict]] = None
    state_fields: Optional[Sequence[Tuple[str, ColumnType]]] = None
    linear: bool = False
    identity: Optional[Dict[str, Any]] = None

    def __post_init__(self) -> None:
        if not self.linear:
            return
        if self.identity is None:
            raise ValueError(
                "Decomposable(linear=True) requires a registered "
                "identity element: identity={state_col: 0, ...}"
            )
        if set(self.identity) != set(self.state_cols):
            raise ValueError(
                f"identity keys {sorted(self.identity)} must match "
                f"state_cols {sorted(self.state_cols)}"
            )
        bad = {k: v for k, v in self.identity.items() if v != 0}
        if bad:
            raise ValueError(
                "a linear Decomposable's merge is elementwise addition, "
                f"so its identity must be the additive zero; got {bad}"
            )


def delta_fold_reason(dec: "Decomposable") -> str:
    """Why a ``group_by(decomposable=...)`` plan cannot back an
    incremental materialized view (``dryad_tpu.views``) — the
    structured ``view_fallback`` reason.  A non-linear merge gives the
    delta fold no algebra at all; a linear one WOULD fold (state adds
    elementwise), but its seed/merge fns trace with jax.numpy and the
    view delta path folds on the HOST from client threads, so builtin
    aggregates remain the supported surface."""
    if not dec.linear:
        return "non-linear decomposable merge has no delta fold"
    return (
        "decomposable delta folds not supported (builtin aggregates "
        "only)"
    )


# Registry of known-linear Decomposables: the coded-redundancy property
# suite (tests/test_coded.py) sweeps every entry, asserting that any
# k-subset of n coded partials reconstructs the merged state exactly
# (ints) / within tolerance (floats).  Users may register their own.
LINEAR_DECOMPOSABLES: Dict[str, Decomposable] = {}


def register_linear(name: str, dec: Decomposable) -> Decomposable:
    """Register a linear Decomposable exemplar (validates the flag)."""
    if not dec.linear:
        raise ValueError(f"{name!r} is not declared linear=True")
    # graftlint: disable=kernel-determinism -- import-time registration API; the table is fixed before any vertex runs
    LINEAR_DECOMPOSABLES[name] = dec
    return dec


# -- builtin linear exemplars (sum / count / moment histograms) -------------

def _vecsum_seed(cols):
    return {"s": cols["v"]}


def _vecsum_merge(a, b):
    return {"s": a["s"] + b["s"]}


def _countsum_seed(cols):
    import jax.numpy as jnp

    return {"cnt": jnp.ones_like(cols["v"]), "s": cols["v"]}


def _countsum_merge(a, b):
    return {"cnt": a["cnt"] + b["cnt"], "s": a["s"] + b["s"]}


def _countsum_finalize(cols):
    import jax.numpy as jnp

    return {"mean": cols["s"] / jnp.maximum(cols["cnt"], 1)}


def _moments_seed(cols):
    import jax.numpy as jnp

    return {
        "cnt": jnp.ones_like(cols["v"]),
        "s1": cols["v"],
        "s2": cols["v"] * cols["v"],
    }


def _moments_merge(a, b):
    return {k: a[k] + b[k] for k in ("cnt", "s1", "s2")}


def _moments_finalize(cols):
    import jax.numpy as jnp

    c = jnp.maximum(cols["cnt"], 1)
    m = cols["s1"] / c
    return {"var": cols["s2"] / c - m * m}


def _intsum_seed(cols):
    return {"t": cols["v"]}


def _intsum_merge(a, b):
    return {"t": a["t"] + b["t"]}


register_linear("vecsum", Decomposable(
    seed=_vecsum_seed, merge=_vecsum_merge, state_cols=["s"],
    out_fields=[("s", ColumnType.FLOAT32)],
    state_fields=[("s", ColumnType.FLOAT32)],
    linear=True, identity={"s": 0},
))
register_linear("countsum", Decomposable(
    seed=_countsum_seed, merge=_countsum_merge, state_cols=["cnt", "s"],
    out_fields=[("mean", ColumnType.FLOAT32)],
    state_fields=[
        ("cnt", ColumnType.FLOAT32), ("s", ColumnType.FLOAT32),
    ],
    finalize=_countsum_finalize,
    linear=True, identity={"cnt": 0, "s": 0},
))
register_linear("moments", Decomposable(
    seed=_moments_seed, merge=_moments_merge,
    state_cols=["cnt", "s1", "s2"],
    out_fields=[("var", ColumnType.FLOAT32)],
    state_fields=[
        ("cnt", ColumnType.FLOAT32), ("s1", ColumnType.FLOAT32),
        ("s2", ColumnType.FLOAT32),
    ],
    finalize=_moments_finalize,
    linear=True, identity={"cnt": 0, "s1": 0, "s2": 0},
))
register_linear("intsum", Decomposable(
    seed=_intsum_seed, merge=_intsum_merge,
    state_cols=["t"],
    out_fields=[("t", ColumnType.INT32)],
    state_fields=[("t", ColumnType.INT32)],
    linear=True, identity={"t": 0},
))
