"""User-defined decomposable aggregations.

The analog of the reference's ``IDecomposable`` contract
(``LinqToDryad/IDecomposable.cs:35-71``): an aggregation splits into
Seed (per-row initial accumulator), Accumulate/RecursiveAccumulate
(associative merge of accumulators — one fn here since accumulators are
columns), and FinalReduce (finalize).  The optimizer uses this to build
the partial-aggregation tree: local combine before the shuffle, final
combine after (``DryadLinqDecomposition.cs:34``;
``DrDynamicAggregateManager.h:117-168``).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from dryad_tpu.columnar.schema import ColumnType


@dataclasses.dataclass
class Decomposable:
    """seed: cols -> state cols (vectorized over rows).
    merge: (state_a, state_b) -> state (associative, vectorized).
    finalize: cols -> cols (optional; runs after the final combine).
    state_cols: physical state column names produced by ``seed``.
    out_fields: logical (name, ColumnType) list for the final output
    columns (after ``finalize`` if present, else the state columns).
    state_fields: OPTIONAL logical (name, ColumnType) list typing the
    state columns themselves.  When given, a terminal
    ``group_by(decomposable=...)`` additionally qualifies for
    independent-vertex submission (``LocalJobSubmission
    .submit_partitioned``): each vertex reduces its partition to typed
    state rows, the driver merges the assembled partials with ``merge``
    and runs ``finalize`` once — the reference's machine-level partial
    aggregation applied to custom combiners
    (``DrDynamicAggregateManager``).  Without it, decomposable plans
    keep the gang path (state dtypes are unknown until trace).
    """

    seed: Callable[[Dict], Dict]
    merge: Callable[[Dict, Dict], Dict]
    state_cols: Sequence[str]
    out_fields: Sequence[Tuple[str, ColumnType]]
    finalize: Optional[Callable[[Dict], Dict]] = None
    state_fields: Optional[Sequence[Tuple[str, ColumnType]]] = None
