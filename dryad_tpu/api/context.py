"""DryadContext — the entry point and job driver.

The analog of ``DryadLinqContext`` (``LinqToDryad/DryadLinqContext.cs:566``):
owns platform selection (reference LOCAL/YARN_NATIVE/YARN_AZURE,
``DryadLinqContext.cs:55-71`` — here TPU mesh vs host-local CPU mesh),
per-context config, dataset ingestion (FromStore/FromEnumerable,
``:1176-1223``), the LocalDebug differential path
(``DryadLinqContext.cs:966-983`` — LINQ-to-Objects there, a NumPy
interpreter here), and job submission, which lowers the plan and runs
the GraphExecutor (replacing the GraphManager process tree).
"""

from __future__ import annotations

import enum
import itertools
import math
import os
import time
from collections import OrderedDict
from typing import Dict, Optional

import numpy as np

from dryad_tpu.api.query import JobHandle, Query
from dryad_tpu.columnar import io as CIO
from dryad_tpu.columnar.batch import ColumnBatch
from dryad_tpu.columnar.schema import ColumnType, Schema, StringDictionary
from dryad_tpu.exec.events import EventLog
from dryad_tpu.exec.executor import GraphExecutor
from dryad_tpu.obs import flightrec, tracectx
from dryad_tpu.obs.diagnose import DiagnosisEngine
from dryad_tpu.rewrite.controller import RewriteController
from dryad_tpu.parallel import distribute as D
from dryad_tpu.parallel.mesh import make_mesh, num_partitions
from dryad_tpu.plan.lower import lower
from dryad_tpu.plan.nodes import Node, PartitionInfo
from dryad_tpu.utils.config import DryadConfig
from dryad_tpu.utils.logging import get_logger

log = get_logger("dryad_tpu.api")


class PlatformKind(enum.Enum):
    """Reference ClusterType LOCAL/YARN_*; here the device platform."""

    AUTO = "auto"
    TPU = "tpu"
    CPU_LOCAL = "cpu_local"


_NP_TYPE_MAP = {
    np.dtype(np.int32): ColumnType.INT32,
    np.dtype(np.int64): ColumnType.INT64,
    np.dtype(np.float32): ColumnType.FLOAT32,
    # float64 is PRESERVED (order-preserving split-word storage,
    # columnar/schema.py): exact round-trip, ordering, min/max, joins;
    # device arithmetic (sum/mean) requires an explicit f32 cast.
    np.dtype(np.float64): ColumnType.FLOAT64,
    np.dtype(np.bool_): ColumnType.BOOL,
    np.dtype(np.uint32): ColumnType.UINT32,
}


def _word_vocab(h0: np.ndarray, h1: np.ndarray) -> np.ndarray:
    """Unique 64-bit word hashes from split (#h0, #h1) columns."""
    h = (h1.astype(np.uint64) << np.uint64(32)) | h0.astype(np.uint64)
    return np.unique(h)


def _infer_schema(arrays: Dict[str, np.ndarray]) -> Schema:
    fields = []
    for name, a in arrays.items():
        a = np.asarray(a)
        if a.dtype == object or a.dtype.kind in ("U", "S"):
            fields.append((name, ColumnType.STRING))
        elif a.dtype in _NP_TYPE_MAP:
            fields.append((name, _NP_TYPE_MAP[a.dtype]))
        else:
            raise TypeError(f"column {name!r}: unsupported dtype {a.dtype}")
    return Schema(fields)


def _fetch_with_miss(batch, deferred):
    """Fetch a result batch host-side with the job's deferred dict-miss
    counters riding the same ``device_get``, resolve the deferred tail
    (raises on a nonzero counter), and return ``(valid, host_cols)``."""
    miss = deferred.miss_arrays()
    try:
        valid, host_cols, miss_vals = batch.fetch_host(extra=miss)
    except Exception as e:  # tunnel/transfer failure: close out the job
        deferred.abort(f"output transfer failed: {e!r}")
        raise
    deferred.finish(miss_vals)
    return valid, host_cols


class DryadContext:
    def __init__(
        self,
        num_partitions_: Optional[int] = None,
        config: Optional[DryadConfig] = None,
        local_debug: bool = False,
        platform: PlatformKind = PlatformKind.AUTO,
        dcn_slices: Optional[int] = None,
        mesh=None,
    ):
        self.config = config or DryadConfig()
        self.config.validate()
        self.local_debug = local_debug
        self.platform = platform
        self.dictionary = StringDictionary()
        self._bindings: Dict[int, tuple] = {}
        # True once any from_stream binding exists: the fast gate for
        # the per-collect stream check (has_stream_input)
        self._any_stream = False
        # Column-name -> TypeCodec for custom user types (the
        # IDryadLinqSerializer hook, columnar/codecs.py).
        self._codecs: Dict[str, object] = {}
        self._binding_fp_cache: Dict[int, Optional[str]] = {}
        # Device-resident ingest cache: input node id -> (binding tuple
        # the batch was ingested from, sharded batch, bytes), LRU by
        # insertion order (see config.device_cache_bytes).  The stored
        # binding identity self-invalidates the entry when a binding is
        # rebound (worker _run_part rebinds per-part slices on a reused
        # context); in-place mutation of arrays passed to from_arrays is
        # NOT tracked — inputs snapshot at first execution.
        self._device_cache: "OrderedDict[int, tuple]" = OrderedDict()
        self.diagnosis: Optional[DiagnosisEngine] = None
        self.rewriter = None
        # Continuous telemetry plane (obs.telemetry): the tap-paced
        # resource sampler and its measured HeadroomProvider, consumed
        # by the adaptive exchange-window and dispatch-depth policies.
        # None (local_debug / obs_telemetry=False) = budget fallbacks.
        self.telemetry = None
        self.headroom = None
        if local_debug:
            self.mesh = None
            self.executor = None
            self.events = EventLog(None)
        else:
            if mesh is not None:
                self.mesh = mesh
            elif dcn_slices is not None:
                # Hybrid multi-slice mesh: inner axis over ICI, outer
                # over DCN (reference machine→pod hierarchy).
                from dryad_tpu.parallel.mesh import make_hybrid_mesh

                if (
                    num_partitions_ is not None
                    and num_partitions_ % dcn_slices != 0
                ):
                    raise ValueError(
                        f"num_partitions_ {num_partitions_} not divisible "
                        f"by dcn_slices {dcn_slices}"
                    )
                ici = (
                    num_partitions_ // dcn_slices
                    if num_partitions_ is not None
                    else None
                )
                self.mesh = make_hybrid_mesh(dcn_slices, ici)
            else:
                self.mesh = make_mesh(num_partitions_)
            path = None
            if self.config.event_log_dir:
                path = os.path.join(
                    self.config.event_log_dir, f"job-{int(time.time()*1000)}.jsonl"
                )
            self.events = EventLog(
                path, mem_cap=self.config.obs_events_mem_cap
            )
            # Flight recorder: always-on crash-forensics ring tapped
            # into this context's stream, dumped on JobFailedError /
            # unhandled exceptions (obs.flightrec).  The driver does
            # NOT dump on clean exit.  The process recorder may already
            # be owned by someone with a better dump location — the
            # worker harness (role "worker-<i>") or a LocalJobSubmission
            # driver, both dumping into the shared job root.  In that
            # case tap this context's stream into the existing ring
            # instead of displacing it.
            if self.config.obs_flight_recorder:
                rec = flightrec.get_recorder()
                if rec is not None:
                    self.events.add_tap(rec.record)
                else:
                    flightrec.install_recorder(
                        capacity=self.config.flightrec_events,
                        snapshot_s=self.config.flightrec_snapshot_s,
                        dump_dir=(
                            self.config.flightrec_dir
                            or self.config.event_log_dir
                            or "."
                        ),
                        role="driver",
                        events=self.events,
                    )
            # Online diagnosis engine: live pathology folds over the
            # same stream (obs.diagnose); diagnoses are emitted back
            # into it and retained for explain(analyze=True)/jobview.
            if self.config.obs_diagnosis:
                self.diagnosis = DiagnosisEngine(
                    config=self.config, events=self.events
                )
                self.events.add_tap(self.diagnosis.observe)
            # Runtime plan rewriter: folds the diagnoses above into
            # pending rewrite actions the execution drivers poll at
            # safe boundaries (rewrite.controller).  Rides the same
            # tap mechanism; needs the diagnosis engine upstream.
            if self.config.obs_diagnosis and self.config.plan_rewrite:
                self.rewriter = RewriteController(
                    config=self.config, events=self.events
                )
                self.events.add_tap(self.rewriter.observe)
            # Resource sampler: opportunistic (event-tap-paced, the
            # flightrec discipline — no thread here; resident
            # processes call ctx.telemetry.start()).  Its samples feed
            # the hbm_pressure diagnosis upstream and the measured
            # HeadroomProvider the executor consults below.
            if getattr(self.config, "obs_telemetry", True):
                from dryad_tpu.obs.telemetry import ResourceMonitor

                self.telemetry = ResourceMonitor(
                    interval_s=self.config.telemetry_sample_s,
                    events=self.events,
                )
                self.headroom = self.telemetry.headroom
                self.events.add_tap(self.telemetry.observe)
            self.executor = GraphExecutor(
                self.mesh, self.config, self.events,
                subquery_runner=self._run_subquery,
                loop_lowerer=self._lower_loop_stage,
            )
            self.executor.rewriter = self.rewriter
            self.executor.headroom = self.headroom

    def rebuild_mesh(self, exclude_device_ids) -> None:
        """Elastic recovery: shrink the mesh past failed devices and
        rebuild the executor (reference: dynamic computer set +
        requeue-with-exclusion, ``Interfaces.cs:336-343``).  Device-
        resident bindings are dropped — re-ingest or resume stages from
        the checkpoint store; host/store bindings survive."""
        from dryad_tpu.parallel.mesh import exclude_devices

        self.mesh = exclude_devices(self.mesh, exclude_device_ids)
        self._bindings = {
            nid: b for nid, b in self._bindings.items() if b[0] != "device"
        }
        # Cached ingests are sharded over the OLD mesh — drop them.
        self._device_cache.clear()
        self.executor = GraphExecutor(
            self.mesh, self.config, self.events,
            subquery_runner=self._run_subquery,
            loop_lowerer=self._lower_loop_stage,
        )
        self.executor.rewriter = self.rewriter
        self.executor.headroom = self.headroom

    # -- ingestion ----------------------------------------------------------
    def from_arrays(
        self,
        arrays: Dict[str, np.ndarray],
        schema: Optional[Schema] = None,
        partition_capacity: Optional[int] = None,
        codecs: Optional[Dict[str, object]] = None,
    ) -> Query:
        """Create a table from host arrays (reference FromEnumerable).

        ``codecs``: column name -> ``columnar.codecs.TypeCodec`` for
        custom user types; each coded column expands into typed device
        columns at ingest and folds back at egress."""
        if codecs:
            from dryad_tpu.columnar.codecs import expand_arrays

            arrays = expand_arrays(arrays, codecs)
            self._codecs.update(codecs)
        schema = schema or _infer_schema(arrays)
        # Register string values at DEFINITION time (unique-first, so
        # the pass is vocabulary-sized): the auto-dense STRING group_by
        # codes against the dictionary at lowering, which runs before
        # ingest would otherwise populate it.  Skipped when the feature
        # is off — ingest registers the same strings at bind time.
        str_vocab = {}
        if getattr(self.config, "auto_dense_strings", True):
            for name in schema.names:
                if (
                    schema.field(name).ctype is ColumnType.STRING
                    and name in arrays
                ):
                    # Unique the object array directly: .astype(str)
                    # would materialize a fixed-width unicode copy of
                    # the whole column (width = longest string) just to
                    # throw it away.  The per-COLUMN hash set feeds the
                    # per-ingest auto-dense gate: one big-vocabulary
                    # ingest elsewhere must not disable the fast path
                    # for every later query (round-3 weak item 7).
                    hs = [
                        self.dictionary.add(str(s))
                        for s in np.unique(np.asarray(arrays[name], object))
                    ]
                    str_vocab[name] = np.sort(
                        np.asarray(hs, dtype=np.uint64)
                    )
        # Ingest column statistics: INT32 ranges feed the int auto-dense
        # group_by rewrite (the observed-data-size adaptation of
        # DrDynamicRangeDistributor.cpp:54-110 applied to key domains).
        # Skipped when the sole consumer is off.
        col_stats = {}
        if getattr(self.config, "auto_dense_ints", True):
            for name in schema.names:
                if (
                    schema.field(name).ctype is ColumnType.INT32
                    and name in arrays
                ):
                    a = np.asarray(arrays[name])
                    if a.size:
                        col_stats[name] = (int(a.min()), int(a.max()))
        node = Node(
            "input", [], schema, PartitionInfo.roundrobin(),
            source="host", col_stats=col_stats, str_vocab=str_vocab,
        )
        self._bindings[node.id] = ("host", arrays, partition_capacity)
        return Query(self, node)

    def append_arrays(
        self, query: Query, arrays: Dict[str, np.ndarray]
    ) -> Optional[str]:
        """Append host rows to an existing ``from_arrays`` table IN
        PLACE — the continuous-ingest write path.  The node keeps its
        identity (registered views and prepared queries keep pointing
        at it); the binding is REBOUND to the concatenated columns, so
        the device-ingest cache and the binding fingerprint both
        self-invalidate.  Auto-dense metadata (string vocab, int key
        ranges) WIDENS so lowering decisions stay sound for the grown
        domain.  Returns the binding fingerprint the table had BEFORE
        the append (None when unfingerprintable) — the invalidation
        key for any result cached against the old contents."""
        node = query.node
        binding = self._bindings.get(node.id)
        if node.kind != "input" or binding is None or binding[0] != "host":
            raise ValueError(
                "append_arrays() takes a from_arrays table; got a "
                f"{node.kind!r} node bound as "
                f"{binding[0] if binding else None!r}"
            )
        if self._codecs and any(c in self._codecs for c in arrays):
            from dryad_tpu.columnar.codecs import expand_arrays

            arrays = expand_arrays(
                arrays, {c: self._codecs[c] for c in arrays
                         if c in self._codecs}
            )
        _, old_arrays, cap = binding
        if set(arrays) != set(old_arrays):
            raise ValueError(
                f"append columns {sorted(arrays)} != table columns "
                f"{sorted(old_arrays)}"
            )
        old_fp = self._binding_fp(node)
        merged = {}
        for name, old in old_arrays.items():
            old = np.asarray(old)
            new = np.asarray(arrays[name])
            if old.dtype == object or old.dtype.kind in ("U", "S"):
                new = np.asarray(new, object)
            elif new.dtype != old.dtype:
                raise TypeError(
                    f"column {name!r}: append dtype {new.dtype} != "
                    f"table dtype {old.dtype}"
                )
            merged[name] = np.concatenate(
                [np.asarray(old, object) if old.dtype == object else old,
                 new]
            )
        # Widen the auto-dense gates for the new rows (same policy as
        # from_arrays; a widened vocab/range only loosens the gate).
        if getattr(self.config, "auto_dense_strings", True):
            vocab = node.params.get("str_vocab") or {}
            for name in vocab:
                if name in arrays:
                    hs = [
                        self.dictionary.add(str(s))
                        for s in np.unique(np.asarray(arrays[name], object))
                    ]
                    vocab[name] = np.unique(np.concatenate([
                        vocab[name], np.asarray(hs, dtype=np.uint64)
                    ]))
            node.params["str_vocab"] = vocab
        if getattr(self.config, "auto_dense_ints", True):
            stats = node.params.get("col_stats") or {}
            for name, (lo, hi) in list(stats.items()):
                a = np.asarray(arrays.get(name, ()))
                if a.size:
                    stats[name] = (
                        min(lo, int(a.min())), max(hi, int(a.max()))
                    )
            node.params["col_stats"] = stats
        self._bindings[node.id] = ("host", merged, cap)
        self._binding_fp_cache.pop(node.id, None)
        self._device_cache.pop(node.id, None)
        return old_fp

    def _tokenize_buf(self, buf: bytes):
        """Tokenize one byte buffer, registering tokens in the context
        dictionary; returns the (h0, h1, r0, r1) physical columns."""
        from dryad_tpu.runtime import bindings as RB

        h0, h1, r0, r1, starts, lens = RB.tokenize(buf)
        hashes = (h1.astype(np.uint64) << np.uint64(32)) | h0.astype(np.uint64)
        uniq, first_idx = np.unique(hashes, return_index=True)
        for h, i in zip(uniq, first_idx):
            s = int(starts[i])
            tok = buf[s : s + int(lens[i])].decode("utf-8", "replace")
            existing = self.dictionary._map.get(int(h))
            if existing is not None and existing != tok:
                raise ValueError(f"hash64 collision: {existing!r} vs {tok!r}")
            self.dictionary._map[int(h)] = tok
        return h0, h1, r0, r1

    def from_text(self, data, column: str = "word") -> Query:
        """Tokenize raw text into a one-STRING-column table using the
        native tokenizer (reference WordCount ingest; tokenization
        happens in generated vertex code there, at the ingest edge
        here).  ``data`` is a filesystem path, a list of paths (read
        with background prefetch, the async channel-reader path), a
        str, or bytes."""
        from dryad_tpu.runtime import bindings as RB

        if isinstance(data, (list, tuple)):
            # Multi-file ingest: the native prefetch channel reads file
            # i+1 while file i tokenizes (reference async channel
            # buffer readers, channelbuffernativereader.cpp).
            parts = []
            with RB.PrefetchChannel(list(data), depth=4, threads=2) as ch:
                for fbuf in ch:
                    parts.append(self._tokenize_buf(fbuf))
            if not parts:
                cols = [np.zeros(0, np.uint32)] * 4
            else:
                cols = [
                    np.concatenate([p[i] for p in parts]) for i in range(4)
                ]
            h0, h1, r0, r1 = cols
            schema = Schema([(column, ColumnType.STRING)])
            node = Node(
                "input", [], schema, PartitionInfo.roundrobin(),
                source="host_physical",
                str_vocab={column: _word_vocab(h0, h1)},
            )
            self._bindings[node.id] = (
                "host_physical",
                {f"{column}#h0": h0, f"{column}#h1": h1,
                 f"{column}#r0": r0, f"{column}#r1": r1},
            )
            return Query(self, node)

        if isinstance(data, str) and os.path.exists(data):
            with open(data, "rb") as fh:
                buf = fh.read()
        elif isinstance(data, str):
            buf = data.encode("utf-8")
        else:
            buf = bytes(data)
        h0, h1, r0, r1 = self._tokenize_buf(buf)
        schema = Schema([(column, ColumnType.STRING)])
        node = Node(
            "input", [], schema, PartitionInfo.roundrobin(),
            source="host_physical",
            str_vocab={column: _word_vocab(h0, h1)},
        )
        self._bindings[node.id] = (
            "host_physical",
            {f"{column}#h0": h0, f"{column}#h1": h1,
             f"{column}#r0": r0, f"{column}#r1": r1},
        )
        return Query(self, node)

    def from_stream(self, chunks, schema: Optional[Schema] = None) -> Query:
        """Out-of-core ingest: an iterable of host tables processed as
        bounded chunks by the streaming executor (``exec.outofcore``).

        The reference streams unbounded channel data through fixed
        buffers (``channelinterface.h:212`` RChannelReader) so a vertex
        handles data far larger than memory; here the morsel unit is a
        host table chunk and every device job stays within the
        ``(P x cap)`` layout.  Queries over a stream input support the
        row-local operators per chunk plus group_by/aggregate/distinct
        (partial combine), order_by (external distribution sort),
        join (Grace bucketing), take and concat."""
        from dryad_tpu.exec.outofcore import ChunkSource

        it = iter(chunks)
        if schema is None:
            first = next(it, None)
            if first is None:
                raise ValueError("an empty stream needs an explicit schema")
            first = {k: np.asarray(v) for k, v in first.items()}
            schema = _infer_schema(first)
            it = itertools.chain([first], it)
        node = Node(
            "input", [], schema, PartitionInfo.roundrobin(), source="stream"
        )
        self._bindings[node.id] = ("stream", ChunkSource(it, schema))
        self._any_stream = True
        return Query(self, node)

    def text_stream(
        self, paths, chunk_bytes: int = 1 << 25, column: str = "word"
    ) -> Query:
        """Chunked tokenizing text ingest for corpora larger than
        memory (streaming ``from_text``; reference HDFS block readers,
        ``channelbufferhdfs.cpp``).  Chunks split at whitespace
        boundaries so no token straddles two chunks.  Chunks are
        emitted as PHYSICAL token columns straight off the native
        tokenizer (hash + prefix-rank words), so the streaming hot
        path never materializes per-token Python strings."""
        if isinstance(paths, str):
            paths = [paths]
        schema = Schema([(column, ColumnType.STRING)])

        def phys(buf):
            h0, h1, r0, r1 = self._tokenize_buf(buf)
            return {
                f"{column}#h0": h0, f"{column}#h1": h1,
                f"{column}#r0": r0, f"{column}#r1": r1,
                "#vocab": {column: _word_vocab(h0, h1)},
            }

        def gen():
            for p in paths:
                with open(p, "rb") as fh:
                    carry = b""
                    while True:
                        buf = fh.read(chunk_bytes)
                        if not buf:
                            if carry.strip():
                                yield phys(carry)
                            break
                        buf = carry + buf
                        # cut at the last whitespace so tokens stay whole
                        cut = max(buf.rfind(b" "), buf.rfind(b"\n"),
                                  buf.rfind(b"\t"), buf.rfind(b"\r"))
                        if cut <= 0:
                            carry = buf
                            continue
                        chunk, carry = buf[:cut], buf[cut:]
                        if chunk.strip():
                            yield phys(chunk)

        return self.from_stream(gen(), schema)

    def store_stream(self, path: str, parts_per_chunk: int = 1) -> Query:
        """Open a store as a chunk stream, one (or N) partition files
        per chunk — the out-of-core counterpart of ``from_store``."""
        from dryad_tpu.columnar.batch import decode_physical_table
        from dryad_tpu.columnar.io import (
            _part_name,
            load_store_meta,
            read_partition_file,
        )

        manifest, schema, dict_map = load_store_meta(path)
        self.dictionary._map.update(dict_map)

        def flush(batch):
            if len(batch) == 1:
                return batch[0]
            return {
                c: np.concatenate([b[c] for b in batch])
                for c in batch[0]
            }

        def gen():
            batch: list = []
            for i in range(manifest["partitions"]):
                phys = read_partition_file(
                    os.path.join(path, _part_name(i))
                )
                batch.append(
                    decode_physical_table(
                        schema, slice(None), phys, self.dictionary
                    )
                )
                if len(batch) >= parts_per_chunk:
                    yield flush(batch)
                    batch = []
            if batch:
                yield flush(batch)

        return self.from_stream(gen(), schema)

    def from_store(self, path: str) -> Query:
        """Open a store by path or URI (reference FromStore/GetTable;
        scheme registry ``columnar/uri.py`` — partfile://, file://,
        mem://, http://)."""
        from dryad_tpu.columnar.uri import read_store_uri

        schema, parts, dictionary = read_store_uri(path)
        self.dictionary = self.dictionary.merge(dictionary)
        # the store dictionary bounds every STRING column's vocabulary
        # (a superset per column, still a sound auto-dense gate)
        store_hashes = np.sort(
            np.fromiter(dictionary._map.keys(), dtype=np.uint64)
        )
        node = Node(
            "input", [], schema, PartitionInfo.roundrobin(), source="store",
            str_vocab={
                f.name: store_hashes
                for f in schema.fields if f.ctype is ColumnType.STRING
            },
        )
        self._bindings[node.id] = ("store", parts, schema)
        return Query(self, node)

    def _from_device_batch(
        self, batch: ColumnBatch, schema: Schema, partition=None
    ) -> Query:
        """``partition``: the producing node's PartitionInfo — the batch
        physically has that layout, so propagating it lets downstream
        consumers elide exchanges the producer already paid for."""
        node = Node(
            "input", [], schema, partition or PartitionInfo(),
            source="device",
        )
        self._bindings[node.id] = ("device", batch)
        return Query(self, node)

    def release(self, query: Query) -> None:
        """Drop a cached device-resident table (the pin created by
        ``Query.cache()``); later use of the query raises the
        stale-binding error rather than recomputing silently.  Only
        device-bound input queries qualify — releasing a source table
        or a derived query is a caller bug, surfaced loudly."""
        binding = self._bindings.get(query.node.id)
        cached_marker = query.node.params.get("cached")  # local_debug pin
        if (
            query.node.kind != "input"
            or binding is None
            or (binding[0] != "device" and not cached_marker)
        ):
            raise ValueError(
                "release() takes the query returned by cache(); got a "
                f"{query.node.kind!r} node bound as "
                f"{binding[0] if binding else None!r}"
            )
        del self._bindings[query.node.id]
        self._device_cache.pop(query.node.id, None)

    # -- execution ----------------------------------------------------------
    def _bind_device(self, node: Node) -> ColumnBatch:
        if node.id not in self._bindings:
            raise RuntimeError(
                f"input node {node.id} has no binding: its device-"
                "resident table was dropped (rebuild_mesh clears cached "
                "tables; release() drops them explicitly) — re-run "
                ".cache() or re-ingest"
            )
        kind, *rest = self._bindings[node.id]
        if kind == "device":
            return rest[0]
        binding = self._bindings[node.id]
        budget = self.config.device_cache_bytes
        if budget and node.id in self._device_cache:
            src, batch, _ = self._device_cache[node.id]
            if src is binding:  # rebound nodes miss (stale entry)
                self._device_cache.move_to_end(node.id)
                return batch
            del self._device_cache[node.id]
        batch = self._ingest_binding(kind, rest, node)
        if budget:
            nbytes = sum(
                a.size * a.dtype.itemsize for a in batch.data.values()
            ) + batch.valid.size
            self._device_cache[node.id] = (binding, batch, nbytes)
            total = sum(e[2] for e in self._device_cache.values())
            while total > budget and len(self._device_cache) > 1:
                _, (_, _, freed) = self._device_cache.popitem(last=False)
                total -= freed
        return batch

    def _ingest_binding(self, kind, rest, node: Node) -> ColumnBatch:
        if kind == "host":
            arrays, cap = rest
            return D.from_host_table(
                node.schema, arrays, self.mesh,
                partition_capacity=cap, dictionary=self.dictionary,
            )
        if kind == "host_physical":
            phys, *opt = rest
            cap = opt[0] if opt else None
            return D.from_physical_table(
                phys, self.mesh, partition_capacity=cap
            )
        if kind == "store":
            parts, schema = rest
            P = num_partitions(self.mesh)
            phys = schema.device_names()

            # Fold store partitions onto mesh partitions (store partition
            # i concatenates into mesh partition i % P) so a store written
            # on a larger mesh loses nothing on a smaller one.
            folded: list = [[] for _ in range(P)]
            for i, cols in enumerate(parts):
                folded[i % P].append(cols)
            rows_per = [
                sum(len(next(iter(c.values()))) if c else 0 for c in group)
                for group in folded
            ]
            cap = math.ceil(max(max(rows_per, default=1), 1) / 8) * 8
            # Host-side (P * cap) layout + one device_put per column
            # (same no-jitted-ingest policy as from_physical_table).
            data = {
                c: np.zeros(P * cap, _phys_dtype(c, schema)) for c in phys
            }
            valid = np.zeros(P * cap, np.bool_)
            for p, group in enumerate(folded):
                at = p * cap
                for cols in group:
                    n = len(next(iter(cols.values()))) if cols else 0
                    for c in phys:
                        data[c][at : at + n] = cols[c]
                    valid[at : at + n] = True
                    at += n
            return D.shard_host_padded(data, valid, self.mesh)
        if kind == "stream":
            raise RuntimeError(
                "a chunk-stream input cannot bind as a device table; "
                "this operator needs the whole input resident (e.g. "
                "cache/apply) — materialize with to_store() first"
            )
        raise RuntimeError(f"unknown binding kind {kind}")

    def _binding_fp(self, node: Node):
        """Content SHA-1 of a plan-input binding (checkpoint identity);
        None for device-resident bindings, which can't be fingerprinted
        without a host transfer.  Cached per input node."""
        if node.id in self._binding_fp_cache:
            return self._binding_fp_cache[node.id]
        from dryad_tpu.exec.checkpoint import content_fingerprint

        kind, *rest = self._bindings[node.id]
        fp = None
        if kind == "host":
            arrays, cap = rest
            fp = content_fingerprint({str(k): np.asarray(v) for k, v in arrays.items()}) + f":{cap}"
        elif kind == "host_physical":
            phys, *opt = rest
            fp = content_fingerprint(phys) + (
                f":{opt[0]}" if opt else ""
            )
        elif kind == "store":
            parts, schema = rest
            merged = {
                f"p{i}/{c}": v for i, cols in enumerate(parts) for c, v in cols.items()
            }
            fp = content_fingerprint(merged)
        self._binding_fp_cache[node.id] = fp
        return fp

    # -- serving-tier surface ----------------------------------------------
    def is_stream_query(self, query: Query) -> bool:
        """True when the plan draws on a chunk-stream binding — such
        plans route through the StreamExecutor and are not valid for
        the async dispatch path (or the serving result cache)."""
        from dryad_tpu.exec.outofcore import has_stream_input

        return has_stream_input(self, query.node)

    def query_fingerprint(self, query: Query):
        """Stable identity of (plan structure, output position, ingest
        content) — the serving tier's result-cache key, or None when
        the query is uncacheable (local_debug, stream inputs, or any
        device-resident binding whose content can't be fingerprinted
        without a host transfer).

        Plan structure comes from the executor's ``graph_key`` (the
        compile-cache machinery), so the key inherits its reference
        semantics: closure-bearing plans (select/where lambdas) match
        only when re-run from the same Query object — prepared
        statements — while value-hashable params match across rebuilt
        queries.  The output is identified by its stage's POSITION in
        the lowered graph (stage ids are fresh per lowering and would
        defeat every repeat).  Ingest content is the per-binding SHA-1
        fingerprint (``_binding_fp``) of every plan input, in plan
        creation order."""
        if self.local_debug or self.is_stream_query(query):
            return None
        graph = lower(
            [query.node], self.config, self.dictionary,
            P=num_partitions(self.mesh) if self.mesh is not None else None,
        )
        fps = []
        for nid in sorted(graph.inputs):
            fp = self._binding_fp(graph.inputs[nid])
            if fp is None:
                return None
            fps.append(fp)
        sid, oidx = graph.outputs[query.node.id]
        pos = {s.id: i for i, s in enumerate(graph.stages)}[sid]
        return (self.executor.graph_key(graph), (pos, oidx), tuple(fps))

    def query_input_bytes(self, query: Query) -> int:
        """Host bytes bound under the plan — the admission-control cost
        of a query (device-resident and stream bindings count zero: no
        host copy is admitted on their behalf)."""
        total = 0
        seen = set()
        stack = [query.node]
        while stack:
            node = stack.pop()
            if node.id in seen:
                continue
            seen.add(node.id)
            stack.extend(node.inputs)
            binding = self._bindings.get(node.id)
            if binding is None:
                continue
            kind, *rest = binding
            if kind == "host":
                arrays, _cap = rest
                total += sum(np.asarray(v).nbytes for v in arrays.values())
            elif kind == "host_physical":
                phys = rest[0]
                total += sum(np.asarray(v).nbytes for v in phys.values())
            elif kind == "store":
                parts, _schema = rest
                total += sum(
                    np.asarray(v).nbytes
                    for cols in parts
                    for v in cols.values()
                )
        return total

    def _execute_device(self, query: Query, defer_miss: bool = False):
        graph = lower(
            [query.node], self.config, self.dictionary,
            P=num_partitions(self.mesh) if self.mesh is not None else None,
        )
        bindings = {
            nid: self._bind_device(n) for nid, n in graph.inputs.items()
        }
        binding_fps = None
        if self.config.checkpoint_dir:
            binding_fps = {
                nid: self._binding_fp(n) for nid, n in graph.inputs.items()
            }
        if defer_miss:
            results, deferred = self.executor.execute(
                graph, bindings, binding_fps, defer_miss=True
            )
            sid, oidx = graph.outputs[query.node.id]
            return results[(sid, oidx)], deferred
        results = self.executor.execute(graph, bindings, binding_fps)
        sid, oidx = graph.outputs[query.node.id]
        return results[(sid, oidx)]

    def _trace_ctx(self):
        """The active trace context, or a fresh mint for a non-serve
        job (serve minted one at admission and it is already active).
        None — a true no-op under ``tracectx.activate`` — when
        ``config.query_trace`` is off (the bench --obs-overhead A/B)."""
        ctx = tracectx.current()
        if ctx is None and getattr(self.config, "query_trace", True):
            ctx = tracectx.mint()
        return ctx

    def run_to_host(self, query: Query) -> Dict[str, np.ndarray]:
        # every span / exchange_round / dispatch_gap below carries the
        # minted (or inherited) context's qid
        with tracectx.activate(self._trace_ctx()):
            return self._run_to_host(query)

    def _run_to_host(self, query: Query) -> Dict[str, np.ndarray]:
        from dryad_tpu.exec.outofcore import StreamExecutor, has_stream_input

        if has_stream_input(self, query.node):
            if self.local_debug:
                raise RuntimeError(
                    "from_stream inputs are not supported in local_debug "
                    "mode (the NumPy interpreter holds whole tables); "
                    "materialize the chunks and use from_arrays"
                )
            return StreamExecutor(self).run_to_host(query.node)
        if self.local_debug:
            from dryad_tpu.exec.localdebug import LocalDebugInterpreter

            interp = LocalDebugInterpreter(self)
            return interp.run_to_logical(query.node)
        # The dict-miss counters ride the SAME device_get as the job
        # outputs (one tunnel round-trip instead of two, BASELINE.md
        # round-4); the deferred check still raises before any result
        # reaches the caller.
        batch, deferred = self._execute_device(query, defer_miss=True)
        valid, host_cols = _fetch_with_miss(batch, deferred)
        self._account_d2h(valid, host_cols)
        table = batch.to_numpy(
            query.schema, self.dictionary, _host=(valid, host_cols)
        )
        if self._codecs:
            from dryad_tpu.columnar.codecs import collapse_table

            table = collapse_table(table, self._codecs)
        return table

    def _account_d2h(self, valid, host_cols) -> None:
        """Device->host transfer byte accounting (obs.metrics): every
        result fetch funnels through here or the streaming executor."""
        if self.executor is not None:
            self.executor.metrics.add(
                "d2h_bytes",
                sum(np.asarray(v).nbytes for v in host_cols.values())
                + np.asarray(valid).nbytes,
            )

    def run_to_host_async(self, query: Query):
        """Dispatch the device job NOW; return a zero-arg ``fetch``
        closure that blocks on the device->host transfer.  The
        streaming pipeline's dispatch/drain split: the driver launches
        bucket k+1's program while bucket k's results transfer
        (``exec.outofcore`` phase 2).  Not valid for stream-input
        plans (those route through the StreamExecutor)."""
        tctx = self._trace_ctx()
        with tracectx.activate(tctx):
            batch, deferred = self._execute_device(query, defer_miss=True)

        def fetch() -> Dict[str, np.ndarray]:
            # the closure carries its query's context: a fetch drained
            # on another thread (DispatchWindow collector, serve
            # driver) still stamps readback spans with the right qid
            with tracectx.activate(tctx):
                valid, host_cols = _fetch_with_miss(batch, deferred)
                self._account_d2h(valid, host_cols)
                table = batch.to_numpy(
                    query.schema, self.dictionary, _host=(valid, host_cols)
                )
                if self._codecs:
                    from dryad_tpu.columnar.codecs import collapse_table

                    table = collapse_table(table, self._codecs)
                return table

        return fetch

    def run_many_to_host_async(self, queries):
        """Dispatch SEVERAL independent queries as ONE lowered program
        (cross-chunk plan fusion, ``config.chunk_fuse``): the roots
        lower together, their stage chains land consecutively in the
        graph, and ``plan_fuse`` folds them into a single dispatched
        region — K dispatch round trips collapse into one.  Each query
        stays its own computation inside the region (its reduction
        order is untouched), so results are byte-identical to K
        separate dispatches.

        Returns one zero-arg ``fetch`` closure per query, resolving
        that query's outputs from the shared execution.  The deferred
        dict-miss check rides the FIRST fetch's transfer (a miss
        anywhere in the group raises there, before any result of the
        group is committed)."""
        tctx = self._trace_ctx()
        with tracectx.activate(tctx):
            graph = lower(
                [q.node for q in queries], self.config, self.dictionary,
                P=num_partitions(self.mesh) if self.mesh is not None else None,
            )
            bindings = {
                nid: self._bind_device(n) for nid, n in graph.inputs.items()
            }
            binding_fps = None
            if self.config.checkpoint_dir:
                binding_fps = {
                    nid: self._binding_fp(n)
                    for nid, n in graph.inputs.items()
                }
            results, deferred = self.executor.execute(
                graph, bindings, binding_fps, defer_miss=True
            )
        state = {"deferred_done": False}

        def make_fetch(query, batch):
            def fetch() -> Dict[str, np.ndarray]:
                with tracectx.activate(tctx):
                    if not state["deferred_done"]:
                        valid, host_cols = _fetch_with_miss(batch, deferred)
                        state["deferred_done"] = True
                    else:
                        valid, host_cols, _ = batch.fetch_host(extra=[])
                    self._account_d2h(valid, host_cols)
                    table = batch.to_numpy(
                        query.schema, self.dictionary,
                        _host=(valid, host_cols),
                    )
                    if self._codecs:
                        from dryad_tpu.columnar.codecs import collapse_table

                        table = collapse_table(table, self._codecs)
                    return table

            return fetch

        fetches = []
        for q in queries:
            sid, oidx = graph.outputs[q.node.id]
            fetches.append(make_fetch(q, results[(sid, oidx)]))
        return fetches

    def submit(self, query: Query) -> JobHandle:
        return JobHandle(self.run_to_host(query))

    def to_store(self, query: Query, path: str) -> JobHandle:
        """Execute and persist (reference ToStore + SubmitAndWait)."""
        with tracectx.activate(self._trace_ctx()):
            return self._to_store(query, path)

    def _to_store(self, query: Query, path: str) -> JobHandle:
        if not self.local_debug:
            from dryad_tpu.exec.outofcore import (
                StreamExecutor,
                has_stream_input,
            )

            if has_stream_input(self, query.node):
                rows = StreamExecutor(self).to_store(query.node, path)
                return JobHandle({"rows": np.asarray([rows])}, path)
        if self.local_debug:
            table = self.run_to_host(query)
            b = ColumnBatch.from_numpy(
                query.schema, table,
                capacity=len(next(iter(table.values()), [])),
                dictionary=self.dictionary,
            )
            parts = [
                {c: np.asarray(v) for c, v in b.data.items()}
            ]
            from dryad_tpu.columnar.uri import write_store_uri

            write_store_uri(
                path, parts, query.schema, self.dictionary,
                self.config.intermediate_compression,
            )
            return JobHandle(table, path)
        batch, deferred = self._execute_device(query, defer_miss=True)
        P = num_partitions(self.mesh)
        cap = batch.capacity // P
        parts = []
        # overlapped d2h copies; miss counters ride the same transfer
        valid, host_cols = _fetch_with_miss(batch, deferred)
        for i in range(P):
            sl = slice(i * cap, (i + 1) * cap)
            m = valid[sl]
            parts.append({c: v[sl][m] for c, v in host_cols.items()})
        from dryad_tpu.columnar.uri import write_store_uri

        write_store_uri(
            path, parts, query.schema, self.dictionary,
            self.config.intermediate_compression,
        )
        return JobHandle(
            batch.to_numpy(
                query.schema, self.dictionary, _host=(valid, host_cols)
            ),
            path,
        )

    # -- do_while support ----------------------------------------------------
    def _lower_loop_stage(self, plan_fn, schema: Schema, example: ColumnBatch):
        """Lower a do_while body/cond subplan to ONE fused stage for the
        on-device loop path.  Raises ValueError when the subplan needs
        more than one stage (multi-consumer / join shapes) — the caller
        falls back to the driver loop."""
        q0 = self._from_device_batch(example, schema)
        out_q = plan_fn(q0)
        graph = lower([out_q.node], self.config, self.dictionary)
        if len(graph.stages) != 1:
            raise ValueError(
                f"subplan lowers to {len(graph.stages)} stages; device "
                f"loop needs exactly one"
            )
        stage = graph.stages[0]
        if stage.input_refs != [("plan_input", q0.node.id)] or len(
            stage.out_slots
        ) != 1:
            raise ValueError("subplan stage shape unsupported for device loop")
        return stage, out_q.schema

    def _run_subquery(self, plan_fn, schema: Schema, current: ColumnBatch, scalar: bool = False):
        # Build each body/cond plan ONCE per do_while and rebind the input
        # batch on later iterations — re-building would create fresh
        # closures every iteration and defeat the executor's structural
        # compile cache (one XLA compile per iteration).  Keyed by the
        # function OBJECT (strong ref), not id(): a freed function's id
        # can be reused and would serve the previous do_while's plan.
        cache_key = (plan_fn, tuple(schema.names))
        cached = getattr(self, "_subplans", None)
        if cached is None:
            cached = self._subplans = {}
        if cache_key not in cached:
            q0 = self._from_device_batch(current, schema)
            cached[cache_key] = (q0.node.id, plan_fn(q0))
        input_node_id, out_q = cached[cache_key]
        self._bindings[input_node_id] = ("device", current)
        if scalar:
            # The cond output is ROW-SHARDED (its one valid row lives on
            # one partition); in a multi-controller gang a plain host
            # fetch of a cross-process array raises, so gather the tiny
            # column through the collective path first.
            batch = self._execute_device(out_q)
            col = next(iter(batch.data.values()))
            valid = batch.valid
            import jax as _jax

            if _jax.process_count() > 1:
                from jax.experimental import multihost_utils as _mh

                col = _mh.process_allgather(col, tiled=True)
                valid = _mh.process_allgather(valid, tiled=True)
            vals = np.asarray(col)[np.asarray(valid)]
            return bool(vals[0]) if len(vals) else False
        return self._execute_device(out_q)


def _phys_dtype(col: str, schema: Schema) -> np.dtype:
    if "#" in col:
        return np.dtype(np.uint32)
    f = schema.field(col)
    return {
        ColumnType.INT32: np.dtype(np.int32),
        ColumnType.FLOAT32: np.dtype(np.float32),
        ColumnType.BOOL: np.dtype(np.bool_),
        ColumnType.UINT32: np.dtype(np.uint32),
    }[f.ctype]
