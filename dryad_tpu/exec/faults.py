"""Fault injection — the SetFakeVertexFailure analog, grown into a
seeded chaos harness.

The reference exposes knobs to fake vertex / vertex-input failures for
testing recovery paths (``DryadVertex/VertexHost/system/dprocess/
include/dryadvertex.h:240,247``).  Here: a process-global registry the
executor (and checkpoint store) consult before running work, with two
injection modes:

- **count-based** knobs (``set_fake_stage_failure`` et al.): fail the
  next N attempts — the original remote-controllable switches;
- a **seeded** :class:`FaultPlan`: probabilistic stage failures,
  stage delays, and checkpoint corruption drawn from one
  ``random.Random(seed)`` stream, with per-stage caps so a chaos run
  is guaranteed to stay inside the retry budget.  The same seed
  replays the same fault schedule — the property the chaos
  differential suite (``tests/test_chaos.py``) is built on.

Injected faults raise :class:`InjectedStageFailure` (a TRANSIENT
failure in the ``exec.failure`` taxonomy), exercising the
versioned-retry + backoff path.
"""

from __future__ import annotations

import dataclasses
import random
import threading
from typing import Dict, Optional, Sequence


class InjectedFault(RuntimeError):
    """Base class of all injected failures (classified TRANSIENT)."""


class InjectedStageFailure(InjectedFault):
    pass


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """Seeded probabilistic fault schedule.

    Draws come from one ``random.Random(seed)`` stream in call order,
    so a fixed (seed, workload) pair replays bit-identically.  Caps
    (``max_failures_per_stage``, ``max_checkpoint_corruptions``) bound
    the injected chaos below the retry budget, so a chaos run is
    *expected to succeed* — the suite asserts oracle-exact results,
    not mere survival.

    - ``stage_failure_prob``: per-attempt probability that a stage
      raises :class:`InjectedStageFailure`;
    - ``stages``: restrict failures/delays to stages whose fused name
      contains one of these op tokens (None = all stages);
    - ``stage_delay_prob`` / ``stage_delay_seconds``: probabilistic
      slow-stage injection (the slow-worker scenario);
    - ``checkpoint_corruption_prob``: probability that a just-saved
      checkpoint gets payload bytes flipped (silent bit rot the CRC
      verification must catch);
    - ``worker_kill_prob`` / ``max_worker_kills``: per-stage-attempt
      probability that the hosting PROCESS dies outright
      (``os._exit``) — the gang chaos scenario: a worker dying inside
      a stage leaves its peers stranded in the stage's collectives
      (mid-collective death).  Only install kill-bearing plans on
      WORKER processes (via the ``set_fault`` mailbox command,
      ``cluster.worker``); a driver-side plan with kills would kill
      the test/driver process itself.
    """

    seed: int = 0
    stage_failure_prob: float = 0.0
    stages: Optional[Sequence[str]] = None
    max_failures_per_stage: int = 2
    stage_delay_prob: float = 0.0
    stage_delay_seconds: float = 0.0
    checkpoint_corruption_prob: float = 0.0
    max_checkpoint_corruptions: int = 1
    worker_kill_prob: float = 0.0
    max_worker_kills: int = 1


class _Registry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._by_stage: Dict[str, int] = {}
        self._delay_by_stage: Dict[str, tuple] = {}  # key -> (secs, count)
        self._corrupt_count = 0
        self._plan: Optional[FaultPlan] = None
        self._plan_rng = random.Random(0)
        self._plan_failures: Dict[str, int] = {}
        self._plan_corruptions = 0
        self._plan_kills = 0
        self._corrupt_rng = random.Random(0xC0FFEE)  # count-based mode

    # -- count-based knobs (the remote-controllable switches) ----------------
    def set_fake_stage_failure(self, stage_name: str, count: int = 1) -> None:
        """Fail the next ``count`` attempts of stages named
        ``stage_name``.  ``count < 0`` means fail EVERY attempt with a
        stable message — a deterministic failure the taxonomy
        (``exec.failure.classify``) fails fast on."""
        with self._lock:
            self._by_stage[stage_name] = count

    def set_fake_stage_delay(
        self, stage_name: str, seconds: float, count: int = 1
    ) -> None:
        """Stall the next ``count`` attempts of matching stages by
        ``seconds`` — the injected slow-stage knob."""
        with self._lock:
            self._delay_by_stage[stage_name] = (float(seconds), int(count))

    def set_fake_checkpoint_corruption(self, count: int = 1) -> None:
        """Corrupt the next ``count`` checkpoint saves (payload byte
        flips after publish — silent bit rot for the CRC check)."""
        with self._lock:
            self._corrupt_count = int(count)

    def install_plan(self, plan: Optional[FaultPlan]) -> None:
        """Install (or clear, with None) the seeded chaos plan."""
        with self._lock:
            self._plan = plan
            self._plan_rng = random.Random(plan.seed if plan else 0)
            self._plan_failures.clear()
            self._plan_corruptions = 0
            self._plan_kills = 0

    def clear(self) -> None:
        with self._lock:
            self._by_stage.clear()
            self._delay_by_stage.clear()
            self._corrupt_count = 0
            self._plan = None
            self._plan_failures.clear()
            self._plan_corruptions = 0
            self._plan_kills = 0

    # -- consultation points -------------------------------------------------
    def _plan_matches(self, tokens: set) -> bool:
        assert self._plan is not None
        if self._plan.stages is None:
            return True
        return any(k in tokens for k in self._plan.stages)

    def maybe_fail(self, stage_name: str) -> None:
        """Fail if any registered name matches the stage's fused-op name
        (stage names are '+'-joined node kinds, e.g. 'input+group_by'),
        or if the installed plan's draw says so."""
        tokens = set(stage_name.split("+"))
        with self._lock:
            for key, n in self._by_stage.items():
                if key != stage_name and key not in tokens:
                    continue
                if n < 0:
                    # stable message: classified DETERMINISTIC on repeat
                    raise InjectedStageFailure(
                        f"injected deterministic failure for stage "
                        f"{stage_name!r} (key {key!r})"
                    )
                if n > 0:
                    self._by_stage[key] = n - 1
                    raise InjectedStageFailure(
                        f"injected failure for stage {stage_name!r} "
                        f"(key {key!r}, {n} remaining)"
                    )
            p = self._plan
            if (
                p is not None
                and p.stage_failure_prob > 0.0
                and self._plan_matches(tokens)
                and self._plan_failures.get(stage_name, 0)
                < p.max_failures_per_stage
                and self._plan_rng.random() < p.stage_failure_prob
            ):
                k = self._plan_failures.get(stage_name, 0) + 1
                self._plan_failures[stage_name] = k
                # per-occurrence message: stays TRANSIENT in the taxonomy
                raise InjectedStageFailure(
                    f"chaos(seed={p.seed}): injected failure #{k} for "
                    f"stage {stage_name!r}"
                )

    def maybe_kill(self, stage_name: str) -> bool:
        """Seeded gang-chaos draw: True when the installed plan says the
        hosting PROCESS should die before executing this stage attempt
        (the caller ``os._exit``s).  Returns False unless a plan with
        ``worker_kill_prob > 0`` is installed — so in-process chaos
        suites (which never set it) can never kill the test runner."""
        with self._lock:
            p = self._plan
            if p is None or p.worker_kill_prob <= 0.0:
                return False
            if self._plan_kills >= p.max_worker_kills:
                return False
            if not self._plan_matches(set(stage_name.split("+"))):
                return False
            if self._plan_rng.random() < p.worker_kill_prob:
                self._plan_kills += 1
                return True
        return False

    def maybe_delay(self, stage_name: str) -> float:
        """Seconds this stage attempt should stall (0.0 = no delay)."""
        tokens = set(stage_name.split("+"))
        with self._lock:
            for key, (secs, n) in self._delay_by_stage.items():
                if n > 0 and (key == stage_name or key in tokens):
                    self._delay_by_stage[key] = (secs, n - 1)
                    return secs
            p = self._plan
            if (
                p is not None
                and p.stage_delay_prob > 0.0
                and self._plan_matches(tokens)
                and self._plan_rng.random() < p.stage_delay_prob
            ):
                return p.stage_delay_seconds
        return 0.0

    def maybe_corrupt_checkpoint(self, directory: str) -> bool:
        """Flip payload bytes in one partition file of a just-published
        checkpoint — AFTER the header line, so the file still parses
        and only the CRC verification can tell (silent bit rot)."""
        with self._lock:
            fire = False
            if self._corrupt_count > 0:
                self._corrupt_count -= 1
                fire = True
            else:
                p = self._plan
                if (
                    p is not None
                    and p.checkpoint_corruption_prob > 0.0
                    and self._plan_corruptions < p.max_checkpoint_corruptions
                    and self._plan_rng.random()
                    < p.checkpoint_corruption_prob
                ):
                    self._plan_corruptions += 1
                    fire = True
            rng = (
                self._plan_rng if self._plan is not None
                else self._corrupt_rng
            )
        if not fire:
            return False
        return _flip_payload_bytes(directory, rng)


def _flip_payload_bytes(directory: str, rng) -> bool:
    """XOR a byte in the first ``.dpf`` payload under ``directory``."""
    import glob
    import os

    for path in sorted(glob.glob(os.path.join(directory, "*.dpf"))):
        with open(path, "rb") as fh:
            buf = bytearray(fh.read())
        nl = buf.find(b"\n")
        if nl < 0 or nl + 1 >= len(buf):
            continue  # no payload to corrupt; try the next file
        at = nl + 1 + rng.randrange(len(buf) - nl - 1)
        buf[at] ^= 0xFF
        with open(path, "wb") as fh:
            fh.write(buf)
        return True
    return False


registry = _Registry()
set_fake_stage_failure = registry.set_fake_stage_failure
set_fake_stage_delay = registry.set_fake_stage_delay
set_fake_checkpoint_corruption = registry.set_fake_checkpoint_corruption
install_plan = registry.install_plan
clear_faults = registry.clear
