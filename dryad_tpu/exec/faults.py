"""Fault injection — the SetFakeVertexFailure analog.

The reference exposes knobs to fake vertex / vertex-input failures for
testing recovery paths (``DryadVertex/VertexHost/system/dprocess/
include/dryadvertex.h:240,247``).  Here: a process-global registry the
executor consults before running a stage attempt; an injected fault
raises, exercising the versioned-retry path.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional


class InjectedStageFailure(RuntimeError):
    pass


class _Registry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._by_stage: Dict[str, int] = {}

    def set_fake_stage_failure(self, stage_name: str, count: int = 1) -> None:
        """Fail the next ``count`` attempts of stages named ``stage_name``."""
        with self._lock:
            self._by_stage[stage_name] = count

    def clear(self) -> None:
        with self._lock:
            self._by_stage.clear()

    def maybe_fail(self, stage_name: str) -> None:
        """Fail if any registered name matches the stage's fused-op name
        (stage names are '+'-joined node kinds, e.g. 'input+group_by')."""
        tokens = set(stage_name.split("+"))
        with self._lock:
            for key, n in self._by_stage.items():
                if n > 0 and (key == stage_name or key in tokens):
                    self._by_stage[key] = n - 1
                    raise InjectedStageFailure(
                        f"injected failure for stage {stage_name!r} "
                        f"(key {key!r}, {n} remaining)"
                    )


registry = _Registry()
set_fake_stage_failure = registry.set_fake_stage_failure
clear_faults = registry.clear
