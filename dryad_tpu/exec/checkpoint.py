"""Stage-boundary checkpointing — durable intermediates + resume.

The reference has no job-level checkpointing; its durability is that
every ``DCT_File`` channel is a persisted file on the producer's disk,
so recovery replays only missing vertices and a job restart re-reads
whatever inputs still exist (SURVEY §5.4; ``DrProcess.h:80-89`` retain/
lease times).  The TPU equivalent implemented here: completed stage
outputs are materialized host-side as ``.dpf`` partition files keyed by
a **content-addressed stage identity** — a Merkle chain of
(op-kind structure + static params + input shapes + the SHA-1 of every
transitive input's data).  Re-running the same stage over the same data
(same process or a restarted driver) loads the persisted output and
skips the stage; changing the input data or any upstream operator
changes the fingerprint and recomputes — stale hits are impossible.

Stages whose inputs cannot be fingerprinted (device-resident bindings,
e.g. do_while loop state) are simply not checkpointed; user callables
in operator params contribute only a structural marker, so a *changed*
user lambda with identical structure is the one identity component the
store cannot see — the same contract as the reference, which trusts the
resubmitted job to ship the same generated vertex DLL.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import zlib
from typing import Dict, Optional, Tuple

import numpy as np

from dryad_tpu.columnar.batch import ColumnBatch
from dryad_tpu.columnar.io import read_partition_file, write_partition_file
from dryad_tpu.exec import faults
from dryad_tpu.exec.failure import CheckpointCorruptionError
from dryad_tpu.obs.span import Tracer
from dryad_tpu.plan.lower import Stage
from dryad_tpu.utils.logging import get_logger

log = get_logger("dryad_tpu.exec.checkpoint")

_VALID = "__valid__"


def _col_crc(a: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(a).tobytes()) & 0xFFFFFFFF


def content_fingerprint(arrays: Dict[str, np.ndarray]) -> str:
    """SHA-1 of a host table's content (column names, dtypes, bytes).

    Object/str columns hash by VALUE, length-prefixed: ``tobytes`` on
    an object column serializes PyObject pointers, which differ per
    process — equal tables must fingerprint equal everywhere (the
    serving tier routes and invalidates by this digest)."""
    h = hashlib.sha1()
    for name in sorted(arrays):
        a = np.ascontiguousarray(np.asarray(arrays[name]))
        h.update(name.encode())
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        if a.dtype == object or a.dtype.kind in ("U", "S"):
            for s in a.ravel():
                b = str(s).encode("utf-8", "surrogatepass")
                h.update(len(b).to_bytes(4, "little"))
                h.update(b)
        else:
            h.update(a.tobytes())
    return h.hexdigest()[:16]


def _stable_param(v) -> str:
    """Structural repr of a static param; callables collapse to '<fn>'
    (cross-process resume assumes the same resubmitted query)."""
    if callable(v):
        return "<fn>"
    if isinstance(v, (list, tuple)):
        return "[" + ",".join(_stable_param(x) for x in v) + "]"
    return repr(v)


def stage_fingerprint(
    stage: Stage,
    shape_key: Tuple,
    input_fps: Tuple[Optional[str], ...],
) -> Optional[str]:
    """Merkle stage identity; None if any input is unfingerprintable."""
    if any(fp is None for fp in input_fps):
        return None
    parts = []
    for op in stage.ops:
        items = ",".join(
            f"{k}={_stable_param(v)}" for k, v in sorted(op.params.items())
        )
        parts.append(f"{op.kind}({items})")
    blob = (
        "|".join(parts)
        + f"|outs={stage.out_slots}|shapes={shape_key}|ins={input_fps}"
    )
    # Fused regions (plan.fuse.FusedStage) chain member ops whose slot
    # numbers overlap; their wiring/exports/member boundaries are part
    # of the identity or two differently-wired regions could alias.
    extra = getattr(stage, "fingerprint_extra", None)
    if extra:
        blob += "|" + extra
    return hashlib.sha1(blob.encode()).hexdigest()[:16]


class CheckpointStore:
    """Directory of per-stage materialized outputs, content-addressed."""

    def __init__(self, root: str, events=None):
        self.root = root
        self.events = events  # optional EventLog for integrity reports
        self._tracer = Tracer(events)  # save/load IO spans (cat=checkpoint)
        # Checkpoints touched (saved or loaded) by THIS run: exempt from
        # gc, so a retention lease shorter than the job's wall time can't
        # delete earlier stages of the running job out from under a
        # later resume-after-failure.
        self._active: set = set()
        os.makedirs(root, exist_ok=True)

    def _dir(self, stage: Stage, fp: str) -> str:
        name = re.sub(r"[^A-Za-z0-9_+-]", "_", stage.name)[:48]
        return os.path.join(self.root, f"{name}-{fp}")

    def save(
        self, stage: Stage, fp: str, outputs: Tuple[ColumnBatch, ...]
    ) -> str:
        with self._tracer.span(
            f"ckpt_save:{stage.name}", cat="checkpoint"
        ):
            return self._save(stage, fp, outputs)

    def _save(
        self, stage: Stage, fp: str, outputs: Tuple[ColumnBatch, ...]
    ) -> str:
        d = self._dir(stage, fp)
        tmp = d + ".tmp"
        os.makedirs(tmp, exist_ok=True)
        meta = {"outputs": len(outputs), "stage": stage.name, "crc": {}}
        for i, b in enumerate(outputs):
            cols = {n: np.asarray(v) for n, v in b.data.items()}
            cols[_VALID] = np.asarray(b.valid)
            write_partition_file(os.path.join(tmp, f"out{i}.dpf"), cols)
            # per-column CRC32 recorded at save, verified at load: a
            # silently bit-rotted payload must fail loudly into the
            # recompute path, never return corrupt data
            meta["crc"][f"out{i}"] = {
                n: _col_crc(a) for n, a in cols.items()
            }
        with open(os.path.join(tmp, "meta.json"), "w") as fh:
            json.dump(meta, fh)
        # atomic publish: a partially-written checkpoint is never visible
        if os.path.exists(d):
            import shutil

            shutil.rmtree(d)
        os.replace(tmp, d)
        self._active.add(d)
        # chaos hook: an installed FaultPlan may flip payload bytes in
        # the published checkpoint (simulated bit rot)
        faults.registry.maybe_corrupt_checkpoint(d)
        return d

    def gc(self, retain_seconds: float) -> int:
        """Drop checkpoints older than the retention lease — the analog
        of the reference's channel-file retain/lease grace times
        (``DrProcessTemplate``, ``DrProcess.h:80-89``).  A loaded
        checkpoint's mtime refreshes on save only; returns the number
        of entries removed."""
        import shutil
        import time as _time

        cutoff = _time.time() - retain_seconds
        removed = 0
        for name in os.listdir(self.root):
            d = os.path.join(self.root, name)
            meta = os.path.join(d, "meta.json")
            if not os.path.isdir(d) or d in self._active:
                continue
            try:
                ts = os.path.getmtime(meta if os.path.exists(meta) else d)
                if ts < cutoff:
                    shutil.rmtree(d)
                    removed += 1
            except OSError:  # concurrent removal: fine
                pass
        return removed

    def load(
        self, stage: Stage, fp: str, mesh
    ) -> Optional[Tuple[ColumnBatch, ...]]:
        d = self._dir(stage, fp)
        meta_path = os.path.join(d, "meta.json")
        if not os.path.exists(meta_path):
            return None
        with self._tracer.span(f"ckpt_load:{stage.name}", cat="checkpoint"):
            return self._load(stage, fp, d, meta_path, mesh)

    def _load(
        self, stage: Stage, fp: str, d: str, meta_path: str, mesh
    ) -> Optional[Tuple[ColumnBatch, ...]]:
        try:
            with open(meta_path) as fh:
                meta = json.load(fh)
            import jax

            from dryad_tpu.parallel.mesh import partition_sharding

            sh = partition_sharding(mesh)
            outs = []
            crcs = meta.get("crc", {})
            for i in range(meta["outputs"]):
                cols = read_partition_file(os.path.join(d, f"out{i}.dpf"))
                self._verify_crc(d, f"out{i}", cols, crcs.get(f"out{i}"))
                valid = cols.pop(_VALID)
                data = {n: jax.device_put(v, sh) for n, v in cols.items()}
                outs.append(ColumnBatch(data, jax.device_put(valid, sh)))
            self._active.add(d)
            return tuple(outs)
        except CheckpointCorruptionError as e:
            # integrity failure is TRANSIENT: fall through to recompute,
            # never serve corrupt data — but say so distinctly (bit rot
            # is a different diagnosis than a torn write)
            log.warning("checkpoint integrity failure: %s; recomputing", e)
            if self.events is not None:
                self.events.emit(
                    "checkpoint_corrupt", stage=stage.id, name=stage.name,
                    path=d, error=str(e),
                )
            return None
        except Exception as e:  # noqa: BLE001 — treat as cache miss
            log.warning("checkpoint %s unreadable (%s); recomputing", d, e)
            return None

    @staticmethod
    def _verify_crc(
        d: str, out_name: str, cols: Dict[str, np.ndarray], expect
    ) -> None:
        """Compare read columns against the CRCs recorded at save.
        Pre-CRC checkpoints (no ``crc`` in meta) load unverified."""
        if not expect:
            return
        for n, a in cols.items():
            want = expect.get(n)
            if want is None:
                continue
            got = _col_crc(a)
            if got != int(want):
                raise CheckpointCorruptionError(
                    f"column {n!r} of {d}/{out_name}.dpf: crc32 {got} != "
                    f"recorded {int(want)}"
                )
