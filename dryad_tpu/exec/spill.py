"""Bucketed spill files for out-of-core execution.

The TPU-native analog of the reference's persisted file channels
between stages (``DryadVertex/VertexHost/system/channel/
channelinterface.h:212`` RChannelReader over ``DCT_File`` channels):
a stage that cannot hold its working set in HBM streams bucketed
``.dpf`` pieces to local disk and re-reads one bucket at a time.
Strings spill as their 64-bit dictionary hashes (8 bytes/row, the
``Hash64.cs`` precedent) and decode back through the context
dictionary on read.
"""

from __future__ import annotations

import contextlib
import os
import shutil
import tempfile
from typing import Dict, Iterator, List, Optional

import numpy as np

from dryad_tpu.columnar.io import read_partition_file, write_partition_file

_STR_MARK = "#spillstr_"  # physical prefix for hash-encoded string cols


class SpillDir:
    """Append-only bucketed spill directory.

    ``append(bucket, table)`` writes one ``.dpf`` piece;
    ``read_bucket(bucket)`` concatenates the bucket's pieces back into
    one host table.  Object/str columns are hash-encoded via the
    context dictionary (which must already contain the values — true
    for any table that passed through ingest).
    """

    def __init__(
        self, dictionary=None, root: Optional[str] = None, own: bool = True
    ):
        # own=True also for caller-provided roots: every streaming-
        # executor root is a fresh mkdtemp (possibly under the
        # configured stream_spill_dir), so cleanup() must remove it.
        self._own = own
        self.root = root or tempfile.mkdtemp(prefix="dryad_spill_")
        os.makedirs(self.root, exist_ok=True)
        self.dictionary = dictionary
        self._pieces: Dict[int, List[str]] = {}
        self._rows: Dict[int, int] = {}
        self.bytes_written = 0

    def _encode(self, table: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        out = {}
        for name, a in table.items():
            a = np.asarray(a)
            if a.dtype == object or a.dtype.kind in ("U", "S"):
                if self.dictionary is None:
                    raise ValueError(
                        f"string column {name!r} needs a dictionary to spill"
                    )
                uniq, inv = np.unique(a.astype(object), return_inverse=True)
                hs = np.asarray(
                    [self.dictionary.add(str(s)) for s in uniq], np.uint64
                )
                out[_STR_MARK + name] = hs[inv]
            else:
                out[name] = a
        return out

    def _decode(self, cols: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        out = {}
        for name, a in cols.items():
            if name.startswith(_STR_MARK):
                hs = a.astype(np.uint64)
                uniq, inv = np.unique(hs, return_inverse=True)
                vals = np.asarray(
                    [self.dictionary._map[int(h)] for h in uniq], object
                )
                out[name[len(_STR_MARK):]] = vals[inv]
            else:
                out[name] = a
        return out

    def append(self, bucket: int, table: Dict[str, np.ndarray]) -> int:
        """Spill one piece; returns the piece's row count."""
        enc = self._encode(table)
        n = len(next(iter(enc.values()))) if enc else 0
        if n == 0:
            return 0
        bdir = os.path.join(self.root, f"bucket_{bucket:05d}")
        os.makedirs(bdir, exist_ok=True)
        pieces = self._pieces.setdefault(bucket, [])
        path = os.path.join(bdir, f"piece_{len(pieces):05d}.dpf")
        write_partition_file(path, enc)
        pieces.append(path)
        self._rows[bucket] = self._rows.get(bucket, 0) + n
        self.bytes_written += os.path.getsize(path)
        return n

    def buckets(self) -> List[int]:
        return sorted(self._pieces)

    def bucket_rows(self, bucket: int) -> int:
        return self._rows.get(bucket, 0)

    def read_bucket(self, bucket: int) -> Dict[str, np.ndarray]:
        pieces = [read_partition_file(p) for p in self._pieces.get(bucket, [])]
        if not pieces:
            return {}
        cols = {
            n: np.concatenate([p[n] for p in pieces]) for n in pieces[0]
        }
        return self._decode(cols)

    def read_bucket_pieces(
        self, bucket: int
    ) -> Iterator[Dict[str, np.ndarray]]:
        """Per-piece iterator (for re-bucketing an oversized bucket
        without holding it whole)."""
        for p in self._pieces.get(bucket, []):
            yield self._decode(read_partition_file(p))

    def drop_bucket(self, bucket: int) -> None:
        for p in self._pieces.pop(bucket, []):
            with contextlib.suppress(OSError):
                os.remove(p)
        self._rows.pop(bucket, None)

    def cleanup(self) -> None:
        if self._own:
            shutil.rmtree(self.root, ignore_errors=True)
        self._pieces.clear()
        self._rows.clear()
