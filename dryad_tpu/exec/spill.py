"""Bucketed spill files for out-of-core execution.

The TPU-native analog of the reference's persisted file channels
between stages (``DryadVertex/VertexHost/system/channel/
channelinterface.h:212`` RChannelReader over ``DCT_File`` channels):
a stage that cannot hold its working set in HBM streams bucketed
``.dpf`` pieces to local disk and re-reads one bucket at a time.
Strings spill as their 64-bit dictionary hashes (8 bytes/row, the
``Hash64.cs`` precedent) and decode back through the context
dictionary on read.
"""

from __future__ import annotations

import contextlib
import os
import shutil
import tempfile
import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from dryad_tpu.columnar.io import read_partition_file, write_partition_file
from dryad_tpu.obs.span import Tracer

_STR_MARK = "#spillstr_"  # physical prefix for hash-encoded string cols


class SpillDir:
    """Append-only bucketed spill directory.

    ``append(bucket, table)`` writes one ``.dpf`` piece;
    ``read_bucket(bucket)`` concatenates the bucket's pieces back into
    one host table.  Object/str columns are hash-encoded via the
    context dictionary (which must already contain the values — true
    for any table that passed through ingest).
    """

    def __init__(
        self, dictionary=None, root: Optional[str] = None, own: bool = True
    ):
        # own=True also for caller-provided roots: every streaming-
        # executor root is a fresh mkdtemp (possibly under the
        # configured stream_spill_dir), so cleanup() must remove it.
        self._own = own
        self.root = root or tempfile.mkdtemp(prefix="dryad_spill_")
        os.makedirs(self.root, exist_ok=True)
        self.dictionary = dictionary
        self._pieces: Dict[int, List[str]] = {}
        self._rows: Dict[int, int] = {}
        self.bytes_written = 0

    def _encode(self, table: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        out = {}
        for name, a in table.items():
            a = np.asarray(a)
            if a.dtype == object or a.dtype.kind in ("U", "S"):
                if self.dictionary is None:
                    raise ValueError(
                        f"string column {name!r} needs a dictionary to spill"
                    )
                uniq, inv = np.unique(a.astype(object), return_inverse=True)
                hs = np.asarray(
                    [self.dictionary.add(str(s)) for s in uniq], np.uint64
                )
                out[_STR_MARK + name] = hs[inv]
            else:
                out[name] = a
        return out

    def _decode(self, cols: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        out = {}
        for name, a in cols.items():
            if name.startswith(_STR_MARK):
                hs = a.astype(np.uint64)
                uniq, inv = np.unique(hs, return_inverse=True)
                vals = np.asarray(
                    [self.dictionary._map[int(h)] for h in uniq], object
                )
                out[name[len(_STR_MARK):]] = vals[inv]
            else:
                out[name] = a
        return out

    def append(self, bucket: int, table: Dict[str, np.ndarray]) -> int:
        """Spill one piece; returns the piece's row count."""
        enc = self._encode(table)
        n = len(next(iter(enc.values()))) if enc else 0
        if n == 0:
            return 0
        bdir = os.path.join(self.root, f"bucket_{bucket:05d}")
        os.makedirs(bdir, exist_ok=True)
        pieces = self._pieces.setdefault(bucket, [])
        path = os.path.join(bdir, f"piece_{len(pieces):05d}.dpf")
        write_partition_file(path, enc)
        pieces.append(path)
        self._rows[bucket] = self._rows.get(bucket, 0) + n
        self.bytes_written += os.path.getsize(path)
        return n

    def buckets(self) -> List[int]:
        return sorted(self._pieces)

    def bucket_rows(self, bucket: int) -> int:
        return self._rows.get(bucket, 0)

    def read_bucket(self, bucket: int) -> Dict[str, np.ndarray]:
        pieces = [read_partition_file(p) for p in self._pieces.get(bucket, [])]
        if not pieces:
            return {}
        cols = {
            n: np.concatenate([p[n] for p in pieces]) for n in pieces[0]
        }
        return self._decode(cols)

    def read_bucket_pieces(
        self, bucket: int
    ) -> Iterator[Dict[str, np.ndarray]]:
        """Per-piece iterator (for re-bucketing an oversized bucket
        without holding it whole)."""
        for p in self._pieces.get(bucket, []):
            yield self._decode(read_partition_file(p))

    def drop_bucket(self, bucket: int) -> None:
        for p in self._pieces.pop(bucket, []):
            with contextlib.suppress(OSError):
                os.remove(p)
        self._rows.pop(bucket, None)

    def cleanup(self) -> None:
        if self._own:
            shutil.rmtree(self.root, ignore_errors=True)
        self._pieces.clear()
        self._rows.clear()


class SpillWriter:
    """Buffered background writer: ``SpillDir.append`` moved off the
    driver loop so bucket writes overlap the next chunk's compute (the
    async channel-writer half of the reference's buffer pool,
    ``channelbufferqueue.cpp``).

    One writer THREAD, FIFO order: per-bucket piece indices are
    assigned in submit order, so the spilled bytes are identical to the
    serial driver's — the streaming differential guarantee
    ("byte-identical to the serial path") holds under the pipeline.

    A write error is latched and re-raised from the NEXT ``submit`` or
    from ``flush()`` — the driver's existing cleanup path (``finally:
    spill.cleanup()``) then removes the directory, so a mid-stream
    fault leaves no orphaned spills.  ``flush()`` is the phase barrier:
    phase 2 may only read bucket metadata after it returns.
    """

    def __init__(self, events=None, queue_depth: int = 8):
        self.events = events
        # writer-thread spans (cat=spill, with piece bytes): the spill
        # track of the Perfetto export + the spill_bytes accounting
        self._tracer = Tracer(events)
        self._max = max(1, queue_depth)
        self._q: List[Tuple] = []
        self._cv = threading.Condition()
        self._err: Optional[BaseException] = None
        self._closed = False
        self._busy = False  # a write is in progress (flush barrier)
        self.write_s = 0.0  # total seconds spent writing (observability)
        self.submit_wait_s = 0.0  # driver blocked on a full queue
        self.pieces = 0
        self._thread = threading.Thread(
            target=self._run, name="dryad-spill-writer", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._closed:
                    self._cv.wait(0.1)
                if not self._q and self._closed:
                    return
                job = self._q.pop(0)
                self._busy = True
                self._cv.notify_all()
            spill, bucket, table, depth = job
            t0 = time.monotonic()
            try:
                b0 = spill.bytes_written
                with self._tracer.span(
                    "spill_piece", cat="spill", bucket=bucket, depth=depth,
                ) as sp:
                    n = spill.append(bucket, table)
                    sp.add(rows=n, bytes=spill.bytes_written - b0)
                self.pieces += 1
                if self.events is not None and n:
                    self.events.emit(
                        "stream_spill", bucket=bucket, rows=n, depth=depth
                    )
            except BaseException as e:  # noqa: BLE001 - latched for driver
                with self._cv:
                    if self._err is None:
                        self._err = e
                    self._q.clear()  # poisoned stream: drop queued writes
            finally:
                self.write_s += time.monotonic() - t0
                with self._cv:
                    self._busy = False
                    self._cv.notify_all()

    def _raise_pending(self) -> None:
        if self._err is not None:
            err, self._err = self._err, None
            if self.events is not None:
                from dryad_tpu.exec.failure import classify

                self.events.emit(
                    "stream_pipeline_error", pipeline="spill",
                    phase="spill",
                    failure_kind=classify(err, []).value,
                    error=f"{type(err).__name__}: {err}",
                )
            raise err

    def submit(self, spill: SpillDir, bucket: int, table, depth: int = 0):
        """Queue one piece write; blocks when ``queue_depth`` writes are
        pending (bounded memory), raises a latched writer error."""
        t0 = time.monotonic()
        with self._cv:
            self._raise_pending()
            while len(self._q) >= self._max and self._err is None \
                    and not self._closed:
                self._cv.wait(0.1)
            self._raise_pending()
            if self._closed:
                raise RuntimeError("spill writer is closed")
            self._q.append((spill, bucket, table, depth))
            self._cv.notify_all()
        self.submit_wait_s += time.monotonic() - t0

    def flush(self) -> None:
        """Barrier: all submitted writes are durable (or the first
        error raises)."""
        with self._cv:
            while (self._q or self._busy) and self._err is None:
                self._cv.wait(0.1)
            self._raise_pending()

    def close(self, drain: bool = True) -> None:
        """Stop the writer.  ``drain=True`` flushes first (clean end of
        stream); ``drain=False`` abandons queued writes (error path —
        the caller is about to remove the spill directory anyway)."""
        if drain and self._err is None:
            with contextlib.suppress(BaseException):
                self.flush()
        with self._cv:
            self._closed = True
            if not drain:
                self._q.clear()
            self._cv.notify_all()
        self._thread.join(timeout=30.0)

    def __enter__(self) -> "SpillWriter":
        return self

    def __exit__(self, exc_type, *exc) -> None:
        self.close(drain=exc_type is None)
