"""Per-partition kernels for each StageOp, composed into one stage fn.

The analog of the generated vertex method body: where the reference
CodeDOM-generates one C# method per stage chaining operator calls over
channel readers/writers (``DryadLinqCodeGen.cs:1910`` AddVertexMethod),
we compose jit-traceable kernels over ColumnBatch slots and let XLA fuse
the chain.  All shapes are static: capacities derive from entry
capacities, stage growth, and the executor's retry ``boost``.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp

from dryad_tpu.columnar.batch import ColumnBatch
from dryad_tpu.ops import join as J
from dryad_tpu.ops import segmented as SEG
from dryad_tpu.ops import shuffle as SH
from dryad_tpu.ops import sort as SORT
from dryad_tpu.ops.hash import partition_ids
from dryad_tpu.parallel.mesh import AXIS
from dryad_tpu.plan import xchgplan as XP


def _round8(n: float) -> int:
    return max(8, int(math.ceil(n / 8.0)) * 8)


# (op kind, param name) pairs whose values are RUNTIME OPERANDS under
# ``stringcode_runtime_tables``: the executor keys its compile cache on
# the param's ``operand_signature()`` (shape-palette tier) instead of
# its content, and the arrays arrive through the stage fn's replicated
# input slot at call time (``exec.operands.DeviceOperandPool``).
# Kernels MUST read these params' arrays via ``ctx.operand(...)`` —
# materializing them with np/jnp.asarray inside the traced body would
# silently re-bake the content as compiled constants (the AST lint in
# tests/test_operand_lint.py enforces this in both directions).
OPERAND_PARAMS = frozenset({
    ("string_code", "table"),
    ("group_reduce_dense", "decode"),
})


def stage_operand_objs(stage) -> List[Any]:
    """Operand-protocol objects of a stage's OPERAND-registered params,
    in deterministic (op order, param name) order and deduplicated by
    identity — the ONE enumeration shared by the trace-time binding
    (``build_stage_fn``), the executor's cache key, and the call-time
    operand upload, so the replicated tuple always lines up."""
    from dryad_tpu.exec.operands import is_operand_capable

    objs: List[Any] = []
    seen = set()
    for op in stage.ops:
        for k in sorted(op.params):
            if (op.kind, k) not in OPERAND_PARAMS:
                continue
            v = op.params[k]
            if v is None or not is_operand_capable(v) or id(v) in seen:
                continue
            seen.add(id(v))
            objs.append(v)
    return objs


class StageContext:
    """Mutable trace-time state while composing one stage function."""

    def __init__(self, P: int, slack: float, boost: int,
                 axes: Tuple[str, ...] = (AXIS,),
                 axis_sizes: Tuple[int, ...] = (),
                 window: int = 0):
        self.P = P
        self.axes = axes
        self.axis_sizes = axis_sizes if axis_sizes else (P,)
        self.slack = slack
        self.boost = boost
        # Staged-exchange bucket window (config.exchange_window);
        # 0 = flat all_to_all.
        self.window = window
        # Static per-round exchange byte accounting, appended by
        # _exchange at trace time and surfaced by the executor as
        # exchange_round events (no device readback involved).
        self.xchg_log: List[Dict[str, int]] = []
        self.slots: Dict[int, ColumnBatch] = {}
        self.entry_caps: Dict[int, int] = {}
        # id(param object) -> tuple of traced operand arrays (bound
        # from the replicated inputs by build_stage_fn); empty on the
        # legacy baked-constant path
        self.operand_map: Dict[int, Tuple] = {}
        self.overflow = jnp.zeros((), jnp.bool_)
        # Rows whose STRING hash words missed the context dictionary
        # (runtime-fabricated values the dense path would silently
        # drop); surfaced by the executor after the job drains.
        self.dict_miss = jnp.zeros((), jnp.int32)

    def operand(self, obj) -> Any:
        """Traced device arrays for an OPERAND-registered param object,
        or None when the stage runs the legacy baked-constant path."""
        return self.operand_map.get(id(obj))

    def bind_inputs(self, batches: Tuple[ColumnBatch, ...]) -> None:
        for i, b in enumerate(batches):
            self.slots[i] = b
            self.entry_caps[i] = b.capacity

    def base_cap(self, slot: int) -> int:
        return self.entry_caps.get(slot, max(self.entry_caps.values() or [64]))


def apply_op(ctx: StageContext, kind: str, p: Dict[str, Any]) -> None:
    fn = _KERNELS.get(kind)
    if fn is None:
        raise NotImplementedError(f"no kernel for stage op {kind!r}")
    fn(ctx, p)


# -- row-wise --------------------------------------------------------------

def _k_select(ctx: StageContext, p) -> None:
    b = ctx.slots[p["slot"]]
    out_cols = p["fn"](dict(b.data))
    ctx.slots[p["slot"]] = ColumnBatch(dict(out_cols), b.valid)


def _k_where(ctx: StageContext, p) -> None:
    b = ctx.slots[p["slot"]]
    ctx.slots[p["slot"]] = b.filter(p["fn"](dict(b.data)))


def _k_project(ctx: StageContext, p) -> None:
    b = ctx.slots[p["slot"]]
    ctx.slots[p["slot"]] = b.select(p["cols"])


def _k_seed(ctx: StageContext, p) -> None:
    b = ctx.slots[p["slot"]]
    new_cols = p["fn"](dict(b.data))
    data = dict(b.data)
    data.update(new_cols)
    ctx.slots[p["slot"]] = ColumnBatch(data, b.valid)


def _k_select_many(ctx: StageContext, p) -> None:
    b = ctx.slots[p["slot"]]
    factor = int(p["factor"])
    n = b.capacity
    out_cols, out_valid = p["fn"](dict(b.data))
    data = {}
    for name, col in out_cols.items():
        if col.shape[:2] != (n, factor):
            raise ValueError(
                f"select_many column {name!r} must be ({n},{factor},...), got {col.shape}"
            )
        data[name] = col.reshape((n * factor,) + col.shape[2:])
    valid = (b.valid[:, None] & out_valid).reshape(n * factor)
    ctx.slots[p["slot"]] = ColumnBatch(data, valid)


def _k_apply(ctx: StageContext, p) -> None:
    b = ctx.slots[p["slot"]]
    if p.get("with_index"):
        out = p["fn"](b, jax.lax.axis_index(ctx.axes))
    else:
        out = p["fn"](b)
    if not isinstance(out, ColumnBatch):
        raise TypeError("apply fn must return a ColumnBatch")
    ctx.slots[p["slot"]] = out


# -- exchanges -------------------------------------------------------------

def _fanout(ctx: StageContext, nparts) -> int:
    """Effective destination count for a fan-reduced exchange (stage-
    level fan-out adaptation, ``DrDynamicRangeDistributor.cpp:54-110``):
    rows concentrate onto the first ``nparts`` partitions; the rest run
    the stage masked-empty.  On hybrid (2-axis) meshes the tree
    exchange ignores nparts, so reduction is disabled there outright —
    a half-applied reduction would inflate the paired resize by P/P_eff
    while the data actually spread full-width."""
    if not nparts or len(ctx.axes) != 1:
        return ctx.P
    return min(int(nparts), ctx.P)


def _exchange(
    ctx: StageContext, b: ColumnBatch, dest, P: int, B: int, axes
) -> Tuple[ColumnBatch, jax.Array]:
    """Route one repartition through the flat or staged exchange.

    ``ctx.window >= 1`` lowers the all-to-all into the planner's
    ppermute schedule (``plan.xchgplan``), bounding peak extra HBM at
    O(window * B) per device; 0 keeps the flat single-collective path.
    Either way the round-by-round byte accounting — a trace-time
    constant — lands on ``ctx.xchg_log`` for the executor to emit as
    ``exchange_round`` events.
    """
    if len(axes) == 2:
        dcn = ctx.axis_sizes[0]
    elif len(ctx.axes) == 2 and axes[0] == ctx.axes[0]:
        dcn = P  # exchange over the DCN axis alone: every hop crosses
    else:
        dcn = 1
    per_row = SH.row_bytes(b)
    if ctx.window < 1 or P == 1:
        ctx.xchg_log.append(XP.flat_accounting(P, dcn, B, per_row))
        return SH.exchange(b, dest, P, B, axes)
    schedule = XP.plan_exchange(P, ctx.window, dcn)
    ctx.xchg_log.extend(schedule.accounting(B, per_row))
    return SH.exchange_staged(b, dest, P, B, axes, schedule)


def _do_exchange_hash(
    ctx: StageContext, slot: int, keys, tree=None, nparts=None
) -> None:
    b = ctx.slots[slot]
    if tree is not None and len(ctx.axes) == 2:
        _tree_exchange_hash(ctx, slot, keys, tree)
        return
    P_eff = _fanout(ctx, nparts)
    dest = partition_ids([b.data[k] for k in keys], P_eff)
    B = SH.bucket_capacity(b.capacity, P_eff, ctx.slack * ctx.boost)
    out, ovf = _exchange(ctx, b, dest, ctx.P, B, ctx.axes)
    ctx.slots[slot] = out
    ctx.overflow = ctx.overflow | ovf


def _tree_exchange_hash(ctx: StageContext, slot: int, keys, tree) -> None:
    """Hierarchical shuffle on a hybrid mesh: ICI hop -> per-slice
    combine -> DCN hop.

    The reference's machine→pod→overall aggregation tree
    (``DrDynamicAggregateManager.h:35-168``) in collective form: rows
    for global partition g first travel over ICI to local device
    g %% P_ici within their slice, duplicate keys are combined there,
    and only the per-slice partials cross DCN to slice g // P_ici —
    cutting DCN bytes by the per-slice duplication factor.  The final
    combine after the DCN hop is the stage's own downstream op.
    """
    D, P_in = ctx.axis_sizes[0], ctx.axis_sizes[1]
    slack = ctx.slack * ctx.boost

    def dest_global(batch):
        return partition_ids([batch.data[k] for k in keys], ctx.P)

    # Hop 1: within-slice exchange over ICI to local index g %% P_ici.
    b = ctx.slots[slot]
    B1 = SH.bucket_capacity(b.capacity, P_in, slack)
    out, ovf = _exchange(
        ctx, b, dest_global(b) % P_in, P_in, B1, (ctx.axes[1],)
    )
    ctx.overflow = ctx.overflow | ovf
    out, ovf = SH.resize(out, _round8(b.capacity * ctx.slack))
    ctx.overflow = ctx.overflow | ovf

    # Per-slice combine (RecursiveAccumulate analog; idempotent specs).
    if tree.get("distinct"):
        out = SEG.distinct(out, tree["keys"])
    elif "merge" in tree:
        out = SEG.group_combine(
            out, tree["keys"], tree["state_cols"], tree["merge"]
        )
    else:
        out = SEG.group_reduce(out, tree["keys"], tree["aggs"])

    # Hop 2: cross-slice exchange over DCN to slice g // P_ici.
    B2 = SH.bucket_capacity(out.capacity, D, slack)
    out2, ovf = _exchange(
        ctx, out, dest_global(out) // P_in, D, B2, (ctx.axes[0],)
    )
    ctx.overflow = ctx.overflow | ovf
    ctx.slots[slot] = out2


def _do_resize(
    ctx: StageContext, slot: int, factor: float, nparts=None
) -> None:
    b = ctx.slots[slot]
    # A fan-reduced exchange concentrates ~P/P_eff partitions' rows
    # onto each live partition; scale the post-shuffle capacity so the
    # concentration itself never trips the overflow retry.
    conc = ctx.P / _fanout(ctx, nparts)
    target = _round8(
        ctx.base_cap(slot) * factor * conc * ctx.boost * ctx.slack
    )
    out, ovf = SH.resize(b, target)
    ctx.slots[slot] = out
    ctx.overflow = ctx.overflow | ovf


# Op kinds whose kernels never set the overflow flag: a stage composed
# only of these has a statically-False overflow, so the driver skips the
# host sync on it and lets JAX async dispatch pipeline it with
# independent stages (the message-pump overlap of the reference GM,
# DrMessagePump.h:116-180, recovered through XLA's async runtime).
NON_OVERFLOW_OPS = frozenset({
    "select", "where", "project", "select_many", "apply", "fork",
    "group_reduce", "group_combine", "group_reduce_dense", "distinct",
    "local_sort", "concat", "scalar_agg", "topk", "string_code",
})


def _k_exchange_hash(ctx: StageContext, p) -> None:
    _do_exchange_hash(
        ctx, p["slot"], p["keys"], p.get("tree"), p.get("nparts")
    )


def _k_exchange_range(ctx: StageContext, p) -> None:
    b = ctx.slots[p["slot"]]
    operands = p["operands_fn"](b)
    # Splitter sample count = sample_rate fraction of the partition
    # (reference 0.1% sampler, DryadLinqSampler.cs:38-42), clamped to
    # [16, 512] so tiny partitions still elect meaningful splitters and
    # huge ones bound the all_gather.  An overflow retry REFINES the
    # election alongside the capacity boost — rate and clamp scale with
    # ctx.boost, so a retry caused by unlucky splitters (a dense value
    # cluster the small sample missed) converges by better splitters,
    # not just by doubling every partition's memory (the data-size
    # recomputation of DrDynamicRangeDistributor.cpp:54-110).
    rate = float(p.get("rate", 0.001)) * ctx.boost
    m = int(min(512 * ctx.boost, max(16 * ctx.boost, b.capacity * rate)))
    P_eff = _fanout(ctx, p.get("nparts"))
    if p.get("spread"):
        # Skew-proof variant for pure ordering (order_by): splitters
        # elected over ALL sort operands plus a uniform synthetic
        # tiebreak, so a heavy key's run is cut across partitions in
        # sampled proportions instead of pinning one partition and
        # boost-doubling everybody (automatic analog of
        # DrDynamicDistributor.h:26,79).  Global order still holds —
        # partition boundaries respect the extended lexicographic key.
        # Not used for range_partition, which promises key colocation.
        words = [o.astype(jnp.uint32) for o in operands]
        words.append(SORT.spread_word(b.capacity))
        splitters = SORT.sample_splitters_multi(
            words, b.valid, P_eff, m, ctx.axes
        )
        dest = SORT.range_dest_multi(words, splitters)
    else:
        splitters = SORT.sample_splitters(
            operands[0], b.valid, P_eff, m, ctx.axes
        )
        dest = SORT.range_dest(operands[0], splitters)
    B = SH.bucket_capacity(b.capacity, P_eff, ctx.slack * ctx.boost)
    out, ovf = _exchange(ctx, b, dest, ctx.P, B, ctx.axes)
    ctx.slots[p["slot"]] = out
    ctx.overflow = ctx.overflow | ovf


def _k_resize(ctx: StageContext, p) -> None:
    # Post-shuffle capacity: entry capacity x pipeline growth x retry
    # boost x slack (hash placement has variance, so the uniform
    # expectation alone overflows regularly).
    _do_resize(ctx, p["slot"], p["factor"], p.get("nparts"))


# -- grouping / sorting ----------------------------------------------------

def _k_group_reduce(ctx: StageContext, p) -> None:
    b = ctx.slots[p["slot"]]
    ctx.slots[p["slot"]] = SEG.group_reduce(b, p["keys"], p["aggs"])


def _k_group_combine(ctx: StageContext, p) -> None:
    b = ctx.slots[p["slot"]]
    ctx.slots[p["slot"]] = SEG.group_combine(
        b, p["keys"], p["state_cols"], p["merge"]
    )


def _k_group_reduce_dense(ctx: StageContext, p) -> None:
    """Dense-key GroupBy: per-partition MXU bucket reduce (Pallas on
    TPU, ``ops/pallas_bucket.py``) + one ``psum_scatter`` over the mesh.

    Output partition i holds buckets [i*per, (i+1)*per); rows for keys
    outside [0, K) are dropped (API contract).  Per-partition counts
    accumulate in f32 on the MXU (exact below 2^24 rows/bucket/partition
    — statically guaranteed by the capacity guard below) and cross the
    mesh as int32, so the global count is exact.  SUM columns accumulate
    in f32 end-to-end: integer sums silently lose exactness once a
    per-bucket total exceeds 2^24 (documented at the API, query.py
    ``dense=``); the sort-based path is the exact alternative.
    """
    from dryad_tpu.ops.pallas_bucket import bucket_sum_count

    b = ctx.slots[p["slot"]]
    if b.capacity > (1 << 24):
        raise ValueError(
            f"dense group_by: partition capacity {b.capacity} exceeds the "
            "f32-exact accumulation range (2^24 rows/partition); use the "
            "sort-based group_by path"
        )
    if ctx.P * b.capacity > 0x7FFFFFFF:
        raise ValueError(
            f"dense group_by: global capacity {ctx.P * b.capacity} exceeds "
            "the int32 count range; use the sort-based group_by path"
        )
    K = int(p["num_buckets"])
    per = max(1, -(-K // ctx.P))  # ceil
    Kp = per * ctx.P
    key = b.data[p["key"]]
    in_range = b.valid & (key >= 0) & (key < K)
    if p.get("guard"):
        # Int auto-dense rewrite: the [0, K) bound came from INGEST
        # statistics, so out-of-range keys mean post-ingest fabrication
        # — count them for the executor's deferred loud failure instead
        # of silently dropping (explicit dense=K keeps its documented
        # drop semantics).
        ctx.dict_miss = ctx.dict_miss + jnp.sum(
            (b.valid & ~in_range).astype(jnp.int32)
        )

    # Distinct value columns needed by sum/mean aggs.
    val_cols: List[str] = []
    for a in p["aggs"]:
        if a.op in ("sum", "mean") and a.col not in val_cols:
            val_cols.append(a.col)
    sums, cnt = bucket_sum_count(
        key, [b.data[c] for c in val_cols], in_range, Kp
    )
    by_col = dict(zip(val_cols, sums))

    scat = lambda x: jax.lax.psum_scatter(
        x, ctx.axes, scatter_dimension=0, tiled=True
    )
    # Counts cross the mesh as int32: each per-partition partial is f32-
    # exact (capacity guard above), and integer reduce-scatter keeps the
    # global total exact past 2^24.
    cnt = scat(jnp.round(cnt).astype(jnp.int32))
    by_col = {c: scat(s) for c, s in by_col.items()}

    me = jax.lax.axis_index(ctx.axes)
    codes = me * per + jnp.arange(per, dtype=jnp.int32)
    decode = p.get("decode")
    if decode is None:
        out: Dict[str, jax.Array] = {p["key"]: codes.astype(key.dtype)}
    else:
        # auto-dense STRING key: gather this partition's code range from
        # the dictionary decode table to reconstruct the physical
        # (#h0, #h1, #r0, #r1) words (ops/stringcode.py); the table
        # arrives as a runtime operand when registered, else baked
        words = decode.slice_rows(
            me * per, per, operands=ctx.operand(decode)
        )  # (per, 4) uint32
        okey = p["out_key"]
        out = {
            f"{okey}#{w}": words[:, i]
            for i, w in enumerate(("h0", "h1", "r0", "r1"))
        }
    for a in p["aggs"]:
        if a.op == "count":
            out[a.out] = cnt
        elif a.op == "sum":
            s = by_col[a.col]
            dt = b.data[a.col].dtype
            out[a.out] = (
                jnp.round(s).astype(dt) if jnp.issubdtype(dt, jnp.integer)
                else s.astype(dt)
            )
        elif a.op == "mean":
            out[a.out] = by_col[a.col] / jnp.maximum(cnt, 1).astype(
                jnp.float32
            )
        else:  # guarded at the API layer
            raise ValueError(f"dense group_by cannot compute {a.op!r}")
    valid = (cnt > 0) & (codes < K)
    ctx.slots[p["slot"]] = ColumnBatch(out, valid)


def _k_string_code(ctx: StageContext, p) -> None:
    """Map a STRING column's Hash64 words to dense dictionary codes
    (``ops/stringcode.py``) — the bridge that lets a plain group_by
    over strings ride the MXU dense path.  Misses map to the padded
    code domain, which the dense kernel's range mask drops."""
    b = ctx.slots[p["slot"]]
    table = p["table"]
    rt = ctx.operand(table)  # runtime-operand arrays, or None = baked
    codes = table.lookup(b.data[p["h0"]], b.data[p["h1"]], operands=rt)
    # Out-of-dictionary rows (miss -> num_codes_padded) would be
    # silently dropped by the dense kernel's range mask; count them so
    # the executor can surface the loss instead (deferred readback, no
    # sync on the dense fast path).  The threshold is the TIER bound on
    # the operand path — num_codes itself would re-bake a per-widen
    # trace constant; nothing occupies [num_codes, padded), so the two
    # thresholds count identically.
    bound = table.num_codes_padded if rt is not None else table.num_codes
    miss = jnp.sum(
        (b.valid & (codes >= jnp.int32(bound))).astype(jnp.int32)
    )
    ctx.dict_miss = ctx.dict_miss + miss
    ctx.slots[p["slot"]] = ColumnBatch(
        {**b.data, p["out"]: codes}, b.valid
    )


def _k_distinct(ctx: StageContext, p) -> None:
    b = ctx.slots[p["slot"]]
    ctx.slots[p["slot"]] = SEG.distinct(b, p["keys"])


def _k_topk(ctx: StageContext, p) -> None:
    """Fused OrderBy+Take(n): per-partition local top-n, one
    ``all_gather`` of the P heads, final local sort — no full range
    exchange, no full-data shuffle (the SimpleRewriter-style plan
    rewrite, ``LinqToDryad/SimpleRewriter.cs``; classic distributed
    top-k).  Output is partition-major globally sorted with exactly n
    valid rows; per-partition capacity shrinks to the padded head size.
    Tie rows beyond position n are dropped in post-sort order (the
    engine's order_by+take makes the same unstable tie choice after a
    shuffle)."""
    b = ctx.slots[p["slot"]]
    operands = p["operands_fn"](b)
    sb = SORT.sort_batch_by_operands(b, operands)  # local sort; valid rows first
    n = int(p["n"])
    # head size never exceeds the partition capacity: slicing past the
    # array would clamp and the gather arithmetic below would duplicate
    # the tail partition's rows
    n_pad = min(b.capacity, max(8, _round8(n)))
    head = ColumnBatch(
        {c: v[:n_pad] for c, v in sb.data.items()}, sb.valid[:n_pad]
    )
    gb = _gather_all(head, ctx.axes)  # every partition: all P heads
    # identical globally-sorted array everywhere
    gsb = SORT.sort_batch_by_operands(gb, p["operands_fn"](gb))
    me = jax.lax.axis_index(ctx.axes)
    start = me * n_pad
    pos = start + jnp.arange(n_pad, dtype=jnp.int32)
    data = {
        c: jax.lax.dynamic_slice_in_dim(v, start, n_pad)
        for c, v in gsb.data.items()
    }
    valid = (
        jax.lax.dynamic_slice_in_dim(gsb.valid, start, n_pad)
        & (pos < jnp.int32(n))
    )
    ctx.slots[p["slot"]] = ColumnBatch(data, valid)


def _k_local_sort(ctx: StageContext, p) -> None:
    b = ctx.slots[p["slot"]]
    ctx.slots[p["slot"]] = SORT.sort_batch_by_operands(b, p["operands_fn"](b))


# -- multi-input -----------------------------------------------------------

def _gather_all(b: ColumnBatch, axes: Tuple[str, ...]) -> ColumnBatch:
    """Replicate a batch to every partition (the broadcast copy-tree of
    ``DrDynamicBroadcast.h:23`` as one ``all_gather`` over ICI)."""
    data = {
        n: jax.lax.all_gather(c, axes, tiled=True) for n, c in b.data.items()
    }
    return ColumnBatch(data, jax.lax.all_gather(b.valid, axes, tiled=True))


def _join_strategy(ctx: StageContext, p, right: ColumnBatch) -> bool:
    """True -> broadcast the right side; False -> co-hash-partition.

    The analog of the reference's dynamic broadcast decision
    (``DynamicManager.cs:51``, which reads actual data size): when the
    plan carries a static ROW-count bound for the right side
    (take(n) heads, aggregates, dense domains — lower.py's estimator),
    that bound decides; otherwise fall back to the capacity heuristic.
    Both are trace-time static, so the choice is baked per compiled
    shape and cached."""
    strategy = p.get("strategy", "shuffle")
    if strategy == "broadcast":
        return True
    if strategy == "auto":
        limit = p.get("broadcast_limit", 1 << 16)
        est = p.get("est_right")
        if est is not None:
            # global row bound: a mostly-empty right batch with large
            # CAPACITY still broadcasts when its rows are bounded small
            return est <= limit
        return right.capacity * ctx.P <= limit
    return False


def _co_partition_for_join(ctx: StageContext, p) -> None:
    """Hash-exchange whichever sides the plan says are not already
    partitioned on the join keys (deferred from lowering when the
    strategy decision is trace-time)."""
    if p.get("need_left_exchange"):
        _do_exchange_hash(ctx, p["left_slot"], p["left_keys"])
        _do_resize(ctx, p["left_slot"], 1.0)
    if p.get("need_right_exchange"):
        _do_exchange_hash(ctx, p["right_slot"], p["right_keys"])
        _do_resize(ctx, p["right_slot"], 1.0)


def _apply_join_strategy(ctx: StageContext, p) -> int:
    """Run the chosen placement (broadcast the right side, or the
    deferred co-partition exchanges) and return the capacity base for
    sizing candidate-pair buffers.  The base uses PRE-broadcast sizes:
    replicating the right side multiplies its capacity by P but not the
    match count."""
    base = max(
        ctx.slots[p["left_slot"]].capacity, ctx.slots[p["right_slot"]].capacity
    )
    if "strategy" in p:
        if _join_strategy(ctx, p, ctx.slots[p["right_slot"]]):
            right = ctx.slots[p["right_slot"]]
            est = p.get("est_right")
            if est is not None:
                # An est-bound broadcast must not gather the FULL
                # capacity (P x cap could dwarf broadcast_limit):
                # shrink each partition to the global row bound first —
                # per-partition valid <= global valid <= est, so this
                # cannot overflow.
                tight = _round8(min(right.capacity, max(8, int(est))))
                if tight < right.capacity:
                    right, ovf = SH.resize(right, tight)
                    ctx.overflow = ctx.overflow | ovf
                    ctx.slots[p["right_slot"]] = right
            ctx.slots[p["right_slot"]] = _gather_all(right, ctx.axes)
        else:
            _co_partition_for_join(ctx, p)
            base = max(
                ctx.slots[p["left_slot"]].capacity,
                ctx.slots[p["right_slot"]].capacity,
            )
    return base


def _k_join(ctx: StageContext, p) -> None:
    base = _apply_join_strategy(ctx, p)
    left = ctx.slots[p["left_slot"]]
    right = ctx.slots[p["right_slot"]]
    out_cap = _round8(base * p["expansion"] * ctx.boost)
    if p.get("outer"):
        out, ovf = J.hash_join_outer(
            left, right, p["left_keys"], p["right_keys"], out_cap,
            p.get("right_defaults") or {}, p.get("suffix", "_r"),
        )
    else:
        out, ovf = J.hash_join(
            left, right, p["left_keys"], p["right_keys"], out_cap, p.get("suffix", "_r")
        )
    ctx.slots[p["left_slot"]] = out
    ctx.overflow = ctx.overflow | ovf


def _k_semi(ctx: StageContext, p) -> None:
    base = _apply_join_strategy(ctx, p)
    left = ctx.slots[p["left_slot"]]
    right = ctx.slots[p["right_slot"]]
    cap = _round8(base * p["expansion"] * ctx.boost)
    mask, ovf = J.exists_mask(
        left, right, p["left_keys"], p["right_keys"], cap
    )
    if p.get("negate"):
        mask = ~mask
    ctx.slots[p["left_slot"]] = left.filter(mask)
    ctx.overflow = ctx.overflow | ovf


def _k_concat(ctx: StageContext, p) -> None:
    batches = [ctx.slots[s] for s in p["slots"]]
    names = set(batches[0].columns)
    aligned = [b.select(sorted(names)) for b in batches]
    ctx.slots[p["out_slot"]] = ColumnBatch.concatenate(aligned)


def _k_group_join_count(ctx: StageContext, p) -> None:
    base = _apply_join_strategy(ctx, p)
    left = ctx.slots[p["left_slot"]]
    right = ctx.slots[p["right_slot"]]
    cap = _round8(base * p["expansion"] * ctx.boost)
    counts, ovf = J.group_join_counts(
        left, right, p["left_keys"], p["right_keys"], cap
    )
    ctx.slots[p["left_slot"]] = left.with_column(p["out"], counts)
    ctx.overflow = ctx.overflow | ovf


def _k_join_ranked(ctx: StageContext, p) -> None:
    """Inner join emitting a group-local match rank (full GroupJoin's
    enumerable group, reference ``DryadLinqQueryable.cs`` GroupJoin
    result-selector overloads)."""
    base = _apply_join_strategy(ctx, p)
    left = ctx.slots[p["left_slot"]]
    right = ctx.slots[p["right_slot"]]
    out_cap = _round8(base * p["expansion"] * ctx.boost)
    operands_fn = p.get("operands_fn")
    operands = operands_fn(right) if operands_fn is not None else ()
    out, ovf = J.hash_join_ranked(
        left, right, p["left_keys"], p["right_keys"], out_cap,
        p.get("suffix", "_r"), p["rank_out"], operands,
        rank_limit=p.get("rank_limit"), boost=ctx.boost,
        # At the retry ladder's last rung the window clamp drops away,
        # so a hash-collision-into-a-hot-run row degrades to the
        # unclamped expansion instead of failing the job.
        final_attempt=ctx.boost >= p.get("rank_limit_max_boost", 1 << 30),
    )
    ctx.slots[p["left_slot"]] = out
    ctx.overflow = ctx.overflow | ovf


def _rank_column(b: ColumnBatch, P: int, axes: Tuple[str, ...]) -> Tuple[ColumnBatch, jax.Array]:
    """Compact and attach each valid row's global rank (partition-major)."""
    c = b.compact()
    # Ranks are uint32 with 0xFFFFFFFF as the invalid sentinel; the max
    # possible rank is the static global capacity, so guard at trace
    # time rather than silently wrapping past 4.29B rows.
    if P * c.capacity >= 0xFFFFFFFF:
        raise ValueError(
            f"rank-based operator: global capacity {P * c.capacity} "
            "exceeds the uint32 rank range (4.29e9 rows)"
        )
    local = jnp.sum(c.valid.astype(jnp.int32))
    counts = jax.lax.all_gather(local, axes)
    me = jax.lax.axis_index(axes)
    offset = jnp.sum(jnp.where(jnp.arange(P) < me, counts, 0))
    rank = (offset + jnp.arange(c.capacity, dtype=jnp.int32)).astype(jnp.uint32)
    rank = jnp.where(c.valid, rank, jnp.uint32(0xFFFFFFFF))
    total = jax.lax.psum(local, axes)
    return ColumnBatch(dict(c.data, **{"#rank": rank}), c.valid), total


def _exchange_by_rank(
    ctx: StageContext, b: ColumnBatch, per: int
) -> ColumnBatch:
    """Repartition rows so global rank r lands at partition r // per,
    locally sorted by rank (position i holds rank pid*per + i)."""
    rank = b.data["#rank"].astype(jnp.int32)
    dest = jnp.clip(rank // per, 0, ctx.P - 1)
    B = SH.bucket_capacity(b.capacity, ctx.P, ctx.slack * ctx.boost)
    out, ovf = _exchange(ctx, b, dest, ctx.P, B, ctx.axes)
    ctx.overflow = ctx.overflow | ovf
    out, ovf2 = SH.resize(out, per)
    ctx.overflow = ctx.overflow | ovf2
    return SORT.sort_batch_by_operands(out, [out.data["#rank"]])


def _k_zip(ctx: StageContext, p) -> None:
    """Pair rows by global position (LINQ Zip: truncate to shorter)."""
    left = ctx.slots[p["left_slot"]]
    right = ctx.slots[p["right_slot"]]
    per = _round8(max(ctx.base_cap(p["left_slot"]), ctx.base_cap(p["right_slot"])) * ctx.boost)
    lb, _lt = _rank_column(left, ctx.P, ctx.axes)
    rb, _rt = _rank_column(right, ctx.P, ctx.axes)
    la = _exchange_by_rank(ctx, lb, per)
    ra = _exchange_by_rank(ctx, rb, per)
    data: Dict[str, jax.Array] = {
        n: c for n, c in la.data.items() if n != "#rank"
    }
    for n, c in ra.data.items():
        if n == "#rank":
            continue
        data[J._suffixed(n, p["suffix"]) if n in data else n] = c
    valid = la.valid & ra.valid
    ctx.slots[p["left_slot"]] = ColumnBatch(data, valid)


def _k_sliding_window(ctx: StageContext, p) -> None:
    """Windows over the global row sequence with a cross-partition halo.

    Ring pass (the sequence-parallel halo-exchange pattern): each
    partition's (size-1)-row prefix rotates backward one step per hop
    for P-1 hops, so every partition observes the prefixes of ALL its
    successors — windows may span any number of (possibly empty)
    partitions.  Arrived rows are compacted valid-first in arrival
    order (= global row order) and the first size-1 fill the halo."""
    b = ctx.slots[p["slot"]].compact()
    w = int(p["size"])
    cap = b.capacity
    n_loc = jnp.sum(b.valid.astype(jnp.int32))

    need = w - 1
    halo_v = None
    halo_cols: Dict[str, jax.Array] = {}
    if need > 0 and ctx.P > 1:
        perm = [(i, i - 1) for i in range(1, ctx.P)]  # no wrap: sequence ends
        work_v = b.valid[:need]
        work_cols = {c: b.data[c][:need] for c in p["cols"]}
        arrived_v: List[jax.Array] = []
        arrived_cols: Dict[str, List[jax.Array]] = {c: [] for c in p["cols"]}
        for _hop in range(ctx.P - 1):
            work_v = jax.lax.ppermute(work_v, ctx.axes, perm)
            work_cols = {
                c: jax.lax.ppermute(col, ctx.axes, perm)
                for c, col in work_cols.items()
            }
            arrived_v.append(work_v)
            for c in p["cols"]:
                arrived_cols[c].append(work_cols[c])
        all_v = jnp.concatenate(arrived_v)
        # Stable sort by invalid flag keeps arrival (= global row) order
        # among valid rows; take the first `need` as the halo.
        operands = [all_v.astype(jnp.uint32) ^ jnp.uint32(1)] + [
            jnp.concatenate(arrived_cols[c]) for c in p["cols"]
        ] + [all_v]
        sorted_ops = jax.lax.sort(
            tuple(operands), num_keys=1, is_stable=True
        )
        halo_v = sorted_ops[-1][:need]
        for i, c in enumerate(p["cols"]):
            halo_cols[c] = sorted_ops[1 + i][:need]

    ext_len = cap + max(need, 0)
    out_cols: Dict[str, jax.Array] = {}
    ext_v = jnp.zeros((ext_len,), jnp.bool_)
    ext_v = jax.lax.dynamic_update_slice(ext_v, b.valid, (0,))
    if halo_v is not None:
        ext_v = jax.lax.dynamic_update_slice(ext_v, halo_v, (n_loc,))
    win_valid = jnp.ones((cap,), jnp.bool_)
    for j in range(w):
        win_valid = win_valid & ext_v[j : j + cap]

    for c in p["cols"]:
        col = b.data[c]
        ext = jnp.zeros((ext_len,), col.dtype)
        ext = jax.lax.dynamic_update_slice(ext, col, (0,))
        if halo_v is not None:
            ext = jax.lax.dynamic_update_slice(ext, halo_cols[c], (n_loc,))
        for j in range(w):
            out_cols[f"{c}_w{j}"] = ext[j : j + cap]

    ctx.slots[p["slot"]] = ColumnBatch(out_cols, win_valid)


# -- global ops ------------------------------------------------------------

def _strip_rank(b: ColumnBatch, keep: jax.Array) -> ColumnBatch:
    return ColumnBatch(
        {n: c for n, c in b.data.items() if n != "#rank"}, keep
    )


def _k_with_rank(ctx: StageContext, p) -> None:
    """Attach each row's global engine-order rank as an int32 column
    (the indexed-operator analog: reference LongSelect / indexed
    Select/Where overloads, ``DryadLinqQueryGen.cs`` LongSelect
    dispatch)."""
    b, _total = _rank_column(ctx.slots[p["slot"]], ctx.P, ctx.axes)
    rank = b.data["#rank"].astype(jnp.int32)
    out = {n: c for n, c in b.data.items() if n != "#rank"}
    out[p["out"]] = jnp.where(b.valid, rank, 0)
    ctx.slots[p["slot"]] = ColumnBatch(out, b.valid)


def _k_take(ctx: StageContext, p) -> None:
    b, _total = _rank_column(ctx.slots[p["slot"]], ctx.P, ctx.axes)
    rank = b.data["#rank"]
    keep = b.valid & (rank < jnp.uint32(p["n"]))
    ctx.slots[p["slot"]] = _strip_rank(b, keep)


def _k_skip(ctx: StageContext, p) -> None:
    """Drop the first n rows of global engine order (reference Skip)."""
    b, _total = _rank_column(ctx.slots[p["slot"]], ctx.P, ctx.axes)
    keep = b.valid & (b.data["#rank"] >= jnp.uint32(p["n"]))
    ctx.slots[p["slot"]] = _strip_rank(b, keep)


def _k_tail(ctx: StageContext, p) -> None:
    """Keep the last n rows of global engine order (Last/TakeLast shape,
    reference Last/LastOrDefault dispatch ``DryadLinqQueryGen.cs``)."""
    b, total = _rank_column(ctx.slots[p["slot"]], ctx.P, ctx.axes)
    cut = jnp.maximum(total - jnp.int32(p["n"]), 0).astype(jnp.uint32)
    keep = b.valid & (b.data["#rank"] >= cut)
    ctx.slots[p["slot"]] = _strip_rank(b, keep)


def _first_false_rank(
    b: ColumnBatch, pred: jax.Array, total: jax.Array, axes: Tuple[str, ...]
) -> jax.Array:
    """Global rank of the first valid row failing ``pred`` (= total if
    every row passes)."""
    rank = b.data["#rank"]
    failing = jnp.where(
        b.valid & jnp.logical_not(pred), rank, jnp.uint32(0xFFFFFFFF)
    )
    local_min = jnp.min(failing)
    global_min = jax.lax.pmin(local_min, axes)
    return jnp.minimum(global_min, total.astype(jnp.uint32))


def _k_take_while(ctx: StageContext, p) -> None:
    """Rows strictly before the first predicate failure (TakeWhile)."""
    b, total = _rank_column(ctx.slots[p["slot"]], ctx.P, ctx.axes)
    pred = p["fn"]({n: c for n, c in b.data.items() if n != "#rank"})
    cut = _first_false_rank(b, pred, total, ctx.axes)
    keep = b.valid & (b.data["#rank"] < cut)
    ctx.slots[p["slot"]] = _strip_rank(b, keep)


def _k_skip_while(ctx: StageContext, p) -> None:
    """Rows from the first predicate failure onward (SkipWhile)."""
    b, total = _rank_column(ctx.slots[p["slot"]], ctx.P, ctx.axes)
    pred = p["fn"]({n: c for n, c in b.data.items() if n != "#rank"})
    cut = _first_false_rank(b, pred, total, ctx.axes)
    keep = b.valid & (b.data["#rank"] >= cut)
    ctx.slots[p["slot"]] = _strip_rank(b, keep)


def _k_reverse(ctx: StageContext, p) -> None:
    """Globally reverse engine row order (reference Reverse,
    ``DryadLinqQueryGen.cs:2731``): invert each row's global rank and
    repartition by the inverted rank."""
    b, total = _rank_column(ctx.slots[p["slot"]], ctx.P, ctx.axes)
    inv = (total.astype(jnp.uint32) - jnp.uint32(1)) - b.data["#rank"]
    inv = jnp.where(b.valid, inv, jnp.uint32(0xFFFFFFFF))
    b = ColumnBatch(dict(b.data, **{"#rank": inv}), b.valid)
    per = _round8(ctx.base_cap(p["slot"]) * ctx.boost)
    out = _exchange_by_rank(ctx, b, per)
    ctx.slots[p["slot"]] = _strip_rank(out, out.valid)


def _k_default_if_empty(ctx: StageContext, p) -> None:
    """If the table is globally empty, emit one default row on partition
    0 (reference DefaultIfEmpty)."""
    b = ctx.slots[p["slot"]].compact()
    total = jax.lax.psum(jnp.sum(b.valid.astype(jnp.int32)), ctx.axes)
    me = jax.lax.axis_index(ctx.axes)
    emit = (total == 0) & (me == 0)
    data = {}
    for name, col in b.data.items():
        dflt = jnp.asarray(p["defaults"].get(name, 0), col.dtype)
        data[name] = jnp.where(
            emit, col.at[0].set(dflt), col
        )
    valid = jnp.where(emit, b.valid.at[0].set(True), b.valid)
    ctx.slots[p["slot"]] = ColumnBatch(data, valid)


def _global_pair_reduce(
    ctx: StageContext, op: str, b: ColumnBatch, lo_col: str, v: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    """Mesh-wide 64-bit word-pair reduce: per-partition pair reduce,
    all_gather the P partial pairs (psum can't carry 64 bits), reduce
    the gathered pairs the same way.  All-invalid partitions contribute
    the op identity (neutral), so the gathered reduce needs no
    validity."""
    hi_col = lo_col[: -len("#h0")] + "#h1"
    plo, phi = SEG.pair_scalar_reduce(
        op, b.data[lo_col], b.data[hi_col], v
    )
    glo = jax.lax.all_gather(plo[None], ctx.axes, tiled=True)
    ghi = jax.lax.all_gather(phi[None], ctx.axes, tiled=True)
    return SEG.pair_scalar_reduce(
        op, glo, ghi, jnp.ones(glo.shape, jnp.bool_)
    )


def _k_scalar_agg(ctx: StageContext, p) -> None:
    b = ctx.slots[p["slot"]]
    v = b.valid
    out: Dict[str, jax.Array] = {}
    for a in p["aggs"]:
        if a.op == "count":
            loc = jnp.sum(v.astype(jnp.int32))
            out[a.out] = jax.lax.psum(loc, ctx.axes)[None]
        elif a.op == "sum":
            col = b.data[a.col]
            loc = jnp.sum(jnp.where(v, col, jnp.zeros((), col.dtype)))
            out[a.out] = jax.lax.psum(loc, ctx.axes)[None]
        elif a.op == "min":
            col = b.data[a.col]
            big = _dtype_max(col.dtype)
            loc = jnp.min(jnp.where(v, col, big))
            out[a.out] = jax.lax.pmin(loc, ctx.axes)[None]
        elif a.op == "max":
            col = b.data[a.col]
            small = _dtype_min(col.dtype)
            loc = jnp.max(jnp.where(v, col, small))
            out[a.out] = jax.lax.pmax(loc, ctx.axes)[None]
        elif a.op == "mean":
            col = b.data[a.col].astype(jnp.float32)
            s = jax.lax.psum(jnp.sum(jnp.where(v, col, 0.0)), ctx.axes)
            c = jax.lax.psum(jnp.sum(v.astype(jnp.float32)), ctx.axes)
            out[a.out] = (s / jnp.maximum(c, 1.0))[None]
        elif a.op == "mean64":
            # Average over long: exact global sum64, f32 divide
            tlo, thi = _global_pair_reduce(ctx, "sum64", b, a.col, v)
            c = jax.lax.psum(jnp.sum(v.astype(jnp.float32)), ctx.axes)
            out[a.out] = (
                SEG.pair_to_f32(tlo, thi) / jnp.maximum(c, 1.0)
            )[None]
        elif a.op in SEG.PAIR_OPS:
            # 64-bit scalar over a split column
            tlo, thi = _global_pair_reduce(ctx, a.op, b, a.col, v)
            out[f"{a.out}#h0"] = tlo[None]
            out[f"{a.out}#h1"] = thi[None]
        elif a.op == "any":
            col = b.data[a.col]
            loc = jnp.any(v & col).astype(jnp.int32)
            out[a.out] = (jax.lax.psum(loc, ctx.axes) > 0)[None]
        elif a.op == "all":
            col = b.data[a.col]
            loc = jnp.all(jnp.where(v, col, True)).astype(jnp.int32)
            out[a.out] = (jax.lax.psum(loc, ctx.axes) >= ctx.P)[None]
        else:
            raise ValueError(f"unknown scalar agg {a.op!r}")
    me = jax.lax.axis_index(ctx.axes)
    valid = (me == 0)[None]
    ctx.slots[p["slot"]] = ColumnBatch(out, valid)


def _k_fork(ctx: StageContext, p) -> None:
    b = ctx.slots[p["slot"]]
    outs = p["fn"](b)
    if len(outs) != p["n_out"]:
        raise ValueError(f"fork fn returned {len(outs)} outputs, expected {p['n_out']}")
    for slot, ob in zip(p["out_slots"], outs):
        if not isinstance(ob, ColumnBatch):
            raise TypeError("fork fn must return ColumnBatches")
        ctx.slots[slot] = ob


def _dtype_max(dt):
    if jnp.issubdtype(dt, jnp.floating):
        return jnp.array(jnp.inf, dt)
    return jnp.array(jnp.iinfo(dt).max, dt)


def _dtype_min(dt):
    if jnp.issubdtype(dt, jnp.floating):
        return jnp.array(-jnp.inf, dt)
    return jnp.array(jnp.iinfo(dt).min, dt)


_KERNELS = {
    "select": _k_select,
    "where": _k_where,
    "project": _k_project,
    "seed": _k_seed,
    "select_many": _k_select_many,
    "apply": _k_apply,
    "exchange_hash": _k_exchange_hash,
    "exchange_range": _k_exchange_range,
    "resize": _k_resize,
    "group_reduce": _k_group_reduce,
    "group_reduce_dense": _k_group_reduce_dense,
    "string_code": _k_string_code,
    "group_combine": _k_group_combine,
    "distinct": _k_distinct,
    "local_sort": _k_local_sort,
    "topk": _k_topk,
    "join": _k_join,
    "semi": _k_semi,
    "concat": _k_concat,
    "take": _k_take,
    "with_rank": _k_with_rank,
    "skip": _k_skip,
    "tail": _k_tail,
    "take_while": _k_take_while,
    "skip_while": _k_skip_while,
    "reverse": _k_reverse,
    "default_if_empty": _k_default_if_empty,
    "scalar_agg": _k_scalar_agg,
    "fork": _k_fork,
    "group_join_count": _k_group_join_count,
    "join_ranked": _k_join_ranked,
    "zip": _k_zip,
    "sliding_window": _k_sliding_window,
}


def build_fused_fn(fused, P: int, slack: float, boost: int,
                   axes: "Tuple[str, ...]" = (AXIS,),
                   axis_sizes: "Tuple[int, ...]" = (),
                   operand_objs: "Tuple[Any, ...]" = (),
                   window: int = 0,
                   xchg_cell: "List[Dict[str, int]]" = None):
    """Compose a whole fused REGION (``plan.fuse.FusedStage``) into one
    per-partition function: the member stage fns chain device-resident
    — member i's output batches feed member j's slots directly in HBM,
    exchanges at the seams stay ``ops/shuffle`` collectives inside the
    one ``shard_map`` region, and the driver never touches the
    boundary.  This body must stay free of host-transfer APIs
    (``np.asarray`` / ``.item()`` / ``jax.device_get``) — enforced
    statically by ``tests/test_fuse_lint.py``.

    Overflow/miss contract: the region's overflow flag is the OR over
    every member's (already mesh-reduced) flag and the dict-miss count
    is the sum — one seam overflowing retries the WHOLE region at the
    next palette boost, the same bounded-palette contract as the
    single-stage path.

    ``operand_objs``: the region's deduplicated OPERAND-registered
    param objects in ``stage_operand_objs(fused)`` order (the chained
    member enumeration); each member fn receives exactly its own
    objects' arrays, so one table shared by two members uploads once.
    """
    members = fused.members
    member_objs = [
        tuple(stage_operand_objs(m)) if operand_objs else ()
        for m in members
    ]
    # Per-member exchange-round accounting cells; each member fn
    # rewrites its own cell idempotently at trace time, and the region
    # fn flattens them in member order into the caller's cell.
    member_cells = [[] for _ in members]
    member_fns = [
        build_stage_fn(
            m, P, slack, boost, axes, axis_sizes,
            operand_objs=member_objs[i],
            window=window, xchg_cell=member_cells[i],
        )
        for i, m in enumerate(members)
    ]

    def fn(sharded_inputs, replicated):
        rep = tuple(replicated)
        rep_map = {}
        pos = 0
        for obj in operand_objs:
            n = obj.operand_arity
            rep_map[id(obj)] = rep[pos:pos + n]
            pos += n
        if pos != len(rep):
            raise ValueError(
                f"fused region {fused.name!r}: {len(rep)} replicated "
                f"operand arrays for {pos} registered operand slots"
            )
        ext = tuple(sharded_inputs)
        member_outs: List[Tuple] = []
        overflow = None
        miss = None
        for i, mfn in enumerate(member_fns):
            ins = tuple(
                ext[src[1]] if src[0] == "ext"
                else member_outs[src[1]][src[2]]
                for src in fused.wiring[i]
            )
            mrep = tuple(
                a for obj in member_objs[i] for a in rep_map[id(obj)]
            )
            outs, (m_ovf, m_miss) = mfn(ins, mrep)
            member_outs.append(outs)
            overflow = m_ovf if overflow is None else (overflow | m_ovf)
            miss = m_miss if miss is None else (miss + m_miss)
        region_outs = tuple(
            member_outs[mi][oi] for mi, oi in fused.exports
        )
        if xchg_cell is not None:
            xchg_cell[:] = [r for c in member_cells for r in c]
        return region_outs, (overflow, miss)

    return fn


def build_stage_fn(stage, P: int, slack: float, boost: int,
                   axes: "Tuple[str, ...]" = (AXIS,),
                   axis_sizes: "Tuple[int, ...]" = (),
                   operand_objs: "Tuple[Any, ...]" = (),
                   window: int = 0,
                   xchg_cell: "List[Dict[str, int]]" = None):
    """Compose the stage's ops into one per-partition function.

    ``operand_objs``: the stage's OPERAND-registered param objects (in
    ``stage_operand_objs`` order) whose arrays arrive flattened through
    the replicated input slot at call time instead of being baked as
    trace constants; empty = the legacy baked path (every caller that
    passes operands must feed the matching arrays on every call)."""

    def fn(sharded_inputs, replicated):
        ctx = StageContext(P, slack, boost, axes, axis_sizes, window)
        ctx.bind_inputs(tuple(sharded_inputs))
        rep = tuple(replicated)
        pos = 0
        for obj in operand_objs:
            n = obj.operand_arity
            ctx.operand_map[id(obj)] = rep[pos:pos + n]
            pos += n
        if pos != len(rep):
            raise ValueError(
                f"stage {stage.name!r}: {len(rep)} replicated operand "
                f"arrays for {pos} registered operand slots"
            )
        for op in stage.ops:
            if op.kind == "do_while":
                raise RuntimeError("do_while stages are driver-evaluated")
            apply_op(ctx, op.kind, op.params)
        outs = tuple(ctx.slots[s] for s in stage.out_slots)
        # Overflow flags from resize/join are per-device; reduce across the
        # mesh so the replicated output is truly uniform (a silently
        # device-local flag loses rows without tripping the retry).
        overflow = jax.lax.psum(ctx.overflow.astype(jnp.int32), axes) > 0
        miss = jax.lax.psum(ctx.dict_miss, axes)
        if xchg_cell is not None:
            # Idempotent rewrite (not append): a retrace must not
            # double-count the static accounting.
            xchg_cell[:] = list(ctx.xchg_log)
        return outs, (overflow, miss)

    return fn
