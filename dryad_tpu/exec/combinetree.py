"""Topology- and distribution-aware combine trees.

Dryad's signature runtime trick was rewriting aggregation trees so
partial reduces happen close to the data before crossing slow links
(``DrDynamicAggregateManager.h`` machine->pod->overall).  The streaming
engine's combine path was flat: every accumulator flush was one N-ary
concat+``group_by`` whose hash exchange crossed the WHOLE mesh — on a
hybrid (DCN x ICI) mesh that is one DCN crossing per flush — with an
all-or-nothing host degrade when merges stopped reducing.

This module rebuilds that path around two observations:

1. **Topology** — per-chunk partial batches are already co-hash-
   partitioned on the group keys (every chunk's partial ``group_by``
   used the same deterministic hash over the same mesh), so equal keys
   are COLOCATED across chunks and an intermediate merge needs no
   exchange at all: concat + one local ``group_reduce``
   (``assume_hash_partition`` elision) moves zero bytes over ICI or
   DCN.  Only the FINAL fold pays one full exchange — which on a hybrid
   mesh rides the tree exchange (``exec.kernels._tree_exchange_hash``):
   one ICI hop, per-slice combine, exactly one DCN hop last.

2. **Distribution** — partials are placed onto tree groups by
   key-histogram similarity (PAPERS.md "Chasing Similarity"): chunks
   with similar key distributions merge early because they collapse
   more.  The same coarse per-key-range histograms
   (:class:`obs.metrics.KeyRangeHistogram`) drive PER-KEY-RANGE host
   degradation (PAPERS.md "Partial Partial Aggregates": partial
   reduction pays even when keys only partly collapse): a range whose
   distinct-key estimate tracks its row count never reduces under
   merging and streams to host accumulation, while hot, still-reducing
   ranges stay on device.

Layering: the device combine path here must stay free of host
transfers (``np.asarray`` / ``.item()`` / ``jax.device_get``) and this
module must never import ``cluster.*`` — the gang driver imports the
PLANNER from here, not the other way around.  Placement decisions read
histogram SNAPSHOTS (:meth:`KeyRangeHistogram.snapshot` dicts) only,
never raw tables or batch payloads (``tests/test_combinetree_lint.py``
enforces all three).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from dryad_tpu.parallel.mesh import (
    dcn_slice_count,
    ici_partitions_per_slice,
)

# evidence floor: a key range must have shown at least this many rows
# before its reduction estimate may degrade it to host accumulation
MIN_DEGRADE_ROWS = 512


# -- tree shape / byte accounting -------------------------------------------


class TreeShape:
    """Mesh-derived tree geometry: level-0 group count and the
    ICI/DCN extents the byte estimator splits exchange traffic over."""

    __slots__ = ("groups", "dcn_slices", "ici_partitions", "fan")

    def __init__(self, mesh, config) -> None:
        self.dcn_slices = dcn_slice_count(mesh)
        self.ici_partitions = ici_partitions_per_slice(mesh)
        g = int(getattr(config, "combine_tree_groups", 0) or 0)
        # auto: one level-0 group per DCN slice keeps every pre-fold
        # merge off the DCN; flat meshes get a small similarity fan
        self.groups = g if g > 0 else max(self.dcn_slices, 1)
        if self.groups == 1 and self.dcn_slices == 1:
            self.groups = 4
        self.fan = max(2, int(getattr(config, "combine_tree_fan", 16)))

    def exchange_split(self, in_bytes: int, out_bytes: int) -> Tuple[int, int]:
        """Estimated (ici_bytes, dcn_bytes) one full hash exchange
        moves for a merge of ``in_bytes`` of partial layout folding to
        ``out_bytes``.  On a hybrid mesh the tree exchange pays hop 1
        over ICI at input volume and hop 2 over DCN at the per-slice
        combined volume; a flat mesh has no DCN at all.  Uniform-hash
        destinations make a (n-1)/n fraction of rows cross."""
        d, p = self.dcn_slices, self.ici_partitions
        ici = in_bytes * (p - 1) // p if p > 1 else 0
        dcn = (
            min(in_bytes, out_bytes) * (d - 1) // d if d > 1 else 0
        )
        return ici, dcn


def batch_bytes(batch) -> int:
    """Layout bytes of a device batch — shape metadata only, no
    readback (``nbytes`` never syncs the dispatch loop)."""
    return sum(int(v.nbytes) for v in batch.data.values()) + int(
        batch.valid.nbytes
    )


def neutral_snapshot(ranges: int) -> Dict[str, Any]:
    """Histogram snapshot for a chunk whose keys cannot be hashed
    host-side (physical pre-encoded chunks): zero counts everywhere, so
    similarity placement treats it as shapeless (empty-group preferred)
    and the degrade planner never acts on it."""
    return {
        "ranges": ranges,
        "rows": 0,
        "counts": [0] * ranges,
        "distinct": [0] * ranges,
        "reduction_ratios": [0.0] * ranges,
    }


# -- similarity placement (snapshot-only) -----------------------------------


def _cosine(a, b) -> float:
    """Cosine similarity of two per-range count vectors; 0 when either
    is empty.  Plain-python fold so the lint can see no table access."""
    dot = na = nb = 0.0
    for x, y in zip(a, b):
        fx, fy = float(x), float(y)
        dot += fx * fy
        na += fx * fx
        nb += fy * fy
    if na <= 0.0 or nb <= 0.0:
        return 0.0
    return dot / ((na ** 0.5) * (nb ** 0.5))


def place(snapshot: Dict[str, Any], centroids: Sequence[Any]) -> int:
    """Pick the tree group for one partial from its key-range snapshot:
    the group whose accumulated count vector is most SIMILAR (similar
    distributions collapse more under merging), preferring an empty
    group over a dissimilar one.  Reads the snapshot dict only."""
    counts = snapshot["counts"]
    best, best_sim, empty = -1, -1.0, -1
    for gi, cent in enumerate(centroids):
        if cent is None:
            if empty < 0:
                empty = gi
            continue
        sim = _cosine(counts, cent)
        if sim > best_sim:
            best, best_sim = gi, sim
    if best_sim <= 0.0 and empty >= 0:
        return empty  # empty group beats any fully-dissimilar one
    return max(best, 0)


def plan_groups(
    snapshots: Sequence[Dict[str, Any]], n_groups: int
) -> List[List[int]]:
    """Similarity grouping of N partials into at most ``n_groups``
    merge groups (the gang driver's level-0 plan): greedy placement of
    each snapshot against running centroids, exactly the device tree's
    routing applied post-hoc.  Reads snapshots only."""
    n_groups = max(1, min(n_groups, len(snapshots)))
    groups: List[List[int]] = [[] for _ in range(n_groups)]
    centroids: List[Optional[List[float]]] = [None] * n_groups
    for i, snap in enumerate(snapshots):
        gi = place(snap, centroids)
        groups[gi].append(i)
        counts = snap["counts"]
        if centroids[gi] is None:
            centroids[gi] = [float(c) for c in counts]
        else:
            cent = centroids[gi]
            for r, c in enumerate(counts):
                cent[r] += float(c)
    return [g for g in groups if g]


# -- per-key-range degrade planner ------------------------------------------


class CombineTreePlanner:
    """Accumulates the stream's key-range distribution and decides
    which ranges stop paying for device merging.

    A range degrades when its cumulative distinct-key estimate is at
    least ``degrade_ratio`` of its cumulative row count (merging keeps
    >= that fraction of rows — the per-range analog of the flat
    combiner's 3/4 capacity check) once it has ``MIN_DEGRADE_ROWS`` of
    evidence.  Decisions consume histogram snapshots only."""

    def __init__(self, ranges: int, degrade_ratio: float) -> None:
        self.ranges = ranges
        self.degrade_ratio = float(degrade_ratio)
        self._counts = [0] * ranges
        self._distinct = [0.0] * ranges
        self._degraded: set = set()

    def note_chunk(self, snapshot: Dict[str, Any]) -> None:
        """Fold one chunk's snapshot into the cumulative view.  The
        cumulative distinct estimate per range is the max of per-chunk
        estimates and the running sum-of-new-mass lower bound is
        skipped: summing per-chunk distinct OVERCOUNTS recurring keys,
        which is exactly the signal — a range where the per-chunk sum
        keeps growing ahead of any one chunk's estimate is recurring
        (reducible), one where counts and distinct grow in lockstep is
        not."""
        counts = snapshot["counts"]
        distinct = snapshot["distinct"]
        for r in range(self.ranges):
            self._counts[r] += int(counts[r])
            self._distinct[r] = max(self._distinct[r], float(distinct[r]))

    def note_cumulative(self, snapshot: Dict[str, Any]) -> None:
        """Replace the cumulative view with an already-merged stream
        snapshot (the driver keeps ONE merged histogram; its distinct
        estimates span the whole stream)."""
        counts = snapshot["counts"]
        distinct = snapshot["distinct"]
        for r in range(self.ranges):
            self._counts[r] = int(counts[r])
            self._distinct[r] = float(distinct[r])

    def degrade_set(self) -> set:
        """Ranges that should stream to host accumulation (monotone:
        once degraded a range stays degraded for the stream — the
        re-probe lever for the FLAT host path lives in the driver)."""
        for r in range(self.ranges):
            if r in self._degraded:
                continue
            c = self._counts[r]
            if c < MIN_DEGRADE_ROWS:
                continue
            if self._distinct[r] >= self.degrade_ratio * c:
                self._degraded.add(r)
        return set(self._degraded)

    def degraded_fraction(self) -> float:
        return len(self._degraded) / float(self.ranges)


# -- the device-side tree combiner ------------------------------------------


class TreeCombiner:
    """Hierarchical accumulator of device-resident partial batches.

    Level 0: per-group pending lists, routed by :func:`place`; a group
    flush is ONE elided N-ary concat+local-reduce (``merge_local`` —
    zero collective bytes, stable fan-in, compile reuse).  Level 1:
    flushed representatives; when they pile past the fan they fold
    through ``merge_local`` again (still exchange-free — partials stay
    co-partitioned under local reduction).  The single exchanged merge
    is the CALLER's final fold+finalize query — the one DCN hop.

    No capacity-based reduction check lives here: whether device
    merging pays is the planner's per-key-range call, made from
    histogram snapshots before batches ever reach the tree."""

    def __init__(
        self,
        merge_local: Callable[[List[Any]], Any],
        shape: TreeShape,
        combine_rows: int,
        emit: Callable[..., None],
    ) -> None:
        self._merge_local = merge_local
        self._shape = shape
        self._combine_rows = max(1, int(combine_rows))
        self._emit = emit
        self._pending: List[List[Any]] = [[] for _ in range(shape.groups)]
        self._caps: List[int] = [0] * shape.groups
        self._centroids: List[Optional[List[float]]] = [None] * shape.groups
        self._reps: List[Any] = []
        self.combines = 0
        self.max_level = 0

    def _group_threshold(self) -> int:
        # divide the row budget over the groups HOLDING batches, not all
        # groups: a low-skew stream routes every partial to one group,
        # and billing that group a 1/groups share would flush 4x more
        # eagerly than the flat baseline for the same HBM bound.  Total
        # held rows stay <= combine_rows either way.
        active = sum(1 for p in self._pending if p) or 1
        return max(1, self._combine_rows // active)

    def push(self, batch, snapshot: Dict[str, Any]) -> None:
        """Route one partial batch to its similarity group; flush the
        group when its layout rows pass the per-group threshold or the
        fan cap.  Never signals degrade — that is the planner's job."""
        gi = place(snapshot, self._centroids)
        self._pending[gi].append(batch)
        self._caps[gi] += int(batch.capacity)
        counts = snapshot["counts"]
        if self._centroids[gi] is None:
            self._centroids[gi] = [float(c) for c in counts]
        else:
            cent = self._centroids[gi]
            for r, c in enumerate(counts):
                cent[r] += float(c)
        if (
            len(self._pending[gi]) >= 2
            and (
                self._caps[gi] > self._group_threshold()
                or len(self._pending[gi]) >= self._shape.fan
            )
        ):
            self._flush_group(gi)
        if len(self._reps) >= self._shape.fan:
            self._fold_reps()

    def _flush_group(self, gi: int) -> None:
        batches = self._pending[gi]
        in_bytes = sum(batch_bytes(b) for b in batches)
        fan = len(batches)
        merged = self._merge_local(batches)
        self.combines += 1
        self._pending[gi] = []
        self._caps[gi] = 0
        self._reps.append(merged)
        self._emit(
            "combine_tree_level", level=0, group=gi, fan_in=fan,
            cap_rows=int(merged.capacity), bytes=in_bytes,
            ici_bytes=0, dcn_bytes=0, device=True,
        )

    def _fold_reps(self) -> None:
        """Collapse level-1 representatives with another elided merge —
        representatives are still co-partitioned partials, so no
        exchange is due yet."""
        reps = self._reps
        in_bytes = sum(batch_bytes(b) for b in reps)
        fan = len(reps)
        merged = self._merge_local(reps)
        self.combines += 1
        self.max_level = max(self.max_level, 1)
        self._reps = [merged]
        self._emit(
            "combine_tree_level", level=1, fan_in=fan,
            cap_rows=int(merged.capacity), bytes=in_bytes,
            ici_bytes=0, dcn_bytes=0, device=True,
        )

    def drain(self) -> List[Any]:
        """All held batches (per-range degrade hands the remainder to
        the host path); the tree is empty afterwards."""
        out: List[Any] = []
        for gi in range(len(self._pending)):
            out.extend(self._pending[gi])
            self._pending[gi] = []
            self._caps[gi] = 0
        out.extend(self._reps)
        self._reps = []
        return out

    def fold(self, width: int = 1):
        """The surviving partials reduced via elided merges (bounded fan
        per program) down to at most ``max(width, 1)`` batches; empty
        list when nothing was pushed.  Elided merges are nearly free,
        while whatever the caller does next — the exchanged root
        reduction, or a D2H into host accumulation — pays per byte it
        ingests, so callers fold to 1 and hand the minimum onward."""
        left = self.drain()
        while len(left) > max(1, width):
            take = left[: self._shape.fan]  # always >= 2 (fan >= 2)
            left = left[self._shape.fan:]
            in_bytes = sum(batch_bytes(b) for b in take)
            merged = self._merge_local(take)
            self.combines += 1
            self.max_level = max(self.max_level, 1)
            self._emit(
                "combine_tree_level", level=1, fan_in=len(take),
                cap_rows=int(merged.capacity), bytes=in_bytes,
                ici_bytes=0, dcn_bytes=0, device=True,
            )
            left.append(merged)
        return left
