"""Failure taxonomy, retry policy, and structured job failure.

The reference's job manager distinguishes *where* a failure came from
before deciding what to do about it: vertices re-execute under a
version budget (``DrVertexRecord.h:164-194``), machines that repeatedly
produce failures are blacklisted so the retries land elsewhere
(``DrGraph.h:42`` failure accounting), and capacity problems re-shape
the graph rather than retrying blindly.  This module is that decision
layer for the TPU framework:

- :class:`FailureKind` — the failure domains:

  * ``TRANSIENT``: injected faults, worker death, unreadable or
    corrupt checkpoints — re-execution on (possibly different)
    resources is expected to succeed;
  * ``DETERMINISTIC``: the same exception class + message reproduced
    on a *different* computer — retrying elsewhere cannot help, fail
    fast with the history instead of burning the budget;
  * ``RESOURCE``: capacity-shaped outcomes (shuffle/join overflow) —
    handled by the executor's boost palette, never by blind retry.

- :class:`RetryPolicy` — exponential backoff with **seeded** jitter
  (deterministic per (seed, key, attempt), so chaos runs replay
  bit-identically) and a per-stage attempt budget.

- :class:`JobFailedError` — the structured terminal error carrying the
  full :class:`Attempt` history, so a failed job is post-mortem
  inspectable (``tools/jobview`` renders the same history from the
  event log).
"""

from __future__ import annotations

import dataclasses
import enum
import random
from typing import List, Optional, Sequence


class FailureKind(enum.Enum):
    """Failure domain of one failed attempt."""

    TRANSIENT = "transient"
    DETERMINISTIC = "deterministic"
    RESOURCE = "resource"


@dataclasses.dataclass
class Attempt:
    """Record of one failed attempt (the DrVertexRecord version entry)."""

    number: int
    error_type: str
    error: str
    kind: str = FailureKind.TRANSIENT.value
    computer: Optional[str] = None
    backoff: float = 0.0

    def describe(self) -> str:
        where = f" on {self.computer}" if self.computer else ""
        wait = f", backoff {self.backoff:.3f}s" if self.backoff else ""
        return (
            f"attempt {self.number}{where}: {self.error_type}: "
            f"{self.error} [{self.kind}{wait}]"
        )


class StageFailedError(RuntimeError):
    """A stage reached a terminal failure (budget, capacity, guard)."""


class CheckpointCorruptionError(StageFailedError):
    """A persisted checkpoint failed its integrity check (CRC
    mismatch).  TRANSIENT: the caller recomputes instead of loading."""


class JobFailedError(StageFailedError):
    """Terminal job failure carrying the full attempt history."""

    def __init__(
        self,
        message: str,
        stage: Optional[str] = None,
        attempts: Sequence[Attempt] = (),
    ):
        self.stage = stage
        self.attempts: List[Attempt] = list(attempts)
        if self.attempts:
            message += "\nattempt history:\n" + "\n".join(
                "  " + a.describe() for a in self.attempts
            )
        super().__init__(message)


def classify(
    error: BaseException,
    history: Sequence[Attempt],
    computer: Optional[str] = None,
) -> FailureKind:
    """Classify a new failure against the attempt history so far.

    A failure whose exception class AND message reproduce an earlier
    attempt's is DETERMINISTIC when the earlier attempt ran on a
    different computer (or when neither side names a computer — the
    single-driver executor, where "elsewhere" does not exist and an
    identical repeat is already proof).  Everything else is TRANSIENT;
    RESOURCE failures (overflow) never reach this function — the
    executor's boost palette owns them.
    """
    et, em = type(error).__name__, str(error)
    for a in history:
        if a.error_type != et or a.error != em:
            continue
        if computer is None or a.computer is None or a.computer != computer:
            return FailureKind.DETERMINISTIC
    return FailureKind.TRANSIENT


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with seeded jitter and an attempt budget.

    ``backoff(key, failures)`` is deterministic in (seed, key,
    failures): chaos suites replay the exact same schedule per seed,
    and two stages with the same failure count still spread out
    (the jitter term de-correlates their retry storms).
    """

    max_attempts: int = 3
    backoff_base: float = 0.05
    backoff_max: float = 2.0
    jitter: float = 0.5
    seed: int = 0

    def backoff(self, key: str, failures: int) -> float:
        """Seconds to wait before the retry after ``failures`` (>= 1)
        consecutive failures of ``key``."""
        raw = min(
            self.backoff_base * (2 ** max(failures - 1, 0)),
            self.backoff_max,
        )
        # random.Random(str) seeds via sha512: stable across processes
        # (hash() is salted per-process and would break replay)
        rng = random.Random(f"{self.seed}:{key}:{failures}")
        return raw * (1.0 + self.jitter * rng.random())

    def exhausted(self, failures: int) -> bool:
        return failures >= self.max_attempts
