"""LocalDebug — a NumPy interpreter over the logical plan.

The analog of the reference's LocalDebug provider, which runs the same
query through LINQ-to-Objects in-process for semantics debugging
(``DryadLinqContext.cs:966-983``, ``DryadLinqQuery.cs:55-137``).  This
interpreter executes logical nodes directly on dense host arrays with
independent (non-XLA) implementations, so differential tests can compare
the distributed engine against it.

Tables here are dicts of *physical* dense numpy columns (no validity
mask — rows are materialized).  User fns receive numpy-backed dicts and
may use jnp ops; outputs are converted back with ``np.asarray``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Tuple

import numpy as np

from dryad_tpu.columnar.batch import ColumnBatch
from dryad_tpu.columnar.schema import Schema
from dryad_tpu.plan import keys as K
from dryad_tpu.plan.nodes import Node, walk

Table = Dict[str, np.ndarray]


def _rows(t: Table) -> int:
    for v in t.values():
        return len(v)
    return 0


def _take_rows(t: Table, idx) -> Table:
    return {k: np.asarray(v)[idx] for k, v in t.items()}


def _call(fn: Callable, cols: Table) -> Dict[str, np.ndarray]:
    out = fn({k: v for k, v in cols.items()})
    return {k: np.asarray(v) for k, v in out.items()}


def _join_split_col(t: Table, col: str) -> np.ndarray:
    """Signed-int64 view of a split (#h0/#h1) column's word pairs."""
    from dryad_tpu.columnar.schema import join64

    return join64(
        np.asarray(t[f"{col}#h0"]), np.asarray(t[f"{col}#h1"]), signed=True
    )


def _key_tuples(t: Table, cols: List[str]) -> List[tuple]:
    arrs = [np.asarray(t[c]) for c in cols]
    return list(zip(*[a.tolist() for a in arrs])) if arrs else [()] * _rows(t)


class LocalDebugInterpreter:
    def __init__(self, ctx):
        self.ctx = ctx
        self.cache: Dict[int, Any] = {}

    # -- public -------------------------------------------------------------
    def run_to_logical(self, root: Node) -> Dict[str, np.ndarray]:
        table = self.run(root)
        return self._decode(table, root.schema)

    def run(self, root: Node) -> Table:
        for node in walk([root]):
            if node.id not in self.cache:
                self.cache[node.id] = self._eval(node)
        val = self.cache[root.id]
        if isinstance(val, tuple):  # fork outputs
            raise RuntimeError("cannot collect a fork node directly")
        return val

    def _decode(self, table: Table, schema: Schema) -> Dict[str, np.ndarray]:
        import jax.numpy as jnp

        n = _rows(table)
        b = ColumnBatch(
            {k: jnp.asarray(v) for k, v in table.items()},
            jnp.ones((n,), jnp.bool_),
        )
        return b.to_numpy(schema, self.ctx.dictionary)

    # -- node dispatch ------------------------------------------------------
    def _eval(self, node: Node) -> Any:
        m = getattr(self, f"_n_{node.kind}", None)
        if m is None:
            raise NotImplementedError(f"localdebug: node kind {node.kind!r}")
        return m(node)

    def _in(self, node: Node, i: int = 0) -> Table:
        return self.cache[node.inputs[i].id]

    # -- inputs -------------------------------------------------------------
    def _n_input(self, node: Node) -> Table:
        if node.id not in self.ctx._bindings:
            raise RuntimeError(
                f"input node {node.id} has no binding: the cached table "
                "was released — re-run .cache() or re-ingest"
            )
        kind, *rest = self.ctx._bindings[node.id]
        if kind == "host":
            arrays, _cap = rest
            n = _rows({k: np.asarray(v) for k, v in arrays.items()})
            b = ColumnBatch.from_numpy(
                node.schema, arrays, capacity=max(n, 1),
                dictionary=self.ctx.dictionary,
            )
            valid = np.asarray(b.valid)
            return {k: np.asarray(v)[valid] for k, v in b.data.items()}
        if kind == "store":
            parts, _schema = rest
            out: Table = {}
            for c in parts[0].keys():
                out[c] = np.concatenate([p[c] for p in parts])
            return out
        if kind == "host_physical":
            (phys,) = rest
            return {k: np.asarray(v) for k, v in phys.items()}
        if kind == "table":  # bound by do_while recursion
            return rest[0]
        raise RuntimeError(f"localdebug: unsupported input binding {kind}")

    # -- row-wise -----------------------------------------------------------
    def _n_select(self, node: Node) -> Table:
        return _call(node.params["fn"], self._in(node))

    def _n_where(self, node: Node) -> Table:
        t = self._in(node)
        mask = np.asarray(node.params["fn"](dict(t))).astype(bool)
        return _take_rows(t, mask)

    def _n_select_many(self, node: Node) -> Table:
        t = self._in(node)
        out_cols, valid = node.params["fn"](dict(t))
        valid = np.asarray(valid).astype(bool).reshape(-1)
        flat = {}
        for k, v in out_cols.items():
            v = np.asarray(v)
            flat[k] = v.reshape((v.shape[0] * v.shape[1],) + tuple(v.shape[2:]))
        return {k: v[valid] for k, v in flat.items()}

    def _n_apply_host(self, node: Node) -> Table:
        t = self._in(node)
        out = node.params["fn"](dict(t), 0)
        phys = node.schema.device_names()
        if set(out.keys()) != set(phys):
            raise ValueError(
                f"apply_host fn output columns {sorted(out)} != "
                f"schema physical columns {phys}"
            )
        return {n: np.asarray(v) for n, v in out.items()}

    def _n_with_rank(self, node: Node) -> Table:
        t = self._in(node)
        n = len(next(iter(t.values()), []))
        out = dict(t)
        out[node.params["out"]] = np.arange(n, dtype=np.int32)
        return out

    def _n_assume_partition(self, node: Node) -> Table:
        return self._in(node)

    def _n_hash_partition(self, node: Node) -> Table:
        return self._in(node)

    def _n_range_partition(self, node: Node) -> Table:
        return self._in(node)

    def _n_tee(self, node: Node) -> Table:
        return self._in(node)

    # -- grouping -----------------------------------------------------------
    def _n_group_by(self, node: Node) -> Table:
        t = self._in(node)
        in_schema = node.inputs[0].schema
        keys = node.params["keys"]
        eq = K.equality_cols(in_schema, keys)
        carry = K.group_carry_cols(in_schema, keys)
        tuples = _key_tuples(t, eq)
        groups: Dict[tuple, List[int]] = {}
        for i, k in enumerate(tuples):
            groups.setdefault(k, []).append(i)
        order = list(groups.values())

        out: Table = {c: np.array([np.asarray(t[c])[idx[0]] for idx in order],
                                  dtype=np.asarray(t[c]).dtype)
                      for c in carry}

        dec = node.params.get("decomposable")
        if dec is not None:
            state = _call(dec.seed, t)
            full = dict(t)
            full.update(state)
            for c in dec.state_cols:
                vals = []
                for idx in order:
                    acc = {k: np.asarray(full[k])[idx[:1]] for k in dec.state_cols}
                    for j in idx[1:]:
                        nxt = {k: np.asarray(full[k])[j : j + 1] for k in dec.state_cols}
                        acc = {k: np.asarray(v) for k, v in dec.merge(acc, nxt).items()}
                    vals.append(acc[c][0])
                out[c] = np.array(vals)
            if dec.finalize is not None:
                out = _call(dec.finalize, out)
            want = K.group_carry_cols(node.schema, node.schema.names)
            return {c: out[c] for c in want}

        from dryad_tpu.columnar.schema import ColumnType, join64, split64

        for op, col, name in node.params["aggs"]:
            ctype = (
                in_schema.field(col).ctype if col is not None else None
            )
            if ctype is ColumnType.FLOAT64 and op in ("sum", "mean"):
                raise ValueError(
                    f"aggregate {op!r} unsupported on float64 column "
                    f"{col!r}: cast to float32"
                )
            if (
                col is not None
                and col not in t
                and (
                    (ctype is ColumnType.INT64 and op in ("sum", "min", "max"))
                    # FLOAT64 words are the order-preserving i64 image:
                    # min/max commute with the monotone transform
                    or (ctype is ColumnType.FLOAT64 and op in ("min", "max"))
                )
            ):
                # split 64-bit column: independent numpy-int64 oracle for
                # the engine's paired-word arithmetic (wrapping sum)
                full = _join_split_col(t, col)
                with np.errstate(over="ignore"):
                    vals64 = np.array(
                        [getattr(full[idx], op)() for idx in order], np.int64
                    )
                out[f"{name}#h0"], out[f"{name}#h1"] = split64(vals64)
                continue
            if (
                col is not None and col not in t
                and ctype is ColumnType.INT64 and op == "mean"
            ):
                full = _join_split_col(t, col)
                # mirror the engine: WRAPPING int64 sum (mod 2^64, the
                # documented contract) then f32 divide — a true-f64 mean
                # here would diverge from the device on overflow
                with np.errstate(over="ignore"):
                    out[name] = np.array(
                        [np.float64(full[idx].sum()) / len(idx)
                         for idx in order],
                        np.float32,
                    )
                continue
            if col is not None and col not in t and (
                in_schema.field(col).ctype.is_split
            ):
                if op == "first":
                    # per-word first, mirroring the device expansion
                    # (plan/lower.py _phys_aggs)
                    for dev in in_schema.field(col).device_names:
                        word = dev.split("#", 1)[1]
                        arr = np.asarray(t[dev])
                        out[f"{name}#{word}"] = np.array(
                            [arr[idx[0]] for idx in order], arr.dtype
                        )
                    continue
                # mirror the device lowering error (plan/lower.py
                # _phys_aggs) instead of a raw KeyError
                raise ValueError(
                    f"aggregate {op!r} unsupported on "
                    f"{in_schema.field(col).ctype.value} column {col!r}"
                )
            vals = []
            for idx in order:
                a = np.asarray(t[col])[idx] if col is not None else None
                if op == "count":
                    vals.append(np.int32(len(idx)))
                elif op == "sum":
                    vals.append(a.sum(dtype=a.dtype))
                elif op == "min":
                    vals.append(a.min())
                elif op == "max":
                    vals.append(a.max())
                elif op == "mean":
                    vals.append(np.float32(a.astype(np.float64).mean()))
                elif op == "first":
                    vals.append(a[0])
                elif op == "any":
                    vals.append(bool(a.any()))
                elif op == "all":
                    vals.append(bool(a.all()))
                else:
                    raise ValueError(op)
            out[name] = np.array(vals)
        return out

    def _n_distinct(self, node: Node) -> Table:
        t = self._in(node)
        eq = K.equality_cols(node.inputs[0].schema, node.params["keys"])
        tuples = _key_tuples(t, eq)
        seen = set()
        idx = []
        for i, k in enumerate(tuples):
            if k not in seen:
                seen.add(k)
                idx.append(i)
        return _take_rows(t, idx)

    # -- join ----------------------------------------------------------------
    def _n_join(self, node: Node) -> Table:
        left, right = node.inputs
        lt, rt = self._in(node, 0), self._in(node, 1)
        lk = K.equality_cols(left.schema, node.params["left_keys"])
        rk = K.equality_cols(right.schema, node.params["right_keys"])
        ltup = _key_tuples(lt, lk)
        rtup = _key_tuples(rt, rk)
        kind = node.params.get("join_kind", "inner")
        if kind in ("semi", "anti"):
            rset = set(rtup)
            mask = np.array([k in rset for k in ltup], bool)
            if kind == "anti":
                mask = ~mask
            return _take_rows(lt, mask)
        rorder = range(len(rtup))
        if kind == "ranked" and node.params.get("order"):
            # Rank order: sort right rows by the requested value order
            # (stable), so match lists enumerate value-ordered.
            import jax.numpy as jnp

            operands_fn = K.ordering_operands(
                right.schema, [tuple(k) for k in node.params["order"]]
            )
            n = _rows(rt)
            b = ColumnBatch(
                {k: jnp.asarray(v) for k, v in rt.items()}, np.ones(n, bool)
            )
            ops = [np.asarray(o) for o in operands_fn(b)]
            rorder = np.lexsort(list(reversed(ops)))
        index: Dict[tuple, List[int]] = {}
        for j in rorder:
            index.setdefault(rtup[j], []).append(j)
        if kind == "count":
            counts = np.array([len(index.get(k, ())) for k in ltup], np.int32)
            out = {c: np.asarray(v) for c, v in lt.items()}
            out[node.params["out"]] = counts
            return out
        li, ri, ranks = [], [], []
        outer = kind == "left"
        defaults = node.params.get("right_defaults") or {}
        # ranked joins with rank_limit=k enumerate only the first k
        # matches per group — same contract as the device path
        limit = node.params.get("rank_limit") if kind == "ranked" else None
        for i, k in enumerate(ltup):
            matches = index.get(k, ())
            if limit is not None:
                matches = matches[:limit]
            for r, j in enumerate(matches):
                li.append(i)
                ri.append(j)
                ranks.append(r)
            if outer and not matches:
                li.append(i)
                ri.append(-1)  # sentinel: default-valued right row
        suffix = node.params.get("suffix", "_r")
        out: Table = {c: np.asarray(lt[c])[li] for c in lt}
        rkset = set(rk)
        ri_arr = np.asarray(ri, np.int64) if ri else np.zeros(0, np.int64)
        for c in rt:
            if c in rkset:
                continue
            if c in out:
                base, _, word = c.partition("#")
                name = f"{base}{suffix}#{word}" if word else f"{c}{suffix}"
            else:
                name = c
            a = np.asarray(rt[c])
            pad = np.broadcast_to(
                np.asarray(defaults.get(c, 0), a.dtype), (1,) + a.shape[1:]
            )
            out[name] = np.concatenate([a, pad])[ri_arr]
        if kind == "ranked":
            out[node.params["rank_out"]] = np.asarray(ranks, np.int32)
        return out

    def _n_zip(self, node: Node) -> Table:
        lt, rt = self._in(node, 0), self._in(node, 1)
        n = min(_rows(lt), _rows(rt))
        suffix = node.params.get("suffix", "_r")
        out: Table = {c: np.asarray(lt[c])[:n] for c in lt}
        for c in rt:
            if c in out:
                base, _, word = c.partition("#")
                name = f"{base}{suffix}#{word}" if word else f"{c}{suffix}"
            else:
                name = c
            out[name] = np.asarray(rt[c])[:n]
        return out

    def _n_sliding_window(self, node: Node) -> Table:
        t = self._in(node)
        w = node.params["size"]
        n = _rows(t)
        m = max(n - w + 1, 0)
        out: Table = {}
        for c in node.params["cols"]:
            a = np.asarray(t[c])
            for j in range(w):
                out[f"{c}_w{j}"] = a[j : j + m]
        return out

    # -- ordering ------------------------------------------------------------
    def _n_order_by(self, node: Node) -> Table:
        t = self._in(node)
        import jax.numpy as jnp

        operands_fn = K.ordering_operands(
            node.inputs[0].schema, [(k, d) for k, d in node.params["keys"]]
        )
        n = _rows(t)
        b = ColumnBatch(
            {k: jnp.asarray(v) for k, v in t.items()}, np.ones(n, bool)
        )
        ops = [np.asarray(o) for o in operands_fn(b)]
        order = np.lexsort(list(reversed(ops)))
        return _take_rows(t, order)

    def _n_take(self, node: Node) -> Table:
        t = self._in(node)
        return _take_rows(t, slice(0, node.params["n"]))

    def _n_skip(self, node: Node) -> Table:
        t = self._in(node)
        return _take_rows(t, slice(node.params["n"], None))

    def _n_tail(self, node: Node) -> Table:
        t = self._in(node)
        n = node.params["n"]
        start = max(_rows(t) - n, 0)
        return _take_rows(t, slice(start, None))

    def _first_false(self, node: Node, t: Table) -> int:
        mask = np.asarray(node.params["fn"](dict(t))).astype(bool)
        bad = np.nonzero(~mask)[0]
        return int(bad[0]) if len(bad) else _rows(t)

    def _n_take_while(self, node: Node) -> Table:
        t = self._in(node)
        return _take_rows(t, slice(0, self._first_false(node, t)))

    def _n_skip_while(self, node: Node) -> Table:
        t = self._in(node)
        return _take_rows(t, slice(self._first_false(node, t), None))

    def _n_reverse(self, node: Node) -> Table:
        t = self._in(node)
        return _take_rows(t, slice(None, None, -1))

    def _n_default_if_empty(self, node: Node) -> Table:
        t = self._in(node)
        if _rows(t):
            return t
        d = node.params["defaults"]
        return {
            k: np.asarray([d.get(k, 0)], dtype=np.asarray(t[k]).dtype)
            for k in t
        }

    def _n_concat(self, node: Node) -> Table:
        ts = [self.cache[i.id] for i in node.inputs]
        cols = sorted(ts[0].keys())
        return {c: np.concatenate([np.asarray(t[c]) for t in ts]) for c in cols}

    # -- aggregates ----------------------------------------------------------
    def _n_aggregate(self, node: Node) -> Table:
        from dryad_tpu.columnar.schema import ColumnType, join64, split64

        t = self._in(node)
        in_schema = node.inputs[0].schema
        n = _rows(t)
        out: Table = {}
        for op, col, name in node.params["aggs"]:
            ctype = in_schema.field(col).ctype if col is not None else None
            if ctype is ColumnType.FLOAT64 and op in ("sum", "mean"):
                raise ValueError(
                    f"aggregate {op!r} unsupported on float64 column "
                    f"{col!r}: cast to float32"
                )
            if col is not None and col not in t and (
                (ctype is ColumnType.INT64 and op in ("sum", "min", "max"))
                or (ctype is ColumnType.FLOAT64 and op in ("min", "max"))
            ):
                # split 64-bit scalar: numpy-int64 oracle on the word
                # pairs (ordered image for f64; wrapping sum for i64).
                # Empty input yields the op IDENTITY, matching the
                # device engine's pair-identity semantics.
                full = _join_split_col(t, col)
                if n == 0:
                    ident = {
                        "sum": 0,
                        "min": np.iinfo(np.int64).max,
                        "max": np.iinfo(np.int64).min,
                    }[op]
                    v64 = np.array([ident], np.int64)
                else:
                    with np.errstate(over="ignore"):
                        v64 = np.array([getattr(full, op)()], np.int64)
                out[f"{name}#h0"], out[f"{name}#h1"] = split64(v64)
                continue
            if (
                col is not None and col not in t
                and ctype is ColumnType.INT64 and op == "mean"
            ):
                full = _join_split_col(t, col)
                with np.errstate(over="ignore"):  # wrapping, as device
                    val = np.float64(full.sum()) / n if n else 0.0
                out[name] = np.array([val], np.float32)
                continue
            if col is not None and col not in t and (
                ctype is not None and ctype.is_split
            ):
                # mirror the device engine's lowering error for
                # unsupported aggregates on split columns (mean/any/all
                # on int64, etc.) instead of a raw KeyError
                raise ValueError(
                    f"aggregate {op!r} unsupported on {ctype.value} "
                    f"column {col!r}"
                )
            a = np.asarray(t[col]) if col is not None else None
            if op == "count":
                out[name] = np.array([n], np.int32)
            elif op == "sum":
                out[name] = np.array([a.sum(dtype=a.dtype)])
            elif n == 0 and op in ("min", "max", "mean", "any", "all"):
                # Sentinel row; Query._scalar returns None via the count
                # guard, matching the device engine.
                if op == "mean":
                    out[name] = np.zeros(1, np.float32)
                elif op in ("any", "all"):
                    out[name] = np.array([op == "all"])
                else:
                    out[name] = np.zeros(1, a.dtype)
            elif op == "min":
                out[name] = np.array([a.min()])
            elif op == "max":
                out[name] = np.array([a.max()])
            elif op == "mean":
                out[name] = np.array([a.astype(np.float64).mean()], np.float32)
            elif op == "any":
                out[name] = np.array([bool(a.any())])
            elif op == "all":
                out[name] = np.array([bool(a.all())])
            else:
                raise ValueError(op)
        return out

    # -- escape hatches -------------------------------------------------------
    def _batch(self, t: Table) -> ColumnBatch:
        import jax.numpy as jnp

        n = _rows(t)
        return ColumnBatch(
            {k: jnp.asarray(v) for k, v in t.items()},
            jnp.ones((n,), jnp.bool_),
        )

    def _unbatch(self, b: ColumnBatch) -> Table:
        valid = np.asarray(b.valid)
        return {k: np.asarray(v)[valid] for k, v in b.data.items()}

    def _n_apply(self, node: Node) -> Table:
        b = self._batch(self._in(node))
        if node.params.get("with_index"):
            out = node.params["fn"](b, 0)
        else:
            out = node.params["fn"](b)
        return self._unbatch(out)

    def _n_fork(self, node: Node) -> Tuple[Table, ...]:
        b = self._batch(self._in(node))
        outs = node.params["fn"](b)
        return tuple(self._unbatch(o) for o in outs)

    def _n_fork_branch(self, node: Node) -> Table:
        forked = self.cache[node.inputs[0].id]
        return forked[node.params["index"]]

    # -- iteration -------------------------------------------------------------
    def _n_do_while(self, node: Node) -> Table:
        from dryad_tpu.api.query import Query
        from dryad_tpu.plan.nodes import Node as N, PartitionInfo

        current = self._in(node)
        body = node.params["body"]
        cond = node.params["cond"]
        for _ in range(node.params.get("max_iter", 100)):
            inp = N("input", [], node.schema, PartitionInfo(), source="table")
            self.ctx._bindings[inp.id] = ("table", current)
            sub = LocalDebugInterpreter(self.ctx)
            out_q = body(Query(self.ctx, inp))
            current = sub.run(out_q.node)

            inp2 = N("input", [], node.schema, PartitionInfo(), source="table")
            self.ctx._bindings[inp2.id] = ("table", current)
            sub2 = LocalDebugInterpreter(self.ctx)
            cond_q = cond(Query(self.ctx, inp2))
            cond_t = sub2.run(cond_q.node)
            col = next(iter(cond_t.values()))
            if not (len(col) and bool(col[0])):
                break
        return current
