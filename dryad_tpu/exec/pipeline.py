"""Bounded chunk pipeline for out-of-core execution.

The reference overlaps channel ingest, vertex compute, and channel
writes with async read-ahead buffers (``channelbufferhdfs.cpp``;
``RChannelReader`` in ``channelinterface.h:212``): a vertex never waits
for the byte it is about to need because the buffer pool fetched it
while the previous one computed.  This module is that overlap for the
TPU streaming driver (``exec.outofcore``):

- :class:`ChunkPrefetcher` — a background producer pulling (and
  host-decoding) chunk k+2 from the source iterator while the driver
  dispatches chunk k+1's device program, with at most
  ``stream_pipeline_depth`` chunks in flight (semaphore flow control,
  so "in flight" counts the producer's in-hand chunk too);
- :class:`PipelineStats` — per-pipeline occupancy/stall accounting
  (producer vs consumer wait), emitted as ``stream_prefetch`` events
  per chunk and one ``stream_pipeline`` summary at close for
  ``tools.jobview``'s stall breakdown;
- exception plumbing: a fault in the producer thread re-raises in the
  consumer (annotated with the ``exec.failure`` taxonomy via a
  ``stream_pipeline_error`` event), the thread always joins, and the
  semaphore protocol guarantees the producer can never deadlock on a
  dead consumer.

The spill half of the pipeline (background bucket writes) lives next
to the format it serializes: ``exec.spill.SpillWriter``.

The dispatch half — the driver keeping N device dispatches in flight
while a background collector drains readbacks in submit order — is
:class:`DispatchWindow` (``config.dispatch_depth``).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Iterator, Optional

from dryad_tpu.obs import flightrec, telemetry, tracectx
from dryad_tpu.obs.span import Tracer

__all__ = [
    "ChunkPrefetcher", "DispatchWindow", "PipelineStats", "prefetched",
]


class PipelineStats:
    """Occupancy/stall counters for one pipeline stage pair."""

    def __init__(self) -> None:
        self.produced = 0
        self.consumed = 0
        self.peak_in_flight = 0
        self.producer_wait_s = 0.0  # producer blocked: consumer behind
        self.consumer_wait_s = 0.0  # consumer blocked: producer behind

    def as_fields(self) -> dict:
        return {
            "produced": self.produced,
            "consumed": self.consumed,
            "peak_in_flight": self.peak_in_flight,
            "producer_wait_s": round(self.producer_wait_s, 4),
            "consumer_wait_s": round(self.consumer_wait_s, 4),
        }


class _Done:
    pass


class _Err:
    def __init__(self, exc: BaseException):
        self.exc = exc


class ChunkPrefetcher:
    """Bounded background iterator: runs ``source`` in a thread, hands
    items to the consumer IN ORDER, and keeps at most ``depth`` items
    in flight (queued + the one the producer holds).

    ``close()`` (idempotent; called by ``__exit__`` and generator
    finalization) stops the producer promptly: it stops pulling new
    items at the next semaphore check and the thread joins.  An
    exception in the producer re-raises from the consumer's next
    ``__next__`` — the original exception object, so the driver's
    failure taxonomy (``exec.failure.classify``) sees the real class
    and message.
    """

    def __init__(
        self,
        source: Iterator,
        depth: int,
        events=None,
        name: str = "prefetch",
    ):
        if depth < 1:
            raise ValueError("pipeline depth must be >= 1")
        self.depth = depth
        self.name = name
        self.events = events
        # producer-thread spans (cat=prefetch): each source pull is one
        # slice on the prefetch track of the Perfetto export
        self._tracer = Tracer(events)
        # the producer thread works FOR the query active at
        # construction: re-activate its trace context in _feed so
        # cat=prefetch spans carry the qid
        self._tctx = tracectx.current()
        self.stats = PipelineStats()
        self._source = source
        self._sem = threading.Semaphore(depth)  # in-flight budget
        self._items: list = []
        self._cv = threading.Condition()
        self._closed = False
        self._finished = False
        self._thread = threading.Thread(
            target=self._feed, name=f"dryad-{name}", daemon=True
        )
        # pipeline occupancy in the flight recorder's microsnapshots
        # (unregistered at close)
        flightrec.probe(
            f"pipeline:{name}",
            lambda: {
                "queued": len(self._items),
                "in_flight": self.stats.produced - self.stats.consumed,
                "depth": self.depth,
            },
        )
        self._thread.start()

    # -- producer ----------------------------------------------------------

    def _feed(self) -> None:
        with tracectx.activate(self._tctx):
            self._feed_inner()

    def _feed_inner(self) -> None:
        tail: Any = _Done()
        try:
            it = iter(self._source)
            while True:
                t0 = time.monotonic()
                # acquire BEFORE pulling the next item: in-flight
                # (queued + producer in-hand) never exceeds depth
                while not self._sem.acquire(timeout=0.1):
                    if self._closed:
                        return
                self.stats.producer_wait_s += time.monotonic() - t0
                if self._closed:
                    return
                try:
                    with self._tracer.span(
                        self.name, cat="prefetch",
                        chunk=self.stats.produced,
                    ):
                        item = next(it)
                except StopIteration:
                    return
                with self._cv:
                    self._items.append(item)
                    self.stats.produced += 1
                    in_flight = self.stats.produced - self.stats.consumed
                    self.stats.peak_in_flight = max(
                        self.stats.peak_in_flight, in_flight
                    )
                    self._cv.notify_all()
                if self.events is not None:
                    self.events.emit(
                        "stream_prefetch", pipeline=self.name,
                        queued=len(self._items), in_flight=in_flight,
                    )
        except BaseException as e:  # noqa: BLE001 - re-raised in consumer
            tail = _Err(e)
        finally:
            with self._cv:
                self._finished = True
                self._items.append(tail)
                self._cv.notify_all()

    # -- consumer ----------------------------------------------------------

    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        t0 = time.monotonic()
        with self._cv:
            while not self._items:
                self._cv.wait(0.1)
                if self._closed and not self._items:
                    raise StopIteration
            item = self._items.pop(0)
        self.stats.consumer_wait_s += time.monotonic() - t0
        if isinstance(item, _Done):
            self._emit_summary()
            raise StopIteration
        if isinstance(item, _Err):
            self._emit_summary(error=item.exc)
            raise item.exc
        self.stats.consumed += 1
        self._sem.release()
        return item

    def close(self) -> None:
        """Stop the producer and join its thread.  Safe to call from
        ``finally`` blocks and repeatedly."""
        with self._cv:
            if self._closed:
                closed_already = True
            else:
                closed_already = False
                self._closed = True
            self._cv.notify_all()
        # unblock a producer waiting on the semaphore
        self._sem.release()
        self._thread.join(timeout=30.0)
        flightrec.unprobe(f"pipeline:{self.name}")
        if not closed_already:
            self._emit_summary()

    def __enter__(self) -> "ChunkPrefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    _summary_emitted = False

    def _emit_summary(self, error: Optional[BaseException] = None) -> None:
        if self.events is None or self._summary_emitted:
            return
        self._summary_emitted = True
        self.events.emit(
            "stream_pipeline", pipeline=self.name, depth=self.depth,
            **self.stats.as_fields(),
        )
        if error is not None:
            from dryad_tpu.exec.failure import classify

            self.events.emit(
                "stream_pipeline_error", pipeline=self.name,
                phase="prefetch",
                failure_kind=classify(error, []).value,
                error=f"{type(error).__name__}: {error}",
            )


class DispatchWindow:
    """Async device-paced dispatch: the driver only FEEDS.

    The driver thread dispatches device work itself (``dispatch``
    returns immediately under JAX async dispatch — the executor is
    driver-owned and not thread-safe) and hands the blocking half — the
    zero-arg ``fetch`` closure from
    ``api.context.DryadContext.run_to_host_async`` — to ONE background
    collector thread via :meth:`submit`.  The collector drains fetches
    strictly in submit order, so chunk commit order (and therefore the
    float accumulation order of everything downstream) is exactly the
    serial loop's and results stay byte-identical.

    Window invariants:

    - at most ``depth`` fetches are in flight (handed to the collector
      and not yet drained): :meth:`submit` blocks past that, the flow
      control that bounds host result memory.  The block waits on the
      COLLECTOR's progress, never the driver's own — a full window can
      always drain itself;
    - the collector ONLY calls fetch closures — device dispatch, chunk
      ingest, combines, and retries all stay on the driver thread;
    - outcomes surface in submit order as ``(tag, value, error)``
      triples from :meth:`ready` / :meth:`drain`; a fetch exception is
      delivered at the drain site (never raised on the collector
      thread), where the driver may re-dispatch the chunk — the retry
      re-enters the window at the failed chunk's commit position;
    - :meth:`close` always joins the collector, also mid-error: a
      poisoned window can never deadlock the driver's ``finally``.

    ``dispatch_gap`` events sample the device-idle seconds between the
    previous drain going idle and the next submit (the metric async
    dispatch exists to drive to ~0); one ``dispatch_window`` summary at
    close carries totals plus the driver thread's CPU seconds over the
    window's life (``driver_cpu_fraction`` in JobMetrics).
    """

    def __init__(
        self,
        depth: int,
        events=None,
        name: str = "dispatch",
        headroom=None,
    ):
        depth = int(depth)
        if depth == -1:
            # adaptive mode: measured HBM headroom picks the depth
            # tier (obs.telemetry.resolve_depth); with no measurement
            # the default applies.  Any resolved depth is
            # byte-identical — the collector drains in submit order
            # regardless of how wide the window is.
            depth = telemetry.resolve_depth(-1, headroom)
        if depth < 1:
            raise ValueError("dispatch depth must be >= 1")
        self.depth = depth
        self.name = name
        self.events = events
        # collector-thread readback spans (cat=readback): the d2h
        # transfer each query's critical path ends on
        self._tracer = Tracer(events)
        self.dispatches = 0
        self.retries = 0
        self.gap_s = 0.0
        self._t0_wall = time.monotonic()
        # driver CPU over the window's life: __init__/close both run on
        # the driver thread, so thread_time deltas are driver-only
        self._t0_cpu = time.thread_time()
        self._pending: list = []  # (tag, fetch, tctx) for the collector
        self._done: list = []  # (tag, value, error) in submit order
        self._outstanding = 0  # submitted - consumed by the driver
        self._cv = threading.Condition()
        self._closed = False
        # None until the first drain-to-empty: the span between window
        # creation and the first submit is ingest warmup, not a
        # dispatch gap — counting it would drown the between-dispatch
        # signal the metric exists for
        self._idle_since: Optional[float] = None
        # when the driver last consumed an outcome (ready/drain pop):
        # once everything submitted has been committed, idle time past
        # this point is between-query think time on a shared window,
        # not a device gap — submit clamps its gap accounting here
        self._last_commit: Optional[float] = None
        self._thread = threading.Thread(
            target=self._collect, name=f"dryad-{name}", daemon=True
        )
        flightrec.probe(
            f"dispatch:{name}",
            lambda: {
                "in_flight": len(self._pending),
                "outstanding": self._outstanding,
                "depth": self.depth,
            },
        )
        self._thread.start()

    # -- collector thread --------------------------------------------------

    def _collect(self) -> None:
        while True:
            with self._cv:
                while not self._pending and not self._closed:
                    self._cv.wait(0.1)
                if not self._pending:
                    return  # closed and drained
                tag, fetch, tctx = self._pending[0]
            value, error = None, None
            try:
                # the fetch works FOR the query that submitted it:
                # readback spans on this thread carry its qid
                with tracectx.activate(tctx), self._tracer.span(
                    "fetch", cat="readback", pipeline=self.name,
                ):
                    value = fetch()
            except BaseException as e:  # noqa: BLE001 - delivered at drain
                error = e
            with self._cv:
                if self._pending:  # close() may have dropped the queue
                    self._pending.pop(0)
                self._done.append((tag, value, error))
                if not self._pending:
                    self._idle_since = time.monotonic()
                self._cv.notify_all()

    # -- driver side -------------------------------------------------------

    def submit(self, tag, fetch) -> None:
        """Hand one dispatched chunk's fetch closure to the collector.
        Call immediately after the async dispatch; blocks while the
        window is full (``depth`` outstanding)."""
        now = time.monotonic()
        with self._cv:
            if self._closed:
                raise RuntimeError(f"dispatch window {self.name} closed")
            if not self._pending and self._idle_since is not None:
                end = now
                if self._outstanding == 0 and self._last_commit is not None:
                    # fully drained AND fully committed: the previous
                    # query/stream ended here, so the tail between its
                    # last commit and this submit is caller think time
                    # (inter-query idle on a shared serve window), not
                    # device starvation — clamp to the last commit
                    end = min(now, self._last_commit)
                gap = max(0.0, end - self._idle_since)
                self.gap_s += gap
                in_flight = len(self._pending)
            else:
                gap = None
            # flow control on UN-FETCHED work only: the collector makes
            # progress independently, so this wait always resolves (a
            # wait on driver-consumed counts would deadlock — the
            # driver is the one blocked here)
            while len(self._pending) >= self.depth and not self._closed:
                self._cv.wait(0.1)
            self._pending.append((tag, fetch, tracectx.current()))
            self._outstanding += 1
            self.dispatches += 1
            self._idle_since = None
            self._cv.notify_all()
        if gap is not None and self.events is not None:
            self.events.emit(
                "dispatch_gap", pipeline=self.name,
                gap_s=round(gap, 6), in_flight=in_flight,
                qid=tracectx.current_qid(),
            )

    def note_retry(self) -> None:
        """Record one drain-time chunk retry (re-entered the window)."""
        self.retries += 1

    def wait(self, timeout: float = 0.1) -> bool:
        """Block until at least one outcome is ready for :meth:`ready`
        (True), or the window is idle/closed or ``timeout`` elapses
        (False).  The serving driver's sleep primitive: with nothing
        runnable in its tenant queues, the multiplexed loop parks here
        instead of spinning on ``ready()``."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while not self._done:
                if self._closed or (
                    not self._pending and self._outstanding == 0
                ):
                    return False
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(min(remaining, 0.1))
            return True

    def ready(self):
        """Yield completed ``(tag, value, error)`` triples in submit
        order WITHOUT blocking — the driver's between-dispatches
        commit opportunity."""
        while True:
            with self._cv:
                if not self._done:
                    return
                item = self._done.pop(0)
                self._outstanding -= 1
                self._last_commit = time.monotonic()
                self._cv.notify_all()
            yield item

    def drain(self):
        """Yield every remaining outcome in submit order, blocking
        until the collector delivers each."""
        while True:
            with self._cv:
                while not self._done:
                    if not self._pending and self._outstanding == 0:
                        return
                    self._cv.wait(0.1)
                item = self._done.pop(0)
                self._outstanding -= 1
                self._last_commit = time.monotonic()
                self._cv.notify_all()
            yield item

    def close(self) -> None:
        """Join the collector.  Safe from ``finally`` and repeatedly;
        undelivered fetches are abandoned (their device work completes
        harmlessly — readback never happens)."""
        with self._cv:
            if self._closed:
                return
            self._closed = True
            # abandoned pending fetches would block the join on device
            # readbacks nobody will consume; the collector checks
            # _closed only between fetches, so drop the queue here
            self._pending.clear()
            self._cv.notify_all()
        self._thread.join(timeout=30.0)
        flightrec.unprobe(f"dispatch:{self.name}")
        if self.events is not None:
            self.events.emit(
                "dispatch_window", pipeline=self.name, depth=self.depth,
                dispatches=self.dispatches, retries=self.retries,
                gap_s=round(self.gap_s, 6),
                wall_s=round(time.monotonic() - self._t0_wall, 6),
                driver_cpu_s=round(
                    time.thread_time() - self._t0_cpu, 6
                ),
            )

    def __enter__(self) -> "DispatchWindow":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def prefetched(source, depth: int, events=None, name: str = "prefetch"):
    """Generator wrapper: yield from a :class:`ChunkPrefetcher` over
    ``source`` when ``depth > 1``, closing it even when the consumer
    abandons the stream early (``take``); pass-through at depth 1 (the
    serial driver — no thread, no reordering risk)."""
    if depth <= 1:
        yield from source
        return
    pf = ChunkPrefetcher(iter(source), depth, events=events, name=name)
    try:
        yield from pf
    finally:
        pf.close()
