"""Bounded chunk pipeline for out-of-core execution.

The reference overlaps channel ingest, vertex compute, and channel
writes with async read-ahead buffers (``channelbufferhdfs.cpp``;
``RChannelReader`` in ``channelinterface.h:212``): a vertex never waits
for the byte it is about to need because the buffer pool fetched it
while the previous one computed.  This module is that overlap for the
TPU streaming driver (``exec.outofcore``):

- :class:`ChunkPrefetcher` — a background producer pulling (and
  host-decoding) chunk k+2 from the source iterator while the driver
  dispatches chunk k+1's device program, with at most
  ``stream_pipeline_depth`` chunks in flight (semaphore flow control,
  so "in flight" counts the producer's in-hand chunk too);
- :class:`PipelineStats` — per-pipeline occupancy/stall accounting
  (producer vs consumer wait), emitted as ``stream_prefetch`` events
  per chunk and one ``stream_pipeline`` summary at close for
  ``tools.jobview``'s stall breakdown;
- exception plumbing: a fault in the producer thread re-raises in the
  consumer (annotated with the ``exec.failure`` taxonomy via a
  ``stream_pipeline_error`` event), the thread always joins, and the
  semaphore protocol guarantees the producer can never deadlock on a
  dead consumer.

The spill half of the pipeline (background bucket writes) lives next
to the format it serializes: ``exec.spill.SpillWriter``.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Iterator, Optional

from dryad_tpu.obs import flightrec
from dryad_tpu.obs.span import Tracer

__all__ = ["ChunkPrefetcher", "PipelineStats", "prefetched"]


class PipelineStats:
    """Occupancy/stall counters for one pipeline stage pair."""

    def __init__(self) -> None:
        self.produced = 0
        self.consumed = 0
        self.peak_in_flight = 0
        self.producer_wait_s = 0.0  # producer blocked: consumer behind
        self.consumer_wait_s = 0.0  # consumer blocked: producer behind

    def as_fields(self) -> dict:
        return {
            "produced": self.produced,
            "consumed": self.consumed,
            "peak_in_flight": self.peak_in_flight,
            "producer_wait_s": round(self.producer_wait_s, 4),
            "consumer_wait_s": round(self.consumer_wait_s, 4),
        }


class _Done:
    pass


class _Err:
    def __init__(self, exc: BaseException):
        self.exc = exc


class ChunkPrefetcher:
    """Bounded background iterator: runs ``source`` in a thread, hands
    items to the consumer IN ORDER, and keeps at most ``depth`` items
    in flight (queued + the one the producer holds).

    ``close()`` (idempotent; called by ``__exit__`` and generator
    finalization) stops the producer promptly: it stops pulling new
    items at the next semaphore check and the thread joins.  An
    exception in the producer re-raises from the consumer's next
    ``__next__`` — the original exception object, so the driver's
    failure taxonomy (``exec.failure.classify``) sees the real class
    and message.
    """

    def __init__(
        self,
        source: Iterator,
        depth: int,
        events=None,
        name: str = "prefetch",
    ):
        if depth < 1:
            raise ValueError("pipeline depth must be >= 1")
        self.depth = depth
        self.name = name
        self.events = events
        # producer-thread spans (cat=prefetch): each source pull is one
        # slice on the prefetch track of the Perfetto export
        self._tracer = Tracer(events)
        self.stats = PipelineStats()
        self._source = source
        self._sem = threading.Semaphore(depth)  # in-flight budget
        self._items: list = []
        self._cv = threading.Condition()
        self._closed = False
        self._finished = False
        self._thread = threading.Thread(
            target=self._feed, name=f"dryad-{name}", daemon=True
        )
        # pipeline occupancy in the flight recorder's microsnapshots
        # (unregistered at close)
        flightrec.probe(
            f"pipeline:{name}",
            lambda: {
                "queued": len(self._items),
                "in_flight": self.stats.produced - self.stats.consumed,
                "depth": self.depth,
            },
        )
        self._thread.start()

    # -- producer ----------------------------------------------------------

    def _feed(self) -> None:
        tail: Any = _Done()
        try:
            it = iter(self._source)
            while True:
                t0 = time.monotonic()
                # acquire BEFORE pulling the next item: in-flight
                # (queued + producer in-hand) never exceeds depth
                while not self._sem.acquire(timeout=0.1):
                    if self._closed:
                        return
                self.stats.producer_wait_s += time.monotonic() - t0
                if self._closed:
                    return
                try:
                    with self._tracer.span(
                        self.name, cat="prefetch",
                        chunk=self.stats.produced,
                    ):
                        item = next(it)
                except StopIteration:
                    return
                with self._cv:
                    self._items.append(item)
                    self.stats.produced += 1
                    in_flight = self.stats.produced - self.stats.consumed
                    self.stats.peak_in_flight = max(
                        self.stats.peak_in_flight, in_flight
                    )
                    self._cv.notify_all()
                if self.events is not None:
                    self.events.emit(
                        "stream_prefetch", pipeline=self.name,
                        queued=len(self._items), in_flight=in_flight,
                    )
        except BaseException as e:  # noqa: BLE001 - re-raised in consumer
            tail = _Err(e)
        finally:
            with self._cv:
                self._finished = True
                self._items.append(tail)
                self._cv.notify_all()

    # -- consumer ----------------------------------------------------------

    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        t0 = time.monotonic()
        with self._cv:
            while not self._items:
                self._cv.wait(0.1)
                if self._closed and not self._items:
                    raise StopIteration
            item = self._items.pop(0)
        self.stats.consumer_wait_s += time.monotonic() - t0
        if isinstance(item, _Done):
            self._emit_summary()
            raise StopIteration
        if isinstance(item, _Err):
            self._emit_summary(error=item.exc)
            raise item.exc
        self.stats.consumed += 1
        self._sem.release()
        return item

    def close(self) -> None:
        """Stop the producer and join its thread.  Safe to call from
        ``finally`` blocks and repeatedly."""
        with self._cv:
            if self._closed:
                closed_already = True
            else:
                closed_already = False
                self._closed = True
            self._cv.notify_all()
        # unblock a producer waiting on the semaphore
        self._sem.release()
        self._thread.join(timeout=30.0)
        flightrec.unprobe(f"pipeline:{self.name}")
        if not closed_already:
            self._emit_summary()

    def __enter__(self) -> "ChunkPrefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    _summary_emitted = False

    def _emit_summary(self, error: Optional[BaseException] = None) -> None:
        if self.events is None or self._summary_emitted:
            return
        self._summary_emitted = True
        self.events.emit(
            "stream_pipeline", pipeline=self.name, depth=self.depth,
            **self.stats.as_fields(),
        )
        if error is not None:
            from dryad_tpu.exec.failure import classify

            self.events.emit(
                "stream_pipeline_error", pipeline=self.name,
                phase="prefetch",
                failure_kind=classify(error, []).value,
                error=f"{type(error).__name__}: {error}",
            )


def prefetched(source, depth: int, events=None, name: str = "prefetch"):
    """Generator wrapper: yield from a :class:`ChunkPrefetcher` over
    ``source`` when ``depth > 1``, closing it even when the consumer
    abandons the stream early (``take``); pass-through at depth 1 (the
    serial driver — no thread, no reordering risk)."""
    if depth <= 1:
        yield from source
        return
    pf = ChunkPrefetcher(iter(source), depth, events=events, name=name)
    try:
        yield from pf
    finally:
        pf.close()
