"""Out-of-core streaming execution — bounded-HBM morsel loop.

The reference streams unbounded byte streams through fixed-size
buffers with async read-ahead (``DryadVertex/VertexHost/system/channel/
channelinterface.h:212`` RChannelReader; ``channelbuffernativereader
.cpp``; ``channelbufferqueue.cpp``), so a vertex processes data far
larger than memory.  The TPU-native equivalent here is a **two-phase
partition-spill driver** over the existing engine:

- phase 1 (scatter): each ingest *chunk* runs the fused row-local
  prefix of the plan as one compiled device program, then is routed to
  range/hash buckets and spilled as ``.dpf`` pieces (the persisted
  file-channel analog, ``exec.spill``);
- phase 2 (gather): each bucket — sized to fit the ``(P x cap)``
  device layout — runs the wide operator (sort / group / join) as a
  normal engine job, and results stream out in bucket order.

Aggregations skip the spill when their aggs decompose: per-chunk
partials accumulate and periodically combine on device (the
machine->pod->overall aggregation tree of
``DrDynamicAggregateManager.h:117-168`` folded into a running
accumulator).  Oversized buckets re-split from *observed* volume —
the ``DrDynamicRangeDistributor.cpp:54-110`` consumer-resize semantics
applied at the spill boundary.
"""

from __future__ import annotations

import math
from collections import deque
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from dryad_tpu.columnar.schema import ColumnType, Schema
from dryad_tpu.exec.combinetree import (
    CombineTreePlanner,
    TreeCombiner,
    TreeShape,
    batch_bytes,
    neutral_snapshot,
)
from dryad_tpu.exec.partial import (
    MERGEABLE_AGGS,
    finalize_fn,
    merge_agg_spec,
    partial_plan,
)
from dryad_tpu.exec.failure import JobFailedError, StageFailedError
from dryad_tpu.exec.faults import InjectedFault
from dryad_tpu.exec.pipeline import DispatchWindow, prefetched
from dryad_tpu.exec.spill import SpillDir, SpillWriter
from dryad_tpu.obs import telemetry
from dryad_tpu.obs.metrics import KeyRangeHistogram, MetricsRegistry
from dryad_tpu.obs.span import Tracer
from dryad_tpu.plan.nodes import Node, walk
from dryad_tpu.utils.logging import get_logger

log = get_logger("dryad_tpu.stream")

# Node kinds applied chunk-locally in phase 1 (row-wise, stateless
# across chunks).  Partitioning hints are identity under streaming:
# every per-chunk/per-bucket engine job re-derives its own exchanges.
ROW_LOCAL = {"select", "where", "select_many"}
PARTITION_HINTS = {"hash_partition", "range_partition", "assume_partition"}

_MIX = np.uint64(0x9E3779B97F4A7C15)


class ChunkSource:
    """A stream ingest binding: an iterable of host tables.  The
    consumed flag lives HERE (not on the per-execution stream view) so
    a second collect() over the same query raises the explicit error
    instead of silently computing on a drained iterator."""

    def __init__(self, chunks, schema: Schema):
        self.chunks = chunks
        self.schema = schema
        self.state = {"consumed": False}


class _IngestScope:
    """Per-call-site chunk ingest state: a stable partition capacity
    (so every chunk compiles to the same shapes) and accumulated
    auto-dense metadata (string vocab / int ranges widen monotonically
    across chunks, so the dense code table saturates and the compile
    cache holds).

    With ``cache_plans`` (the pipelined driver) the scope also reuses
    the ingest Node itself: a chunk that introduces no new vocabulary,
    no wider int range, and fits the stable capacity REBINDS the
    previous chunk's input node to its arrays instead of building a
    fresh node — so downstream plan chains, lowering keys, and compiled
    programs repeat exactly (the cached-chunk-plan half of the
    pipeline; without it, a widened vocab baked into the coding tables
    forces a fresh XLA compile per chunk)."""

    def __init__(self, ctx, cache_plans: bool = False, slots: int = 1):
        self.ctx = ctx
        self.cap: Optional[int] = None
        # With cross-chunk fusion, K chunks are lowered into ONE
        # multi-root program — each needs its OWN input node (and
        # binding) alive at dispatch, so the reuse cache round-robins
        # over `slots` cached nodes instead of rebinding a single one.
        self.slots = max(1, int(slots))
        self._slot_counter = 0
        self.vocab: Dict[str, np.ndarray] = {}
        self.stats: Dict[str, Tuple[int, int]] = {}
        self.cache_plans = cache_plans
        # Runtime-operand coding tables (compile-once dictionary
        # coding): vocab widening within a pow2 palette tier keeps
        # every traced shape identical, so it must NOT bump the cached-
        # plan epoch — the cached input node is reused with its
        # str_vocab param refreshed in place (_maybe_reuse), the
        # lowering rebuilds the widened tables, and the executor's
        # operand pool scatters just the delta.
        self._runtime_tables = bool(
            getattr(ctx.config, "stringcode_runtime_tables", True)
        )
        # bumps whenever vocab/stats/capacity widen beyond what cached
        # plans can absorb: cached input nodes and the chains built on
        # them are valid while it holds still
        self.version = 0
        # (cap, binding kind) -> (version, node) reusable ingest input
        self._cached_input: Dict[Tuple, Tuple[int, Node]] = {}
        # (input node id, pending/extra node ids) -> cloned chain root
        self.chain_cache: Dict[Tuple, Node] = {}

    def _fit_cap(self, n: int, P: int) -> int:
        if self.cap is None or n > self.cap * P:
            self.cap = max(1, math.ceil(n / P / 8) * 8)
            self.version += 1
        return self.cap

    def _widen_vocab(self, col: str, v: np.ndarray) -> np.ndarray:
        from dryad_tpu.ops.stringcode import palette_domain

        prev = self.vocab.get(col)
        new = v if prev is None else np.union1d(prev, v)
        if prev is None:
            self.version += 1
        elif len(new) != len(prev):
            if not self._runtime_tables or palette_domain(
                len(new)
            ) != palette_domain(len(prev)):
                # legacy baked tables invalidate on ANY widen; runtime
                # tables only on a palette-tier crossing
                self.version += 1
        self.vocab[col] = new
        return new

    def _account(self, table: Dict[str, np.ndarray], n: int, P: int) -> None:
        """Ingest-side byte/row accounting: H2D-bound bytes and the
        layout-vs-valid rows behind the padding-waste ratio."""
        ex = getattr(self.ctx, "executor", None)
        if ex is None or self.cap is None:
            return
        ex.metrics.add(
            "h2d_bytes",
            sum(
                np.asarray(v).nbytes for c, v in table.items()
                if c != "#vocab"
            ),
        )
        ex.metrics.add("rows_in", n)
        ex.metrics.add("valid_rows", n)
        ex.metrics.add("layout_rows", self.cap * P)

    def ingest(self, table: Dict[str, np.ndarray], schema: Schema):
        ctx = self.ctx
        from dryad_tpu.parallel.mesh import num_partitions

        P = num_partitions(ctx.mesh) if ctx.mesh is not None else 8
        if is_physical_chunk(table, schema):
            return self._maybe_reuse(self._ingest_physical(table, schema, P))
        n = len(next(iter(table.values()))) if table else 0
        self._fit_cap(n, P)
        self._account(table, n, P)
        q = ctx.from_arrays(table, schema=schema, partition_capacity=self.cap)
        node = q.node
        # Widen auto-dense metadata to the stream scope.  The widened
        # dicts REPLACE the node's params — never written into the
        # original dicts, which clones share by reference (in-place
        # widening would leak one chunk's vocabulary into every node
        # holding the same params dict).
        sv = node.params.get("str_vocab") or {}
        if sv:
            node.params["str_vocab"] = {
                col: self._widen_vocab(col, vocab)
                for col, vocab in sv.items()
            }
        cs = node.params.get("col_stats") or {}
        if cs:
            merged = {}
            for col, (mn, mx) in cs.items():
                if col in self.stats:
                    pmn, pmx = self.stats[col]
                    nmn, nmx = min(mn, pmn), max(mx, pmx)
                else:
                    nmn, nmx = mn, mx
                if self.stats.get(col) != (nmn, nmx):
                    self.version += 1
                self.stats[col] = (nmn, nmx)
                merged[col] = (nmn, nmx)
            node.params["col_stats"] = merged
        return self._maybe_reuse(q)

    def _maybe_reuse(self, q):
        """Swap the freshly built input node for the cached one when
        this chunk's metadata is covered by it (vocab/stats widen
        monotonically, so an unchanged version proves coverage)."""
        if not self.cache_plans:
            return q
        from dryad_tpu.api.query import Query

        ctx = self.ctx
        node = q.node
        binding = ctx._bindings.get(node.id)
        if binding is None:
            return q
        slot = self._slot_counter % self.slots
        self._slot_counter += 1
        key = (self.cap, binding[0], slot)
        cached = self._cached_input.get(key)
        if cached is not None and cached[0] == self.version:
            cnode = cached[1]
            # adopt the fresh chunk's binding under the cached node id;
            # the content fingerprint is per-binding, so drop the stale
            # cached one (checkpoint identity must follow the data)
            ctx._bindings[cnode.id] = ctx._bindings.pop(node.id)
            ctx._binding_fp_cache.pop(cnode.id, None)
            # refresh the cached node's vocabulary metadata in place: a
            # within-tier widen reuses the node (and every chain/compiled
            # program built on it) but the NEXT lowering must code
            # against the full accumulated vocab — a stale str_vocab
            # would build tables missing this chunk's new words and fail
            # them loudly as dictionary misses.
            sv = cnode.params.get("str_vocab")
            if sv:
                cnode.params["str_vocab"] = {
                    c: self.vocab.get(c, vv) for c, vv in sv.items()
                }
            return Query(ctx, cnode)
        self._cached_input[key] = (self.version, node)
        return q

    def _ingest_physical(self, table: Dict[str, np.ndarray], schema, P):
        """Pre-encoded chunk (physical columns, e.g. straight off the
        native tokenizer): bind as host_physical — no per-token Python
        string work on the streaming hot path (review r5; the
        reference's vertices likewise consume tokenized channel bytes
        directly, ``channelbufferhdfs.cpp``)."""
        from dryad_tpu.api.query import Query
        from dryad_tpu.plan.nodes import PartitionInfo

        ctx = self.ctx
        vocab = table.pop("#vocab", None) or {}
        for col, v in vocab.items():
            self._widen_vocab(col, v)
        n = len(next(iter(table.values()))) if table else 0
        self._fit_cap(n, P)
        self._account(table, n, P)
        node = Node(
            "input", [], schema, PartitionInfo.roundrobin(),
            source="host_physical",
            str_vocab={c: v.copy() for c, v in self.vocab.items()},
        )
        ctx._bindings[node.id] = ("host_physical", table, self.cap)
        return Query(ctx, node)


class _AsyncDispatcher:
    """Driver-side async chunk dispatcher: marries the
    :class:`~dryad_tpu.exec.pipeline.DispatchWindow` with cross-chunk
    plan fusion.

    Queries queue up to ``fuse`` deep and dispatch in submit order —
    a fused batch lowers as ONE multi-root program
    (``run_many_to_host_async``), collapsing K dispatch round trips
    into one — and each chunk's readback fetch hands off to the
    window's collector thread.  Outcomes are delivered strictly in
    submit order, so the caller's commit body (spill / accumulate /
    combine) observes the exact serial sequence and results stay
    byte-identical with the ``dispatch_depth=1`` loop.

    A fetch error surfacing at the drain site re-executes that chunk
    serially via the caller's ``retry`` callback — the retried result
    re-enters the stream at the failed chunk's commit position.
    Terminal failures (:class:`JobFailedError` — the executor already
    burned its attempt budget) and non-stage errors propagate; the
    caller's ``finally`` closes the window, which never deadlocks.
    """

    def __init__(self, ctx, depth, fuse, events=None, name="chunks",
                 retry=None):
        self.ctx = ctx
        self.fuse = max(1, int(fuse))
        self.retry = retry
        # a fused batch enters the window whole, so the window must
        # admit at least `fuse` in-flight fetches
        self.win = DispatchWindow(
            max(1, int(depth), self.fuse), events=events, name=name,
        )
        self._queued: List[Tuple[Any, Any]] = []  # awaiting fused dispatch

    def submit(self, tag, query) -> None:
        self._queued.append((tag, query))
        if len(self._queued) >= self.fuse:
            self._dispatch()

    def _dispatch(self) -> None:
        queued, self._queued = self._queued, []
        if not queued:
            return
        if len(queued) == 1:
            fetches = [self.ctx.run_to_host_async(queued[0][1])]
        else:
            fetches = self.ctx.run_many_to_host_async(
                [q for _tag, q in queued]
            )
        for (tag, _q), fetch in zip(queued, fetches):
            self.win.submit(tag, fetch)

    def ready(self):
        """Completed (tag, table) pairs, non-blocking — the driver's
        between-dispatches commit opportunity."""
        return self._deliver(self.win.ready())

    def drain(self):
        """Flush the fused queue and deliver every remaining outcome
        in submit order (blocking)."""
        self._dispatch()
        return self._deliver(self.win.drain())

    def _deliver(self, outcomes):
        for tag, value, error in outcomes:
            if error is not None:
                value = self._retry_one(tag, error)
            yield tag, value

    def _retry_one(self, tag, error):
        transient = isinstance(
            error, (StageFailedError, InjectedFault)
        ) and not isinstance(error, JobFailedError)
        if self.retry is None or not transient:
            raise error
        self.win.note_retry()
        log.warning(
            "async chunk fetch failed (%s: %s); retrying serially at "
            "the drain site", type(error).__name__, error,
        )
        return self.retry(tag)

    def close(self) -> None:
        self.win.close()


class _Stream:
    """A lazily-realized chunk stream: base chunks plus a pending
    chain of row-local plan nodes applied per chunk on device.

    Derived streams (``with_pending``) SHARE the consumption state with
    their base: two branches over one chunk iterator must raise the
    explicit already-consumed error, not silently split the data."""

    def __init__(
        self, base_schema: Schema, chunks: Iterator, pending=(),
        _state: Optional[dict] = None,
    ):
        self.base_schema = base_schema
        self.chunks = chunks
        self.pending: List[Node] = list(pending)
        self._state = _state if _state is not None else {"consumed": False}

    @property
    def consumed(self) -> bool:
        return self._state["consumed"]

    @consumed.setter
    def consumed(self, v: bool) -> None:
        self._state["consumed"] = v

    @property
    def schema(self) -> Schema:
        return self.pending[-1].schema if self.pending else self.base_schema

    def with_pending(self, node: Node) -> "_Stream":
        return _Stream(
            self.base_schema, self.chunks, self.pending + [node],
            _state=self._state,
        )


class StreamNotSupported(NotImplementedError):
    pass


def has_stream_input(ctx, root: Node) -> bool:
    if not getattr(ctx, "_any_stream", False):
        return False  # context never created a stream binding
    return bool(stream_reaching_ids(ctx, root))


def stream_reaching_ids(ctx, root: Node) -> set:
    """Ids of nodes whose subtree contains a stream binding — computed
    in ONE topological walk (consulted per node during evaluation)."""
    ids: set = set()
    for n in walk([root]):
        b = ctx._bindings.get(n.id)
        if (b is not None and b[0] == "stream") or any(
            i.id in ids for i in n.inputs
        ):
            ids.add(n.id)
    return ids


def is_physical_chunk(table, schema: Schema) -> bool:
    """Chunks may arrive pre-encoded as physical columns (``name#h0``
    etc., straight off the native tokenizer) instead of logical host
    arrays; ``#vocab`` optionally carries the chunk's string vocab."""
    cols = set(table) - {"#vocab"}
    return cols != set(schema.names) and any("#" in c for c in cols)


def _chunk_rows(table) -> int:
    for c, v in table.items():
        if c != "#vocab":
            return len(v)
    return 0


class _DeviceCombiner:
    """Accumulator of device-resident partial batches — the
    ``DrDynamicAggregateManager.h:117-168`` machine->pod->overall
    aggregation tree kept entirely in HBM.

    Partials pile up untouched until their combined LAYOUT rows (sum of
    batch capacities — an upper bound on actual rows known without any
    device readback, so pushes never block the dispatch loop) exceed
    ``combine_rows`` or the fan-in cap; then ONE N-ary concat+merge job
    folds them to a single batch.  Concat is one plan node whatever the
    arity, so a flush compiles one program per distinct fan-in — and a
    steady stream flushes at a stable fan-in, reusing it.  This matches
    the serial driver's combine cadence (few, wide merges — not a
    per-chunk tree) while skipping its per-chunk D2H and host
    re-ingest.

    Merging on device only pays while merges actually REDUCE (the
    "merge where it reduces" scheduling of PAPERS.md "Chasing
    Similarity"): ``push`` returns False when a flush kept >= 3/4 of
    its inputs' combined layout — high-cardinality keys, whose merged
    batch would re-enter the accumulator near the threshold and force
    a shape-churning flush every chunk.  The caller then ``drain()``s
    and degrades to host-side threshold accumulation."""

    MAX_FANIN = 64  # bounds single-program arity (trace/compile cost)

    def __init__(self, merge_many, combine_rows: int, emit, split=None):
        self._merge_many = merge_many
        self._combine_rows = combine_rows
        self._emit = emit
        # optional (in_bytes, out_bytes) -> (ici, dcn) estimator
        # (combinetree.TreeShape.exchange_split): every flat flush pays
        # a FULL hash exchange, and tagging its collective byte split on
        # the event puts tree-on and tree-off runs on one scale
        self._split = split
        self._pending: List[Any] = []
        self.combines = 0

    def _cap(self) -> int:
        return sum(b.capacity for b in self._pending)

    def push(self, batch) -> bool:
        """Insert one partial; False = the flush this push triggered
        did not reduce (caller should ``drain()`` and change policy)."""
        self._pending.append(batch)
        if len(self._pending) < 2 or (
            self._cap() <= self._combine_rows
            and len(self._pending) < self.MAX_FANIN
        ):
            return True
        in_cap = self._cap()
        fan = len(self._pending)
        in_bytes = sum(batch_bytes(b) for b in self._pending)
        merged = self._merge_many(self._pending)
        self.combines += 1
        self._pending = [merged]
        ici, dcn = (
            self._split(in_bytes, batch_bytes(merged))
            if self._split else (0, 0)
        )
        self._emit("stream_combine", cap_rows=merged.capacity,
                   device=True, fan_in=fan, level=0,
                   ici_bytes=ici, dcn_bytes=dcn)
        return merged.capacity < 0.75 * in_cap

    def drain(self) -> List[Any]:
        """All held batches; the combiner is empty afterwards."""
        out = self._pending
        self._pending = []
        return out

    def fold(self):
        """Merge everything left into one batch; None when nothing was
        pushed."""
        if not self._pending:
            return None
        if len(self._pending) == 1:
            return self._pending.pop()
        merged = self._merge_many(self._pending)
        self.combines += 1
        self._pending = []
        return merged


class StreamExecutor:
    """Drives a plan whose input is a chunk stream; every device job it
    launches is bounded by the chunk/bucket budgets."""

    def __init__(self, ctx):
        self.ctx = ctx
        cfg = ctx.config
        self.bucket_rows = int(getattr(cfg, "stream_bucket_rows", 1 << 21))
        # The staged exchange (plan.xchgplan, config.exchange_window)
        # caps the per-dispatch redistribution footprint at
        # O(window * B) instead of the flat path's O(P * B); spend the
        # reclaimed HBM on bigger buckets — fewer device jobs, fewer
        # spill round-trips — scaling by the P/window buffer shrink,
        # clamped to 4x so ingest chunking stays responsive.
        # (-1 = auto policy resolves per-compilation in the executor;
        # no static bucket scaling can be assumed here)
        window = int(getattr(cfg, "exchange_window", 0))
        if window > 0:
            P = self._P()
            if P > window:
                self.bucket_rows *= min(4, max(1, P // window))
        self.combine_rows = int(getattr(cfg, "stream_combine_rows", 1 << 20))
        self.num_buckets = int(getattr(cfg, "stream_buckets", 32))
        # chunk pipeline: ingest / compute / readback-spill overlap with
        # this many chunks in flight; 1 = the serial legacy driver
        self.pipeline_depth = max(
            1, int(getattr(cfg, "stream_pipeline_depth", 1))
        )
        self.writer_queue = int(getattr(cfg, "stream_writer_queue", 8))
        # async device-paced dispatch: how many chunk dispatches stay
        # in flight (readbacks drained by the DispatchWindow collector
        # thread); 1 = today's serial driver, the differential
        # baseline; -1 = adaptive — measured HBM headroom (the
        # context's telemetry HeadroomProvider) picks the tier, and
        # the collector's submit-order drain keeps ANY resolved depth
        # byte-identical to serial
        self.dispatch_depth = max(1, telemetry.resolve_depth(
            int(getattr(cfg, "dispatch_depth", 1)),
            getattr(ctx, "headroom", None),
        ))
        # cross-chunk fusion: K chunk partial-plans lowered as one
        # multi-root program, collapsing K dispatch RTTs into one
        self.chunk_fuse = max(1, int(getattr(cfg, "chunk_fuse", 1)))
        self.max_split_depth = 3
        self.events = ctx.executor.events if ctx.executor else None
        # runtime plan rewriter (rewrite.controller): polled at chunk
        # boundaries for hot-bucket splits and combine pins; None when
        # diagnosis/rewrite is off
        self.rewriter = getattr(ctx, "rewriter", None)
        # driver-loop spans (cat=chunk structural, engine jobs land on
        # cat=execute inside) + the shared counter registry
        self.tracer = Tracer(self.events)
        self.metrics = (
            ctx.executor.metrics if ctx.executor else MetricsRegistry()
        )
        self._small_nodes: Dict[int, Node] = {}
        self._eval_cache: Dict[int, Tuple[str, Any]] = {}
        self._stream_ids: Optional[set] = None

    @property
    def _pipelined(self) -> bool:
        return self.pipeline_depth > 1

    def _scope(self, slots: int = 1) -> _IngestScope:
        return _IngestScope(
            self.ctx, cache_plans=self._pipelined or slots > 1, slots=slots,
        )

    @property
    def _async_dispatch(self) -> bool:
        """Async drain path: window the chunk dispatches when the
        driver is NOT already device-resident pipelining partials."""
        return self.dispatch_depth > 1 or self.chunk_fuse > 1

    def _spill_writer(self) -> Optional[SpillWriter]:
        if not self._pipelined:
            return None
        return SpillWriter(events=self.events, queue_depth=self.writer_queue)

    # ---- public --------------------------------------------------------

    def run_to_host(self, root: Node) -> Dict[str, np.ndarray]:
        kind, val = self._eval(root)
        if kind == "small":
            self.metrics.emit(self.events)
            return val
        tables = list(self._realized(val))
        self.metrics.emit(self.events)
        return _concat_tables(tables, val.schema)

    def run_stream(self, root: Node):
        """(schema, iterator of host tables) — the bounded-memory
        result surface (Query.collect_stream)."""
        kind, val = self._eval(root)
        if kind == "small":
            return root.schema, iter([val])
        return val.schema, self._realized(val)

    def to_store(self, root: Node, path: str) -> int:
        """Stream results into a partitioned store; returns row count.
        Partitions write incrementally (one per emitted table); the
        shared metadata writer stamps the manifest at the end."""
        import os

        from dryad_tpu.columnar.io import _part_name, write_store_meta
        from dryad_tpu.runtime.bindings import write_partition

        kind, val = self._eval(root)
        schema = val.schema if kind == "stream" else root.schema
        tables = self._realized(val) if kind == "stream" else iter([val])
        os.makedirs(path, exist_ok=True)
        total = 0
        i = 0
        for t in tables:
            n = len(next(iter(t.values()))) if t else 0
            if not n:
                continue
            phys = _encode_store_part(t, schema, self.ctx.dictionary)
            write_partition(os.path.join(path, _part_name(i)), phys, None)
            total += n
            i += 1
        write_store_meta(path, i, schema, self.ctx.dictionary)
        self._emit("stream_store", path=path, rows=total, partitions=i)
        self.metrics.emit(self.events)
        return total

    # ---- helpers -------------------------------------------------------

    def _P(self) -> int:
        from dryad_tpu.parallel.mesh import num_partitions

        return (
            num_partitions(self.ctx.mesh)
            if self.ctx.mesh is not None else 8
        )

    def _emit(self, kind: str, **fields) -> None:
        if self.events is not None:
            self.events.emit(kind, **fields)

    def _run_engine(self, node: Node) -> Dict[str, np.ndarray]:
        from dryad_tpu.api.query import Query

        with self.tracer.span(f"engine:{node.kind}", cat="chunk"):
            return self.ctx.run_to_host(Query(self.ctx, node))

    def _clone(self, n: Node, new_inputs: Sequence[Node]) -> Node:
        return Node(n.kind, list(new_inputs), n.schema, n.partition, **n.params)

    def _materialize_small(self, node: Node) -> Node:
        """Run a stream-free subtree once; re-ingest as a host table so
        per-chunk jobs reuse the same binding instead of recomputing."""
        if node.id in self._small_nodes:
            return self._small_nodes[node.id]
        if node.kind == "input" and self.ctx._bindings.get(node.id, ("",))[0] in (
            "host", "host_physical",
        ):
            self._small_nodes[node.id] = node  # already a cheap binding
            return node
        table = self._run_engine(node)
        q = self.ctx.from_arrays(table, schema=node.schema)
        self._small_nodes[node.id] = q.node
        return q.node

    def _chain_root(self, scope: _IngestScope, q, nodes: Sequence[Node]):
        """Clone the pending chain onto an ingest query ONCE per
        (reused) input node; a rebound chunk reuses the whole chain —
        no per-chunk Node cloning, and the lowering keys repeat."""
        if not scope.cache_plans:
            cur = q.node
            for n in nodes:
                cur = self._clone(n, [cur] + n.inputs[1:])
            return cur
        key = (q.node.id,) + tuple(n.id for n in nodes)
        root = scope.chain_cache.get(key)
        if root is None:
            root = q.node
            for n in nodes:
                root = self._clone(n, [root] + n.inputs[1:])
            scope.chain_cache[key] = root
        return root

    def _realize_table(
        self, table: Dict[str, np.ndarray], stream: _Stream,
        scope: _IngestScope, extra: Sequence[Node] = (),
    ) -> Dict[str, np.ndarray]:
        """Apply the stream's pending chain (+ extra nodes) to one chunk
        as a single engine job."""
        if not stream.pending and not extra:
            if is_physical_chunk(table, stream.base_schema):
                from dryad_tpu.columnar.batch import decode_physical_table

                t = {c: v for c, v in table.items() if c != "#vocab"}
                return decode_physical_table(
                    stream.base_schema, slice(None), t,
                    self.ctx.dictionary,
                )
            return table
        q = scope.ingest(table, stream.base_schema)
        cur = self._chain_root(
            scope, q, list(stream.pending) + list(extra)
        )
        return self._run_engine(cur)

    def _realized(self, stream: _Stream) -> Iterator[Dict[str, np.ndarray]]:
        scope = self._scope()
        for table in self._iter_base(stream):
            yield self._realize_table(table, stream, scope)

    # ---- evaluator -----------------------------------------------------

    def _eval(self, node: Node):
        """Memoized: a diamond (tee) re-requesting a node gets the same
        result object — small tables share; a second consumer of a
        stream raises the explicit already-consumed error."""
        if node.id in self._eval_cache:
            return self._eval_cache[node.id]
        if self._stream_ids is None:  # one walk per execution
            self._stream_ids = stream_reaching_ids(self.ctx, node)
        r = self._eval_inner(node)
        self._eval_cache[node.id] = r
        return r

    def _reaches_stream(self, node: Node) -> bool:
        # _stream_ids covers every node under the execution root (one
        # topological walk at first _eval)
        return node.id in self._stream_ids

    def _eval_inner(self, node: Node):
        b = self.ctx._bindings.get(node.id)
        if node.kind == "input" and b is not None and b[0] == "stream":
            src: ChunkSource = b[1]
            self._emit("stream_start", node=node.id)
            return "stream", _Stream(
                src.schema, iter(src.chunks), _state=src.state
            )
        if not self._reaches_stream(node):
            return "small", self._run_engine(node)

        if node.kind in PARTITION_HINTS:
            return self._eval(node.inputs[0])
        if node.kind == "concat":
            return self._eval_concat(node)
        if node.kind == "join":
            return self._eval_join(node)
        # single-chain operators: a subtree that STREAMS may still
        # evaluate to a small table (e.g. group_by output feeding
        # order_by) — then this operator runs as one engine job over
        # the materialized input.
        k, v = self._eval(node.inputs[0])
        if k == "small":
            q = self.ctx.from_arrays(v, schema=node.inputs[0].schema)
            cur = self._clone(node, [q.node] + node.inputs[1:])
            return "small", self._run_engine(cur)
        if node.kind in ROW_LOCAL:
            return "stream", v.with_pending(node)
        if node.kind == "group_by":
            return self._eval_group(node, v)
        if node.kind == "aggregate":
            return self._eval_aggregate(node, v)
        if node.kind == "distinct":
            return self._eval_distinct(node, v)
        if node.kind == "order_by":
            return self._eval_order_by(node, v)
        if node.kind == "take":
            return self._eval_take(node, v)
        raise StreamNotSupported(
            f"operator {node.kind!r} over a chunk stream is not supported; "
            "materialize with to_store first"
        )

    # ---- group_by ------------------------------------------------------

    def _eval_group(self, node: Node, stream: _Stream):
        agg_list = node.params.get("aggs")
        keys = list(node.params["keys"])
        if agg_list and all(op in MERGEABLE_AGGS for op, _c, _o in agg_list):
            return self._group_partial(node, stream, keys, agg_list)
        # non-mergeable (custom decomposable without typed state, etc.):
        # Grace hash-bucketing, original group node per bucket.
        return "stream", _Stream(
            node.schema,
            self._grace_buckets([(stream, keys)], [node], node.schema),
        )

    def _finalize_query(self, q, plan, keys, out_schema):
        """Append the merge finalizer (mean = sum/count, renames) to a
        merged-partials query."""
        fin = finalize_fn(plan)

        def full(cols, _fin=fin, _keys=keys):
            from dryad_tpu.exec.partial import copy_physical

            out = {}
            for kk in _keys:
                copy_physical(cols, kk, kk, out)
            out.update(_fin(cols))
            return out

        return q.select(full, schema=out_schema)

    def _chunk_partial_query(self, scope, stream, table, node, keys, partial):
        """One chunk's partial group query, chain-cached: a rebound
        chunk reuses the ingest node, the pending clones, AND the
        group node — the whole per-chunk plan repeats (tentpole (a))."""
        from dryad_tpu.api.query import Query

        q = scope.ingest(table, stream.base_schema)
        key = ("gp", q.node.id)
        pq = scope.chain_cache.get(key)
        if pq is None:
            cur = self._chain_root(scope, q, stream.pending)
            pq = Query(self.ctx, cur).group_by(
                keys, partial,
                dense=node.params.get("dense"),
                salt=node.params.get("salt"),
            )
            if scope.cache_plans:
                scope.chain_cache[key] = pq
        return pq

    def _dispatcher(self, name: str, retry=None) -> _AsyncDispatcher:
        return _AsyncDispatcher(
            self.ctx, self.dispatch_depth, self.chunk_fuse,
            events=self.events, name=name, retry=retry,
        )

    def _group_partial(self, node, stream, keys, agg_list):
        if self._pipelined:
            return self._group_partial_device(node, stream, keys, agg_list)
        if self._async_dispatch:
            return self._group_partial_async(node, stream, keys, agg_list)
        return self._group_partial_serial(node, stream, keys, agg_list)

    def _group_partial_serial(self, node, stream, keys, agg_list):
        """Legacy serial driver (stream_pipeline_depth=1): per-chunk
        host readback of partials, host-side combine re-ingest."""
        partial, plan = partial_plan(agg_list)
        merge_spec = merge_agg_spec(plan)
        scope = self._scope()
        mscope = self._scope()
        acc: List[Dict[str, np.ndarray]] = []
        acc_rows = 0
        pschema = None

        def combine(tables, final: bool):
            cat = _concat_tables(tables, pschema)
            q = mscope.ingest(cat, pschema).group_by(keys, merge_spec)
            if final:
                q = self._finalize_query(q, plan, keys, node.schema)
            return self.ctx.run_to_host(q)

        shape = TreeShape(self.ctx.mesh, self.ctx.config)
        nchunks = 0
        for table in self._iter_base(stream):
            n = _chunk_rows(table)
            pq = self._chunk_partial_query(
                scope, stream, table, node, keys, partial
            )
            if pschema is None:
                pschema = pq.schema
            pt = self.ctx.run_to_host(pq)
            rows = len(next(iter(pt.values()))) if pt else 0
            acc.append(pt)
            acc_rows += rows
            nchunks += 1
            self._emit("stream_chunk", rows=n, partial_rows=rows)
            if acc_rows > self.combine_rows and len(acc) > 1:
                in_bytes = sum(
                    int(np.asarray(v).nbytes)
                    for t in acc for v in t.values()
                )
                merged = combine(acc, final=False)
                acc = [merged]
                acc_rows = len(next(iter(merged.values()))) if merged else 0
                out_bytes = sum(
                    int(np.asarray(v).nbytes) for v in merged.values()
                )
                ici, dcn = shape.exchange_split(in_bytes, out_bytes)
                self._emit("stream_combine", rows_out=acc_rows, level=0,
                           ici_bytes=ici, dcn_bytes=dcn)
        if pschema is None:  # empty stream
            return "small", _empty_table(node.schema)
        out = combine(acc, final=True)
        self._emit("stream_group_done", chunks=nchunks,
                   groups=len(next(iter(out.values()))) if out else 0)
        return "small", out

    def _group_partial_async(self, node, stream, keys, agg_list):
        """Async serial driver (``dispatch_depth``/``chunk_fuse`` > 1
        without the device-resident pipeline): the exact
        ``_group_partial_serial`` accumulate/combine body, but chunk
        partial dispatches stay in flight through the
        :class:`DispatchWindow` and readbacks drain on the collector
        thread.  Commits run strictly in submit order, so the host
        accumulator (and its float reduction order) matches the serial
        loop bit-for-bit."""
        partial, plan = partial_plan(agg_list)
        merge_spec = merge_agg_spec(plan)
        # one cached-input slot per fused chunk: a fused batch needs
        # all K input nodes bound simultaneously at dispatch
        scope = self._scope(slots=self.chunk_fuse)
        mscope = self._scope()
        acc: List[Dict[str, np.ndarray]] = []
        st = {"acc_rows": 0, "nchunks": 0, "pschema": None}
        shape = TreeShape(self.ctx.mesh, self.ctx.config)

        def combine(tables, final: bool):
            cat = _concat_tables(tables, st["pschema"])
            q = mscope.ingest(cat, st["pschema"]).group_by(keys, merge_spec)
            if final:
                q = self._finalize_query(q, plan, keys, node.schema)
            return self.ctx.run_to_host(q)

        def retry(tag):
            # serial re-execution of ONE chunk: the original cached
            # input node may have been rebound to a later chunk by
            # slot reuse, so re-ingest the retained host table through
            # a fresh uncached scope
            _n, table = tag
            rscope = _IngestScope(self.ctx)
            rq = self._chunk_partial_query(
                rscope, stream, table, node, keys, partial
            )
            return self.ctx.run_to_host(rq)

        def commit(tag, pt):
            n, _table = tag
            rows = len(next(iter(pt.values()))) if pt else 0
            acc.append(pt)
            st["acc_rows"] += rows
            st["nchunks"] += 1
            self._emit("stream_chunk", rows=n, partial_rows=rows)
            if st["acc_rows"] > self.combine_rows and len(acc) > 1:
                in_bytes = sum(
                    int(np.asarray(v).nbytes)
                    for t in acc for v in t.values()
                )
                merged = combine(acc, final=False)
                acc[:] = [merged]
                st["acc_rows"] = (
                    len(next(iter(merged.values()))) if merged else 0
                )
                out_bytes = sum(
                    int(np.asarray(v).nbytes) for v in merged.values()
                )
                ici, dcn = shape.exchange_split(in_bytes, out_bytes)
                self._emit("stream_combine", rows_out=st["acc_rows"],
                           level=0, ici_bytes=ici, dcn_bytes=dcn)

        dsp = self._dispatcher("grouppartial", retry=retry)
        try:
            for table in self._iter_base(stream):
                n = _chunk_rows(table)
                pq = self._chunk_partial_query(
                    scope, stream, table, node, keys, partial
                )
                if st["pschema"] is None:
                    st["pschema"] = pq.schema
                dsp.submit((n, table), pq)
                for tag, pt in dsp.ready():
                    commit(tag, pt)
            for tag, pt in dsp.drain():
                commit(tag, pt)
        finally:
            dsp.close()
        if st["pschema"] is None:  # empty stream
            return "small", _empty_table(node.schema)
        out = combine(acc, final=True)
        self._emit("stream_group_done", chunks=st["nchunks"],
                   groups=len(next(iter(out.values()))) if out else 0)
        return "small", out

    def _batch_to_host(self, batch, schema) -> Dict[str, np.ndarray]:
        """Materialize a device batch as a host logical table (the
        degrade path when device-side combining stops paying)."""
        self.metrics.add(
            "d2h_bytes",
            sum(int(v.nbytes) for v in batch.data.values())
            + int(batch.valid.nbytes),
        )
        return batch.to_numpy(schema, self.ctx.dictionary)

    def _group_partial_device(self, node, stream, keys, agg_list):
        """Pipelined driver: per-chunk partials stay DEVICE-RESIDENT
        (dispatched, never fetched), accumulate as ColumnBatches in HBM
        and merge device-to-device — the scatter phase pays one D2H at
        the END instead of one per chunk (the DrDynamicAggregateManager
        machine->pod tree folded onto the accelerator; DrJAX's
        device-resident MapReduce partials).

        With ``config.combine_tree`` on (default), accumulation runs
        through the topology/distribution-aware tree of
        :mod:`exec.combinetree`; the flat N-ary combiner below stays as
        the differential baseline and covers engine-order-sensitive
        aggregates (``first``), which the tree's similarity routing
        would reorder."""
        tree = bool(getattr(self.ctx.config, "combine_tree", True))
        ov = (
            self.rewriter.combine_tree_override()
            if self.rewriter is not None else None
        )
        if ov is not None and bool(ov) != tree:
            # combine_thrash rewrite: flip the strategy for streams
            # that START after the diagnosis (both strategies compute
            # the same groups — only the merge cadence differs)
            tree = bool(ov)
            self._emit(
                "plan_rewrite", phase="applied", action="flip_combine",
                rule="combine_thrash", subject="stream_combine",
                tree=tree,
            )
        if tree and not any(
            op == "first" for op, _c, _o in agg_list
        ):
            return self._group_partial_tree(node, stream, keys, agg_list)
        return self._group_partial_flat(node, stream, keys, agg_list)

    def _combine_pinned(self) -> bool:
        """True when a combine_thrash rewrite pinned the streaming
        combine to host accumulation (the always-correct conservative
        side of the oscillation)."""
        return (
            self.rewriter is not None
            and self.rewriter.combine_pin() == "host"
        )

    def _first_chunk_irreducible(self, table, stream, keys, batch, n):
        """Static high-cardinality signal for the first chunk: count the
        chunk's distinct keys with a HOST-side hash (exact, no device
        readback).  The partial batch's layout capacity is only trusted
        as a fallback for physical (device-resident) chunks, and only
        below the chunk's row count — the pow2 palette can pad capacity
        past n, which says nothing about the keys."""
        if n <= 0:
            return False
        if not is_physical_chunk(table, stream.base_schema):
            h = _host_key_hash64(table, keys, dictionary=self.ctx.dictionary)
            return np.unique(h).size >= 0.75 * n
        return n > batch.capacity >= 0.75 * n

    def _group_partial_flat(self, node, stream, keys, agg_list):
        """Flat N-ary device combiner (the tree-off baseline).

        High-cardinality streams whose merges show no reduction (static
        capacity check in :class:`_DeviceCombiner`) degrade to the
        serial driver's host-side threshold accumulation — on such
        streams device merging re-processes every row for nothing,
        while host accumulation pays one cheap transfer per chunk.  The
        degrade is no longer sticky: after
        ``config.stream_host_reprobe`` CONSECUTIVE host combines that
        do reduce below the device capacity check, the device path is
        retried with the merged accumulator re-ingested."""
        partial, plan = partial_plan(agg_list)
        merge_spec = merge_agg_spec(plan)
        scope = self._scope()
        mscope = self._scope()
        pschema = None
        shape = TreeShape(self.ctx.mesh, self.ctx.config)
        reprobe_after = int(
            getattr(self.ctx.config, "stream_host_reprobe", 0) or 0
        )

        def merge_many(batches):
            qs = [self.ctx._from_device_batch(b, pschema) for b in batches]
            q = qs[0].concat(*qs[1:])  # ONE N-ary concat node/stage
            return self.ctx._execute_device(q.group_by(keys, merge_spec))

        def host_combine(tables, final: bool):
            cat = _concat_tables(tables, pschema)
            q = mscope.ingest(cat, pschema).group_by(keys, merge_spec)
            if final:
                q = self._finalize_query(q, plan, keys, node.schema)
            return self.ctx.run_to_host(q)

        comb = _DeviceCombiner(
            merge_many, self.combine_rows, self._emit,
            split=shape.exchange_split,
        )
        host_acc: Optional[List[Dict[str, np.ndarray]]] = None
        host_rows = 0
        reduce_streak = 0  # consecutive host combines that DID reduce
        nchunks = 0
        pin_applied = False
        if self._combine_pinned():
            # pin_combine rewrite: start (and stay) on host
            # accumulation — no probe merge, no reprobe oscillation
            host_acc = []
            pin_applied = True
            self._emit("stream_combine_policy", mode="host", chunks=0,
                       pinned=True)
            self._emit(
                "plan_rewrite", phase="applied", action="pin_combine",
                rule="combine_thrash", subject="stream_combine",
                mode="host",
            )
        for table in self._iter_base(stream):
            n = _chunk_rows(table)
            pq = self._chunk_partial_query(
                scope, stream, table, node, keys, partial
            )
            if pschema is None:
                pschema = pq.schema
            batch = self.ctx._execute_device(pq)  # partial stays in HBM
            nchunks += 1
            self._emit("stream_chunk", rows=n, partial_cap=batch.capacity)
            if host_acc is None and nchunks == 1 \
                    and self._first_chunk_irreducible(table, stream, keys,
                                                     batch, n):
                # the FIRST chunk's keys are ~all distinct: device
                # merging cannot pay — degrade before paying even one
                # probe merge
                host_acc = []
                self._emit("stream_combine_policy", mode="host",
                           chunks=nchunks, static=True)
            if host_acc is None:
                if comb.push(batch):
                    continue
                # no reduction: degrade to host accumulation
                host_acc = [
                    self._batch_to_host(b, pschema) for b in comb.drain()
                ]
                host_rows = sum(
                    len(next(iter(t.values()))) if t else 0
                    for t in host_acc
                )
                self._emit("stream_combine_policy", mode="host",
                           chunks=nchunks)
            else:
                pt = self._batch_to_host(batch, pschema)
                host_acc.append(pt)
                host_rows += len(next(iter(pt.values()))) if pt else 0
            if host_rows > self.combine_rows and len(host_acc) > 1:
                pre_rows = host_rows
                in_bytes = sum(
                    int(np.asarray(v).nbytes)
                    for t in host_acc for v in t.values()
                )
                merged = host_combine(host_acc, final=False)
                host_acc = [merged]
                host_rows = len(next(iter(merged.values()))) if merged else 0
                out_bytes = sum(
                    int(np.asarray(v).nbytes) for v in merged.values()
                )
                ici, dcn = shape.exchange_split(in_bytes, out_bytes)
                self._emit("stream_combine", rows_out=host_rows, level=0,
                           ici_bytes=ici, dcn_bytes=dcn)
                # un-stick the degrade: host combines that keep reducing
                # mean the keys DO collapse — the earlier no-reduction
                # signal was transient (skew burst, unlucky first chunk)
                if host_rows < 0.75 * pre_rows:
                    reduce_streak += 1
                else:
                    reduce_streak = 0
                if self._combine_pinned():
                    # a combine_thrash diagnosis mid-stream pins the
                    # degrade: stop re-probing the device path
                    reduce_streak = 0
                    if not pin_applied:
                        pin_applied = True
                        self._emit(
                            "plan_rewrite", phase="applied",
                            action="pin_combine", rule="combine_thrash",
                            subject="stream_combine", mode="host",
                        )
                elif (
                    reprobe_after
                    and reduce_streak >= reprobe_after
                    and host_rows > 0
                ):
                    back = self.ctx._execute_device(
                        mscope.ingest(merged, pschema)
                    )
                    self.metrics.add(
                        "h2d_bytes",
                        sum(int(np.asarray(v).nbytes)
                            for v in merged.values()),
                    )
                    comb.push(back)
                    host_acc = None
                    host_rows = 0
                    reduce_streak = 0
                    self._emit("stream_combine_policy", mode="device",
                               chunks=nchunks, reprobe=True)
        if pschema is None:  # empty stream
            return "small", _empty_table(node.schema)
        if host_acc is not None:
            out = host_combine(host_acc, final=True)
        else:
            folded = comb.fold()
            q = self.ctx._from_device_batch(folded, pschema).group_by(
                keys, merge_spec
            )
            q = self._finalize_query(q, plan, keys, node.schema)
            out = self.ctx.run_to_host(q)
        self._emit("stream_group_done", chunks=nchunks,
                   groups=len(next(iter(out.values()))) if out else 0)
        return "small", out

    def _group_partial_tree(self, node, stream, keys, agg_list):
        """Combine-tree driver (``exec.combinetree``): chunk partials
        route into similarity-placed tree groups whose merges ELIDE the
        hash exchange — partials are co-hash-partitioned on the group
        keys, so equal keys are already colocated and one local reduce
        merges them with zero collective bytes.  Only the final
        merge+finalize query pays a full exchange: on a hybrid mesh the
        tree exchange's ICI hop, per-slice combine, and exactly one DCN
        hop last.

        The all-or-nothing host degrade becomes PER-KEY-RANGE: the
        driver hashes each raw chunk's keys host-side (before ingest),
        folds them into a :class:`KeyRangeHistogram`, and ranges whose
        distinct-key estimate tracks their row count — merging cannot
        reduce them — split out of subsequent chunks and stream to host
        accumulation, while hot, still-reducing ranges stay on
        device."""
        cfg = self.ctx.config
        partial, plan = partial_plan(agg_list)
        merge_spec = merge_agg_spec(plan)
        scope = self._scope()
        cscope = self._scope()  # degraded-range (cold) chunk plans
        mscope = self._scope()  # host-side combine plans
        pschema = None
        shape = TreeShape(self.ctx.mesh, cfg)
        ranges = int(getattr(cfg, "combine_tree_ranges", 64))
        planner = CombineTreePlanner(
            ranges, float(getattr(cfg, "combine_tree_degrade_ratio", 0.75))
        )
        hist = KeyRangeHistogram(ranges)

        def merge_local(batches):
            # every chunk's partial group_by hash-exchanged on the same
            # keys over the same mesh, so the batches are co-partitioned
            # and the merge elides its exchange entirely
            # (plan.lower._needs_hash_exchange on the assume claim)
            qs = [self.ctx._from_device_batch(b, pschema) for b in batches]
            q = qs[0].concat(*qs[1:]).assume_hash_partition(keys)
            return self.ctx._execute_device(q.group_by(keys, merge_spec))

        def host_combine(tables, final: bool):
            cat = _concat_tables(tables, pschema)
            q = mscope.ingest(cat, pschema).group_by(keys, merge_spec)
            if final:
                q = self._finalize_query(q, plan, keys, node.schema)
            return self.ctx.run_to_host(q)

        comb = TreeCombiner(merge_local, shape, self.combine_rows, self._emit)
        host_acc: List[Dict[str, np.ndarray]] = []
        host_rows = 0
        degraded: set = set()
        nchunks = 0
        for table in self._iter_base(stream):
            n = _chunk_rows(table)
            h = None
            if not is_physical_chunk(table, stream.base_schema):
                h = _host_key_hash64(
                    table, keys, dictionary=self.ctx.dictionary
                )
            snap = None
            if h is not None:
                ch = KeyRangeHistogram(ranges)
                ch.observe(h)
                hist.merge(ch)
                snap = ch.snapshot()
            nchunks += 1
            hot: Optional[Dict[str, Any]] = table
            if degraded and h is not None:
                rid = KeyRangeHistogram.range_ids(h, ranges)
                cold_mask = np.isin(
                    rid, np.fromiter(degraded, np.int64, len(degraded))
                )
                if cold_mask.any():
                    cold = {
                        c: np.asarray(v)[cold_mask]
                        for c, v in table.items()
                    }
                    hot = (
                        {
                            c: np.asarray(v)[~cold_mask]
                            for c, v in table.items()
                        }
                        if not cold_mask.all() else None
                    )
                    cq = self._chunk_partial_query(
                        cscope, stream, cold, node, keys, partial
                    )
                    if pschema is None:
                        pschema = cq.schema
                    pt = self.ctx.run_to_host(cq)
                    host_acc.append(pt)
                    host_rows += len(next(iter(pt.values()))) if pt else 0
            if hot is not None:
                pq = self._chunk_partial_query(
                    scope, stream, hot, node, keys, partial
                )
                if pschema is None:
                    pschema = pq.schema
                batch = self.ctx._execute_device(pq)  # stays in HBM
                self._emit(
                    "stream_chunk", rows=n, partial_cap=batch.capacity
                )
                comb.push(batch, snap or neutral_snapshot(ranges))
            else:
                self._emit("stream_chunk", rows=n, partial_cap=0)
            if host_rows > self.combine_rows and len(host_acc) > 1:
                in_bytes = sum(
                    int(np.asarray(v).nbytes)
                    for t in host_acc for v in t.values()
                )
                merged = host_combine(host_acc, final=False)
                host_acc = [merged]
                host_rows = len(next(iter(merged.values()))) if merged else 0
                out_bytes = sum(
                    int(np.asarray(v).nbytes) for v in merged.values()
                )
                ici, dcn = shape.exchange_split(in_bytes, out_bytes)
                self._emit("stream_combine", rows_out=host_rows, level=0,
                           ici_bytes=ici, dcn_bytes=dcn)
            if h is not None:
                planner.note_cumulative(hist.snapshot())
                new = planner.degrade_set()
                if new - degraded:
                    degraded = new
                    self._emit(
                        "combine_tree_degrade", degraded=len(degraded),
                        fraction=round(planner.degraded_fraction(), 4),
                        chunks=nchunks,
                    )
        if pschema is None:  # empty stream
            return "small", _empty_table(node.schema)
        if not host_acc:
            # pure device path: collapse the survivors to ONE batch with
            # elided merges first — the root query's exchange pays bytes
            # proportional to what it ingests, and elided merges are
            # nearly free, so the root must see the minimum — then run
            # the one exchanged merge+finalize reduction, with the DCN
            # hop accounted at the distribution-informed output estimate
            # (the exchange folds to at most the estimated distinct keys)
            folded = comb.fold(1)
            if not folded:  # every chunk was empty
                return "small", _empty_table(node.schema)
            root = folded[0]
            in_bytes = batch_bytes(root)
            est_rows = (
                float(hist.distinct_estimates().sum()) if hist.rows else 0.0
            )
            per_row = in_bytes / max(int(root.capacity), 1)
            out_bytes = int(min(in_bytes, per_row * max(est_rows, 1.0)))
            ici, dcn = shape.exchange_split(in_bytes, out_bytes)
            self._emit(
                "combine_tree_level", level=comb.max_level + 1,
                fan_in=1, cap_rows=int(root.capacity), bytes=in_bytes,
                ici_bytes=ici, dcn_bytes=dcn, device=True,
            )
            q = self.ctx._from_device_batch(root, pschema).group_by(
                keys, merge_spec
            )
            q = self._finalize_query(q, plan, keys, node.schema)
            out = self.ctx.run_to_host(q)
        else:
            # degraded ranges finish host-side: the device remainder
            # folds once, pays ONE D2H, and merges with the host
            # accumulator in the final combine
            folded = comb.fold(1)
            tables = list(host_acc)
            if folded:
                tables.append(self._batch_to_host(folded[0], pschema))
            out = host_combine(tables, final=True)
        self._emit("stream_group_done", chunks=nchunks,
                   groups=len(next(iter(out.values()))) if out else 0)
        return "small", out

    # ---- scalar aggregate ---------------------------------------------

    def _eval_aggregate(self, node: Node, stream: _Stream):
        from dryad_tpu.api.query import Query

        agg_list = node.params["aggs"]
        bad = [op for op, _c, _o in agg_list
               if op not in MERGEABLE_AGGS or op == "first"]
        if bad:
            raise StreamNotSupported(
                f"streaming scalar aggregate cannot merge {bad}"
            )
        partial, plan = partial_plan(agg_list)
        merge_spec = merge_agg_spec(plan)
        scope = self._scope(
            slots=1 if self._pipelined else self.chunk_fuse
        )
        fin = finalize_fn(plan)
        pschema = None

        def chunk_query(table):
            q = scope.ingest(table, stream.base_schema)
            key = ("agg", q.node.id)
            pq = scope.chain_cache.get(key)
            if pq is None:
                cur = self._chain_root(scope, q, stream.pending)
                pq = Query(self.ctx, cur).aggregate_as_query(partial)
                if scope.cache_plans:
                    scope.chain_cache[key] = pq
            return pq

        if self._pipelined:
            # device-resident partials + N-ary device merge: one D2H
            # total (scalar partials are one row each, so flushes
            # always reduce and never degrade)
            def merge_many(batches):
                qs = [
                    self.ctx._from_device_batch(b, pschema) for b in batches
                ]
                q = qs[0].concat(*qs[1:]).aggregate_as_query(merge_spec)
                return self.ctx._execute_device(q)

            comb = _DeviceCombiner(
                merge_many, self.combine_rows, self._emit,
                split=TreeShape(self.ctx.mesh, self.ctx.config).exchange_split,
            )
            for table in self._iter_base(stream):
                pq = chunk_query(table)
                if pschema is None:
                    pschema = pq.schema
                comb.push(self.ctx._execute_device(pq))
            folded = comb.fold()
            if folded is None:
                raise StreamNotSupported(
                    "scalar aggregate over an empty stream"
                )
            q = self.ctx._from_device_batch(folded, pschema)
            q = q.aggregate_as_query(merge_spec)
            q = q.select(lambda cols: fin(cols), schema=node.schema)
            return "small", self.ctx.run_to_host(q)

        # serial driver: host partials, bounded by the SAME combine
        # threshold as _group_partial — a long stream must not grow the
        # accumulator one partial row per chunk without bound
        acc_t: List[Dict[str, np.ndarray]] = []
        st = {"rows": 0}
        mscope = self._scope()

        def commit(pt):
            acc_t.append(pt)
            st["rows"] += len(next(iter(pt.values()))) if pt else 0
            if st["rows"] > self.combine_rows and len(acc_t) > 1:
                cat = _concat_tables(acc_t, pschema)
                merged = self.ctx.run_to_host(
                    mscope.ingest(cat, pschema).aggregate_as_query(merge_spec)
                )
                acc_t[:] = [merged]
                st["rows"] = len(next(iter(merged.values()))) if merged else 0
                self._emit(
                    "stream_combine", rows_out=st["rows"],
                    level=0, ici_bytes=0, dcn_bytes=0,
                )

        if self._async_dispatch:
            # async serial driver: partial dispatches stay in flight
            # through the window; the host accumulator commits at the
            # drain site in submit order (same body, same float order)
            def retry(table):
                rscope = _IngestScope(self.ctx)
                rq = Query(
                    self.ctx,
                    self._chain_root(
                        rscope, rscope.ingest(table, stream.base_schema),
                        stream.pending,
                    ),
                ).aggregate_as_query(partial)
                return self.ctx.run_to_host(rq)

            dsp = self._dispatcher("aggpartial", retry=retry)
            try:
                for table in self._iter_base(stream):
                    pq = chunk_query(table)
                    if pschema is None:
                        pschema = pq.schema
                    dsp.submit(table, pq)
                    for _tag, pt in dsp.ready():
                        commit(pt)
                for _tag, pt in dsp.drain():
                    commit(pt)
            finally:
                dsp.close()
        else:
            for table in self._iter_base(stream):
                pq = chunk_query(table)
                if pschema is None:
                    pschema = pq.schema
                commit(self.ctx.run_to_host(pq))
        if pschema is None:
            raise StreamNotSupported("scalar aggregate over an empty stream")
        cat = _concat_tables(acc_t, pschema)
        q = mscope.ingest(cat, pschema).aggregate_as_query(merge_spec)
        q = q.select(lambda cols: fin(cols), schema=node.schema)
        return "small", self.ctx.run_to_host(q)

    def _iter_base(self, stream: _Stream):
        """Non-empty base chunks, read ahead by the prefetch thread when
        the pipeline is on: the source generator's host work (tokenize,
        disk read, decode) for chunk k+2 overlaps the driver's device
        dispatch of chunk k+1 (``exec.pipeline``)."""
        if stream.consumed:
            raise RuntimeError("stream already consumed (tee over streams "
                               "needs an explicit to_store)")
        stream.consumed = True

        def nonempty():
            for table in stream.chunks:
                if _chunk_rows(table):
                    yield table

        yield from prefetched(
            nonempty(), self.pipeline_depth, events=self.events,
            name="ingest",
        )

    # ---- distinct ------------------------------------------------------

    def _eval_distinct(self, node: Node, stream: _Stream):
        keys = list(node.params["keys"] or stream.schema.names)
        scope = self._scope()
        acc: List[Dict[str, np.ndarray]] = []
        acc_rows = 0
        spill = None
        writer = None
        try:
            for table in self._iter_base(stream):
                t = self._realize_table(table, stream, scope, extra=[node])
                rows = len(next(iter(t.values()))) if t else 0
                if spill is not None:
                    self._spill_by_hash(spill, t, keys, 0, writer=writer)
                    continue
                acc.append(t)
                acc_rows += rows
                if acc_rows > self.combine_rows and len(acc) > 1:
                    cscope = self._scope()
                    cat = _concat_tables(acc, node.schema)
                    cur = self._clone(
                        node, [cscope.ingest(cat, node.schema).node]
                    )
                    merged = self._run_engine(cur)
                    acc = [merged]
                    acc_rows = (
                        len(next(iter(merged.values()))) if merged else 0
                    )
                    if acc_rows > self.bucket_rows:
                        # high cardinality: switch to Grace spilling
                        spill = SpillDir(self.ctx.dictionary,
                                         root=self._spill_root())
                        writer = self._spill_writer()
                        self._spill_by_hash(spill, merged, keys, 0,
                                            writer=writer)
                        acc = []
                        self._emit("stream_distinct_spill", rows=acc_rows)
            if writer is not None:
                writer.flush()
        except BaseException:
            if writer is not None:
                writer.close(drain=False)
                writer = None
            if spill is not None:
                spill.cleanup()
            raise
        finally:
            if writer is not None:
                writer.close()
        if spill is None:
            if not acc:
                return "small", _empty_table(node.schema)
            cscope = self._scope()
            cat = _concat_tables(acc, node.schema)
            cur = self._clone(node, [cscope.ingest(cat, node.schema).node])
            return "small", self._run_engine(cur)

        def buckets():
            try:
                bscope = self._scope()
                for b in spill.buckets():
                    rows = spill.bucket_rows(b)
                    t = spill.read_bucket(b)
                    bscope.cap = self._bucket_cap(rows)
                    cur = self._clone(
                        node, [bscope.ingest(t, node.schema).node]
                    )
                    out = self._run_engine(cur)
                    self._emit("stream_bucket", bucket=b, depth=0, rows=rows)
                    yield out
            finally:
                spill.cleanup()

        return "stream", _Stream(node.schema, buckets())

    # ---- order_by (external distribution sort) -------------------------

    def _eval_order_by(self, node: Node, stream: _Stream):
        keys = list(node.params["keys"])  # [(name, desc)]
        return "stream", _Stream(
            node.schema, self._external_sort(node, stream, keys)
        )

    def _bucket_cap(self, rows: int) -> int:
        """Per-partition capacity for a bucket job from its OBSERVED
        rows: the next power-of-two step of the per-partition need
        (min 8), capped at the configured bucket budget.  Padding
        shrinks from the worst-case layout (~16x waste on typical
        shapes) to < 2x the data, while the pow2 palette keeps the
        number of distinct compiled programs logarithmic.

        The serial legacy driver (depth 1) keeps its original
        worst-case capacity — one compiled program for ALL buckets, and
        the differential baseline the pipeline is measured against."""
        P = self._P()
        full = max(1, math.ceil(self.bucket_rows / P / 8) * 8)
        if not self._pipelined:
            return full
        need = max(1, -(-max(rows, 1) // P))
        cap = 8
        while cap < need:
            cap *= 2
        return min(cap, full)

    def _external_sort(
        self, node, stream, keys, pieces=None, depth=0, splitters=None
    ):
        """Route chunks to range buckets by the primary key, then sort
        each bucket on device and emit in key order.  Oversized buckets
        re-split from observed volume; a single-value bucket falls
        through to the secondary keys (or emits as-is when none —
        equal-key order is unspecified).

        Pipelined (depth knob > 1): bucket writes go through the
        background SpillWriter so they overlap the next chunk's
        routing, and phase 2 keeps ``stream_pipeline_depth`` bucket
        sorts in flight — read/decode of bucket k+2 on the prefetch
        thread, dispatch of k+1, readback of k."""
        primary, pdesc = keys[0]
        spill = SpillDir(self.ctx.dictionary, root=self._spill_root())
        writer = self._spill_writer()
        # rewrite-split hot buckets: bucket -> {"splitters", "spill",
        # "extent", "rows"} — rows landing in a refined bucket route
        # straight into its sub-range spill at depth+1 (rewrite
        # controller's split_bucket action, claimed at chunk bounds)
        refined: Dict[int, dict] = {}
        try:
            scope = self._scope()
            if pieces is not None:
                src = prefetched(
                    self._iter_pieces_realized(pieces),
                    self.pipeline_depth, events=self.events,
                    name=f"resplit{depth}",
                )
            else:
                src = (self._realize_table(t, stream, scope)
                       for t in self._iter_base(stream))
            # exact per-bucket key extent, tracked at spill time — the
            # all-equal decision below must not rest on a sample (a few
            # minority rows in a fat bucket would go out unsorted)
            extent: Dict[int, Tuple] = {}
            for t in src:
                col = _sort_key_view(t[primary])
                if splitters is None:
                    splitters = _sample_splitters(col, self.num_buckets)
                # chunk boundary = safe application point: no partial
                # chunk is in flight, bucket contents are self-contained
                if self.rewriter is not None:
                    self._apply_sort_splits(
                        spill, writer, refined, primary, depth
                    )
                bids = np.searchsorted(splitters, col, side="right")
                for b in np.unique(bids):
                    sel = bids == b
                    piece = {c: v[sel] for c, v in t.items()}
                    if int(b) in refined:
                        self._route_refined(
                            refined[int(b)], piece, primary, depth
                        )
                        continue
                    vals = col[sel]
                    mn, mx = vals.min(), vals.max()
                    if b in extent:
                        pmn, pmx = extent[b]
                        mn, mx = min(mn, pmn), max(mx, pmx)
                    extent[int(b)] = (mn, mx)
                    self.metrics.observe(
                        "partition_rows", int(sel.sum()), depth=depth
                    )
                    if writer is not None:
                        writer.submit(spill, int(b), piece, depth)
                    else:
                        b0 = spill.bytes_written
                        n = spill.append(int(b), piece)
                        self.metrics.add(
                            "spill_bytes", spill.bytes_written - b0
                        )
                        self._emit("stream_spill", bucket=int(b), rows=n,
                                   depth=depth)
            if writer is not None:
                writer.flush()  # phase barrier: bucket metadata is final
            order = spill.buckets()
            if refined:
                order = sorted(set(order) | set(refined))
            if pdesc:
                order = list(reversed(order))
            yield from self._sort_buckets(
                node, spill, order, extent, keys, depth,
                refined=refined or None,
            )
        finally:
            if writer is not None:
                writer.close(drain=False)
            for rec in refined.values():
                rec["spill"].cleanup()
            spill.cleanup()

    def _apply_sort_splits(self, spill, writer, refined, primary, depth):
        """Claim pending split_bucket rewrites for this depth and turn
        each into a range refinement: sub-splitters from the bucket's
        live sample, already-spilled pieces re-routed eagerly, future
        rows routed on arrival (``_route_refined``).  Byte-identity:
        sub-buckets nest inside the parent range and emit in range
        order, so the global sorted order is exactly preserved."""
        acts = self.rewriter.claim_splits(depth)
        acts = [a for a in acts
                if int(a.params["bucket"]) not in refined]
        if not acts or depth >= self.max_split_depth:
            return
        if writer is not None:
            writer.flush()  # bucket piece lists must be final to reroute
        for act in acts:
            b = int(act.params["bucket"])
            if b not in spill.buckets():
                continue  # diagnosis about another spill at this depth
            sample = _bucket_sample(spill, b, primary)
            sub = _splitters_from_sample(
                sample, int(act.params.get("fan", 8) or 8)
            )
            if len(sub) == 0:
                continue  # single-valued: a range split cannot help
            rec = {
                "splitters": sub,
                "spill": SpillDir(
                    self.ctx.dictionary, root=self._spill_root()
                ),
                "extent": {},
                "rows": 0,
            }
            for piece in spill.read_bucket_pieces(b):
                self._route_refined(rec, piece, primary, depth)
            spill.drop_bucket(b)
            refined[b] = rec
            self._emit(
                "plan_rewrite", phase="applied", action="split_bucket",
                rule=act.rule, subject=act.subject, bucket=b,
                depth=depth, fan=int(len(sub)) + 1,
            )

    def _route_refined(self, rec, piece, primary, depth):
        """Route one piece of a rewrite-split bucket into its sub-range
        spill at ``depth + 1``, tracking exact sub-extents (the same
        invariant phase 1 keeps for the parent buckets)."""
        col = _sort_key_view(piece[primary])
        bids = np.searchsorted(rec["splitters"], col, side="right")
        rspill = rec["spill"]
        for sb in np.unique(bids):
            sel = bids == sb
            vals = col[sel]
            mn, mx = vals.min(), vals.max()
            if int(sb) in rec["extent"]:
                pmn, pmx = rec["extent"][int(sb)]
                mn, mx = min(mn, pmn), max(mx, pmx)
            rec["extent"][int(sb)] = (mn, mx)
            sub = {c: v[sel] for c, v in piece.items()}
            self.metrics.observe(
                "partition_rows", int(sel.sum()), depth=depth + 1
            )
            b0 = rspill.bytes_written
            n = rspill.append(int(sb), sub)
            self.metrics.add("spill_bytes", rspill.bytes_written - b0)
            self._emit("stream_spill", bucket=int(sb), rows=n,
                       depth=depth + 1)
            rec["rows"] += n

    def _sort_buckets(self, node, spill, order, extent, keys, depth,
                      refined=None):
        """Phase 2 of the external sort: per-bucket device sorts in
        key order, with read-ahead and a bounded dispatch window when
        pipelined."""
        from dryad_tpu.api.query import Query

        primary, _pdesc = keys[0]
        # one scope for all buckets: the pow2 capacity palette keeps
        # repeated bucket sizes on the same compiled program
        bscope = self._scope(slots=self.chunk_fuse)

        def reads():
            for b in order:
                if refined and b in refined:
                    # rewrite-split: contents live in the sub-spill,
                    # the driver recurses below (never read whole)
                    yield b, refined[b]["rows"], None
                    continue
                rows = spill.bucket_rows(b)
                # oversized buckets are re-split by the driver, which
                # streams their pieces — don't read them whole ahead
                table = (
                    spill.read_bucket(b) if rows <= self.bucket_rows
                    else None
                )
                yield b, rows, table

        src = prefetched(
            reads(), self.pipeline_depth, events=self.events,
            name=f"sortread{depth}",
        )
        inflight: deque = deque()  # (fetch, bucket, rows)

        def drain_one():
            fetch, b, rows = inflight.popleft()
            out = fetch()
            self._emit("stream_bucket", bucket=b, rows=rows, depth=depth)
            spill.drop_bucket(b)
            return out

        def retry(tag):
            # serial re-run of one bucket through a fresh scope (the
            # shared bscope's cached node may have been rebound to a
            # later bucket by the time the drain site sees the error)
            b, rows, t = tag
            rscope = _IngestScope(self.ctx)
            rscope.cap = self._bucket_cap(rows)
            return self._run_engine(
                self._clone(node, [rscope.ingest(t, node.schema).node])
            )

        dsp = (
            self._dispatcher(f"sortdrain{depth}", retry=retry)
            if self._async_dispatch else None
        )

        def committed(outcomes):
            for (db, drows, _dt), out in outcomes:
                self._emit("stream_bucket", bucket=db, rows=drows,
                           depth=depth)
                spill.drop_bucket(db)
                yield out

        try:
            for b, rows, t in src:
                if t is not None:
                    bscope.cap = self._bucket_cap(rows)
                    cur = self._clone(
                        node, [bscope.ingest(t, node.schema).node]
                    )
                    if dsp is not None:
                        # async drain path: the collector owns the
                        # readback, the driver commits in key order
                        dsp.submit((b, rows, t), Query(self.ctx, cur))
                        yield from committed(dsp.ready())
                    elif self._pipelined:
                        fetch = self.ctx.run_to_host_async(
                            Query(self.ctx, cur)
                        )
                        inflight.append((fetch, b, rows))
                        while len(inflight) >= self.pipeline_depth:
                            yield drain_one()
                    else:
                        out = self._run_engine(cur)
                        self._emit("stream_bucket", bucket=b, rows=rows,
                                   depth=depth)
                        yield out
                        spill.drop_bucket(b)
                    continue
                # refined or oversized: results must stay in key order,
                # so the dispatch window drains before the recursion
                if dsp is not None:
                    yield from committed(dsp.drain())
                while inflight:
                    yield drain_one()
                if refined and b in refined:
                    # rewrite-split bucket: sub-ranges nest inside the
                    # parent range, so emitting them in range order
                    # here preserves the global sorted order exactly
                    rec = refined[b]
                    rorder = sorted(rec["spill"].buckets())
                    if _pdesc:
                        rorder = list(reversed(rorder))
                    self._emit("stream_bucket_split", bucket=b,
                               rows=rows, depth=depth, mode="rewrite",
                               fanout=len(rorder))
                    yield from self._sort_buckets(
                        node, rec["spill"], rorder, rec["extent"],
                        keys, depth + 1,
                    )
                    continue
                if depth >= self.max_split_depth:
                    raise RuntimeError(
                        f"sort bucket {b} still holds {rows} rows at "
                        f"split depth {depth}; raise stream_bucket_rows"
                    )
                mn, mx = extent[b]
                if mn == mx:  # exact: every primary value identical
                    if len(keys) > 1:
                        self._emit("stream_bucket_split", bucket=b,
                                   rows=rows, depth=depth,
                                   mode="secondary_key")
                        yield from self._external_sort(
                            node, None, keys[1:],
                            pieces=(spill, b), depth=depth + 1,
                        )
                    else:
                        # all key values equal: any order is sorted
                        self._emit("stream_bucket_split", bucket=b,
                                   rows=rows, depth=depth,
                                   mode="equal_keys")
                        for piece in spill.read_bucket_pieces(b):
                            yield piece
                    spill.drop_bucket(b)
                    continue
                # fan-out from OBSERVED volume (DrDynamicRangeDistributor
                # .cpp:54-110: copies = sampled size / data per vertex)
                # and splitters from the whole bucket's sample, not its
                # first piece — the first-chunk estimate failed here.
                sample = _bucket_sample(spill, b, primary)
                fan = min(256, max(2, -(-rows // self.bucket_rows) * 2))
                sub = _splitters_from_sample(sample, fan)
                self._emit("stream_bucket_split", bucket=b, rows=rows,
                           depth=depth, mode="resplit", fanout=fan)
                yield from self._external_sort(
                    node, None, keys, pieces=(spill, b),
                    depth=depth + 1, splitters=sub,
                )
                spill.drop_bucket(b)
            if dsp is not None:
                yield from committed(dsp.drain())
            while inflight:
                yield drain_one()
        finally:
            if dsp is not None:
                dsp.close()
            if hasattr(src, "close"):
                src.close()

    def _iter_pieces_realized(self, pieces):
        spill, b = pieces
        yield from spill.read_bucket_pieces(b)

    # ---- join ----------------------------------------------------------

    def _eval_join(self, node: Node):
        left, right = node.inputs
        lstream = self._reaches_stream(left)
        rstream = self._reaches_stream(right)
        if lstream and not rstream:
            rnode = self._materialize_small(right)
            k, s = self._eval(left)
            assert k == "stream"
            clone = self._clone(node, [None, rnode])  # input[0] = chain
            return "stream", s.with_pending(clone)
        if rstream and not lstream:
            # chain enters the RIGHT slot: per-chunk join with the
            # materialized left is wrong for outer kinds (left rows
            # would duplicate per chunk) — Grace both sides instead.
            pass
        lk_cols = list(node.params["left_keys"])
        rk_cols = list(node.params["right_keys"])
        kl, ls = self._eval(left)
        kr, rs = self._eval(right)
        ls = ls if kl == "stream" else _table_as_stream(ls, left.schema)
        rs = rs if kr == "stream" else _table_as_stream(rs, right.schema)
        return "stream", _Stream(
            node.schema,
            self._grace_join(node, ls, rs, lk_cols, rk_cols),
        )

    def _grace_join(self, node, ls, rs, lk, rk, depth=0):
        lspill = SpillDir(self.ctx.dictionary, root=self._spill_root())
        rspill = SpillDir(self.ctx.dictionary, root=self._spill_root())
        writer = self._spill_writer()
        # rewrite-split hot buckets: bucket -> (left sub-spill, right
        # sub-spill), re-hashed at salt=depth+1 on BOTH sides so
        # matching keys stay co-bucketed (split_bucket action claimed
        # at chunk boundaries of either spill loop)
        jrefined: Dict[int, Tuple[SpillDir, SpillDir]] = {}
        try:
            lscope = self._scope()
            rscope = self._scope()
            for t in (self._realize_table(x, ls, lscope)
                      for x in self._iter_base(ls)):
                if self.rewriter is not None:
                    self._apply_join_splits(
                        jrefined, lspill, rspill, lk, rk, writer, depth
                    )
                self._spill_by_hash(lspill, t, lk, depth, writer=writer,
                                    refined=jrefined, side=0)
            for t in (self._realize_table(x, rs, rscope)
                      for x in self._iter_base(rs)):
                if self.rewriter is not None:
                    self._apply_join_splits(
                        jrefined, lspill, rspill, lk, rk, writer, depth
                    )
                self._spill_by_hash(rspill, t, rk, depth, writer=writer,
                                    refined=jrefined, side=1)
            if writer is not None:
                writer.flush()
            yield from self._join_buckets(
                node, lspill, rspill, lk, rk, depth,
                refined=jrefined or None,
            )
        finally:
            if writer is not None:
                writer.close(drain=False)
            for l2, r2 in jrefined.values():
                l2.cleanup()
                r2.cleanup()
            lspill.cleanup()
            rspill.cleanup()

    def _apply_join_splits(self, jrefined, lspill, rspill, lk, rk,
                           writer, depth):
        """Claim pending split_bucket rewrites for this depth and
        re-hash the hot bucket into per-side sub-spills at depth+1 —
        the SAME salt/fanout the oversized rehash path would use, so
        the resulting per-key co-bucketing (and thus the join output)
        is identical; only when the work happens changes."""
        acts = self.rewriter.claim_splits(depth)
        acts = [a for a in acts
                if int(a.params["bucket"]) not in jrefined]
        if not acts or depth >= self.max_split_depth:
            return
        if writer is not None:
            writer.flush()  # bucket piece lists must be final to reroute
        for act in acts:
            b = int(act.params["bucket"])
            l2 = SpillDir(self.ctx.dictionary, root=self._spill_root())
            r2 = SpillDir(self.ctx.dictionary, root=self._spill_root())
            jrefined[b] = (l2, r2)
            if b in lspill.buckets():
                for piece in lspill.read_bucket_pieces(b):
                    self._spill_by_hash(l2, piece, lk, depth + 1)
                lspill.drop_bucket(b)
            if b in rspill.buckets():
                for piece in rspill.read_bucket_pieces(b):
                    self._spill_by_hash(r2, piece, rk, depth + 1)
                rspill.drop_bucket(b)
            self._emit(
                "plan_rewrite", phase="applied", action="split_bucket",
                rule=act.rule, subject=act.subject, bucket=b,
                depth=depth,
            )

    def _join_buckets(self, node, lspill, rspill, lk, rk, depth,
                      refined=None):
        jkind = node.params.get("join_kind", "inner")
        # shared per-side scopes: the pow2 capacity palette keeps
        # repeated bucket sizes on the same compiled join program
        lscope = self._scope()
        rscope = self._scope()
        allb = set(lspill.buckets()) | set(rspill.buckets())
        if refined:
            allb |= set(refined)
        for b in sorted(allb):
            if refined and b in refined:
                # rewrite-split: both sides already re-hashed at
                # depth+1 — join the sub-buckets in the parent's slot
                # (exactly where the oversized rehash would emit them)
                l2, r2 = refined[b]
                rows2 = (
                    sum(l2.bucket_rows(x) for x in l2.buckets())
                    + sum(r2.bucket_rows(x) for x in r2.buckets())
                )
                self._emit("stream_bucket_split", bucket=b, rows=rows2,
                           depth=depth, mode="rewrite")
                yield from self._join_buckets(node, l2, r2, lk, rk,
                                              depth + 1)
                continue
            lrows = lspill.bucket_rows(b)
            rrows = rspill.bucket_rows(b)
            if lrows == 0 and jkind in ("inner", "left", "semi", "anti",
                                        "count", "ranked"):
                continue
            if rrows == 0 and jkind in ("inner", "semi", "ranked"):
                continue
            if lrows + rrows > self.bucket_rows:
                if depth >= self.max_split_depth:
                    raise RuntimeError(
                        f"join bucket {b} holds {lrows}+{rrows} rows at "
                        f"split depth {depth}; raise stream_bucket_rows "
                        "(skewed key?)"
                    )
                self._emit("stream_bucket_split", bucket=b,
                           rows=lrows + rrows, depth=depth, mode="rehash")
                l2 = SpillDir(self.ctx.dictionary, root=self._spill_root())
                r2 = SpillDir(self.ctx.dictionary, root=self._spill_root())
                try:
                    for piece in lspill.read_bucket_pieces(b):
                        self._spill_by_hash(l2, piece, lk, depth + 1)
                    for piece in rspill.read_bucket_pieces(b):
                        self._spill_by_hash(r2, piece, rk, depth + 1)
                    yield from self._join_buckets(node, l2, r2, lk, rk,
                                                  depth + 1)
                finally:
                    l2.cleanup()
                    r2.cleanup()
                continue
            lt = lspill.read_bucket(b)
            rt = rspill.read_bucket(b)
            if not lt:
                lt = _empty_table(node.inputs[0].schema)
            if not rt:
                rt = _empty_table(node.inputs[1].schema)
            lscope.cap = self._bucket_cap(lrows)
            rscope.cap = self._bucket_cap(rrows)
            lq = lscope.ingest(lt, node.inputs[0].schema)
            rq = rscope.ingest(rt, node.inputs[1].schema)
            cur = self._clone(node, [lq.node, rq.node])
            out = self._run_engine(cur)
            self._emit("stream_bucket", bucket=b, rows=lrows + rrows,
                       depth=depth)
            yield out

    def _grace_buckets(self, sides, tail_nodes, out_schema):
        """Generic single-input Grace: spill each (stream, keys) side,
        then run the tail nodes per bucket (used for non-mergeable
        group_by)."""
        (stream, keys), = sides
        spill = SpillDir(self.ctx.dictionary, root=self._spill_root())
        writer = self._spill_writer()
        try:
            scope = self._scope()
            for t in (self._realize_table(x, stream, scope)
                      for x in self._iter_base(stream)):
                self._spill_by_hash(spill, t, keys, 0, writer=writer)
            if writer is not None:
                writer.flush()
            bscope = self._scope()
            base_schema = stream.schema
            yield from self._grace_bucket_tables(
                spill, bscope, base_schema, tail_nodes
            )
        finally:
            if writer is not None:
                writer.close(drain=False)
            spill.cleanup()

    def _grace_bucket_tables(self, spill, bscope, base_schema, tail_nodes):
        for b in spill.buckets():
            rows = spill.bucket_rows(b)
            t = spill.read_bucket(b)
            bscope.cap = self._bucket_cap(rows)
            cur = bscope.ingest(t, base_schema).node
            for n in tail_nodes:
                cur = self._clone(n, [cur] + n.inputs[1:])
            out = self._run_engine(cur)
            self._emit("stream_bucket", bucket=b, depth=0, rows=rows)
            yield out

    def _spill_by_hash(self, spill, table, keys, depth, writer=None,
                       refined=None, side=0):
        bids = _host_hash_buckets(
            table, keys, self.num_buckets, salt=depth,
            dictionary=self.ctx.dictionary,
        )
        for b in np.unique(bids):
            sel = bids == b
            piece = {c: v[sel] for c, v in table.items()}
            if refined and int(b) in refined:
                # rewrite-split hot bucket: route straight into the
                # per-side sub-spill at depth+1 (same salt the rehash
                # resplit uses — co-bucketing is preserved)
                self._spill_by_hash(
                    refined[int(b)][side], piece, keys, depth + 1,
                    writer=writer,
                )
                continue
            # per-partition row histogram = the skew signal
            # distribution-aware scheduling needs (PAPERS.md "Chasing
            # Similarity"); one sample per (bucket, piece)
            self.metrics.observe(
                "partition_rows", int(sel.sum()), depth=depth
            )
            if writer is not None:
                writer.submit(spill, int(b), piece, depth)
                continue
            b0 = spill.bytes_written
            n = spill.append(int(b), piece)
            self.metrics.add("spill_bytes", spill.bytes_written - b0)
            self._emit("stream_spill", bucket=int(b), rows=n, depth=depth)

    def _spill_root(self):
        import os
        import tempfile

        base = getattr(self.ctx.config, "stream_spill_dir", None)
        if base:
            os.makedirs(base, exist_ok=True)
            return tempfile.mkdtemp(prefix="spill_", dir=base)
        return None

    # ---- take / concat -------------------------------------------------

    def _eval_take(self, node: Node, s: _Stream):
        want = int(node.params["n"])

        def gen():
            got = 0
            for t in self._realized(s):
                rows = len(next(iter(t.values()))) if t else 0
                if got + rows >= want:
                    keep = want - got
                    yield {c: v[:keep] for c, v in t.items()}
                    return
                got += rows
                yield t

        return "stream", _Stream(node.schema, gen())

    def _eval_concat(self, node: Node):
        parts = [self._eval(i) for i in node.inputs]

        def gen():
            for (k, v), inp in zip(parts, node.inputs):
                if k == "small":
                    yield v
                else:
                    yield from self._realized(v)

        return "stream", _Stream(node.schema, gen())


# ---- host-side helpers -------------------------------------------------


def _concat_tables(
    tables: List[Dict[str, np.ndarray]], schema: Optional[Schema]
) -> Dict[str, np.ndarray]:
    tables = [t for t in tables if t and len(next(iter(t.values())))]
    if not tables:
        if schema is None:
            return {}
        return _empty_table(schema)
    names = list(tables[0].keys())
    return {n: np.concatenate([np.asarray(t[n]) for t in tables])
            for n in names}


def _empty_table(schema: Schema) -> Dict[str, np.ndarray]:
    out = {}
    for f in schema.fields:
        if f.ctype is ColumnType.STRING:
            out[f.name] = np.array([], object)
        else:
            out[f.name] = np.array([], f.ctype.numpy_dtype)
    return out


def _table_as_stream(table, schema) -> "_Stream":
    return _Stream(schema, iter([table]))


def _sort_key_view(col: np.ndarray) -> np.ndarray:
    """An order-preserving comparable view of a sort-key column.
    String columns become object arrays: numpy compares them lexically
    and reductions (min/max for the exact bucket extent) dispatch to
    Python comparisons, which fixed-width ``<U``/``<S`` dtypes lack."""
    a = np.asarray(col)
    if a.dtype.kind in ("U", "S"):
        return a.astype(object)
    return a


def _sample_splitters(col: np.ndarray, buckets: int) -> np.ndarray:
    """B-1 value splitters from the first chunk (the 0.1% sampler of
    ``DryadLinqSampler.cs:38-42`` collapsed onto the leading morsel;
    estimation error is repaired by observed-volume re-splits)."""
    n = len(col)
    if n == 0:
        return np.asarray([])
    take = min(n, 1 << 16)
    idx = np.linspace(0, n - 1, take).astype(np.int64)
    return _splitters_from_sample(col[idx], buckets)


def _splitters_from_sample(sample: np.ndarray, buckets: int) -> np.ndarray:
    if len(sample) == 0:
        return np.asarray([])
    s = np.sort(sample)
    pos = np.linspace(0, len(s) - 1, buckets + 1).astype(np.int64)[1:-1]
    return np.unique(s[pos])


def _bucket_sample(spill: SpillDir, bucket: int, primary: str) -> np.ndarray:
    vals = []
    for piece in spill.read_bucket_pieces(bucket):
        col = np.asarray(piece[primary])
        take = min(len(col), 4096)
        if take:
            vals.append(col[np.linspace(0, len(col) - 1, take).astype(np.int64)])
    return np.concatenate(vals) if vals else np.asarray([])


def _host_key_hash64(
    table, keys, salt: int = 0, dictionary=None
) -> np.ndarray:
    """Deterministic 64-bit row hash over the key columns.  Any mixing
    works as long as every consumer uses the same function; equal
    logical values must produce equal words, so strings hash via the
    engine dictionary (``Hash64.cs`` precedent) and numerics widen to a
    canonical 64-bit pattern.  Feeds both the exchange bucket ids and
    the combine-tree key-range histograms (same high bits, coarser
    modulus), so range-level decisions align with exchange routing."""
    n = len(np.asarray(table[keys[0]]))
    h = np.full(n, np.uint64(0x84222325 + salt * 0x1000193), np.uint64)
    for kcol in keys:
        a = np.asarray(table[kcol])
        if a.dtype == object or a.dtype.kind in ("U", "S"):
            uniq, inv = np.unique(a.astype(object), return_inverse=True)
            hs = np.asarray(
                [dictionary.add(str(s)) for s in uniq], np.uint64
            )
            w = hs[inv]
        elif a.dtype.kind == "f":
            w = np.ascontiguousarray(a.astype(np.float64)).view(np.uint64)
        elif a.dtype.kind == "b":
            w = a.astype(np.uint64)
        else:
            w = a.astype(np.int64).view(np.uint64)
        h = (h ^ w) * _MIX
        h ^= h >> np.uint64(29)
    return h


def _host_hash_buckets(
    table, keys, buckets: int, salt: int = 0, dictionary=None
) -> np.ndarray:
    """Deterministic row hash over the key columns -> bucket ids."""
    h = _host_key_hash64(table, keys, salt=salt, dictionary=dictionary)
    return ((h >> np.uint64(33)) % np.uint64(buckets)).astype(np.int64)


def _encode_store_part(table, schema: Schema, dictionary):
    """Host table -> physical store columns via the shared ingest
    encoding, so streamed parts read back through the same ``store``
    binding path as engine-written ones."""
    from dryad_tpu.columnar.batch import encode_physical

    out = {}
    for f in schema.fields:
        out.update(encode_physical(f, np.asarray(table[f.name]), dictionary))
    return out
