"""Job packages — the serialized submission artifact.

The reference ships a job as staged resources: the generated vertex DLL,
the XML query plan, and a serialized object store of client-side objects
captured by lambdas (``LinqToDryad/DryadLinqObjectStore.cs:173``,
resource staging ``DryadLinqQueryGen.cs:950-955``).  The TPU-native
equivalent: the logical plan IS Python objects, so a job package is one
pickle blob holding the node DAG, the input bindings (host tables /
store partitions), the string dictionary, and the config.  A remote
driver process (or a ControlPlane worker told the package path over the
mailbox) loads and executes it against its own mesh.

User functions (including lambdas and ``__main__``-level defs) ship BY
VALUE via cloudpickle when it is available — the analog of the
reference compiling lambdas into the shipped vertex DLL
(``DryadLinqCodeGen.cs:1910``).  Without cloudpickle the stdlib pickler
applies and functions must live in a module importable on the worker.
"""

from __future__ import annotations

import pickle
from typing import Any, Dict, Optional

from dryad_tpu.plan.nodes import fresh_id, walk

try:
    import cloudpickle as _pickler
except ImportError:  # pragma: no cover - cloudpickle present in-tree
    _pickler = pickle

PACKAGE_VERSION = 1


def pack_query(
    query, path: str, binding_overrides: Optional[Dict[int, tuple]] = None
) -> Dict[str, Any]:
    """Serialize a lazy Query (plan + reachable input bindings +
    dictionary + config) to ``path``.  Returns the manifest summary.

    ``binding_overrides``: node id -> replacement binding shipped in
    place of the context's (the driver-routed ``host_routed`` layouts
    of co-partitioned vertex submissions) — the live context's
    bindings stay untouched."""
    ctx = query.ctx
    nodes = walk([query.node])
    bindings: Dict[int, tuple] = {}
    overrides = binding_overrides or {}
    for n in nodes:
        if n.id in overrides:
            bindings[n.id] = overrides[n.id]
            continue
        if n.id in ctx._bindings:
            kind = ctx._bindings[n.id][0]
            if kind == "device":
                raise ValueError(
                    "cannot pack a query over device-resident bindings; "
                    "materialize to host or a store first"
                )
            bindings[n.id] = ctx._bindings[n.id]
    blob = {
        "version": PACKAGE_VERSION,
        "node": query.node,
        "bindings": bindings,
        "dictionary": dict(ctx.dictionary._map),
        "config": ctx.config,
    }
    with open(path, "wb") as fh:
        _pickler.dump(blob, fh, protocol=pickle.HIGHEST_PROTOCOL)
    return {
        "version": PACKAGE_VERSION,
        "nodes": len(nodes),
        "bindings": len(bindings),
        "dict_entries": len(ctx.dictionary._map),
    }


def load_query(path: str, ctx=None, mesh=None):
    """Load a job package into a (possibly provided) context and return
    the lazy Query, NOT yet executed.  ``mesh`` lets a worker process run
    the plan over a specific (e.g. global multi-process) device mesh;
    ``ctx`` defaults to a fresh DryadContext built from the packaged
    config."""
    from dryad_tpu.api.context import DryadContext
    from dryad_tpu.api.query import Query

    if ctx is not None and mesh is not None:
        raise ValueError(
            "pass either ctx or mesh, not both (a provided ctx already "
            "owns its mesh)"
        )
    with open(path, "rb") as fh:
        blob = pickle.load(fh)
    if blob.get("version") != PACKAGE_VERSION:
        raise ValueError(f"unsupported package version {blob.get('version')}")
    if ctx is None:
        ctx = DryadContext(config=blob["config"], mesh=mesh)
    ctx.dictionary._map.update(blob["dictionary"])
    # Re-key the loaded DAG onto THIS process's node-id counter.  Node
    # ids are process-local (plan.nodes._ids), and everything —
    # walk/consumers dedup, lowering cursors, binding lookups — keys on
    # them; a loaded DAG carrying the packer's ids collides with any
    # node built locally (e.g. the topk node _rewrite_topk creates at
    # lower time gets a fresh LOCAL id, which in a young process starts
    # at 0 — exactly where the packer's ids also started), and with a
    # second package from a different packer.  A collision is silent:
    # walk drops one of the twins and the plan lowers wrong or not at
    # all.
    remap: Dict[int, int] = {}
    for n in walk([blob["node"]]):
        remap[n.id] = n.id = fresh_id()
    ctx._bindings.update(
        {remap[i]: b for i, b in blob["bindings"].items() if i in remap}
    )
    return Query(ctx, blob["node"])


def slice_binding(binding: tuple, part: int, nparts: int) -> tuple:
    """Restrict one packed input binding to vertex-task ``part`` of
    ``nparts`` — the per-vertex input channel of the reference's
    independent-vertex execution model (a ``DrStorageVertex`` holds one
    input partition, ``GraphManager/vertex/DrVertex.h:146``).  Host rows
    split into ``nparts`` contiguous blocks; store partitions deal
    round-robin.  The union over parts is exactly the full input."""
    import numpy as np

    kind, *rest = binding
    if kind == "host":
        arrays, _cap = rest
        return (
            "host",
            {k: np.array_split(np.asarray(v), nparts)[part]
             for k, v in arrays.items()},
            None,
        )
    if kind == "host_routed":
        # driver-routed layout: rows pre-ordered by key bucket, part p
        # owns [offsets[p], offsets[p+1]) — the co-partitioned input
        # channels of a routed join/sort vertex submission
        arrays, offsets = rest
        lo, hi = int(offsets[part]), int(offsets[part + 1])
        return (
            "host",
            {k: np.asarray(v)[lo:hi] for k, v in arrays.items()},
            None,
        )
    if kind == "host_physical":
        phys, *opt = rest
        return (
            "host_physical",
            {k: np.array_split(np.asarray(v), nparts)[part]
             for k, v in phys.items()},
        )
    if kind == "store":
        parts, schema = rest
        return ("store", parts[part::nparts], schema)
    raise ValueError(f"cannot slice binding kind {kind!r}")


def run_package(path: str, ctx=None):
    """Load a job package and execute it, returning the host table —
    the entry point a worker process calls after learning the package
    path from the control plane."""
    return load_query(path, ctx=ctx).collect()
