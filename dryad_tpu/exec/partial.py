"""Partial-aggregation decomposition shared by the vertex-task and
streaming executors.

The reference decomposes GroupBy aggregations into
Seed/Accumulate/RecursiveAccumulate/FinalReduce so partial combines can
run close to the data and merge up an aggregation tree
(``LinqToDryad/DryadLinqDecomposition.cs:34``;
``GraphManager/stagemanager/DrDynamicAggregateManager.h:117-168``).
Here the same decomposition serves two consumers: per-vertex partials
in ``cluster.localjob`` and per-chunk partials in ``exec.outofcore``.
"""

from __future__ import annotations

# Builtin aggregates whose partials merge associatively.  "first"
# merges correctly only when partial rows concatenate in engine order
# (the callers enforce their own ordering constraints).
MERGEABLE_AGGS = frozenset(
    {"sum", "count", "min", "max", "mean", "any", "all", "first"}
)


def partial_plan(agg_list):
    """Decompose builtin aggs into partial specs plus the merge plan.

    Returns ``(partial, plan)`` where ``partial`` is an agg spec dict
    for the chunk/vertex-side group_by and ``plan`` rows are
    ``(out_name, op, partial_col_names)`` for the final merge.
    """
    partial, plan = {}, []
    for op, col, out in agg_list:
        if op == "mean":
            partial[f"{out}__ps"] = ("sum", col)
            partial[f"{out}__pc"] = ("count", None)
            plan.append((out, "mean", (f"{out}__ps", f"{out}__pc")))
        else:
            partial[f"{out}__p"] = (op, col)
            plan.append((out, op, (f"{out}__p",)))
    return partial, plan


def merge_agg_spec(plan):
    """Agg spec that merges partial columns into partial columns of the
    same names — closed under composition, so intermediate compaction
    rounds can apply it repeatedly before the final round."""
    spec = {}
    for _out, op, pcols in plan:
        if op == "mean":
            spec[pcols[0]] = ("sum", pcols[0])
            spec[pcols[1]] = ("sum", pcols[1])
        elif op in ("sum", "count"):
            spec[pcols[0]] = ("sum", pcols[0])
        elif op in ("min", "max", "any", "all", "first"):
            spec[pcols[0]] = (op, pcols[0])
        else:  # pragma: no cover - guarded by MERGEABLE_AGGS
            raise AssertionError(f"unmergeable agg {op}")
    return spec


_PHYS_SUFFIXES = ("#h0", "#h1", "#r0", "#r1")


def copy_physical(cols, src: str, dst: str, out) -> None:
    """Copy a logical column between physical column dicts, whatever
    its physical width (plain, split-word, or string 4-column)."""
    if src in cols:
        out[dst] = cols[src]
        return
    found = False
    for suf in _PHYS_SUFFIXES:
        if f"{src}{suf}" in cols:
            out[f"{dst}{suf}"] = cols[f"{src}{suf}"]
            found = True
    if not found:
        raise KeyError(src)


def finalize_fn(plan):
    """Row-wise finalizer mapping merged partial columns to the user's
    output columns (mean = sum/count; everything else renames).  Runs
    traced over PHYSICAL columns, so renames carry split-word/string
    physical columns through."""

    def fn(cols):
        out = {}
        for name, op, pcols in plan:
            if op == "mean":
                import jax.numpy as jnp

                if pcols[0] not in cols:
                    raise KeyError(
                        f"streaming mean over a split-word column "
                        f"({pcols[0]}) is not supported"
                    )
                c = cols[pcols[1]]
                denom = jnp.maximum(c, 1).astype("float32")
                out[name] = cols[pcols[0]].astype("float32") / denom
            else:
                copy_physical(cols, pcols[0], name, out)
        return out

    return fn
