"""Partial-aggregation decomposition shared by the vertex-task and
streaming executors.

The reference decomposes GroupBy aggregations into
Seed/Accumulate/RecursiveAccumulate/FinalReduce so partial combines can
run close to the data and merge up an aggregation tree
(``LinqToDryad/DryadLinqDecomposition.cs:34``;
``GraphManager/stagemanager/DrDynamicAggregateManager.h:117-168``).
Here the same decomposition serves two consumers: per-vertex partials
in ``cluster.localjob`` and per-chunk partials in ``exec.outofcore``.
"""

from __future__ import annotations

# Builtin aggregates whose partials merge associatively.  "first"
# merges correctly only when partial rows concatenate in engine order
# (the callers enforce their own ordering constraints).
MERGEABLE_AGGS = frozenset(
    {"sum", "count", "min", "max", "mean", "any", "all", "first"}
)

# The LINEAR subset: partials that merge by elementwise ADDITION over
# their state columns ("mean" decomposes to sum + count, both linear).
# Only these qualify for coded stage redundancy (``redundancy.policy``):
# an integer linear combination of linear partials is itself a valid
# partial, so any k of n coded vertices reconstruct the stage output.
# min/max/any/all are lattice ops (idempotent, not invertible) and
# "first" is order-dependent — none of them form a vector space.
LINEAR_AGGS = frozenset({"sum", "count", "mean"})


def plan_is_linear(plan) -> bool:
    """True when every merge-plan row is a linear aggregate."""
    return all(op in LINEAR_AGGS for _out, op, _pcols in plan)


def partial_plan(agg_list):
    """Decompose builtin aggs into partial specs plus the merge plan.

    Returns ``(partial, plan)`` where ``partial`` is an agg spec dict
    for the chunk/vertex-side group_by and ``plan`` rows are
    ``(out_name, op, partial_col_names)`` for the final merge.
    """
    partial, plan = {}, []
    for op, col, out in agg_list:
        if op == "mean":
            partial[f"{out}__ps"] = ("sum", col)
            partial[f"{out}__pc"] = ("count", None)
            plan.append((out, "mean", (f"{out}__ps", f"{out}__pc")))
        else:
            partial[f"{out}__p"] = (op, col)
            plan.append((out, op, (f"{out}__p",)))
    return partial, plan


def merge_agg_spec(plan):
    """Agg spec that merges partial columns into partial columns of the
    same names — closed under composition, so intermediate compaction
    rounds can apply it repeatedly before the final round."""
    spec = {}
    for _out, op, pcols in plan:
        if op == "mean":
            spec[pcols[0]] = ("sum", pcols[0])
            spec[pcols[1]] = ("sum", pcols[1])
        elif op in ("sum", "count"):
            spec[pcols[0]] = ("sum", pcols[0])
        elif op in ("min", "max", "any", "all", "first"):
            spec[pcols[0]] = (op, pcols[0])
        else:  # pragma: no cover - guarded by MERGEABLE_AGGS
            raise AssertionError(f"unmergeable agg {op}")
    return spec


def state_reductions(plan):
    """Partial STATE column -> associative host reduction ("sum" /
    "min" / "max" / "any" / "all") for an intermediate, UN-finalized
    merge round: mean's sum+count columns both add, lattice ops stay
    themselves.  "first" has no associative state reduction (order-
    dependent) and is absent from the mapping — callers must fall back
    to a flat engine-order merge when the plan carries it."""
    red = {}
    for _out, op, pcols in plan:
        if op == "mean":
            red[pcols[0]] = "sum"
            red[pcols[1]] = "sum"
        elif op in ("sum", "count"):
            red[pcols[0]] = "sum"
        elif op in ("min", "max", "any", "all"):
            red[pcols[0]] = op
    return red


def seed_state_rows(arrays, agg_list):
    """Seed partial STATE columns directly from raw host rows — the
    delta-ingest counterpart of running the chunk-side partial
    group_by: each input row becomes one state row (sum/min/max carry
    the value, count/mean-count carry 1) which then folds through
    :func:`merge_state_rows` exactly like any other streaming chunk.
    State columns keep their SOURCE dtypes (count columns are int32,
    matching the count output ctype) so a later finalize narrows to
    the same output schema a direct run of the plan produces."""
    import numpy as np

    n = 0
    for a in arrays.values():
        n = len(np.asarray(a))
        break
    out = {}
    for op, col, name in agg_list:
        if op == "count":
            out[f"{name}__p"] = np.ones(n, np.int32)
        elif op == "mean":
            out[f"{name}__ps"] = np.asarray(arrays[col]).copy()
            out[f"{name}__pc"] = np.ones(n, np.int32)
        elif op in ("any", "all"):
            out[f"{name}__p"] = np.asarray(arrays[col]).astype(np.bool_)
        elif op in ("sum", "min", "max"):
            out[f"{name}__p"] = np.asarray(arrays[col]).copy()
        else:  # "first" and friends are order-dependent — no seed
            raise ValueError(f"agg {op!r} has no row-seeded state")
    return out


_MIX64 = 0x9E3779B97F4A7C15


def key_hash64(cols, keys):
    """Deterministic row hash over the key columns, shared by the
    driver's combine-tree placement histograms and the gang workers'
    level-(-1) pre-merge histograms.  Strings hash with the engine's
    framework Hash64 (``columnar.schema.hash64_str``) — NOT Python's
    process-salted ``hash()`` — so a snapshot computed in a worker
    process describes the same key ranges the driver (or any peer)
    would compute for the same rows."""
    import numpy as np

    from dryad_tpu.columnar.schema import hash64_str

    mix = np.uint64(_MIX64)
    n = len(cols[keys[0]])
    h = np.full(n, np.uint64(0x84222325), np.uint64)
    for k in keys:
        a = np.asarray(cols[k])
        if a.dtype == object or a.dtype.kind in ("U", "S"):
            uniq, inv = np.unique(a.astype(object), return_inverse=True)
            hs = np.asarray(
                [hash64_str(str(s)) for s in uniq], np.uint64
            )
            w = hs[inv]
        elif a.dtype.kind == "f":
            w = np.ascontiguousarray(a.astype(np.float64)).view(np.uint64)
        elif a.dtype.kind == "b":
            w = a.astype(np.uint64)
        else:
            w = a.astype(np.int64).view(np.uint64)
        h = (h ^ w) * mix
        h ^= h >> np.uint64(29)
    return h


def merge_state_rows(cols, keys, red):
    """Fold partial STATE rows by key with the plan's associative
    reductions (:func:`state_reductions`) — no finalize, so the result
    is itself a valid partial table.  One fold step of the aggregation
    tree, shared by the driver's level-0 merge groups
    (``cluster.localjob._tree_merge_state``) and the gang workers'
    level-(-1) pre-merge (``cluster.worker`` ``combineparts``)."""
    import numpy as np

    n = len(cols[keys[0]]) if keys else 0
    tups = list(zip(*[np.asarray(cols[k]).tolist() for k in keys])) if n \
        else []
    index = {}
    for i, t in enumerate(tups):
        index.setdefault(t, []).append(i)
    out = {k: [] for k in keys}
    for c in red:
        out[c] = []
    for t, idxs in index.items():
        for k, kv in zip(keys, t):
            out[k].append(kv)
        ii = np.asarray(idxs)
        for c, op in red.items():
            v = np.asarray(cols[c])[ii]
            if op == "sum":
                out[c].append(v.sum())
            elif op == "min":
                out[c].append(v.min())
            elif op == "max":
                out[c].append(v.max())
            elif op == "any":
                out[c].append(np.any(v))
            else:  # all
                out[c].append(np.all(v))
    res = {
        k: np.asarray(out[k], dtype=np.asarray(cols[k]).dtype)
        for k in keys
    }
    for c in red:
        # promoted accumulators (int sums widen) keep their width; the
        # flat root pass narrows to the output schema at finalize
        res[c] = np.asarray(out[c])
    return res


# -- coded combine (redundancy/: k-of-n partial aggregates) -----------------

def align_partials(tables, key_cols, state_cols):
    """Align partial STATE tables onto the sorted union of their keys.

    Returns ``(key_arrays, mats)`` where ``key_arrays`` maps each key
    column to its union array (ascending tuple order — deterministic
    regardless of which tables are present) and ``mats`` maps each
    state column to a ``(len(tables), n_keys)`` matrix whose row i is
    table i's values scattered onto the union (missing keys are the
    additive identity 0 — the linearity contract).  Integer/bool state
    columns accumulate in exact Python ints (object dtype) so the
    coded decode can stay bit-exact; floats accumulate in float64.
    """
    import numpy as np

    keysets = []
    for t in tables:
        if key_cols:
            ks = list(zip(*[np.asarray(t[k]).tolist() for k in key_cols]))
        else:
            n = len(np.asarray(t[state_cols[0]])) if state_cols else 0
            ks = [()] * n
        keysets.append(ks)
    union = sorted(set().union(*keysets)) if keysets else []
    index = {key: i for i, key in enumerate(union)}
    key_arrays = {}
    for pos, kname in enumerate(key_cols):
        dt = np.asarray(tables[0][kname]).dtype if tables else None
        key_arrays[kname] = np.asarray([u[pos] for u in union], dtype=dt)
    mats = {}
    for c in state_cols:
        dt = np.asarray(tables[0][c]).dtype if tables else np.dtype(float)
        exact = dt.kind in "iub"
        acc_dt = object if exact else np.float64
        mat = np.zeros((len(tables), len(union)), dtype=acc_dt)
        for ti, (t, ks) in enumerate(zip(tables, keysets)):
            vals = np.asarray(t[c])
            idx = [index[key] for key in ks]
            if exact:
                for p, v in zip(idx, vals.tolist()):
                    mat[ti, p] += v  # duplicate keys merge additively
            else:
                np.add.at(mat[ti], idx, vals.astype(np.float64))
        mats[c] = mat
    return key_arrays, mats


def coded_combine(tables, coeffs, key_cols, state_cols):
    """The worker-side ENCODE step: one coded partial table as the
    integer-weighted sum of its support partials, keyed on the sorted
    union of their keys.  Integer states come back exact int64; float
    states come back float64 (narrowing happens only at finalize).
    """
    import numpy as np

    key_arrays, mats = align_partials(tables, key_cols, state_cols)
    out = dict(key_arrays)
    for c, mat in mats.items():
        if mat.dtype == object:
            w = np.asarray([int(x) for x in coeffs], dtype=object)
            comb = (w[:, None] * mat).sum(axis=0) if len(mat) else mat.sum(0)
            out[c] = np.asarray([int(v) for v in comb], dtype=np.int64)
        else:
            w = np.asarray(coeffs, np.float64)
            out[c] = w @ mat
    return out


_PHYS_SUFFIXES = ("#h0", "#h1", "#r0", "#r1")


def copy_physical(cols, src: str, dst: str, out) -> None:
    """Copy a logical column between physical column dicts, whatever
    its physical width (plain, split-word, or string 4-column)."""
    if src in cols:
        out[dst] = cols[src]
        return
    found = False
    for suf in _PHYS_SUFFIXES:
        if f"{src}{suf}" in cols:
            out[f"{dst}{suf}"] = cols[f"{src}{suf}"]
            found = True
    if not found:
        raise KeyError(src)


def finalize_fn(plan):
    """Row-wise finalizer mapping merged partial columns to the user's
    output columns (mean = sum/count; everything else renames).  Runs
    traced over PHYSICAL columns, so renames carry split-word/string
    physical columns through."""

    def fn(cols):
        out = {}
        for name, op, pcols in plan:
            if op == "mean":
                import jax.numpy as jnp

                if pcols[0] not in cols:
                    raise KeyError(
                        f"streaming mean over a split-word column "
                        f"({pcols[0]}) is not supported"
                    )
                c = cols[pcols[1]]
                denom = jnp.maximum(c, 1).astype("float32")
                out[name] = cols[pcols[0]].astype("float32") / denom
            else:
                copy_physical(cols, pcols[0], name, out)
        return out

    return fn
