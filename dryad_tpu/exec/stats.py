"""Stage duration statistics and outlier detection.

Port of the *logic* (not code) of the reference's speculative-duplication
model (``GraphManager/stagemanager/DrStageStatistics.cpp``): a robust
Gaussian fit over completed task durations — trimming the top 20% as
suspected outliers — with an outlier threshold at mean + 3 sigma
(``DrStageStatistics.cpp:24-25,93,490-558``).  Intra-pod SPMD steps are
lockstep so speculation is moot there; the driver uses this for
multi-slice / DCN stage retries and for surfacing stragglers in the
event log.
"""

from __future__ import annotations

import math
from typing import List, Optional

TRIM_FRACTION = 0.2  # reference: top 20% trimmed before fitting
DEFAULT_SIGMAS = 3.0  # reference: 3-sigma outlier threshold
MIN_SAMPLES = 3
# Multiplicative floor on the outlier threshold: with fewer than 4
# samples the trimmed fit keeps 1-3 points and the variance
# degenerates toward 0, so mean + 3*sigma collapses onto the mean and
# flags EVERY subsequent attempt a straggler.  Clamping to
# floor_ratio x the trimmed mean keeps the model usable from the very
# first completions (seeded test: tests/test_quarantine.py).
FLOOR_RATIO = 1.5


class StageStatistics:
    """Robust duration model for one stage's attempts."""

    def __init__(
        self,
        outlier_sigmas: float = DEFAULT_SIGMAS,
        floor_ratio: float = FLOOR_RATIO,
    ):
        self.durations: List[float] = []
        self.outlier_sigmas = outlier_sigmas
        self.floor_ratio = floor_ratio

    def record(self, seconds: float) -> None:
        self.durations.append(float(seconds))

    def _trimmed(self) -> List[float]:
        d = sorted(self.durations)
        k = int(len(d) * (1.0 - TRIM_FRACTION))
        return d[: max(k, 1)]

    def mean_std(self) -> Optional[tuple]:
        if len(self.durations) < MIN_SAMPLES:
            return None
        t = self._trimmed()
        m = sum(t) / len(t)
        var = sum((x - m) ** 2 for x in t) / max(len(t) - 1, 1)
        return m, math.sqrt(var)

    def outlier_threshold(self) -> Optional[float]:
        """Duration beyond which an attempt counts as a straggler,
        clamped to ``floor_ratio`` x the trimmed mean (see FLOOR_RATIO:
        the fit degenerates with < 4 samples)."""
        ms = self.mean_std()
        if ms is None:
            return None
        m, s = ms
        return max(m + self.outlier_sigmas * s, m * self.floor_ratio)

    def spare_threshold(self) -> Optional[float]:
        """Coarse spare-launch trigger for coded redundancy.

        Duplication must IDENTIFY the straggling attempt, so it waits
        for the full robust model (>= MIN_SAMPLES completions).  Coded
        parity covers whichever r vertices are slow — any k completions
        reconstruct — so it may act on a much weaker signal: from the
        FIRST completed sample, ``floor_ratio x max(completed)``."""
        thr = self.outlier_threshold()
        if thr is not None:
            return thr
        if not self.durations:
            return None
        return max(self.durations) * self.floor_ratio

    def is_outlier(self, seconds: float) -> bool:
        thr = self.outlier_threshold()
        return thr is not None and seconds > thr


class FailureWindow:
    """Sliding-window failure counter — the machine-level failure
    accounting behind computer quarantine (the reference blacklists
    computers whose recent failure count crosses a threshold,
    ``DrGraph.h:42`` m_maxActiveFailureCount at machine scope).

    Timestamps come from the caller's clock, so schedulers with an
    injectable clock stay fully fake-time testable."""

    def __init__(self, window_seconds: float):
        self.window = float(window_seconds)
        self._times: List[float] = []

    def record(self, now: float) -> int:
        """Record one failure at ``now``; returns the in-window count."""
        self._times.append(float(now))
        return self.count(now)

    def count(self, now: float) -> int:
        """Failures inside (now - window, now]; prunes expired entries."""
        cutoff = float(now) - self.window
        self._times = [t for t in self._times if t > cutoff]
        return len(self._times)

    def clear(self) -> None:
        self._times.clear()
