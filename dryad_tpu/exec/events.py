"""Append-only job event log — the Calypso reporter analog.

The reference GM appends timestamped job events (process/vertex state
transitions, final topology) to ``calypso.log`` in the job's DFS
directory (``GraphManager/reporting/DrCalypsoReporting.cpp``), consumed
post-hoc by the JobBrowser.  Here: JSONL events per job, consumed by
``dryad_tpu.tools.jobview`` and exported to Perfetto by
``dryad_tpu.obs.trace``.

Every event carries two clocks: ``ts`` (wall, ``time.time()`` — for
human-readable placement and cross-process merging) and ``mono``
(``time.monotonic()`` — for derived durations, immune to wall-clock
steps).  Field values are normalized to native Python types before
serialization so numeric folds (jobview, ``obs.metrics``) never see
stringified numpy scalars.

The full event schema lives in :data:`EVENT_KINDS` below — one entry
per ``kind`` emitted anywhere in the package.  A static lint test
(``tests/test_event_schema.py``) cross-references this registry against
every ``emit(...)`` call site, so the schema cannot rot as kinds are
added.

Events may be emitted from pipeline threads; ``EventLog`` is
thread-safe.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

# ``kind`` -> one-line schema doc.  Kept in sync with emit() call sites
# by tests/test_event_schema.py (both directions: every emitted kind is
# documented; every documented kind is emitted somewhere).
EVENT_KINDS: Dict[str, str] = {
    # -- job / stage lifecycle (exec.executor) ----------------------------
    "job_start": "job begins; stages=count, topology=[{id,name,deps}]",
    "job_complete": "job drained cleanly (after deferred miss checks)",
    "job_failed": "terminal job failure; stage/name/failure_kind/reason",
    "stage_start": "one stage attempt begins; stage/name/version/boost",
    "stage_complete": "attempt succeeded; seconds, async/deferred flags",
    "stage_failed": "attempt failed; error, failure_kind, backoff",
    "stage_overflow": "shuffle capacity overflow; retried at boost*2",
    "stage_straggler": "attempt duration beyond the outlier threshold",
    "stage_dispatched": "speculative dispatch joined the overflow window",
    "overflow_drain": "batched readback of the speculative window's flags",
    "stage_fanout": "stage lowered at reduced width; nparts/of",
    "fused_dispatch": "fused region dispatched as ONE program; members",
    "fuse_break": "plan fusion kept a driver seam; after/before/reason",
    "stage_width_adapt": "observed-volume width adaptation; nparts/of",
    "stage_delay_injected": "fault-injection delay before the attempt",
    "exchange_round": "one planned exchange round; round/window/bytes/"
                      "ici_bytes/dcn_bytes (window 0 = flat all_to_all)",
    "dict_miss": "rows outside the dense key domain; stage_name/rows",
    # -- checkpointing (exec.checkpoint / executor) -----------------------
    "stage_checkpoint_hit": "stage served from the checkpoint store",
    "stage_checkpoint_saved": "stage outputs persisted; path",
    "checkpoint_corrupt": "CRC mismatch at load; recomputed instead",
    "checkpoint_gc": "retention lease removed old checkpoints; removed",
    # -- do_while (exec.executor) -----------------------------------------
    "do_while_iter": "driver-loop iteration began; iter",
    "do_while_max_iter": "loop stopped at the iteration budget",
    "do_while_state_boost": "loop state outgrew capacity; boost",
    "do_while_device_start": "whole loop compiled on device; boost",
    "do_while_device_done": "device loop finished; iters",
    "do_while_device_fallback": "device lowering rejected; driver loop",
    # -- apply_host (exec.executor) ---------------------------------------
    "apply_host_start": "host-callback stage began; stage",
    "apply_host_done": "host-callback stage finished; stage",
    # -- out-of-core streaming (exec.outofcore / pipeline / spill) --------
    "stream_start": "a stream binding began evaluation; node",
    "stream_chunk": "one ingest chunk processed; rows, partial_rows/cap",
    "stream_spill": "one bucket piece spilled; bucket/rows/depth",
    "stream_bucket": "one bucket's device job finished; bucket/rows",
    "stream_bucket_split": "oversized bucket re-split; mode/fanout",
    "stream_store": "streamed results persisted; path/rows/partitions",
    "stream_prefetch": "one chunk prefetched; queued, in_flight sample",
    "stream_pipeline": "pipeline close summary; produced, stall seconds",
    "stream_pipeline_error": "prefetch/spill-thread fault; failure_kind",
    "stream_combine": "partial compaction; device/fan_in or rows_out, "
                      "plus level/ici_bytes/dcn_bytes collective split",
    "stream_combine_policy": "combine degrade/reprobe decision; mode",
    "stream_group_done": "streaming group_by finished; chunks/groups",
    "dispatch_gap": "device-idle gap between consecutive async chunk "
                    "dispatches; gap_s, in_flight at submit",
    "dispatch_window": "async dispatch window close summary; depth/"
                       "dispatches/retries/gap_s/driver_cpu_s",
    # -- combine tree (exec.combinetree / outofcore / localjob) -----------
    "combine_tree_level": "one tree merge; level/group/fan_in/cap_rows/"
                          "bytes/ici_bytes/dcn_bytes/device",
    "combine_tree_degrade": "key ranges degraded to host; degraded/"
                            "fraction/chunks",
    "stream_distinct_spill": "distinct switched to Grace spilling; rows",
    # -- observability (obs.span / obs.metrics / executor) ----------------
    "span": "closed hierarchical span; name/cat/span_id/parent_id/dur",
    "metrics": "counter/histogram registry snapshot; counters/hists",
    "xla_compile": "stage (re)compiled; stage/key/trace_s/compile_s",
    "telemetry_merged": "driver absorbed worker span/counter batches",
    # -- diagnosis / flight recorder (obs.diagnose / exec.events) ---------
    "resource_sample": "continuous telemetry sample; hbm/rss/probes",
    "diagnosis": "online pathology detected; rule/severity/evidence/hint",
    "plan_rewrite": "runtime plan rewrite decided/applied; "
                    "action/rule/phase (rewrite.controller)",
    "events_dropped": "in-memory ring evicted events; dropped total",
    # -- cluster: scheduler / quarantine (cluster.scheduler) --------------
    "process_failed": "a scheduled process failed; computer/error",
    "process_stranded": "hard affinity unsatisfiable after removal",
    "process_dispatch": "queued process placed on a computer; wait_s",
    "computer_quarantined": "failure threshold crossed; cooldown",
    "computer_probation": "cooldown expired; probation re-admission",
    "computer_readmitted": "probation success; computer healthy again",
    # -- cluster: gang / vertex jobs (cluster.localjob) -------------------
    "worker_started": "worker process launched; worker",
    "worker_joined": "worker announced on the control plane; worker",
    "worker_dead": "worker process died; worker",
    "command_batch": "batched worker command stream posted; worker/"
                     "commands/round_trips_saved",
    "gang_window": "overlapped gang command window close summary; "
                   "depth/dispatches/peak_in_flight/retries",
    "gang_partial_combine": "worker-side level -1 partial pre-merge; "
                            "worker/parts/rows/read_bytes/cache hits",
    "gang_run_start": "gang SPMD submission began; seq/workers",
    "gang_run_complete": "gang SPMD submission finished; seconds",
    "gang_straggler": "gang run duration beyond the outlier threshold",
    "gang_rebuild": "gang reshaped/restarted; dead/workers/generation",
    "gang_member_lost_mid_job": "mid-job death; auto-shrink attempt",
    "vertex_job_start": "independent vertex-task job began; nparts",
    "vertex_job_complete": "vertex-task job finished; seq",
    "vertex_job_failed": "a vertex task exhausted retries; part",
    "vertex_complete": "one vertex task finished; part/seconds/computer",
    "vertex_retry": "vertex task re-executed; attempt/backoff/error",
    "vertex_duplicate": "straggling task speculatively duplicated",
    "vertex_duplicate_win": "the duplicate finished first; winner",
    "vertex_duplicate_cancel": "the losing attempt was canceled; loser",
    "vertex_routed": "driver routed inputs for a shuffle-bearing plan",
    "vertex_partials_merged": "driver merged per-vertex partials; rows",
    "assemble_fetch": "result partitions fetched; wire/raw bytes",
    # -- coded stage redundancy (cluster.localjob / redundancy) -----------
    "coded_job_start": "coded k-of-n stage began; seq/k/n/r/kind",
    "coded_launch": "parity spares launched; trigger/threshold/spares",
    "coded_task_complete": "one coded vertex done; coded/parity/seconds",
    "coded_task_failed": "one coded vertex failed; coded/error",
    "coded_retry": "coded vertex relaunched (coverage shortfall); coded",
    "coded_cancel": "unneeded coded vertices canceled at k completions",
    "coded_reconstruct": "output reconstructed; used/parity_used/exact",
    "coded_waste_bytes": "completed-but-unused coded output bytes",
    "coded_job_complete": "coded stage finished; seq/seconds",
    "coded_fallback": "stage ineligible for coding; reason",
    # -- gang chaos (exec.faults via cluster.worker set_fault) ------------
    "worker_killed_injected": "seeded chaos kill: process exits mid-stage",
    # -- multihost shared quarantine (obs.gang / cluster.scheduler) -------
    "quarantine_delta": "local failure deltas shipped to peer drivers",
    "quarantine_absorbed": "peer failure delta folded into local blacklist",
    # -- serving tier (serve.service) -------------------------------------
    "query_admitted": "tenant query passed admission; tenant/query/cost",
    "query_rejected": "admission refused past quota; tenant/reason/limit",
    "query_complete": "tenant query resolved; tenant/query/seconds/ok",
    "result_cache_hit": "repeat query served from the result cache",
    "tenant_quota": "tenant quota state transition; saturated or ok",
    # -- materialized views (views.matview / serve.service) ---------------
    "view_register": "plan admitted as a resident view; tenant/view/rows",
    "view_delta": "append folded into a view's partial state; rows/bytes",
    "view_snapshot": "view served a read; fresh (0 dispatches) or "
                     "finalized (1 dispatch); staleness_s",
    "view_fallback": "view registration refused; structured reason "
                     "(mirrors coded_fallback)",
    # -- serving fleet (serve.fleet router / supervisor) ------------------
    "replica_started": "engine replica joined the fleet; replica/mode",
    "replica_dead": "heartbeat went stale; replica reaped, gen bumped",
    "fleet_submit": "front door admitted + routed a query to a replica",
    "fleet_result": "front door delivered a replica's result; seconds",
    "fleet_reroute": "in-flight query replayed to the failover replica",
    "fleet_rejected": "front-door fast reject (negative quota memo)",
}

# ``kind`` -> (required payload keys, optional payload keys).  The
# graftlint ``event-schema`` checker cross-references every literal
# emit() call site against this table: explicit keys must stay inside
# required+optional, and every required key must be present (sites
# forwarding a ``**kwargs`` blob are checked for inclusion only).
# Together with EVENT_KINDS this IS the event schema — jobview and the
# trace tooling may rely on required keys existing on every record.
EVENT_PAYLOADS: Dict[str, Tuple[Tuple[str, ...], Tuple[str, ...]]] = {
    "job_start": (("stages", "topology"), ()),
    "job_complete": ((), ()),
    "job_failed": (("failure_kind", "reason"), ("name", "stage")),
    "stage_start": (("boost", "name", "stage", "version"), ()),
    "stage_complete": (
        ("name", "seconds", "stage", "version"),
        ("async", "deferred"),
    ),
    "stage_failed": (
        ("backoff", "error", "failure_kind", "failures", "name", "stage",
         "version"),
        (),
    ),
    "stage_overflow": (("boost", "name", "stage", "version"), ()),
    "stage_straggler": (
        ("name", "seconds", "stage", "threshold", "version"), (),
    ),
    "stage_dispatched": (
        ("boost", "inflight", "name", "stage", "version"), (),
    ),
    "overflow_drain": (("inflight", "stages"), ()),
    "stage_fanout": (("name", "nparts", "of", "stage"), ()),
    "fused_dispatch": (("boost", "members", "name", "stage", "version"), ()),
    "fuse_break": (("after", "before", "reason"), ()),
    "stage_width_adapt": (
        ("name", "nparts", "observed_rows", "of", "stage"), (),
    ),
    "stage_delay_injected": (("name", "seconds", "stage"), ()),
    "exchange_round": (
        ("bytes", "dcn_bytes", "ici_bytes", "round", "window"),
        ("name", "qid", "stage"),
    ),
    "dict_miss": (("rows", "stage_name"), ()),
    "stage_checkpoint_hit": (("name", "stage"), ()),
    "stage_checkpoint_saved": (("name", "path", "stage"), ()),
    "checkpoint_corrupt": (("error", "name", "path", "stage"), ()),
    "checkpoint_gc": (("removed",), ()),
    "do_while_iter": (("iter", "stage"), ()),
    "do_while_max_iter": (("iters", "stage"), ()),
    "do_while_state_boost": (("boost", "stage"), ()),
    "do_while_device_start": (("boost", "stage"), ()),
    "do_while_device_done": (("iters", "stage"), ()),
    "do_while_device_fallback": (("reason", "stage"), ()),
    "apply_host_start": (("stage",), ()),
    "apply_host_done": (("stage",), ()),
    "stream_start": (("node",), ()),
    "stream_chunk": (("rows",), ("partial_cap", "partial_rows")),
    "stream_spill": (("bucket", "depth", "rows"), ()),
    "stream_bucket": (("bucket", "depth", "rows"), ()),
    "stream_bucket_split": (
        ("bucket", "depth", "mode", "rows"), ("fanout",),
    ),
    "stream_store": (("partitions", "path", "rows"), ()),
    "stream_prefetch": (("in_flight", "pipeline", "queued"), ()),
    "stream_pipeline": (("depth", "pipeline"), ()),
    "stream_pipeline_error": (
        ("error", "failure_kind", "phase", "pipeline"), (),
    ),
    "stream_combine": (
        ("dcn_bytes", "ici_bytes", "level"),
        ("cap_rows", "device", "fan_in", "rows_out"),
    ),
    "stream_combine_policy": (
        ("chunks", "mode"), ("pinned", "reprobe", "static"),
    ),
    "stream_group_done": (("chunks", "groups"), ()),
    "dispatch_gap": (("gap_s",), ("in_flight", "pipeline", "qid")),
    "dispatch_window": (
        ("depth", "dispatches", "gap_s", "retries"),
        ("driver_cpu_s", "pipeline", "wall_s"),
    ),
    "combine_tree_level": (
        ("bytes", "cap_rows", "dcn_bytes", "device", "fan_in",
         "ici_bytes", "level"),
        ("group",),
    ),
    "combine_tree_degrade": (("chunks", "degraded", "fraction"), ()),
    "stream_distinct_spill": (("rows",), ()),
    "span": (
        ("cat", "dur", "name", "parent_id", "span_id", "thread"),
        ("qid",),
    ),
    "metrics": ((), ("counters", "hists")),
    "xla_compile": (("compile_s", "key", "stage", "trace_s"), ("qid",)),
    "telemetry_merged": (("events", "offsets"), ()),
    "process_failed": (("computer", "error", "process"), ()),
    "process_stranded": (("computer", "process"), ()),
    "process_dispatch": (("computer", "process", "wait_s"), ()),
    "computer_quarantined": (
        ("computer", "cooldown", "failures", "probation"), (),
    ),
    "computer_probation": (("computer",), ()),
    "computer_readmitted": (("computer",), ()),
    "worker_started": (("worker",), ()),
    "worker_joined": (("worker",), ()),
    "worker_dead": (("worker",), ()),
    "command_batch": (
        ("commands", "round_trips_saved", "worker"),
        ("clamped_from", "seqs"),
    ),
    "gang_window": (
        ("depth", "dispatches", "peak_in_flight", "pipeline",
         "retries", "wall_s"),
        ("qid", "workers"),
    ),
    "gang_partial_combine": (
        ("cache_hits", "cache_misses", "parts", "read_bytes", "rows",
         "worker"),
        ("bytes", "in_rows", "seconds"),
    ),
    "gang_run_start": (("seq", "workers"), ()),
    "gang_run_complete": (("seconds", "seq"), ()),
    "gang_straggler": (("seconds", "seq", "threshold"), ()),
    "gang_rebuild": (("dead", "generation", "workers"), ()),
    "gang_member_lost_mid_job": (("attempt", "dead"), ()),
    "vertex_job_start": (("nparts", "seq", "speculation"), ()),
    "vertex_job_complete": (("seq",), ()),
    "vertex_job_failed": (("failure_kind", "part"), ()),
    "vertex_complete": (("computer", "part", "seconds"), ()),
    "vertex_retry": (
        ("attempt", "backoff", "computer", "error", "failure_kind",
         "part"),
        (),
    ),
    "vertex_duplicate": (("elapsed", "part", "threshold"), ()),
    "vertex_duplicate_win": (("part", "seconds", "winner"), ()),
    "vertex_duplicate_cancel": (("loser", "part"), ()),
    "vertex_routed": (("inputs", "nparts", "plan_kind"), ()),
    "vertex_partials_merged": (("rows", "seq"), ()),
    "assemble_fetch": (("parts", "raw_bytes", "wire_bytes"), ()),
    "coded_job_start": (("agg", "k", "n", "r", "seq"), ()),
    "coded_launch": (
        ("k", "n", "r", "seq", "threshold", "trigger"), (),
    ),
    "coded_task_complete": (
        ("coded", "computer", "parity", "seconds", "seq"), (),
    ),
    "coded_task_failed": (
        ("coded", "error", "failure_kind", "parity", "seq"), (),
    ),
    "coded_retry": (("attempt", "coded", "seq"), ()),
    "coded_cancel": (("canceled", "seq"), ()),
    "coded_reconstruct": (
        ("amplification", "exact", "parity_used", "seconds", "seq",
         "used"),
        (),
    ),
    "coded_waste_bytes": (("bytes", "seq", "unused"), ()),
    "coded_job_complete": (("seconds", "seq"), ()),
    "coded_fallback": (("reason",), ()),
    "worker_killed_injected": (("name", "stage"), ()),
    "quarantine_delta": (("computer", "count", "src"), ()),
    "quarantine_absorbed": (("deltas", "source"), ()),
    "resource_sample": (
        ("source",),
        ("hbm_headroom_bytes", "hbm_limit_bytes", "hbm_used_bytes",
         "probes", "rss_kb"),
    ),
    "diagnosis": (
        ("evidence", "hint", "rule", "severity"),
        ("name", "qid", "stage"),
    ),
    "plan_rewrite": (
        ("action", "phase", "rule"),
        ("boost", "bucket", "depth", "fan", "mode", "ratio", "rows",
         "stage", "subject", "tree", "window"),
    ),
    "events_dropped": (("dropped",), ()),
    "query_admitted": (("cost_bytes", "query", "tenant"), ("queued",)),
    "query_rejected": (
        ("current", "limit", "query", "reason", "tenant"), (),
    ),
    "query_complete": (
        ("ok", "query", "seconds", "tenant"), ("cached", "error"),
    ),
    "result_cache_hit": (("query", "tenant"), ("rows",)),
    "tenant_quota": (
        ("inflight", "limit", "state", "tenant"), ("bytes",),
    ),
    "view_register": (
        ("tenant", "view"), ("rows", "state_rows", "windows"),
    ),
    "view_delta": (
        ("rows", "tenant", "view"), ("bytes", "state_rows", "windows"),
    ),
    "view_snapshot": (
        ("fresh", "tenant", "view"), ("qid", "rows", "staleness_s"),
    ),
    "view_fallback": (("reason", "tenant"), ()),
    "replica_started": (("mode", "replica"), ("pid",)),
    "replica_dead": (
        ("generation", "replica"), ("inflight", "stale_s"),
    ),
    "fleet_submit": (
        ("query", "replica", "tenant", "tier"), ("fingerprint",),
    ),
    "fleet_result": (
        ("ok", "query", "seconds", "tenant"), ("cached", "replica"),
    ),
    "fleet_reroute": (
        ("from_replica", "query", "tenant", "to_replica"), (),
    ),
    "fleet_rejected": (
        ("reason", "tenant"), ("current", "limit", "query"),
    ),
}


# Event kinds scoped to ONE query: their emit sites must stamp the
# active trace context's query id as an explicit ``qid=`` keyword
# (``obs.tracectx.current_qid()`` — None outside any query scope).
# The graftlint ``trace-context`` checker cross-references this tuple
# against every emit site both ways: a kind listed here whose emit
# site omits ``qid=`` is a finding, and so is a kind listed here that
# is not in EVENT_KINDS (stale registry entry).  Keep as a plain
# literal — the checker parses it from the AST.
QUERY_SCOPED_KINDS: Tuple[str, ...] = (
    "diagnosis",
    "dispatch_gap",
    "exchange_round",
    "gang_window",
    "span",
    "view_snapshot",
)


def _to_native(v: Any) -> Any:
    """Normalize numpy scalars/arrays (and containers of them) to
    native Python types so JSON round-trips preserve numbers — the
    old ``default=str`` fallback silently stringified them, corrupting
    jobview's numeric folds."""
    # numpy scalars expose .item(); arrays expose .tolist(); test by
    # attribute to avoid importing numpy on the hot path
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, dict):
        return {str(k): _to_native(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_to_native(x) for x in v]
    item = getattr(v, "item", None)
    if item is not None and getattr(v, "shape", None) == ():
        return v.item()  # numpy scalar (0-d)
    tolist = getattr(v, "tolist", None)
    if tolist is not None:
        return v.tolist()  # numpy array
    return v


class EventLog:
    """Thread-safe append-only JSONL event sink.

    ``mem_cap`` bounds the in-memory mirror with a ring buffer (long
    out-of-core jobs emit per-chunk events without bound); the file
    sink, when configured, always keeps the full stream.  ``None``
    keeps the unbounded list (test-friendly default).

    Ring evictions are COUNTED (``dropped``) and announced in-stream
    with ``events_dropped`` markers on a doubling schedule, so the
    diagnosis engine and blackbox merges see "the stream is truncated
    here" instead of misreading a gap as idleness.

    ``add_tap(fn)`` registers a live observer called with every
    appended event OUTSIDE the log lock — the feed for the online
    diagnosis engine and the flight recorder.  Taps must be fast and
    must never raise (exceptions are swallowed; observability cannot
    fail the job).
    """

    def __init__(self, path: Optional[str] = None,
                 mem_cap: Optional[int] = None):
        self.path = path
        self.mem_cap = mem_cap
        self._lock = threading.Lock()
        self._mem = (
            deque(maxlen=mem_cap) if mem_cap else []
        )  # type: ignore[var-annotated]
        self.dropped = 0  # total ring evictions since construction
        self._next_drop_marker = 1  # doubling threshold for the marker
        self._taps: List[Any] = []
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._fh = open(path, "a", buffering=1)
        else:
            self._fh = None

    def add_tap(self, fn) -> None:
        """Register a live per-event observer (called outside the
        lock, after the event is appended)."""
        self._taps.append(fn)

    def remove_tap(self, fn) -> None:
        try:
            self._taps.remove(fn)
        except ValueError:
            pass

    def emit(self, kind: str, **fields: Any) -> None:
        ev = {
            "ts": time.time(), "mono": time.monotonic(), "kind": kind,
            **{k: _to_native(v) for k, v in fields.items()},
        }
        self._append(ev)

    def absorb(self, ev: Dict[str, Any]) -> None:
        """Append a pre-stamped event AS-IS (no re-stamping) — the
        driver-side merge path for worker telemetry batches whose
        clocks were already offset-corrected (``obs.gang``)."""
        self._append({k: _to_native(v) for k, v in ev.items()})

    def _append(self, ev: Dict[str, Any]) -> None:
        marker = False
        with self._lock:
            if (
                self.mem_cap
                and len(self._mem) == self.mem_cap
            ):
                self.dropped += 1
                if self.dropped >= self._next_drop_marker:
                    # next marker at 2x: O(log drops) markers total, so
                    # the announcement cannot itself flood the ring
                    self._next_drop_marker = max(
                        self._next_drop_marker * 2, self.dropped * 2
                    )
                    marker = True
            self._mem.append(ev)
            if self._fh:
                self._fh.write(json.dumps(ev, default=str) + "\n")
        for tap in self._taps:
            try:
                tap(ev)
            except Exception:
                pass  # observability must never fail the job
        if marker:
            self.emit("events_dropped", dropped=self.dropped)

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._mem)

    def filter(self, *kinds: str) -> List[Dict[str, Any]]:
        """Snapshot of events whose ``kind`` is one of ``kinds`` —
        the recovery/chaos suites assert on specific transitions
        (quarantine, retry, corruption) without refolding the stream."""
        with self._lock:
            return [e for e in self._mem if e["kind"] in kinds]

    def drain(self) -> List[Dict[str, Any]]:
        """Atomically snapshot AND clear the in-memory mirror — the
        worker-side telemetry shipping primitive (the file sink, if
        any, is unaffected)."""
        with self._lock:
            out = list(self._mem)
            self._mem.clear()
            return out

    def close(self) -> None:
        with self._lock:
            if self._fh:
                self._fh.close()
                self._fh = None

    @staticmethod
    def load(path: str) -> List[Dict[str, Any]]:
        out = []
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if line:
                    out.append(json.loads(line))
        return out
