"""Append-only job event log — the Calypso reporter analog.

The reference GM appends timestamped job events (process/vertex state
transitions, final topology) to ``calypso.log`` in the job's DFS
directory (``GraphManager/reporting/DrCalypsoReporting.cpp``), consumed
post-hoc by the JobBrowser.  Here: JSONL events per job, consumed by
``dryad_tpu.tools.jobview``.

Streaming (out-of-core) event kinds, emitted by ``exec.outofcore`` /
``exec.pipeline`` / ``exec.spill`` and folded by jobview's streaming +
pipeline lines:

- ``stream_start`` / ``stream_chunk`` / ``stream_spill`` /
  ``stream_bucket`` / ``stream_bucket_split`` / ``stream_store`` — the
  chunk/spill/bucket lifecycle;
- ``stream_prefetch`` — one per prefetched chunk: ``queued`` (queue
  depth) and ``in_flight`` (pipeline occupancy sample);
- ``stream_pipeline`` — per-pipeline close summary: ``produced``,
  ``peak_in_flight``, ``producer_wait_s`` (prefetch stalled on the
  driver), ``consumer_wait_s`` (driver stalled on ingest);
- ``stream_pipeline_error`` — a prefetch/spill-thread fault, with its
  ``exec.failure`` classification, before it re-raises downstream;
- ``stream_combine`` — partial compaction; ``device=True`` + ``fan_in``
  for HBM-resident N-ary merges, ``rows_out`` for host merges;
- ``stream_combine_policy`` — the device→host degrade decision for
  non-reducing (high-cardinality) merge streams.

Events may be emitted from pipeline threads; ``EventLog`` is
thread-safe.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional


class EventLog:
    """Thread-safe append-only JSONL event sink."""

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self._lock = threading.Lock()
        self._mem: List[Dict[str, Any]] = []
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._fh = open(path, "a", buffering=1)
        else:
            self._fh = None

    def emit(self, kind: str, **fields: Any) -> None:
        ev = {"ts": time.time(), "kind": kind, **fields}
        with self._lock:
            self._mem.append(ev)
            if self._fh:
                self._fh.write(json.dumps(ev, default=str) + "\n")

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._mem)

    def filter(self, *kinds: str) -> List[Dict[str, Any]]:
        """Snapshot of events whose ``kind`` is one of ``kinds`` —
        the recovery/chaos suites assert on specific transitions
        (quarantine, retry, corruption) without refolding the stream."""
        with self._lock:
            return [e for e in self._mem if e["kind"] in kinds]

    def close(self) -> None:
        with self._lock:
            if self._fh:
                self._fh.close()
                self._fh = None

    @staticmethod
    def load(path: str) -> List[Dict[str, Any]]:
        out = []
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if line:
                    out.append(json.loads(line))
        return out
