"""GraphExecutor — the job manager.

The TPU-native GraphManager (reference ``GraphManager/vertex/DrGraph.h:75``,
``DrGraphExecutor.cpp:15-65``): executes the stage DAG in dependency
order.  Where the reference schedules per-vertex processes with cohorts,
property mailboxes and channel files, this driver launches one compiled
SPMD program per stage on the mesh and keeps intermediates in HBM.

Fault tolerance keeps the reference *semantics* in TPU form:
- versioned re-execution with a failure budget
  (``DrVertexRecord.h:164-194`` version generator; ``DrGraph.h:42``
  m_maxActiveFailureCount) — each stage attempt is a numbered version;
  injected/real failures re-run it, and the budget aborts the job;
- adaptive shapes: shuffle/join overflow is a *retryable* outcome that
  re-compiles the stage with a boosted capacity from a bounded palette
  (the dynamic fan-out sizing of ``DrDynamicRangeDistributor.cpp:54``
  turned into a shape-palette choice);
- per-stage duration statistics feed the straggler model
  (``exec.stats``) and every transition lands in the event log
  (``exec.events``, the Calypso reporter analog).
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax

from dryad_tpu.columnar.batch import ColumnBatch
from dryad_tpu.exec import faults
from dryad_tpu.exec.checkpoint import CheckpointStore, stage_fingerprint
from dryad_tpu.exec.events import EventLog
from dryad_tpu.exec.failure import (
    Attempt,
    FailureKind,
    JobFailedError,
    RetryPolicy,
    StageFailedError,
    classify,
)
from dryad_tpu.exec.kernels import (
    NON_OVERFLOW_OPS,
    OPERAND_PARAMS,
    build_fused_fn,
    build_stage_fn,
    stage_operand_objs,
)
from dryad_tpu.exec.operands import DeviceOperandPool, is_operand_capable
from dryad_tpu.exec.stats import StageStatistics
from dryad_tpu.obs import flightrec, tracectx
from dryad_tpu.obs.metrics import MetricsRegistry
from dryad_tpu.obs.span import Tracer
from dryad_tpu.parallel.mesh import mesh_axes, num_partitions
from dryad_tpu.parallel.stage import compile_fused, compile_stage
from dryad_tpu.plan.fuse import (
    ADAPT_OK_OPS,
    SHRINKING_OPS,
    FusedStage,
    fuse as fuse_plan,
)
from dryad_tpu.plan.lower import Stage, StageGraph, StageOp
from dryad_tpu.plan.xchgplan import resolve_window
from dryad_tpu.utils.config import DryadConfig
from dryad_tpu.utils.logging import get_logger

log = get_logger("dryad_tpu.exec")


def _stage_has_miss_guard(stage) -> bool:
    """Stages whose compiled program accumulates a dense-domain miss
    counter needing the deferred readback: STRING dictionary coding, or
    the guarded int auto-dense bucket reduce."""
    return any(
        op.kind == "string_code"
        or (op.kind == "group_reduce_dense" and op.params.get("guard"))
        for op in stage.ops
    )


class DeferredFinish:
    """Tail of an ``execute(defer_miss=True)`` job: the dict-miss
    counters whose readback the caller batches into its own
    device->host transfer, plus the guarded checkpoint writes that
    must not happen until those counters prove clean.

    Contract: fetch ``miss_arrays()`` alongside the job outputs (one
    ``device_get``), then call ``finish(host_vals)`` — it raises
    ``StageFailedError`` on a nonzero counter (discarding the gated
    checkpoints) and writes them otherwise.  ``finish()`` with no
    argument falls back to its own readback."""

    def __init__(self, executor, pending, ckpts):
        self._executor = executor
        self._pending = pending
        self._ckpts = ckpts

    def miss_arrays(self):
        return [m for _, m in self._pending]

    def abort(self, reason: str) -> None:
        """Terminal path for a failed output transfer: drop the gated
        checkpoint writes (never persist unproven results) and emit
        ``job_failed`` so the event log distinguishes a transfer
        failure from a job that simply hung (ADVICE r4)."""
        self._ckpts = []
        self._pending = []
        self._executor.events.emit(
            "job_failed", reason=reason, failure_kind="transient"
        )

    def finish(self, host_vals=None) -> None:
        if host_vals is None:
            host_vals = (
                jax.device_get(self.miss_arrays()) if self._pending else []
            )
        if len(host_vals) != len(self._pending):
            raise AssertionError(
                f"DeferredFinish.finish: {len(host_vals)} host values for "
                f"{len(self._pending)} pending miss counters — fetch "
                "miss_arrays() alongside the outputs"
            )
        for (name, _), m in zip(self._pending, host_vals):
            if int(m):
                self._ckpts = []  # poisoned results: never persist
                self._executor.events.emit(
                    "job_failed",
                    reason=f"dict miss in {name}",
                    failure_kind="deterministic",
                )
                self._executor._raise_miss(name, int(m))
        for stage, fp, outs in self._ckpts:
            self._executor._write_checkpoint(stage, fp, outs)
        self._ckpts = []
        self._pending = []
        self._executor.events.emit("job_complete")


# StageFailedError/JobFailedError live in exec.failure (imported above
# and re-exported here for the existing call sites and tests).


def _lowering_key_hash(key) -> str:
    """Short per-run digest of a compile-cache key — the lowering-key
    identity ``xla_compile`` events are grouped by (object reprs embed
    ids, so the hash is stable within a run, which is the scope the
    recompile accounting needs)."""
    import zlib

    return format(zlib.crc32(repr(key).encode()) & 0xFFFFFFFF, "08x")


class _CompileTimed:
    """First-call timing shim over a freshly compiled stage program.

    ``jax.jit`` traces + compiles on the FIRST invocation at these
    shapes (the cache key includes the shape key, so a fresh entry
    always pays it there); that call's wall time is recorded as the
    compile cost for this lowering key and emitted as ONE
    ``xla_compile`` event — the signal that makes the vocab-widening
    recompile open item (ROADMAP) measurable.  Subsequent calls pay a
    single attribute check.
    """

    __slots__ = (
        "fn", "_exec", "_name", "_key", "_build_s", "_pending",
        "xchg_rounds",
    )

    def __init__(self, fn, executor, name, key_hash, build_s,
                 xchg_rounds=None):
        self.fn = fn
        self._exec = executor
        self._name = name
        self._key = key_hash
        self._build_s = build_s
        self._pending = True
        # Static exchange-round byte accounting, filled at trace time by
        # the stage builder's cell (kernels._exchange): one dict per
        # round, emitted as exchange_round events on every dispatch.
        self.xchg_rounds = xchg_rounds if xchg_rounds is not None else []

    def __call__(self, *args):
        if not self._pending:
            return self.fn(*args)
        self._pending = False
        t0 = time.monotonic()
        out = self.fn(*args)
        dt = time.monotonic() - t0
        ex = self._exec
        ex.metrics.add("xla_compiles", 1.0, stage=self._name)
        ex.metrics.add("xla_compile_s", dt, stage=self._name)
        ex.events.emit(
            "xla_compile", stage=self._name, key=self._key,
            qid=tracectx.current_qid(),
            trace_s=round(self._build_s, 6), compile_s=round(dt, 6),
        )
        return out


def _phys_np_dtype(col: str, schema):
    """numpy dtype of one physical device column."""
    import numpy as np

    from dryad_tpu.columnar.schema import ColumnType

    if "#" in col:
        return np.dtype(np.uint32)
    return {
        ColumnType.INT32: np.dtype(np.int32),
        ColumnType.FLOAT32: np.dtype(np.float32),
        ColumnType.BOOL: np.dtype(np.bool_),
        ColumnType.UINT32: np.dtype(np.uint32),
    }[schema.field(col).ctype]


class GraphExecutor:
    def __init__(
        self,
        mesh,
        config: Optional[DryadConfig] = None,
        events: Optional[EventLog] = None,
        subquery_runner: Optional[Callable] = None,
        loop_lowerer: Optional[Callable] = None,
    ):
        self.mesh = mesh
        self.config = config or DryadConfig()
        self.events = events or EventLog(None)
        # structured tracing + counters (obs): spans serialize into the
        # event stream; the registry feeds JobMetrics/bench attribution
        self.tracer = Tracer(self.events)
        self.metrics = MetricsRegistry()
        self.P = num_partitions(mesh)
        self._compiled: Dict[Tuple, Any] = {}
        # Static-vs-operand split for plan params: OPERAND-registered
        # params (the string coding tables) key the compile cache by
        # shape-palette TIER and travel as call-time device inputs via
        # the content-addressed operand pool — vocabulary widening
        # within a tier reuses the compiled program and scatters only
        # the widened table delta to the device.  Off = the legacy
        # baked-constant path (key by content; recompile per widen).
        self.runtime_operands = bool(
            getattr(self.config, "stringcode_runtime_tables", True)
        )
        self.operand_pool = DeviceOperandPool(mesh, metrics=self.metrics)
        # health probes for the flight recorder's microsnapshots
        # (no-ops when no recorder is installed): compiled-program and
        # operand-pool residency on THIS executor (last one wins when
        # a process holds several — fine for forensics)
        flightrec.probe(
            "xla_programs", lambda: len(self._compiled)
        )
        flightrec.probe(
            "operand_pool",
            lambda: {
                "tiers": len(self.operand_pool._tiers),
                "hits": self.operand_pool.hits,
                "full_uploads": self.operand_pool.full_uploads,
                "delta_scatters": self.operand_pool.delta_scatters,
            },
        )
        # Runtime plan rewriter (dryad_tpu.rewrite), wired by the
        # context AFTER construction — the engine never imports the
        # policy layer, it only consults the handle.  Consulted for
        # per-stage starting-boost floors (overflow pre-widening) and
        # the auto exchange-window hint.
        self.rewriter = None
        # Measured-headroom provider (obs.telemetry.HeadroomProvider),
        # wired by the context alongside the rewriter.  Consulted by
        # the auto exchange-window policy; None (or a provider with no
        # measurement yet) falls back to the configured HBM budget.
        self.headroom = None
        self._rewrites_applied: set = set()
        # do_while loop-state compaction programs (see _compact_loop_state)
        self._compact_cache: Dict[Tuple, Any] = {}
        self.stats: Dict[str, StageStatistics] = {}
        # Callback used by do_while stages to run body/cond subplans.
        self.subquery_runner = subquery_runner
        self.loop_lowerer = loop_lowerer
        self._profiling = False
        # (stage name, device int32) dictionary-miss counters awaiting
        # their deferred readback (_check_pending_miss)
        self._pending_miss: List[Tuple[str, Any]] = []
        # (stage, fp, outs) checkpoint saves of miss-GUARDED stages,
        # persisted only after their counters drain clean
        self._pending_ckpt: List[Tuple[Any, Any, Any]] = []
        self.checkpoints = (
            CheckpointStore(self.config.checkpoint_dir, events=self.events)
            if self.config.checkpoint_dir
            else None
        )
        # Failure-domain retry policy (exec.failure): transient stage
        # failures back off exponentially with seeded jitter under the
        # per-stage budget; deterministic repeats fail fast.
        self.retry_policy = RetryPolicy(
            max_attempts=self.config.max_stage_failures,
            backoff_base=self.config.retry_backoff_base,
            backoff_max=self.config.retry_backoff_max,
            jitter=self.config.retry_jitter,
            seed=self.config.retry_seed,
        )
        # injectable sleep: backoff-timing tests record instead of wait
        self._sleep: Callable[[float], None] = time.sleep

    # -- compilation cache -------------------------------------------------
    def _stage_key(self, stage: Stage, split_operands: bool = True) -> Tuple:
        """Structural stage identity: op kinds + static params + fn object
        ids.  Re-lowering the same logical plan yields new stage ids but
        identical structure (fn objects live on the plan nodes), so
        repeated collect()/do_while iterations hit the cache.

        Params registered as OPERANDs (``kernels.OPERAND_PARAMS``, with
        the runtime-tables split on) key by their shape-palette TIER
        (``operand_signature()``) instead of content — the compiled fn
        takes their arrays as call-time device inputs, so every table
        of a tier shares one program.  ``split_operands=False`` keeps
        the content key (the do_while device path, which builds its
        loop body without operand plumbing and must not share programs
        across table contents)."""
        if isinstance(stage, FusedStage):
            # Member-local slot numbers overlap across members, so the
            # chained-op key alone would alias differently wired
            # regions; fold the member keys (each with its own
            # out_slots) plus the region wiring/exports.
            return (
                "fused",
                tuple(
                    self._stage_key(m, split_operands)
                    for m in stage.members
                ),
                tuple(stage.wiring),
                tuple(stage.exports),
            )
        split = split_operands and self.runtime_operands
        parts = []
        for op in stage.ops:
            items = []
            for k, v in sorted(op.params.items()):
                if (
                    split
                    and (op.kind, k) in OPERAND_PARAMS
                    and is_operand_capable(v)
                ):
                    items.append((k, ("operand", v.operand_signature())))
                    continue
                if isinstance(v, list):
                    v = tuple(v)
                try:
                    hash(v)
                except TypeError:
                    v = repr(v)  # unhashable static param: structural repr
                # Hashable objects (incl. functions, AggSpecs) go into the
                # key BY REFERENCE — the key holds them alive, so a freed
                # object's id can never alias a new one (id()-keyed caches
                # silently serve stale compiled programs after GC reuse).
                items.append((k, v))
            parts.append((op.kind, tuple(items)))
        return (tuple(parts), tuple(stage.out_slots))

    def graph_key(self, graph) -> Tuple:
        """Public structural identity of a LOWERED stage graph — one
        ``_stage_key`` per stage, in graph order.  The serving tier's
        result-cache keying surface: built on the exact machinery the
        compile cache uses, so two lowerings share a graph key iff
        their stages would share compiled programs.  fn-valued params
        key BY REFERENCE (see ``_stage_key``), so closure-bearing plans
        match only when re-run from the same Query object — prepared-
        statement semantics — while value-hashable params (group_by
        agg tuples, take counts, ...) match across rebuilt queries."""
        return tuple(self._stage_key(s) for s in graph.stages)

    def _stage_rep(self, stage: Stage) -> Tuple:
        """Call-time replicated operand arrays for a dispatch of
        ``stage`` — the flattened device buffers of every OPERAND
        param, in ``stage_operand_objs`` order (the same enumeration
        ``build_stage_fn`` bound the trace against)."""
        if not self.runtime_operands:
            return ()
        return tuple(
            a
            for obj in stage_operand_objs(stage)
            for a in self.operand_pool.get(obj)
        )

    def _get_compiled(
        self, stage: Stage, boost: int, shape_key: Tuple,
        fan: Optional[int] = None,
    ):
        """``fan``: observed-volume width override — exchanges/resizes
        lowered at full width (nparts=None) concentrate onto ``fan``
        partitions instead.  Fans quantize to powers of two, so the
        compile cache forms a small width palette reused across jobs
        (the re-dispatch-without-recompile requirement of
        ``DrDynamicRangeDistributor.cpp:54-110`` adaptation)."""
        run_stage = stage
        if fan:
            run_stage = self._fan_adapted_stage(stage, fan)
        window = self._resolve_window(shape_key, boost)
        # the resolved window shapes the lowered exchange: it must be
        # part of the compile identity (the auto policy / rewriter
        # hint may resolve differently across dispatches)
        key = (self._stage_key(run_stage), boost, shape_key, window)
        hit = self._compiled.get(key)
        if hit is None:
            t0 = time.monotonic()
            objs = tuple(
                stage_operand_objs(run_stage)
                if self.runtime_operands else ()
            )
            axes = mesh_axes(self.mesh)
            sizes = tuple(self.mesh.shape[a] for a in axes)
            cell: List[Dict[str, int]] = []
            if isinstance(run_stage, FusedStage):
                fn = build_fused_fn(
                    run_stage, self.P, self.config.shuffle_slack, boost,
                    axes, sizes, operand_objs=objs,
                    window=window, xchg_cell=cell,
                )
                compiled = compile_fused(self.mesh, fn)
            else:
                fn = build_stage_fn(
                    run_stage, self.P, self.config.shuffle_slack, boost,
                    axes, sizes, operand_objs=objs,
                    window=window, xchg_cell=cell,
                )
                compiled = compile_stage(self.mesh, fn)
            hit = _CompileTimed(
                compiled, self, run_stage.name,
                _lowering_key_hash(key), time.monotonic() - t0,
                xchg_rounds=cell,
            )
            self._compiled[key] = hit
        return hit

    @staticmethod
    def _shape_key(inputs: Tuple[ColumnBatch, ...]) -> Tuple:
        return tuple(
            (tuple(sorted(b.data.keys())), b.capacity) for b in inputs
        )

    def _resolve_window(self, shape_key: Tuple, boost: int) -> int:
        """Effective staged-exchange window for one compilation.

        Static ``config.exchange_window >= 0`` passes through; ``-1``
        delegates to :func:`plan.xchgplan.resolve_window` with a
        conservative per-destination bucket estimate derived from the
        shape key (capacity x columns x 8B, widened by slack/boost —
        the same quantities the lowered exchange sizes its send buffer
        from), the configured HBM budget, the runtime rewriter's
        retune hint when one is pinned, and the MEASURED live headroom
        when a telemetry provider is wired (precedence: hint >
        measured > budget).  Live headroom is quantized to a power of
        two before it enters the policy — the resolved window rides
        the compile-cache key, and raw byte-exact measurements would
        fragment the palette into one entry per sample.  Deterministic
        in its (quantized) inputs.
        """
        cfgw = int(getattr(self.config, "exchange_window", 0))
        if cfgw >= 0:
            return cfgw
        slack = float(getattr(self.config, "shuffle_slack", 1.25))
        bucket_bytes = 1
        for cols, capacity in shape_key:
            rows = -(-int(capacity) * max(1, int(boost)) // max(1, self.P))
            est = int(rows * slack) * max(1, len(cols)) * 8
            bucket_bytes = max(bucket_bytes, est)
        budget = (
            int(getattr(self.config, "exchange_hbm_budget_mb", 256)) << 20
        )
        hint = None
        if self.rewriter is not None:
            hint = self.rewriter.exchange_window_hint()
        headroom = None
        if self.headroom is not None:
            h = self.headroom.headroom_bytes()
            if h is not None and int(h) > 0:
                headroom = 1 << (int(h).bit_length() - 1)
        return resolve_window(
            cfgw, self.P, bucket_bytes, budget, hint=hint,
            headroom_bytes=headroom,
        )

    # -- execution ---------------------------------------------------------
    def execute(
        self,
        graph: StageGraph,
        bindings: Dict[int, ColumnBatch],
        binding_fps: Optional[Dict[int, Optional[str]]] = None,
        defer_miss: bool = False,
    ) -> Any:
        """Run all stages; returns (stage_id, out_idx) -> output batch.

        ``bindings``: plan-input node id -> mesh-sharded global batch.
        ``binding_fps``: plan-input node id -> content SHA-1 (or None if
        the binding can't be fingerprinted) for checkpoint identity.

        ``defer_miss=True`` returns ``(results, DeferredFinish)``
        instead: the dict-miss readback (and the checkpoint writes it
        gates) are handed to the caller, who batches the counters into
        its own device->host transfer and calls ``finish(host_vals)``
        — saving one ~70 ms tunnel round-trip per job versus the
        synchronous check (BASELINE.md).
        """
        # Whole-DAG fusion (plan.fuse): maximal runs of device-eligible
        # stages collapse into FusedStage regions — one compiled
        # program, one dispatch per region.  Per-execute cost is
        # O(stages); the compile cache keys regions structurally, so
        # repeated submissions (and the out-of-core driver's cached
        # chunk plans) reuse fused programs across calls.  Off = the
        # legacy per-stage path, kept as the differential baseline.
        if getattr(self.config, "plan_fuse", True) and len(graph.stages) > 1:
            graph, fuse_report = fuse_plan(
                graph, self.config,
                single_axis=len(mesh_axes(self.mesh)) == 1,
            )
            for br in fuse_report.breaks:
                self.events.emit(
                    "fuse_break", after=br["after"], before=br["before"],
                    reason=br["reason"],
                )
        # Topology rides the event log so jobview can redraw the DAG
        # post-hoc — the reference JobBrowser reconstructs the graph
        # from GM logs the same way (``JobBrowser/JOM/jobinfo.cs:62``).
        topology = [
            {
                "id": s.id,
                "name": s.name,
                "deps": [
                    ["in", idx] if ref == "plan_input" else [ref, idx]
                    for ref, idx in s.input_refs
                ],
            }
            for s in graph.stages
        ]
        self.events.emit(
            "job_start", stages=len(graph.stages), topology=topology
        )
        results: Dict[Tuple[int, int], ColumnBatch] = {}
        # do_while subqueries re-enter execute(); the adaptation state
        # is per-graph (stage ids restart per lowering), so save and
        # restore the outer job's view around the nested run
        adapt_state = (
            getattr(self, "_observed_rows", None),
            getattr(self, "_count_wanted", None),
            getattr(self, "_adapt_safe", None),
        )
        self._prepare_width_adapt(graph)
        # do_while re-enters execute() through subquery_runner; only the
        # top-level call may own the profiler session.
        profile = (
            jax.profiler.trace(self.config.profile_dir)
            if self.config.profile_dir and not self._profiling
            else contextlib.nullcontext()
        )
        self._profiling = bool(self.config.profile_dir)
        # stage id -> Merkle fingerprint (None = not checkpointable)
        stage_fps: Dict[int, Optional[str]] = {}
        # Re-entrancy (do_while subqueries) and failure hygiene: drain
        # only the counters THIS call added; on failure discard them so
        # a stale counter can't fail a later unrelated job.
        mark = len(self._pending_miss)
        mark_ckpt = len(self._pending_ckpt)
        try:
            with profile:
                self._execute_stages(graph, bindings, results, binding_fps, stage_fps)
        except BaseException:
            del self._pending_miss[mark:]
            del self._pending_ckpt[mark_ckpt:]
            raise
        finally:
            if adapt_state[0] is not None:
                (self._observed_rows, self._count_wanted,
                 self._adapt_safe) = adapt_state
            if not isinstance(profile, contextlib.nullcontext):
                self._profiling = False
        if defer_miss:
            pending = self._pending_miss[mark:]
            del self._pending_miss[mark:]
            ckpts = self._pending_ckpt[mark_ckpt:]
            del self._pending_ckpt[mark_ckpt:]
            # job_complete is emitted by DeferredFinish.finish() once
            # the miss counters prove clean — a miss-failed job must
            # not be logged as completed (jobview counts on it).
            return results, DeferredFinish(self, pending, ckpts)
        try:
            self._check_pending_miss(mark)
        except BaseException:
            # guarded stages' results are poisoned — never persist them
            del self._pending_ckpt[mark_ckpt:]
            raise
        # miss counters clean: guarded stages' checkpoints may persist
        for stage, fp, outs in self._pending_ckpt[mark_ckpt:]:
            self._write_checkpoint(stage, fp, outs)
        del self._pending_ckpt[mark_ckpt:]
        self.events.emit("job_complete")
        return results

    # -- observed-volume stage-width adaptation -----------------------------
    #
    # The reference resizes a consumer stage from MEASURED upstream
    # volume and rewires the graph (DrDynamicRangeDistributor.cpp:54-110
    # copies = sampledSize/samplingRate/dataPerVertex;
    # DrPipelineSplitManager.h:23).  Here: completed stages report their
    # observed output row counts (riding readbacks that happen anyway),
    # and a consumer whose exchanges were lowered at full width because
    # the STATIC estimator had no bound re-dispatches at a reduced
    # power-of-two width when the observed volume is tail-sized.
    # Producers are untouched; correctness is internal to the adapted
    # stage — a join side whose exchange was ELIDED on partition claims
    # gets a matching reduced-width exchange inserted (the runtime
    # graph-rewiring of the reference's distributors).

    # op kinds proven width-insensitive (everything else blocks
    # adaptation: zip/sliding_window/rank/take-style ops depend on row
    # placement or engine order across the full mesh width).  ONE
    # definition shared with the fuse pass, whose adapt-seam rule must
    # mirror this gate (plan.fuse leaves adaptation candidates unfused).
    _ADAPT_OK_OPS = ADAPT_OK_OPS

    def _prepare_width_adapt(self, graph: StageGraph) -> None:
        self._observed_rows: Dict[Tuple[int, int], int] = {}
        self._count_wanted: set = set()
        # (producer sid, out idx) -> True iff EVERY consumer re-routes
        # that input through a leading exchange.  An ADAPTED stage's
        # output no longer satisfies the full-width hash claim its plan
        # node advertises, so a consumer that elided its exchange on
        # that claim would silently mis-join — such producers must not
        # adapt (the static twin of lower.py's `reduced` guard).
        self._adapt_safe: Dict[Tuple[int, int], bool] = {}
        single_axis = len(mesh_axes(self.mesh)) == 1
        limit = getattr(self.config, "tail_fanout_rows", 0)
        for st in graph.stages:
            for j, (ref, idx) in enumerate(st.input_refs):
                if ref == "plan_input":
                    continue
                key = (ref, idx)
                ok = self._slot_reroutes(st, j)
                self._adapt_safe[key] = (
                    self._adapt_safe.get(key, True) and ok
                )
            if single_axis and limit and self._adaptable(st):
                for ref, _idx in st.input_refs:
                    if ref != "plan_input":
                        self._count_wanted.add(ref)

    def _consumers_allow_adapt(self, stage: Stage) -> bool:
        """Every consumer of this stage's outputs re-routes them
        through a leading exchange (missing key = no consumers)."""
        return all(
            self._adapt_safe.get((stage.id, i), True)
            for i in range(len(stage.out_slots))
        )

    @staticmethod
    def _slot_reroutes(stage: Stage, slot: int) -> bool:
        """True when the first op touching ``slot`` is an exchange —
        rows re-route by key, so upstream placement is irrelevant."""
        if isinstance(stage, FusedStage):
            # member-local slot numbers make the scan meaningless for a
            # region; be strict (pins the producer to full width)
            return False
        for op in stage.ops:
            touched = [
                op.params.get(k)
                for k in ("slot", "left_slot", "right_slot")
                if k in op.params
            ]
            if slot in touched:
                return op.kind in ("exchange_hash", "exchange_range")
        return False  # pass-through or unknown: be strict

    def _adaptable(self, stage: Stage) -> bool:
        if isinstance(stage, FusedStage):
            # a region compiles at its static widths; the fuse pass
            # leaves genuine adaptation candidates unfused instead
            return False
        return all(
            op.kind in self._ADAPT_OK_OPS for op in stage.ops
        ) and any(
            op.kind in ("exchange_hash", "exchange_range")
            and not op.params.get("nparts")
            for op in stage.ops
        )

    def _fan_adapted_stage(self, stage: Stage, fan: int) -> Stage:
        """Stage copy at reduced width: full-width exchanges/resizes
        concentrate onto ``fan`` partitions, and a join slot whose
        exchange was elided on static partition claims gets a matching
        reduced-width exchange inserted so both sides stay
        co-partitioned."""
        ops: List[StageOp] = []
        exchanged = set()
        for op in stage.ops:
            if op.kind == "join":
                for side, keys_p in (
                    ("left_slot", "left_keys"), ("right_slot", "right_keys")
                ):
                    sl = op.params[side]
                    if sl not in exchanged and keys_p in op.params:
                        ops.append(StageOp("exchange_hash", {
                            "slot": sl,
                            "keys": list(op.params[keys_p]),
                            "nparts": fan,
                        }))
                        ops.append(StageOp("resize", {
                            "slot": sl, "factor": 1.0, "nparts": fan,
                        }))
                        exchanged.add(sl)
            if op.kind in ("exchange_hash", "exchange_range", "resize"):
                exchanged.add(op.params.get("slot"))
                if not op.params.get("nparts"):
                    ops.append(StageOp(op.kind, {**op.params, "nparts": fan}))
                    continue
            ops.append(op)
        return Stage(
            stage.id, stage.name, list(stage.input_refs), ops=ops,
            out_slots=list(stage.out_slots), growth=stage.growth,
        )

    # aggregation-shaped ops that shrink data by orders of magnitude;
    # shared with plan.fuse (the adapt-seam rule keys on the same set)
    _SHRINKING_OPS = SHRINKING_OPS

    def _drain_for_adapt(self, stage: Stage, window) -> bool:
        """Worth syncing the window early: this stage could adapt its
        width, every input's count is pending in the window (or already
        known), and at least one producer is aggregation-shaped (the
        shapes that shrink data by orders of magnitude — draining for a
        map stage would pay the sync the window exists to avoid)."""
        limit = getattr(self.config, "tail_fanout_rows", 0)
        if not limit or len(mesh_axes(self.mesh)) != 1:
            return False
        if not self._adaptable(stage):
            return False
        if not self._consumers_allow_adapt(stage):
            return False  # a consumer pinned this stage to full width
        in_window = {w["stage"].id: w for w in window}
        shrinker = False
        for ref, idx in stage.input_refs:
            if ref == "plan_input":
                return False
            if (ref, idx) in self._observed_rows:
                continue  # already counted (earlier drain)
            w = in_window.get(ref)
            if w is None or not w.get("counts"):
                return False
            if any(
                op.kind in self._SHRINKING_OPS
                for op in w["stage"].ops
            ):
                shrinker = True
        return shrinker

    def _record_observed(
        self, stage: Stage, host_counts, capacities=None
    ) -> None:
        for idx, c in enumerate(host_counts):
            self._observed_rows[(stage.id, idx)] = int(c)
            # rows-out + layout accounting ride the readback that
            # happened anyway: valid vs layout rows is the padding-
            # waste ratio JobMetrics reports
            self.metrics.add("rows_out", int(c), stage=stage.name)
            self.metrics.add("valid_rows", int(c))
            if capacities is not None and idx < len(capacities):
                self.metrics.add("layout_rows", int(capacities[idx]))

    def _adapt_fan_for(self, stage: Stage) -> Optional[int]:
        """Reduced width for this stage from its inputs' OBSERVED rows;
        None = run as lowered (full width or static reduction)."""
        limit = getattr(self.config, "tail_fanout_rows", 0)
        if not limit or len(mesh_axes(self.mesh)) != 1:
            return None
        if not self._adaptable(stage):
            return None
        if not self._consumers_allow_adapt(stage):
            return None
        total = 0
        for ref, idx in stage.input_refs:
            if ref == "plan_input":
                return None  # static bindings: lowering already decided
            c = self._observed_rows.get((ref, idx))
            if c is None:
                return None
            total += c
        from dryad_tpu.plan.lower import tail_width

        w = tail_width(total, self.config, self.P)
        if w is None:
            return None
        fan = 1 << (w - 1).bit_length()  # pow2 palette for cache reuse
        return fan if fan < self.P else None

    def _raise_miss(self, name: str, m: int) -> None:
        self.events.emit("dict_miss", stage_name=name, rows=m)
        raise StageFailedError(
            f"stage {name!r}: {m} rows fall outside the dense "
            "path's key domain (STRING values missing from the "
            "context dictionary, or INT32 keys past their "
            "ingest-time range — fabricated at run time?); the "
            "dense kernel would drop them. Register/ingest the "
            "values, or use group_by(salt=) to force the sort "
            "path."
        )

    def _check_pending_miss(self, mark: int = 0) -> None:
        """Drain deferred dictionary-miss counters added at or after
        ``mark`` (ONE batched readback for all guarded stages, after
        all dispatches).  A nonzero count means rows carried STRING
        hash words absent from the context dictionary — the dense
        kernel dropped them, so fail loudly instead of returning a
        silently wrong aggregate."""
        pending = self._pending_miss[mark:]
        del self._pending_miss[mark:]
        if not pending:
            return
        vals = jax.device_get([m for _, m in pending])
        for (name, _), m in zip(pending, vals):
            if int(m):
                self._raise_miss(name, int(m))

    def _execute_stages(self, graph, bindings, results, binding_fps, stage_fps):
        depth = max(1, self.config.overflow_sync_depth)
        # Speculative dispatch window (DrMessagePump.h:116-180 pump
        # concurrency): overflow-capable stages dispatch without their
        # per-stage host sync; flags drain in one batched readback when
        # the window fills, before any host-consuming stage, and at job
        # end.  Downstream stages consume the optimistic results — an
        # overflow (rare) re-runs the affected suffix synchronously.
        window: List[Dict] = []
        # the window list object outlives this call only in the probe
        # closure; re-registering per run keeps the sample live
        flightrec.probe("inflight_dispatches", lambda: len(window))
        for stage in graph.stages:
            if stage.ops and stage.ops[0].kind == "do_while":
                self._drain_window(window, graph, bindings, results,
                                   binding_fps or {}, stage_fps)
                stage_fps[stage.id] = None  # loop state is data-dependent
                self._run_do_while(stage, graph, bindings, results)
                continue
            if stage.ops and stage.ops[0].kind == "apply_host":
                self._drain_window(window, graph, bindings, results,
                                   binding_fps or {}, stage_fps)
                stage_fps[stage.id] = None  # host fn is opaque
                self._run_apply_host(stage, bindings, results)
                continue
            if window and self._drain_for_adapt(stage, window):
                # adaptation opportunity: an aggregation-shaped producer
                # of this stage sits undrained in the window, so its
                # observed count is one batched readback away — pay the
                # sync now to dispatch this stage at observed width
                # (DrDynamicRangeDistributor.cpp:54-110 semantics)
                self._drain_window(window, graph, bindings, results,
                                   binding_fps or {}, stage_fps)
            self._run_stage(
                stage, graph, bindings, results, binding_fps or {}, stage_fps,
                window=window if depth > 1 else None,
            )
            if len(window) >= depth:
                self._drain_window(window, graph, bindings, results,
                                   binding_fps or {}, stage_fps)
        self._drain_window(window, graph, bindings, results,
                           binding_fps or {}, stage_fps)

    def _drain_window(self, window, graph, bindings, results,
                      binding_fps, stage_fps) -> None:
        """Resolve all speculatively dispatched stages: ONE batched
        overflow readback for the all-clear case; on an overflow,
        finalize the clean prefix and re-run the overflowing stage and
        everything dispatched after it synchronously (their inputs or
        contents were garbage) at an escalated boost."""
        if not window:
            return
        import jax.numpy as jnp

        flags = [w["flag"] for w in window if w["flag"] is not None]
        self.events.emit(
            "overflow_drain", inflight=len(window),
            stages=[w["stage"].name for w in window],
        )
        combined = (
            False if not flags
            else flags[0] if len(flags) == 1
            else jnp.any(jnp.stack(flags))
        )
        # observed row counts ride the SAME batched readback
        counted = [w for w in window if w.get("counts")]
        combined_v, counts_v = jax.device_get(
            (combined, [w["counts"] for w in counted])
        )
        count_of = {id(w): cv for w, cv in zip(counted, counts_v)}
        if not bool(combined_v):
            for w in window:
                if id(w) in count_of:
                    self._record_observed(
                        w["stage"], count_of[id(w)],
                        [o.capacity for o in w["outs"]],
                    )
                self._finalize_entry(w, results)
            window.clear()
            return
        bad = next(
            i for i, w in enumerate(window)
            if w["flag"] is not None and bool(w["flag"])
        )
        # entries at/after the pivot hold garbage: record counts only
        # for the clean prefix and purge any stale count the redo's
        # overflow-free stages won't overwrite
        for w in window[:bad]:
            if id(w) in count_of:
                self._record_observed(
                    w["stage"], count_of[id(w)],
                    [o.capacity for o in w["outs"]],
                )
            self._finalize_entry(w, results)
        for w in window[bad:]:
            for i in range(len(w["stage"].out_slots)):
                self._observed_rows.pop((w["stage"].id, i), None)
        redo = window[bad:]
        window.clear()
        first = redo[0]
        self.events.emit(
            "stage_overflow", stage=first["stage"].id,
            name=first["stage"].name, version=first["version"],
            boost=first["boost"],
        )
        # Windowed dispatches always ran at boost 1 (the speculative
        # branch returns on the first attempt); the synchronous redo's
        # own retry loop handles further escalation and the boost
        # ceiling.
        for j, w in enumerate(redo):
            self._run_stage(
                w["stage"], graph, bindings, results, binding_fps, stage_fps,
                boost0=2 if j == 0 else 1, window=None,
            )

    def _finalize_entry(self, w, results) -> None:
        """A speculative dispatch whose overflow flag came back clean:
        emit its completion, queue its dict-miss counter, and save its
        checkpoint (none of which may happen before the flag clears)."""
        stage = w["stage"]
        # dispatch-to-drain wall time covers the WHOLE window's
        # dispatches + the batched readback, so it must not feed the
        # straggler duration model (sync runs still do); it is reported
        # on the event for observability only.
        dt = time.time() - w["t0"]
        self.events.emit(
            "stage_complete", stage=stage.id, name=stage.name,
            version=w["version"], seconds=dt, deferred=True,
        )
        if _stage_has_miss_guard(stage):
            self._pending_miss.append((stage.name, w["miss"]))
        if not w.get("fan"):  # adapted layouts never persist (see sync)
            self._save_checkpoint(stage, w["fp"], w["outs"])

    def _save_checkpoint(self, stage, fp, outs) -> None:
        """Shared checkpoint save (sync + deferred paths).  Stages with
        a dense-domain miss guard DEFER their save to the job-end miss
        drain: saving now could persist a dropped-rows result that a
        later identical submission would load, silently bypassing the
        loud-failure guarantee (code-review r4)."""
        if self.checkpoints is None or fp is None:
            return
        if _stage_has_miss_guard(stage):
            self._pending_ckpt.append((stage, fp, outs))
            return
        self._write_checkpoint(stage, fp, outs)

    def _write_checkpoint(self, stage, fp, outs) -> None:
        if self.config.checkpoint_retain_seconds is not None:
            n = self.checkpoints.gc(self.config.checkpoint_retain_seconds)
            if n:
                self.events.emit("checkpoint_gc", removed=n)
        try:
            path = self.checkpoints.save(
                stage, fp, tuple(outs[: len(stage.out_slots)])
            )
            self.events.emit(
                "stage_checkpoint_saved", stage=stage.id,
                name=stage.name, path=path,
            )
        except OSError as e:
            log.warning(
                "checkpoint save failed for %s: %s", stage.name, e
            )

    @staticmethod
    def _publish(stage, outs, results) -> None:
        """Publish a stage's outputs.  Fused regions also alias each
        export under its ORIGINAL (member stage id, out idx) — callers
        (context/worker/out-of-core) resolve plan outputs against the
        PRE-fusion graph they lowered, and fusion must stay invisible
        to them."""
        for i in range(len(stage.out_slots)):
            results[(stage.id, i)] = outs[i]
        if isinstance(stage, FusedStage):
            for pos, (mi, oi) in enumerate(stage.exports):
                results[(stage.members[mi].id, oi)] = outs[pos]

    def _resolve_inputs(
        self,
        stage: Stage,
        bindings: Dict[int, ColumnBatch],
        results: Dict[Tuple[int, int], ColumnBatch],
    ) -> Tuple[ColumnBatch, ...]:
        ins: List[ColumnBatch] = []
        for ref, idx in stage.input_refs:
            if ref == "plan_input":
                ins.append(bindings[idx])
            else:
                ins.append(results[(ref, idx)])
        return tuple(ins)

    def _run_stage(
        self,
        stage: Stage,
        graph: StageGraph,
        bindings: Dict[int, ColumnBatch],
        results: Dict[Tuple[int, int], ColumnBatch],
        binding_fps: Dict[int, Optional[str]] = {},
        stage_fps: Dict[int, Optional[str]] = {},
        boost0: int = 1,
        window: Optional[List[Dict]] = None,
    ) -> None:
        inputs = self._resolve_inputs(stage, bindings, results)
        shape_key = self._shape_key(inputs)
        fp = None
        if self.checkpoints is not None:
            input_fps = tuple(
                (
                    binding_fps.get(idx)
                    if ref == "plan_input"
                    else (
                        f"{stage_fps.get(ref)}:{idx}"
                        if stage_fps.get(ref) is not None
                        else None
                    )
                )
                for ref, idx in stage.input_refs
            )
            fp = stage_fingerprint(stage, shape_key, input_fps)
            stage_fps[stage.id] = fp
            if fp is not None:
                hit = self.checkpoints.load(stage, fp, self.mesh)
                if hit is not None and len(hit) == len(stage.out_slots):
                    self.events.emit(
                        "stage_checkpoint_hit", stage=stage.id, name=stage.name
                    )
                    self._publish(stage, hit, results)
                    return
        st = self.stats.setdefault(stage.name, StageStatistics(self.config.outlier_sigmas))

        fan = [
            op.params.get("nparts") for op in stage.ops
            if op.params.get("nparts")
        ]
        # kernels disable fan reduction on hybrid meshes and clamp to
        # P; the event must describe what actually runs
        if fan and len(mesh_axes(self.mesh)) == 1 and min(fan) < self.P:
            # stage-level fan-out adaptation record (the rewired-graph
            # event of DrDynamicRangeDistributor.cpp:54-110)
            self.events.emit(
                "stage_fanout", stage=stage.id, name=stage.name,
                nparts=min(fan), of=self.P,
            )
        can_overflow = any(
            op.kind not in NON_OVERFLOW_OPS for op in stage.ops
        )
        adapt_fan = self._adapt_fan_for(stage)
        if adapt_fan:
            self.events.emit(
                "stage_width_adapt", stage=stage.id, name=stage.name,
                nparts=adapt_fan, of=self.P,
                observed_rows=sum(
                    self._observed_rows.get((r, i), 0)
                    for r, i in stage.input_refs
                ),
            )
        # counts ride readbacks that happen anyway: the sync overflow
        # flag, or the window's batched drain (where even overflow-free
        # stages' counts are free); only the async non-window path
        # never pays a readback for them
        want_count = stage.id in self._count_wanted and (
            can_overflow or bool(window)
        )
        boost = boost0
        if self.rewriter is not None and can_overflow:
            # proactive palette pre-widening: an overflow_loop diagnosis
            # raises this stage-name's starting tier so the NEXT
            # dispatch skips the doomed narrow attempt entirely
            floor = self.rewriter.boost_floor(stage.name)
            if floor > boost:
                boost = floor
                if (stage.name, floor) not in self._rewrites_applied:
                    self._rewrites_applied.add((stage.name, floor))
                    self.events.emit(
                        "plan_rewrite", phase="applied",
                        action="prewiden_palette", rule="overflow_loop",
                        subject=stage.name, stage=stage.name,
                        boost=floor,
                    )
        failures = 0
        version = 0
        attempts: List[Attempt] = []  # failed-attempt history (post-mortem)
        while True:
            version += 1
            self.events.emit(
                "stage_start", stage=stage.id, name=stage.name, version=version, boost=boost
            )
            if isinstance(stage, FusedStage):
                # one dispatch covering the whole region (the
                # dispatches-per-plan signal jobview/JobMetrics fold)
                self.events.emit(
                    "fused_dispatch", stage=stage.id, name=stage.name,
                    members=len(stage.members), version=version,
                    boost=boost,
                )
            t0 = time.time()
            try:
                faults.registry.maybe_fail(stage.name)
                if faults.registry.maybe_kill(stage.name):
                    # Gang chaos (FaultPlan.worker_kill_prob, installed
                    # on workers via the set_fault mailbox command):
                    # this PROCESS dies mid-stage, leaving gang peers
                    # inside the stage's collectives — the
                    # mid-collective-death scenario the driver's
                    # auto-recovery (rebuild_gang) must absorb.
                    self.events.emit(
                        "worker_killed_injected", stage=stage.id,
                        name=stage.name,
                    )
                    # os._exit skips atexit: the blackbox must be on
                    # disk BEFORE the process vanishes mid-collective
                    flightrec.dump_now(f"worker_killed:{stage.name}")
                    os._exit(113)
                inj_delay = faults.registry.maybe_delay(stage.name)
                if inj_delay:
                    self.events.emit(
                        "stage_delay_injected", stage=stage.id,
                        name=stage.name, seconds=inj_delay,
                    )
                    self._sleep(inj_delay)
                # escalated boosts drop the reduced width first: the
                # concentration itself may be what overflowed
                fn = self._get_compiled(
                    stage, boost, shape_key,
                    fan=adapt_fan if boost < 4 else None,
                )
                # Per-stage step marker: stages show up as named steps in
                # the XLA profiler timeline (SURVEY 5.1).  The obs span
                # (cat=execute) is the jobview/Perfetto twin: dispatch +
                # any rides-along readback, attributed to this attempt.
                with jax.profiler.StepTraceAnnotation(
                    stage.name, step_num=version
                ), self.tracer.span(
                    stage.name, cat="execute", stage=stage.id,
                    version=version, boost=boost,
                ):
                    # OPERAND params ride the replicated slot: current
                    # table content from the pool (uploaded/scattered
                    # once per content, reused across dispatches)
                    outs, (overflow, dict_miss) = fn(
                        inputs, self._stage_rep(stage)
                    )
                    # Static per-round exchange accounting (filled at
                    # trace time by kernels._exchange): every dispatch
                    # re-ships these bytes, so emit per attempt.
                    for rnd in fn.xchg_rounds:
                        self.events.emit(
                            "exchange_round", stage=stage.id,
                            name=stage.name,
                            qid=tracectx.current_qid(), **rnd,
                        )
                    counts_dev = None
                    if want_count:
                        import jax.numpy as jnp

                        counts_dev = [
                            jnp.sum(outs[i].valid)
                            for i in range(len(stage.out_slots))
                        ]
                    if window is not None and (can_overflow or window):
                        # Speculative dispatch: publish the optimistic
                        # results so downstream stages can dispatch too,
                        # and defer the overflow sync to the window
                        # drain (one batched readback for the window).
                        # A non-overflow stage joins an OPEN window too:
                        # it may have consumed speculative inputs, so a
                        # redo must recompute it (flag None = never the
                        # overflow pivot).
                        self._publish(stage, outs, results)
                        window.append(dict(
                            stage=stage, version=version, boost=boost,
                            fp=fp, flag=overflow if can_overflow else None,
                            miss=dict_miss, outs=outs, t0=t0,
                            counts=counts_dev,
                            fan=adapt_fan if boost < 4 else None,
                        ))
                        self.events.emit(
                            "stage_dispatched", stage=stage.id,
                            name=stage.name, version=version, boost=boost,
                            inflight=len(window),
                        )
                        return
                    # Overflow-free stages skip the host sync: their
                    # flag is statically False, so the driver moves on
                    # and JAX async dispatch overlaps this stage's
                    # device time with independent stages (the GM
                    # message-pump concurrency, DrMessagePump.h:116).
                    if can_overflow and counts_dev is not None:
                        # ONE readback for flag + observed counts
                        overflow, host_counts = jax.device_get(
                            (overflow, counts_dev)
                        )
                        overflow = bool(overflow)
                        self._record_observed(
                            stage, host_counts,
                            [o.capacity for o in outs],
                        )
                    else:
                        overflow = bool(overflow) if can_overflow else False
            except faults.InjectedFault as e:
                failures += 1
                kind = classify(e, attempts)
                exhausted = self.retry_policy.exhausted(failures)
                # deterministic repeats fail fast: identical class +
                # message means elsewhere/later cannot help
                terminal = exhausted or kind is FailureKind.DETERMINISTIC
                backoff = (
                    0.0 if terminal
                    else self.retry_policy.backoff(stage.name, failures)
                )
                attempts.append(Attempt(
                    number=version, error_type=type(e).__name__,
                    error=str(e), kind=kind.value, backoff=backoff,
                ))
                self.events.emit(
                    "stage_failed", stage=stage.id, name=stage.name,
                    version=version, error=str(e), failures=failures,
                    failure_kind=kind.value, backoff=round(backoff, 4),
                )
                if terminal:
                    self.events.emit(
                        "job_failed", stage=stage.id, name=stage.name,
                        failure_kind=kind.value, reason=str(e),
                    )
                    why = (
                        "failed deterministically (identical error "
                        "reproduced; retrying cannot help)"
                        if kind is FailureKind.DETERMINISTIC
                        and not exhausted
                        else "exceeded failure budget "
                        f"({self.config.max_stage_failures})"
                    )
                    flightrec.dump_now(f"job_failed:{stage.name}")
                    raise JobFailedError(
                        f"stage {stage.name!r} {why}: {e}",
                        stage=stage.name, attempts=attempts,
                    ) from e
                if backoff:
                    self._sleep(backoff)
                continue  # versioned re-execution (with backoff)

            dt = time.time() - t0
            st.record(dt)
            if st.is_outlier(dt):
                self.events.emit(
                    "stage_straggler", stage=stage.id, name=stage.name,
                    version=version, seconds=dt,
                    threshold=st.outlier_threshold(),
                )
            if overflow:
                self.events.emit(
                    "stage_overflow", stage=stage.id, name=stage.name,
                    version=version, boost=boost,
                )
                if boost >= 2 ** self.config.max_shuffle_retries:
                    self.events.emit(
                        "job_failed", stage=stage.id, name=stage.name,
                        failure_kind="resource",
                        reason="shuffle overflow at max boost",
                    )
                    # An expansion join that outgrows every boost is
                    # usually a hot-key quadratic blowup — point at the
                    # knob that actually bounds it.
                    join_exp = any(
                        "expansion" in op.params for op in stage.ops
                    )
                    hint = (
                        "raise the join's expansion= argument (hot keys "
                        "multiply pair counts quadratically), "
                        "shuffle_slack, or partition count"
                        if join_exp
                        else "raise shuffle_slack or partition count"
                    )
                    flightrec.dump_now(f"overflow_exhausted:{stage.name}")
                    raise StageFailedError(
                        f"stage {stage.name!r} still overflowing at "
                        f"boost {boost}; {hint}"
                    )
                boost *= 2
                continue  # adaptive re-shape

            self.events.emit(
                "stage_complete", stage=stage.id, name=stage.name,
                version=version, seconds=dt,
                # async stages report DISPATCH time; device time overlaps
                # downstream stages (jobview surfaces the distinction)
                **({} if can_overflow else {"async": True}),
            )
            if _stage_has_miss_guard(stage):
                # Deferred readback: checked after the job drains so the
                # dense fast path keeps its async dispatch.
                self._pending_miss.append((stage.name, dict_miss))
            self._publish(stage, outs, results)
            # a fan-adapted run's outputs sit in a reduced-width layout
            # the fingerprint doesn't describe — never persist them
            # under the full-width identity
            if not (adapt_fan and boost < 4):
                self._save_checkpoint(stage, fp, outs)
            return

    def _run_do_while(
        self,
        stage: Stage,
        graph: StageGraph,
        bindings: Dict[int, ColumnBatch],
        results: Dict[Tuple[int, int], ColumnBatch],
    ) -> None:
        """Driver-loop iteration (DoWhile, ``DryadLinqQueryNode.cs:4555``).

        Each iteration re-lowers and runs the body subplan on the current
        dataset; the cond subplan yields a host boolean to continue.
        """
        if self.subquery_runner is None:
            raise RuntimeError("do_while requires a subquery_runner (use DryadContext)")
        p = stage.ops[0].params
        (current,) = self._resolve_inputs(stage, bindings, results)
        # Device-side fixed point: with do_while_device_auto (default
        # on) EVERY do_while first tries the lax.while_loop seam — the
        # driver loop below costs one dispatch round trip per
        # iteration, the device loop costs one total.  Ineligible
        # subplans (multi-stage body/cond, carry-shape changes) fall
        # back via the existing exception contract, so auto mode is
        # behavior-preserving for plans the lowerer rejects.
        device_auto = bool(
            getattr(self.config, "do_while_device_auto", False)
        )
        if (p.get("device") or device_auto) and self.loop_lowerer is not None:
            try:
                results[(stage.id, 0)] = self._run_do_while_device(
                    stage, p, current
                )
                return
            except (ValueError, TypeError) as e:
                # ValueError: the lowerer rejected the subplan (multi-stage
                # body/cond).  TypeError: the body lowers to one stage but
                # changes the carry pytree shape (e.g. capacity resize with
                # slack), which lax.while_loop rejects at trace time.
                # Either way the driver loop below handles it.
                self.events.emit(
                    "do_while_device_fallback", stage=stage.id, reason=str(e)
                )
        max_iter = p["max_iter"]
        # Compact the loop state back to a STABLE capacity after every
        # body round: body plans grow capacity by their slack factors,
        # so feeding the output straight back re-compiles every
        # iteration against monotonically growing shapes (by iteration
        # ~20 the compiles dominate by orders of magnitude).  With
        # compaction, iteration 2+ reuse iteration 1's compiled stages;
        # a state that genuinely outgrows the capacity boosts it through
        # the bounded palette, same as stage overflow retries.
        base_pp = max(8, -(-current.capacity // self.P))
        boost = 1
        it = 0
        while True:
            it += 1
            if it > max_iter:
                self.events.emit("do_while_max_iter", stage=stage.id, iters=it - 1)
                break
            self.events.emit("do_while_iter", stage=stage.id, iter=it)
            current = self.subquery_runner(p["body"], p["schema"], current)
            while True:
                compacted, ovf = self._compact_loop_state(
                    current, base_pp * boost
                )
                if not ovf:
                    current = compacted
                    break
                if boost >= 2 ** self.config.max_shuffle_retries:
                    raise RuntimeError(
                        f"do_while state exceeded compaction capacity at "
                        f"boost {boost} (base {base_pp} rows/partition)"
                    )
                boost *= 2
                self.events.emit(
                    "do_while_state_boost", stage=stage.id, boost=boost
                )
            cont = self.subquery_runner(p["cond"], p["schema"], current, scalar=True)
            if not bool(cont):
                break
        results[(stage.id, 0)] = current

    def _compact_loop_state(self, batch: ColumnBatch, target_pp: int):
        """One cached SPMD program per (columns signature, target):
        per-partition compaction of valid rows to a fixed capacity,
        returning (batch, overflowed)."""
        import jax.numpy as jnp

        from dryad_tpu.exec.kernels import _round8
        from dryad_tpu.ops import shuffle as SH

        target_pp = _round8(target_pp)
        sig = (
            tuple(
                (n, str(a.dtype), a.shape[1:])
                for n, a in sorted(batch.data.items())
            ),
            batch.capacity, target_pp,
        )
        if sig not in self._compact_cache:
            axes = mesh_axes(self.mesh)

            def fn(shard, _rep):
                out, ovf = SH.resize(shard, target_pp)
                # reduce across the mesh: a device-local flag would
                # silently drop rows when only a non-primary partition
                # overflows (same rule as build_stage_fn's psum)
                ovf = jax.lax.psum(ovf.astype(jnp.int32), axes) > 0
                return out, (ovf,)

            self._compact_cache[sig] = compile_stage(self.mesh, fn)
        out, (ovf,) = self._compact_cache[sig](batch, ())
        return out, bool(ovf)

    def _run_apply_host(self, stage, bindings, results) -> None:
        """Host-callback Apply: pull each partition to host, run the
        user fn, push back sharded (the arbitrary-user-code escape
        hatch; device->host->device round trip per job — the documented
        perf cliff, SURVEY 7.3)."""
        import math

        import numpy as np
        from dryad_tpu.parallel.mesh import partition_sharding

        p = stage.ops[0].params
        (b,) = self._resolve_inputs(stage, bindings, results)
        self.events.emit("apply_host_start", stage=stage.id)
        P = self.P
        cap = b.capacity // P
        if jax.process_count() > 1:
            # a plain host fetch of a cross-process array raises in a
            # multi-controller gang; gather the batch first (apply_host
            # is already the documented device->host perf cliff) — every
            # process then computes all partitions deterministically
            from jax.experimental import multihost_utils as _mh

            valid = np.asarray(_mh.process_allgather(b.valid, tiled=True))
            host_cols = {
                n: np.asarray(_mh.process_allgather(v, tiled=True))
                for n, v in b.data.items()
            }
        else:
            valid, host_cols, _ = b.fetch_host()  # overlapped d2h copies
        schema = p["schema"]
        phys = schema.device_names()
        expected = {n: _phys_np_dtype(n, schema) for n in phys}
        out_parts = []
        for i in range(P):
            sl = slice(i * cap, (i + 1) * cap)
            m = valid[sl]
            part = {n: v[sl][m] for n, v in host_cols.items()}
            out = p["fn"](part, i)
            if set(out.keys()) != set(phys):
                raise ValueError(
                    f"apply_host fn output columns {sorted(out)} != "
                    f"schema physical columns {phys} (partition {i})"
                )
            # Validate + cast against the declared schema up front so a
            # dtype drift fails here, not in a downstream compile.
            out = {n: np.asarray(v, expected[n]) for n, v in out.items()}
            lens = {len(v) for v in out.values()} or {0}
            if len(lens) != 1:
                raise ValueError(
                    f"apply_host fn returned ragged columns: { {n: len(v) for n, v in out.items()} }"
                )
            out_parts.append(out)
        new_cap = max(
            8,
            int(
                math.ceil(
                    max((len(next(iter(op.values()), [])) for op in out_parts),
                        default=1) / 8.0
                )
            ) * 8,
        )
        sh = partition_sharding(self.mesh)
        data = {}
        for n in phys:
            buf = np.zeros((P * new_cap,), expected[n])
            for i, op in enumerate(out_parts):
                v = op[n]
                buf[i * new_cap : i * new_cap + len(v)] = v
            data[n] = jax.device_put(buf, sh)
        vbuf = np.zeros((P * new_cap,), np.bool_)
        for i, op in enumerate(out_parts):
            nrows = len(next(iter(op.values()), []))
            vbuf[i * new_cap : i * new_cap + nrows] = True
        out_batch = ColumnBatch(data, jax.device_put(vbuf, sh))
        self.events.emit("apply_host_done", stage=stage.id)
        results[(stage.id, 0)] = out_batch

    def _run_do_while_device(self, stage, p, current: ColumnBatch) -> ColumnBatch:
        """On-device DoWhile: the WHOLE loop compiles as one
        ``lax.while_loop`` inside one shard_map program — no host
        round-trip per iteration (the TPU-first upgrade over the
        reference's GM-evaluated loop, ``DryadLinqQueryNode.cs:4555``).

        Requirements (else ValueError -> driver-loop fallback): body and
        cond each lower to one fused stage; the body preserves the batch
        pytree structure (same columns, same capacity).
        """
        import jax.numpy as jnp

        body_stage, body_schema = self.loop_lowerer(
            p["body"], p["schema"], current
        )
        cond_stage, cond_schema = self.loop_lowerer(
            p["cond"], body_schema, current
        )
        cond_col = cond_schema.device_names()[0]
        max_iter = int(p["max_iter"])
        axes = mesh_axes(self.mesh)
        axis_sizes = tuple(self.mesh.shape[a] for a in axes)

        boost = 1
        while True:
            body_fn = build_stage_fn(
                body_stage, self.P, self.config.shuffle_slack, boost,
                axes, axis_sizes,
            )
            cond_fn = build_stage_fn(
                cond_stage, self.P, self.config.shuffle_slack, boost,
                axes, axis_sizes,
            )

            def outer(sharded_inputs, _rep):
                (b0,) = sharded_inputs

                def cond(state):
                    i, b, ovf, _miss = state
                    couts, (covf, _cm) = cond_fn((b,), ())
                    go = couts[0].data[cond_col][0].astype(jnp.bool_)
                    return (i < max_iter) & go & ~(ovf | covf)

                def body(state):
                    i, b, ovf, miss = state
                    bouts, (bovf, bmiss) = body_fn((b,), ())
                    return (i + jnp.int32(1), bouts[0], ovf | bovf, miss + bmiss)

                # DoWhile runs the body BEFORE checking cond (reference
                # semantics, DryadLinqQueryNode.cs:4555; driver fallback
                # below mirrors it) — so seed the loop state with one body
                # application rather than letting lax.while_loop evaluate
                # cond on the un-iterated input.
                bouts0, (bovf0, bmiss0) = body_fn((b0,), ())
                it, bout, ovf, miss = jax.lax.while_loop(
                    cond, body, (jnp.int32(1), bouts0[0], bovf0, bmiss0)
                )
                # A cond-stage overflow terminates the loop (its `go` bit
                # is garbage) but lives only inside cond's trace; recover
                # it by re-evaluating cond on the final state so the host
                # retries with a larger boost instead of accepting a
                # result whose termination decision overflowed.
                _, (covf, _cm) = cond_fn((bout,), ())
                return (bout,), (ovf | covf, it, miss)

            # split_operands=False: these fns were built WITHOUT
            # operand plumbing (the loop body bakes table constants),
            # so the cache must key by table content, not tier.
            key = (
                "do_while_device",
                self._stage_key(body_stage, split_operands=False),
                self._stage_key(cond_stage, split_operands=False),
                self._shape_key((current,)),
                max_iter, boost,
            )
            fn = self._compiled.get(key)
            if fn is None:
                fn = compile_stage(self.mesh, outer)
                self._compiled[key] = fn
            self.events.emit(
                "do_while_device_start", stage=stage.id, boost=boost
            )
            (out,), (overflow, iters, miss) = fn((current,), ())
            if not bool(overflow):
                if any(
                    op.kind == "string_code"
                    for s in (body_stage, cond_stage)
                    for op in s.ops
                ):
                    self._pending_miss.append((stage.name, miss))
                self.events.emit(
                    "do_while_device_done", stage=stage.id, iters=int(iters)
                )
                return out
            self.events.emit(
                "stage_overflow", stage=stage.id, name=stage.name,
                version=1, boost=boost,
            )
            if boost >= 2 ** self.config.max_shuffle_retries:
                raise StageFailedError(
                    f"device do_while still overflowing at boost {boost}"
                )
            boost *= 2
