"""Device operand pool — content-addressed upload cache for runtime
table operands.

The static-vs-operand split (``stringcode_runtime_tables``) moves the
string coding tables out of the compiled program and into call-time
device inputs.  Something still has to get the table CONTENT onto the
device — and a widening vocabulary produces a new table per widen, so a
naive ``device_put`` per dispatch would trade O(chunks) recompiles for
O(chunks) full re-uploads.  The pool exploits the dictionary's
append-only growth instead: within one shape-palette tier a widened
table differs from its predecessor only at the slots/rows the new
entries filled (``ops/stringcode.py`` builds subset tables in insertion
order precisely to keep this true), so the pool **scatters just the
delta** into the resident device buffer and re-uploads in full only on
a tier change or when the delta stops being small.

One pool per :class:`~dryad_tpu.exec.executor.GraphExecutor` — the
driver's and each worker's executor cache independently (the job
package ships table objects inside the plan; every process uploads its
own copy once).

Participating objects implement the small operand protocol:
``operand_signature()`` (hashable shape-palette tier — everything the
traced program bakes in), ``operand_arrays()`` (the host numpy arrays,
leading axis = scatter axis), ``operand_sha()`` (content digest), and
``operand_arity`` (len of ``operand_arrays()``).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Tuple

import numpy as np


def is_operand_capable(v: Any) -> bool:
    """True when ``v`` implements the operand protocol."""
    return (
        hasattr(v, "operand_signature")
        and hasattr(v, "operand_arrays")
        and hasattr(v, "operand_sha")
    )


class DeviceOperandPool:
    """Per-executor cache: operand tier -> resident device buffers.

    Only the LATEST content per tier stays resident (tiers are the
    power-of-two palette, so the pool holds O(log vocab) buffer sets,
    not O(widenings)); re-requesting the resident sha is free, a new
    sha on a known tier scatters the row delta, an unknown tier
    uploads in full.
    """

    def __init__(self, mesh=None, metrics=None):
        self.mesh = mesh
        self.metrics = metrics
        # tier -> (sha, host array tuple, device array tuple)
        self._tiers: Dict[Tuple, Tuple[str, Tuple, Tuple]] = {}
        # observable behavior (tests / debugging)
        self.full_uploads = 0
        self.delta_scatters = 0
        self.hits = 0
        # The serving tier multiplexes many tenants' dispatches over
        # ONE executor, and the DispatchWindow collector may fetch
        # while the driver dispatches — get() must be safe under that
        # concurrency (tier residency + counters mutate together).
        self._lock = threading.Lock()

    # -- accounting --------------------------------------------------------
    def _account(self, nbytes: int) -> None:
        if self.metrics is not None:
            # operand traffic IS host->device traffic: fold it into the
            # job-level h2d accounting and keep a specific counter too
            self.metrics.add("h2d_bytes", int(nbytes))
            self.metrics.add("operand_h2d_bytes", int(nbytes))

    def _put(self, arr: np.ndarray):
        import jax

        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            return jax.device_put(
                np.asarray(arr), NamedSharding(self.mesh, PartitionSpec())
            )
        return jax.device_put(np.asarray(arr))

    # -- the one entry point -----------------------------------------------
    def get(self, obj) -> Tuple:
        """Device arrays for ``obj`` (uploading/scattering as needed).
        Thread-safe: concurrent sessions racing one tier serialize on
        the pool lock, so residency can never interleave into a state
        where the stored sha and device buffers disagree."""
        sha = obj.operand_sha()
        host = tuple(
            np.ascontiguousarray(a) for a in obj.operand_arrays()
        )
        # Residency keys on the BUFFER layout (type + shapes/dtypes),
        # not the full compile signature: a probe-bound tier change
        # recompiles the program but the resident buffers still match
        # row for row, so the widen delta still scatters.
        tier = (type(obj).__name__,) + tuple(
            (a.shape, str(a.dtype)) for a in host
        )
        with self._lock:
            cur = self._tiers.get(tier)
            if cur is not None and cur[0] == sha:
                self.hits += 1
                return cur[2]
            dev: Optional[Tuple] = None
            if cur is not None:
                dev = self._scatter_delta(cur[1], cur[2], host)
            if dev is None:
                dev = tuple(self._put(a) for a in host)
                self._account(sum(a.nbytes for a in host))
                self.full_uploads += 1
            else:
                self.delta_scatters += 1
            self._tiers[tier] = (sha, host, dev)
            return dev

    def _scatter_delta(self, prev_host, prev_dev, host) -> Optional[Tuple]:
        """Update resident buffers row-wise to the new content; None
        when a full upload is cheaper (delta > half the rows) or the
        shapes diverged (tier hash collision — never expected)."""
        deltas = []
        total = 0
        for old, new in zip(prev_host, host):
            if old.shape != new.shape or old.dtype != new.dtype:
                return None
            diff = old != new
            if diff.ndim > 1:
                diff = diff.reshape(diff.shape[0], -1).any(axis=1)
            idx = np.nonzero(diff)[0]
            if len(idx) > new.shape[0] // 2:
                return None
            deltas.append(idx)
            total += len(idx)
        out = []
        nbytes = 0
        for old_dev, new, idx in zip(prev_dev, host, deltas):
            if len(idx) == 0:
                out.append(old_dev)
                continue
            vals = np.ascontiguousarray(new[idx])
            nbytes += idx.nbytes + vals.nbytes
            out.append(old_dev.at[idx].set(vals))
        self._account(nbytes)
        return tuple(out)
