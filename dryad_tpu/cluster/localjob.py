"""LocalJobSubmission — an N-process local job, end to end.

The reference's minimum distributed bar (``LinqToDryad/
LocalJobSubmission.cs:97-147``): one job-manager process plus N worker
processes on one machine, composed from the same parts a real cluster
uses.  This module is that composition for the TPU framework — it turns
the cluster layer's pieces into one working subsystem:

- ``ProcessService`` (mailbox + file server + block cache) is the
  control/data plane, hosted in the driver (C15 analog);
- ``LocalScheduler`` places the per-worker command round-trips on the
  workers' computer slots with hard affinities (C14);
- N ``cluster.worker`` OS processes join one JAX multi-controller
  runtime (``init_distributed``) so their devices form a single global
  mesh and each submitted plan executes as ONE gang-scheduled SPMD
  program spanning processes (cross-process collectives over gloo/ICI);
- ``ControlPlane`` barriers gate stage boundaries (start / durable-
  output) and carry membership, heartbeats, and failure reports;
- job packages ship the plan (``exec.jobpackage``), result partitions
  come back as partition files read through the file server's HTTP
  range reads (the managed-channel path, ``HttpReader.cs:78-110``).

Usage::

    with LocalJobSubmission(num_workers=2, devices_per_worker=4) as sub:
        table = sub.submit(query)
"""

from __future__ import annotations

import json
import os
import pickle
import socket
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from dryad_tpu.cluster.interfaces import (
    Affinity,
    ClusterProcess,
    Computer,
    ProcessState,
)
from dryad_tpu.cluster.scheduler import LocalScheduler
from dryad_tpu.cluster.service import ProcessService, ServiceClient
from dryad_tpu.columnar.io import parse_partition_bytes
from dryad_tpu.columnar.schema import StringDictionary
from dryad_tpu.exec import partial as _partial
from dryad_tpu.exec.events import EventLog
from dryad_tpu.exec.failure import (
    Attempt,
    FailureKind,
    JobFailedError,
    RetryPolicy,
    classify,
)
from dryad_tpu.exec.jobpackage import pack_query
from dryad_tpu.exec.stats import StageStatistics
from dryad_tpu.obs import flightrec, tracectx
from dryad_tpu.obs.diagnose import DiagnosisEngine
from dryad_tpu.obs.span import Tracer
from dryad_tpu.utils.logging import get_logger

log = get_logger("dryad_tpu.cluster.localjob")


def _driver_key_hash(cols, keys) -> np.ndarray:
    """Row hash over the key columns for similarity HISTOGRAMS.  Now
    that gang workers ship level-(-1) pre-merge snapshots, driver- and
    worker-computed histograms must live in ONE range space, so this
    delegates to the shared deterministic hash
    (``exec.partial.key_hash64`` — engine Hash64 for strings, never
    Python's process-salted ``hash()``)."""
    return _partial.key_hash64(cols, keys)


def _merge_group_state(cols, keys, red) -> Dict[str, np.ndarray]:
    """Fold one merge group's partial STATE rows by key with the plan's
    associative reductions (``exec.partial.state_reductions``) — no
    finalize, so the result is itself a valid partial table.  The fold
    itself lives in ``exec.partial.merge_state_rows`` so the gang
    workers' level-(-1) pre-merge is the same code path byte for
    byte."""
    return _partial.merge_state_rows(cols, keys, red)


def _free_port() -> int:
    """Pick a coordinator port from a pid-derived candidate sequence so
    concurrent LocalJobSubmissions on one machine probe DIFFERENT ports
    (the bind-check-close window lasts until worker 0 rebinds it — a
    kernel-assigned port 0 can't be reserved across processes)."""
    base = 21000 + (os.getpid() * 131) % 20000
    for off in range(64):
        port = base + off
        try:
            with socket.socket() as s:
                s.bind(("127.0.0.1", port))
                return port
        except OSError:
            continue
    with socket.socket() as s:  # fall back to a kernel-assigned port
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class WorkerLauncher:
    """The worker-start seam (reference: composing Peloponnese process
    groups for LOCAL vs YARN, ``LocalJobSubmission.cs:141-147`` /
    ``YarnJobSubmission.cs:63-111``).  ``spec`` carries everything
    needed to start one worker; implementations may exec a subprocess
    (below), ssh to a host, or exec into a pod."""

    def start(self, spec: Dict):
        """Launch one worker; returns an opaque handle."""
        raise NotImplementedError

    def poll(self, handle) -> Optional[int]:
        """Exit code if the worker died, else None."""
        raise NotImplementedError

    def stop(self, handle, timeout: float = 5.0) -> None:
        raise NotImplementedError

    def wait(self, handle, timeout: float) -> None:
        raise NotImplementedError


class SubprocessLauncher(WorkerLauncher):
    """Local OS-process launcher (the reference's LOCAL platform)."""

    def start(self, spec: Dict) -> subprocess.Popen:
        lf = open(spec["log_path"], "w")
        try:
            return subprocess.Popen(
                spec["argv"], stdout=lf, stderr=subprocess.STDOUT,
                env=spec["env"],
            )
        finally:
            lf.close()

    def poll(self, handle) -> Optional[int]:
        return handle.poll()

    def wait(self, handle, timeout: float) -> None:
        handle.wait(timeout=timeout)

    def stop(self, handle, timeout: float = 5.0) -> None:
        handle.terminate()
        try:
            handle.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            handle.kill()


class CommandLauncher(SubprocessLauncher):
    """Launcher that wraps the worker argv in a host command template —
    the remote-cluster seam (``YarnJobSubmission.cs:63-111`` composes
    worker process groups the same way).  ``template`` is a list of
    prefix tokens; ``{host}`` substitutes a per-worker host from
    ``hosts`` (round-robin).

    What this seam does and does NOT solve: the template only controls
    HOW the worker command starts.  True off-machine launch (ssh /
    kubectl exec) additionally needs (a) the wrapper to forward the
    worker environment (spec["env"] applies to the local wrapper
    process, so e.g. ssh needs ``env K=V ...`` tokens or a remote
    profile), (b) an interpreter + checkout reachable at the same
    paths on the remote host (shared filesystem or baked image), and
    (c) the driver's ProcessService and coordinator bound on a
    routable address — pass ``bind_host``/``advertise_host`` to
    :class:`LocalJobSubmission` for that.  The template alone is
    exercised in-tree with local prefixes (``env``, ``nice`` …).
    """

    def __init__(self, template: Optional[List[str]] = None,
                 hosts: Optional[List[str]] = None):
        self.template = list(template or [])
        self.hosts = list(hosts or [])
        self.forward_env = False

    def start(self, spec: Dict):
        host = (
            self.hosts[spec["index"] % len(self.hosts)]
            if self.hosts else "localhost"
        )
        prefix = [t.replace("{host}", host) for t in self.template]
        tail = list(spec["argv"])
        if self.forward_env:
            # materialize the worker env as `env K=V ...` argv tokens so
            # a remote shell (ssh) starts the worker with the same
            # environment the local launcher would have injected; every
            # token is shell-quoted because ssh joins argv with spaces
            # and the REMOTE shell re-parses the line — unquoted values
            # like XLA_FLAGS='--a --b' would split, and metacharacters
            # (PS1 with $(...), LESSOPEN with |) would execute remotely
            import shlex

            tail = ["env"] + [
                f"{k}={v}" for k, v in sorted(spec.get("env", {}).items())
            ] + tail
            tail = [shlex.quote(t) for t in tail]
        spec = dict(spec, argv=prefix + tail)
        return super().start(spec)

    @classmethod
    def ssh(cls, hosts: List[str], ssh_args: Optional[List[str]] = None):
        """Preset for ssh-launched workers — the YARN/Peloponnese
        remote process-group shape (``YarnJobSubmission.cs:63-111``):
        ``ssh -tt <args> {host} env K=V ... python -m dryad_tpu.cluster.worker ...``.
        ``-tt`` forces a remote tty so that killing the local ssh
        client (the launcher's stop/kill escalation for a wedged
        worker) hangs up the remote side and the worker dies with it —
        without it sshd leaves the remote process running.
        Requirements (interpreter + checkout on the remote path, driver
        services bound on a routable address) are in the class
        docstring.  The env-forwarding argv form is what the in-tree
        template test exercises with a local stand-in."""
        out = cls(["ssh", "-tt", *(ssh_args or []), "{host}"], hosts)
        out.forward_env = True
        return out


class LocalJobSubmission:
    """Driver for N worker processes jointly executing submitted queries.

    ``defer_workers``: leave that many workers unstarted; they may join
    LATE via :meth:`start_worker` — submissions block in
    ``wait_for_members`` until the full gang announced (elastic
    membership, ``LocalScheduler.cs:88`` WaitForReasonableNumberOf
    Computers / ``PeloponneseInterface.cs:370``).
    """

    def __init__(
        self,
        num_workers: int = 2,
        devices_per_worker: int = 2,
        root: Optional[str] = None,
        worker_timeout: float = 300.0,
        launcher: Optional[WorkerLauncher] = None,
        defer_workers: int = 0,
        bind_host: str = "127.0.0.1",
        advertise_host: Optional[str] = None,
    ):
        """``bind_host``/``advertise_host``: where the driver's service
        and coordinator listen / how workers address them — loopback
        for local gangs; bind "0.0.0.0" and advertise a routable name
        when a :class:`CommandLauncher` starts workers off-machine."""
        from dryad_tpu.parallel.multihost import ControlPlane

        self.n = num_workers
        self.k = devices_per_worker
        self.timeout = worker_timeout
        self.root = root or tempfile.mkdtemp(prefix="dryad-localjob-")
        self.job_id = f"job-{os.getpid()}-{int(time.time() * 1000)}"
        self.advertise = advertise_host or "127.0.0.1"
        self.service = ProcessService(self.root, host=bind_host)
        self.launcher = launcher or SubprocessLauncher()
        self.events = EventLog(os.path.join(self.root, "events.jsonl"))
        # Flight recorder: the gang driver's ring dumps next to the
        # workers' (every process writes blackbox-<pid>.json under
        # <root>/blackbox), and this dump is the one carrying the
        # per-worker clock offsets tools/blackbox.py corrects with.
        flightrec.install_recorder(
            capacity=2048,
            snapshot_s=1.0,
            dump_dir=os.path.join(self.root, "blackbox"),
            role="driver",
            events=self.events,
        )
        # Online diagnosis over the driver-side stream.  The engine's
        # per-family duration models persist ACROSS submissions, which
        # is what lets a later coded job pre-launch parity from prior
        # jobs' completion times instead of waiting for its own first
        # failure (see _submit_coded).
        self.diagnosis = DiagnosisEngine(events=self.events)
        self.events.add_tap(self.diagnosis.observe)
        # Computers register on ANNOUNCE (elastic membership), not at
        # construction — a late worker's slot must not accept tasks
        # that would stall until it exists.  The scheduler shares the
        # submission's event log so quarantine transitions land in the
        # same stream jobview folds.
        self.scheduler = LocalScheduler([], events=self.events)
        self.tracer = Tracer(self.events)
        self._client = ServiceClient("127.0.0.1", self.service.port)
        self._cp = ControlPlane(self.job_id, -1, mailbox=self.service.mailbox)
        self._status_ver: Dict[int, int] = {}
        # per-worker telemetry read cursors + clock offsets (obs.gang)
        self._telemetry_state: Dict[int, Dict] = {}
        # per-plan-signature duration models: the outlier fit assumes
        # repeated attempts of the SAME work (DrStageStatistics), so
        # heterogeneous queries must not share one model
        self._gang_stats: Dict[Tuple, StageStatistics] = {}
        self._seq = 0
        self._cseq = 0  # unique per driver command; echoed in statuses
        # mailbox round trips actually paid (one per command posted);
        # the asyncpipe bench reads this to show command batching
        # collapsing K round trips per worker into one
        self.round_trips = 0
        self._handles: Dict[int, object] = {}
        self._logs: Dict[int, str] = {}
        self._registered: set = set()
        self._dead: set = set()
        self._coord = f"{self.advertise}:{_free_port()}"
        self._base_job_id = self.job_id
        self._gen = 0  # gang generation (bumped by rebuild_gang)
        for i in range(self.n - max(defer_workers, 0)):
            self.start_worker(i)

    # -- worker process group (the Peloponnese "Worker" group) ---------------
    def _worker_spec(self, i: int) -> Dict:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("XLA_FLAGS", None)  # workers set their own device count
        # Workers must resolve the same modules as the driver: packed
        # plans pickle user fns BY REFERENCE to their defining module
        # (the local-mode analog of the reference staging the generated
        # vertex DLL to every worker, LocalJobSubmission.cs:141-147).
        repo = os.path.dirname(os.path.dirname(os.path.dirname(__file__)))
        paths = [repo] + [p for p in sys.path if p] + [
            p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p
        ]
        env["PYTHONPATH"] = os.pathsep.join(dict.fromkeys(paths))
        return {
            "argv": [
                sys.executable, "-m", "dryad_tpu.cluster.worker",
                "--service-host", self.advertise,
                "--service-port", str(self.service.port),
                "--job", self.job_id,
                "--pid", str(i),
                "--nproc", str(self.n),
                "--devices-per-proc", str(self.k),
                "--coordinator", self._coord,
                "--root", self.root,
            ],
            "env": env,
            "log_path": os.path.join(self.root, f"worker{i}.log"),
            "index": i,
        }

    def start_worker(self, i: int) -> None:
        """Start (possibly late) worker ``i`` through the launcher."""
        if i in self._handles:
            raise ValueError(f"worker {i} already started")
        spec = self._worker_spec(i)
        self._logs[i] = spec["log_path"]
        self._handles[i] = self.launcher.start(spec)
        self.events.emit("worker_started", worker=i)
        log.info(
            "started worker %d/%d x %d devices (job %s, psvc :%d)",
            i, self.n, self.k, self.job_id, self.service.port,
        )

    def _sync_membership(self, timeout: float = 120.0, gang: bool = True) -> None:
        """Block until the gang announced; register each announced
        worker's computer with the scheduler exactly once.

        ``gang=True`` (SPMD jobs) needs EVERY worker: a started worker
        dying before it announces fails fast with its log tail instead
        of burning the membership timeout.  ``gang=False`` (independent
        vertex tasks) tolerates dead workers — survivors carry the job
        (DrVertex re-execution semantics)."""
        deadline = time.monotonic() + timeout
        while True:
            if gang:
                self._check_workers_alive()
            else:
                self._reap_dead_workers()
            for i in self._cp.announced(self.n):
                if i not in self._registered:
                    self._registered.add(i)
                    self.scheduler.add_computer(
                        Computer(f"worker{i}", slots=1)
                    )
                    self.events.emit("worker_joined", worker=i)
            live = len(self._registered - self._dead)
            need = self.n if gang else max(1, self.n - len(self._dead))
            if live >= need:
                return
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"only {live}/{need} workers announced after {timeout}s"
                )
            time.sleep(0.1)

    def _worker_log_tail(self, i: int, nbytes: int = 2000) -> str:
        try:
            with open(self._logs[i], "rb") as fh:
                fh.seek(0, os.SEEK_END)
                size = fh.tell()
                fh.seek(max(0, size - nbytes))
                return fh.read().decode("utf-8", "replace")
        except OSError:
            return "<no log>"

    def _check_workers_alive(self) -> None:
        for i, h in self._handles.items():
            rc = self.launcher.poll(h)
            if rc is not None:
                raise RuntimeError(
                    f"worker {i} exited rc={rc}; log tail:\n"
                    + self._worker_log_tail(i)
                )

    def _reap_dead_workers(self) -> None:
        """Deregister dead workers' computers so vertex-task retries and
        duplicates place on survivors only."""
        for i, h in self._handles.items():
            if i in self._dead:
                continue
            if self.launcher.poll(h) is not None:
                self._dead.add(i)
                self.scheduler.remove_computer(f"worker{i}")
                self.events.emit("worker_dead", worker=i)
                log.warning("worker %d died; removed from scheduling", i)

    # -- submission ----------------------------------------------------------
    def _next_cseq(self) -> int:
        self._cseq += 1
        return self._cseq

    def _check_worker_alive(self, i: int) -> None:
        h = self._handles.get(i)
        if h is not None:
            rc = self.launcher.poll(h)
            if rc is not None:
                raise RuntimeError(
                    f"worker {i} exited rc={rc}; log tail:\n"
                    + self._worker_log_tail(i)
                )

    def _round_trip_body(
        self, i: int, cmd: Dict, proc: ClusterProcess, gang: bool = True
    ) -> Dict:
        """The GM->worker command protocol: set ``cmd/<i>``, long-poll
        ``status/<i>`` (DVertexCommand / DVertexStatus,
        ``dvertexcommand.cpp:29-30``).  ``cmd`` must carry a unique
        ``cseq``; statuses echoing an older cseq (a run the driver
        already timed out on or canceled) are consumed and discarded so
        they can't be misattributed to this command.

        ``gang`` commands fail fast when ANY worker dies (a gang SPMD
        program cannot finish without every member); vertex-task round
        trips watch only their OWN worker, so an unrelated death leaves
        independent work running (re-execution handles the victim)."""
        mb = self.service.mailbox
        self.round_trips += 1
        mb.set_prop(self.job_id, f"cmd/{i}", json.dumps(cmd).encode())
        deadline = time.monotonic() + self.timeout
        while not proc.cancelled:
            after = self._status_ver.get(i, 0)
            got = mb.get_prop(self.job_id, f"status/{i}", after, timeout=1.0)
            if got is not None:
                self._status_ver[i] = got[0]
                st = json.loads(got[1])
                if st.get("cseq") != cmd["cseq"]:
                    continue  # stale status from an abandoned command
                if st.get("state") == "failed":
                    raise RuntimeError(
                        f"worker {i} failed: {st.get('error')}"
                    )
                return st
            if gang:
                self._check_workers_alive()
            else:
                self._check_worker_alive(i)
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"worker {i}: no status after {self.timeout}s; "
                    f"log tail:\n" + self._worker_log_tail(i)
                )
        return {"state": "canceled"}

    @staticmethod
    def _stamp_trace(cmd: Dict) -> Dict:
        """Attach the active query's trace context to a mailbox
        envelope (driver thread — the context is live HERE, not on the
        round-trip process that later posts the command)."""
        ctx = tracectx.current()
        if ctx is not None and "trace" not in cmd:
            cmd["trace"] = ctx.to_wire()
        return cmd

    def _command_round_trip(self, i: int, cmd: Dict):
        """Round trip pinned to worker ``i`` (gang commands)."""
        self._stamp_trace(cmd)

        def fn(proc: ClusterProcess) -> Dict:
            return self._round_trip_body(i, cmd, proc)

        return fn

    def _placed_round_trip(self, cmd: Dict):
        """Round trip to whichever worker the scheduler placed the
        process on (vertex tasks: any computer may serve any task)."""
        self._stamp_trace(cmd)

        def fn(proc: ClusterProcess) -> Dict:
            i = int(proc.computer.removeprefix("worker"))
            return self._round_trip_body(i, cmd, proc, gang=False)

        return fn

    def rebuild_gang(self, num_workers: Optional[int] = None) -> int:
        """Mid-job gang elasticity (the reference's mutable computer
        set, ``ClusterInterface/Interfaces.cs:336-343``,
        ``LocalScheduler.cs:88``): reshape the gang to ``num_workers``
        (default: the current survivors) and restart it under a fresh
        coordinator + announce namespace.  The multi-controller JAX
        runtime pins its membership at init, so a gang that lost a
        member RESTARTS rather than limping — survivors (possibly
        wedged in collectives with the dead peer) are stopped, every
        slot respawns, and the caller re-runs its submission."""
        dead = set(self._dead) | {
            i for i, h in self._handles.items()
            if self.launcher.poll(h) is not None
        }
        target = num_workers if num_workers is not None else max(
            1, self.n - len(dead)
        )
        self.events.emit(
            "gang_rebuild", dead=sorted(dead), workers=target,
            generation=self._gen + 1,
        )
        for h in self._handles.values():
            try:
                if self.launcher.poll(h) is None:
                    self.launcher.stop(h)
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
        for i in list(self._registered):
            self.scheduler.remove_computer(f"worker{i}")
        self._handles.clear()
        self._logs.clear()
        self._registered.clear()
        self._dead.clear()
        self._status_ver.clear()
        self.n = target
        self._gen += 1
        # Fresh namespace: stale announce/status props from the old
        # generation must not satisfy the new gang's membership wait.
        from dryad_tpu.parallel.multihost import ControlPlane

        self.job_id = f"{self._base_job_id}-g{self._gen}"
        self._cp = ControlPlane(self.job_id, -1, mailbox=self.service.mailbox)
        self._telemetry_state = {}  # fresh namespace, fresh cursors
        self._coord = f"{self.advertise}:{_free_port()}"
        for i in range(self.n):
            self.start_worker(i)
        return target

    def submit(
        self, query, auto_recover: bool = True
    ) -> Dict[str, np.ndarray]:
        """Pack the query, run it across the worker gang, assemble the
        result table (reference SubmitAndWait).

        ``auto_recover``: a gang member dying MID-JOB no longer fails
        the submission — the gang auto-shrinks to the survivors
        (:meth:`rebuild_gang`) and the job re-runs, up to two
        reshapes (the elastic computer-set semantics of the
        reference's scheduler)."""
        attempts = 0
        while True:
            try:
                return self._submit_gang(query)
            except (RuntimeError, TimeoutError):
                dead = {
                    i for i, h in self._handles.items()
                    if self.launcher.poll(h) is not None
                }
                if (
                    not auto_recover
                    or not dead
                    or attempts >= 2
                    or self.n - len(dead) < 1
                ):
                    raise
                attempts += 1
                self.events.emit(
                    "gang_member_lost_mid_job", dead=sorted(dead),
                    attempt=attempts,
                )
                # Forensics checkpoint: the dead worker already left
                # its own dump (or not, if it was SIGKILLed); the
                # driver's view of the fatal window must survive the
                # recovery that is about to rewrite gang state.
                flightrec.dump_now(
                    f"gang_member_lost:{','.join(map(str, sorted(dead)))}"
                )
                log.warning(
                    "gang member(s) %s died mid-job; shrinking to %d "
                    "workers and re-running", sorted(dead),
                    self.n - len(dead),
                )
                self.rebuild_gang()

    def _submit_gang(self, query) -> Dict[str, np.ndarray]:
        self._check_workers_alive()
        self._sync_membership()
        self._seq += 1
        seq = self._seq
        job_dir = os.path.join(self.root, self.job_id, f"r{seq}")
        os.makedirs(job_dir, exist_ok=True)
        pkg_rel = f"{self.job_id}/r{seq}/job.pkg"
        with self.tracer.span("pack", cat="driver", seq=seq):
            pack_query(query, os.path.join(self.root, pkg_rel))
        result_rel = f"{self.job_id}/r{seq}/result"

        cmd = {
            "kind": "run", "package": pkg_rel,
            "result_dir": result_rel, "seq": seq, "cseq": self._next_cseq(),
        }
        t_run0 = time.monotonic()
        self.events.emit("gang_run_start", seq=seq, workers=self.n)
        procs = []
        terminal = (
            ProcessState.COMPLETED, ProcessState.FAILED,
            ProcessState.CANCELED,
        )
        try:
            for i in range(self.n):
                p = ClusterProcess(
                    self._command_round_trip(i, cmd),
                    name=f"run{seq}-w{i}",
                    affinities=[Affinity(f"worker{i}", hard=True)],
                )
                self.scheduler.schedule(p)
                procs.append(p)
            for i, p in enumerate(procs):
                if not p.wait(self.timeout + 30.0):
                    raise TimeoutError(
                        f"worker {i} command round-trip hung"
                    )
            failed = [
                p for p in procs if p.state is not ProcessState.COMPLETED
            ]
            if failed:
                errs = "; ".join(f"{p.name}: {p.error}" for p in failed)
                raise RuntimeError(f"local job failed: {errs}")
        except BaseException:
            # a failed/auto-recovering gang run must not leak queued
            # commands into the (possibly rebuilt) gang's mailboxes
            for p in procs:
                if p.state not in terminal:
                    self.scheduler.cancel(p)
            raise
        # Gang runs are lockstep (a mid-program straggler cannot be
        # duplicated), so the duration model here SURFACES outliers for
        # the jobview diagnosis rather than acting (the stage-level half
        # of DrStageStatistics; the acting half lives in
        # submit_partitioned).  Keyed by plan structure: only repeats
        # of the same pipeline feed one model.
        from dryad_tpu.plan.nodes import walk

        sig = tuple(nd.kind for nd in walk([query.node]))
        st = self._gang_stats.setdefault(sig, StageStatistics())
        dt = time.monotonic() - t_run0
        if st.is_outlier(dt):
            self.events.emit(
                "gang_straggler", seq=seq, seconds=round(dt, 3),
                threshold=round(st.outlier_threshold(), 3),
            )
        st.record(dt)
        self.events.emit(
            "gang_run_complete", seq=seq, seconds=round(dt, 3)
        )
        self._collect_telemetry()

        part_ids = sorted(
            {g for p in procs for g in p.result.get("parts", [])}
        )
        return self._assemble(query, result_rel, part_ids)

    def submit_many(self, queries, batch: Optional[int] = None) -> List[
        Dict[str, np.ndarray]
    ]:
        """Run several gang SPMD queries with BATCHED worker command
        streams: one ``runbatch`` mailbox round trip per worker
        carries up to ``batch`` run sub-commands (default: the first
        query's ``config.command_batch``; <= 1 falls back to per-query
        :meth:`submit`).  Workers execute the sub-commands
        back-to-back — the per-command start/done barriers stay
        aligned because every gang member runs the same list in the
        same order — and ship ONE aggregated status, so mailbox round
        trips per gang job drop from ``n`` to ``n / K``.  Results
        return in query order; any sub-command failure fails the batch
        with the first error (per-command classification preserved in
        the aggregated status)."""
        queries = list(queries)
        cfgs = [getattr(q.ctx, "config", None) for q in queries]
        if batch is None:
            # the gang executes ONE envelope per worker, so the most
            # conservative query governs the whole batch — reading only
            # queries[0] would silently over-batch a stricter peer
            sizes = [int(getattr(c, "command_batch", 0) or 0) for c in cfgs]
            batch = min(sizes) if sizes else 0
            if sizes and batch != max(sizes):
                self.events.emit(
                    "command_batch", worker=-1, commands=batch,
                    round_trips_saved=0, clamped_from=max(sizes),
                )
        depths = [int(getattr(c, "gang_batch_depth", 1) or 1) for c in cfgs]
        depth = min(depths) if depths else 1
        if batch <= 1 or len(queries) <= 1:
            return [self.submit(q) for q in queries]
        if depth > 1:
            return self._submit_gang_windowed(queries, batch, depth)
        out: List[Dict[str, np.ndarray]] = []
        for at in range(0, len(queries), batch):
            out.extend(self._submit_gang_batch(queries[at:at + batch]))
        return out

    def _pack_batch(self, queries) -> Tuple[List[Dict], List[str]]:
        """Pack each query of one batch; returns the run sub-commands
        (each with its own seq — the start/done barrier keys; the batch
        envelope owns the cseq echo) and the per-query result dirs."""
        subs: List[Dict] = []
        result_rels: List[str] = []
        for query in queries:
            self._seq += 1
            seq = self._seq
            os.makedirs(
                os.path.join(self.root, self.job_id, f"r{seq}"),
                exist_ok=True,
            )
            pkg_rel = f"{self.job_id}/r{seq}/job.pkg"
            with self.tracer.span("pack", cat="driver", seq=seq):
                pack_query(query, os.path.join(self.root, pkg_rel))
            result_rel = f"{self.job_id}/r{seq}/result"
            result_rels.append(result_rel)
            subs.append({
                "kind": "run", "package": pkg_rel,
                "result_dir": result_rel, "seq": seq,
            })
        return subs, result_rels

    def _record_sub_durations(self, queries, per_worker_results) -> None:
        """Fold the workers' per-sub-command wall clocks into the
        per-plan duration models.  The batch path used to smear ONE
        batch-wide dt over K plans, poisoning every model with K-1
        foreign commands' time; workers now ship each sub-command's own
        duration, and the gang sample is the max across members (a gang
        command is as slow as its slowest member)."""
        from dryad_tpu.plan.nodes import walk

        for j, query in enumerate(queries):
            secs = [
                r[j].get("seconds")
                for r in per_worker_results
                if j < len(r) and r[j].get("seconds") is not None
            ]
            if not secs:
                continue
            sig = tuple(nd.kind for nd in walk([query.node]))
            st = self._gang_stats.setdefault(sig, StageStatistics())
            st.record(max(secs))

    def _submit_gang_batch(self, queries) -> List[Dict[str, np.ndarray]]:
        self._check_workers_alive()
        self._sync_membership()
        subs, result_rels = self._pack_batch(queries)
        seqs = [s["seq"] for s in subs]
        cmd = {"kind": "runbatch", "cmds": subs, "cseq": self._next_cseq()}
        t_run0 = time.monotonic()
        self.events.emit("gang_run_start", seq=seqs[0], workers=self.n)
        for i in range(self.n):
            self.events.emit(
                "command_batch", worker=i, commands=len(subs),
                round_trips_saved=len(subs) - 1, seqs=seqs,
            )
        procs = []
        terminal = (
            ProcessState.COMPLETED, ProcessState.FAILED,
            ProcessState.CANCELED,
        )
        try:
            for i in range(self.n):
                p = ClusterProcess(
                    self._command_round_trip(i, cmd),
                    name=f"runbatch{seqs[0]}-w{i}",
                    affinities=[Affinity(f"worker{i}", hard=True)],
                )
                self.scheduler.schedule(p)
                procs.append(p)
            for i, p in enumerate(procs):
                if not p.wait(self.timeout + 30.0):
                    raise TimeoutError(
                        f"worker {i} batch command round-trip hung"
                    )
            failed = [
                p for p in procs if p.state is not ProcessState.COMPLETED
            ]
            if failed:
                errs = "; ".join(f"{p.name}: {p.error}" for p in failed)
                raise RuntimeError(f"local job failed: {errs}")
        except BaseException:
            for p in procs:
                if p.state not in terminal:
                    self.scheduler.cancel(p)
            raise
        dt = time.monotonic() - t_run0
        self.events.emit(
            "gang_run_complete", seq=seqs[0], seconds=round(dt, 3)
        )
        self._collect_telemetry()
        self._record_sub_durations(
            queries, [p.result.get("results") or [] for p in procs]
        )
        out: List[Dict[str, np.ndarray]] = []
        for j, (query, result_rel) in enumerate(zip(queries, result_rels)):
            part_ids: set = set()
            for p in procs:
                sub_sts = p.result.get("results") or []
                if j < len(sub_sts):
                    part_ids.update(sub_sts[j].get("parts") or [])
            out.append(self._assemble(query, result_rel, sorted(part_ids)))
        return out

    def _submit_gang_windowed(
        self, queries, batch: int, depth: int
    ) -> List[Dict[str, np.ndarray]]:
        """Overlapped command streams: keep up to ``depth`` runbatch
        envelopes in flight per worker (``config.gang_batch_depth``).
        The driver thread only FEEDS — it packs each batch, posts its
        envelope to every worker's command mailbox, and hands the
        blocking status drain to the :class:`GangDispatchWindow`
        collector — so the gang starts batch k+1 the moment it finishes
        batch k instead of idling through a driver round trip.

        Two distinct keys make the overlap safe on a latest-value
        mailbox: each envelope posts its status to its OWN per-envelope
        key (``wstatus/<i>/c<cseq>``), and the worker ACKS the dequeue
        itself (``ack/<i>/c<cseq>``) so the feed never overwrites the
        shared ``cmd/<i>`` slot while an unread envelope sits in it.
        Results commit strictly in submit order; a batch with failed
        sub-commands re-runs those queries SERIALLY at its commit
        position (fresh seqs — consumed barrier keys are never reused),
        so the output is byte-identical to the depth-1 serial loop."""
        from dryad_tpu.cluster.gangwindow import GangDispatchWindow

        mb = self.service.mailbox
        self._check_workers_alive()
        self._sync_membership()
        chunks = [
            queries[at:at + batch] for at in range(0, len(queries), batch)
        ]
        results: List[Optional[List[Dict[str, np.ndarray]]]] = (
            [None] * len(chunks)
        )
        posted = [0] * self.n
        statused = [0] * self.n
        last_ack: List[Optional[str]] = [None] * self.n

        def await_ack(i: int, key: str) -> None:
            deadline = time.monotonic() + self.timeout
            while True:
                if mb.get_prop(self.job_id, key, 0, timeout=0.5) is not None:
                    return
                self._check_workers_alive()
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"worker {i}: envelope never dequeued "
                        f"(no ack on {key}); log tail:\n"
                        + self._worker_log_tail(i)
                    )

        def await_status(i: int, skey: str, cseq: int, deadline) -> Dict:
            while True:
                got = mb.get_prop(self.job_id, skey, 0, timeout=1.0)
                if got is not None:
                    st = json.loads(got[1])
                    if st.get("cseq") == cseq:
                        statused[i] += 1
                        return st
                self._check_workers_alive()
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"worker {i}: no windowed status after "
                        f"{self.timeout}s; log tail:\n"
                        + self._worker_log_tail(i)
                    )

        def commit(tag, value, error, win) -> None:
            """Consume one drained batch at its commit position (submit
            order): surface drain-site errors, re-run failed queries
            serially, record durations, assemble."""
            if error is not None:
                raise error
            chunk, sts = value["chunk"], value["statuses"]
            per_worker = [st.get("results") or [] for st in sts]
            self.events.emit(
                "gang_run_complete", seq=value["seqs"][0],
                seconds=round(time.monotonic() - value["t_post"], 3),
            )
            self._collect_telemetry()
            self._record_sub_durations(chunk, per_worker)
            out: List[Dict[str, np.ndarray]] = []
            for j, query in enumerate(chunk):
                failed = any(
                    j < len(r) and r[j].get("state") != "completed"
                    for r in per_worker
                ) or any(j >= len(r) for r in per_worker)
                if failed:
                    # the shared cmd slot may still hold a later unread
                    # envelope — wait for its dequeue ack before the
                    # serial re-run posts into the same slot
                    win.note_retry()
                    for i in range(self.n):
                        if last_ack[i] is not None:
                            await_ack(i, last_ack[i])
                    out.append(self.submit(query, auto_recover=False))
                    continue
                part_ids: set = set()
                for r in per_worker:
                    part_ids.update(r[j].get("parts") or [])
                out.append(
                    self._assemble(
                        query, value["result_rels"][j], sorted(part_ids)
                    )
                )
            results[tag] = out

        win = GangDispatchWindow(
            depth, events=self.events, name="submit_many"
        )
        try:
            for k, chunk in enumerate(chunks):
                subs, result_rels = self._pack_batch(chunk)
                seqs = [s["seq"] for s in subs]
                cseq = self._next_cseq()
                self.events.emit(
                    "gang_run_start", seq=seqs[0], workers=self.n
                )
                t_post = time.monotonic()
                skeys: List[str] = []
                for i in range(self.n):
                    self.events.emit(
                        "command_batch", worker=i, commands=len(subs),
                        round_trips_saved=len(subs) - 1, seqs=seqs,
                    )
                    if last_ack[i] is not None:
                        await_ack(i, last_ack[i])
                    ack = f"ack/{i}/c{cseq}"
                    skey = f"wstatus/{i}/c{cseq}"
                    env = self._stamp_trace({
                        "kind": "runbatch", "cmds": subs, "cseq": cseq,
                        "ack": ack, "skey": skey,
                    })
                    self.round_trips += 1
                    mb.set_prop(
                        self.job_id, f"cmd/{i}", json.dumps(env).encode()
                    )
                    last_ack[i] = ack
                    posted[i] += 1
                    win.note_in_flight(posted[i] - statused[i])
                    skeys.append(skey)

                def drain(cseq=cseq, skeys=skeys, chunk=chunk,
                          result_rels=result_rels, seqs=seqs,
                          t_post=t_post) -> Dict:
                    deadline = time.monotonic() + self.timeout
                    sts = [
                        await_status(i, skey, cseq, deadline)
                        for i, skey in enumerate(skeys)
                    ]
                    return {
                        "statuses": sts, "chunk": chunk,
                        "result_rels": result_rels, "seqs": seqs,
                        "t_post": t_post,
                    }

                win.submit(k, drain)
                for tag, value, error in win.ready():
                    commit(tag, value, error, win)
            for tag, value, error in win.drain():
                commit(tag, value, error, win)
        finally:
            win.close(workers=self.n)
        out: List[Dict[str, np.ndarray]] = []
        for res in results:
            out.extend(res or [])
        return out

    def _collect_telemetry(self) -> int:
        """Absorb worker span/counter batches into the driver's event
        log (clock-offset corrected) — the cluster-wide trace merge.
        Best-effort: a telemetry hiccup must never fail a job that
        already completed.  Also the shared-quarantine exchange point:
        the driver ships its scheduler's local failure deltas through
        the same channel and folds any peer driver's deltas into its
        own blacklist (multihost quarantine, ``obs.gang``)."""
        try:
            from dryad_tpu.obs.gang import ship_failure_deltas

            ship_failure_deltas(self._cp, self.scheduler, self.events)
            n = self._cp.drain_telemetry(
                self.n, self._telemetry_state, self.events,
                scheduler=self.scheduler,
            )
            # Stash the drain's min-RTT clock offsets in the flight
            # recorder so a post-mortem blackbox merge can apply the
            # same correction live telemetry got (tools.blackbox).
            rec = flightrec.get_recorder()
            if rec is not None:
                rec.set_info(worker_offsets={
                    i: st.get("off")
                    for i, st in self._telemetry_state.items()
                    if st.get("off") is not None
                })
            return n
        except Exception as e:  # noqa: BLE001 — observability only
            log.warning("worker telemetry drain failed: %s", e)
            return 0

    # -- independent vertex tasks with speculative duplication ---------------
    _PARTITIONED_OPS = frozenset(
        {"select", "where", "project", "select_many", "resize"}
    )

    def submit_partitioned(
        self,
        query,
        nparts: Optional[int] = None,
        speculation: bool = True,
        coded: Optional[bool] = None,
    ) -> Dict[str, np.ndarray]:
        """Run a partition-local plan as ``nparts`` INDEPENDENT vertex
        tasks — the reference's execution model (one re-executable
        vertex per partition, ``DrVertex.h:49``), with **speculative
        duplication**: completed-task durations feed the robust stage
        model (``exec.stats``, ``DrStageStatistics.cpp:93``), and a
        task running past the outlier threshold is duplicated onto the
        least-loaded idle worker, first completion wins, the loser is
        canceled (``DrVertex.cpp:444`` RequestDuplicate,
        ``DrStageManager.h:156`` CheckForDuplicates).

        Exchange-free plans qualify directly (each vertex sees one
        input partition; the union of outputs is the job output).  A
        plan whose TERMINAL node is a builtin-agg ``group_by`` or a
        scalar aggregate also qualifies: it is split into per-vertex
        partial reduction plus a driver-side final merge — the
        reference's machine-level partial-aggregation vertices
        (``DrDynamicAggregateManager.h:35-168``), so speculation and
        re-execution cover real aggregation work.  Other shuffling
        plans run as one gang-scheduled SPMD program via
        :meth:`submit`, where lockstep collectives make mid-program
        speculation meaningless.

        **Coded redundancy** (``dryad_tpu.redundancy``): when the
        terminal partial's combiner is LINEAR (sum/count/mean, or a
        ``Decomposable(linear=True)``), the job runs as k systematic +
        r parity CODED vertices instead — any k of the k+r completions
        reconstruct the stage output (exactly for integer
        accumulators), so a straggler needs no identification and a
        killed vertex no re-execution.  ``coded=None`` follows
        ``config.coded_redundancy``; True forces it (raising if the
        plan is ineligible); False keeps the duplicate path.
        """
        from dryad_tpu.cluster.interfaces import ProcessState as PS
        from dryad_tpu.plan.lower import lower

        self._reap_dead_workers()
        self._sync_membership(gang=False)
        rewrite = self._rewrite_partial_group(query)
        if rewrite is not None:
            run_query, merge, gate_node = rewrite
        else:
            run_query, merge, gate_node = query, None, query.node
        # The gate checks what vertices actually run per-partition: for
        # a rewritten plan, the pre-group slice (the group tail is
        # partition-local by construction — its exchange is identity on
        # the one-device vertex mesh).
        graph = lower([gate_node], query.ctx.config, query.ctx.dictionary)
        overrides = None
        bad_all = [
            op.kind
            for st in graph.stages
            for op in st.ops
            if op.kind not in self._PARTITIONED_OPS
        ]
        if bad_all:
            # routed slices order by key hash, not engine order — a
            # terminal partial merge containing "first" would return a
            # hash-assignment-dependent value (the r4 guard's exact
            # failure mode), so such plans keep the gang path
            if merge is not None and any(
                op == "first" for _o, op, _p in merge[2]
            ):
                raise ValueError(
                    "partitioned submission cannot route a plan whose "
                    "terminal aggregate uses 'first' (routing reorders "
                    "rows, making 'first' nparts-dependent) — use "
                    "submit()"
                )
            # shuffle-bearing plan: qualify anyway when the driver can
            # make its exchanges partition-local by ROUTING the host
            # inputs (co-partitioned join sides; range-routed sort) —
            # the reference speculates every vertex kind
            # (DrStageManager.h:156, DrVertex.cpp:444), so joins and
            # sorts must run as duplicable vertex tasks too.
            nparts = nparts or self._auto_fanout(query)
            overrides = self._route_for_vertices(gate_node, query.ctx,
                                                 nparts)
            if overrides is None:
                raise ValueError(
                    f"partitioned submission requires an exchange-free "
                    f"plan, a terminal builtin-agg group_by/aggregate "
                    f"partial, or a driver-routable join/order_by over "
                    f"host inputs; plan contains {sorted(set(bad_all))} "
                    f"— use submit()"
                )
            self.events.emit(
                "vertex_routed", plan_kind=overrides[0],
                nparts=nparts, inputs=sorted(overrides[1]),
            )
            overrides = overrides[1]
        query = run_query
        nparts = nparts or self._auto_fanout(query)
        if merge is not None and overrides is None:
            from dryad_tpu.redundancy import policy as coded_policy

            decision = coded_policy.decide(
                query, merge, query.ctx.config, nparts, requested=coded,
            )
            if decision.apply:
                return self._submit_coded(query, merge, nparts, decision)
            if coded is True:
                raise ValueError(
                    f"coded submission requested but the plan is "
                    f"ineligible: {decision.reason}"
                )
            if coded is None and query.ctx.config.coded_redundancy:
                self.events.emit(
                    "coded_fallback", reason=decision.reason,
                )
        elif coded is True:
            raise ValueError(
                "coded submission requires a terminal linear partial "
                "aggregation over unrouted inputs — use coded=None/False"
            )
        self._seq += 1
        seq = self._seq
        job_dir = os.path.join(self.root, self.job_id, f"r{seq}")
        os.makedirs(job_dir, exist_ok=True)
        pkg_rel = f"{self.job_id}/r{seq}/job.pkg"
        self._register_strings(query)
        pack_query(
            query, os.path.join(self.root, pkg_rel),
            binding_overrides=overrides,
        )
        result_rel = f"{self.job_id}/r{seq}/result"
        self.events.emit(
            "vertex_job_start", seq=seq, nparts=nparts,
            speculation=speculation,
        )

        stats = StageStatistics()
        run_t0: Dict[int, float] = {}  # ClusterProcess.id -> RUNNING ts

        cache_bytes = int(
            getattr(query.ctx.config, "gang_partition_cache_bytes", 0) or 0
        )

        def make_proc(part: int, attempt: int) -> ClusterProcess:
            cmd = {
                "kind": "runpart", "package": pkg_rel, "part": part,
                "nparts": nparts, "result_dir": result_rel, "seq": seq,
                "cseq": self._next_cseq(), "cache_bytes": cache_bytes,
            }
            # Primaries spread round-robin as a soft preference;
            # duplicates go wherever a slot is free first.
            affs = (
                [Affinity(f"worker{part % self.n}")] if attempt == 0 else []
            )
            p = ClusterProcess(
                self._placed_round_trip(cmd),
                name=f"part{part}-a{attempt}", affinities=affs,
            )

            def watch(pr: ClusterProcess) -> None:
                if pr.state is PS.RUNNING:
                    run_t0[pr.id] = time.monotonic()

            p.on_state(watch)
            return p

        terminal = (PS.COMPLETED, PS.FAILED, PS.CANCELED)
        tasks: Dict[int, Dict] = {}
        winners: Dict[int, int] = {}  # part -> worker that completed it
        part_fps: Dict[int, str] = {}  # part -> content fp (cache key)
        for part in range(nparts):
            p = make_proc(part, 0)
            tasks[part] = {
                "procs": [p], "dup": False,
                # failure-domain bookkeeping: Attempt history, proc ids
                # already folded into it, and the backoff gate for the
                # next re-execution (None = no retry pending)
                "attempts": [], "seen": set(), "retry_at": None,
            }
            self.scheduler.schedule(p)

        pending = set(range(nparts))
        # nparts tasks over n worker slots run in ceil(nparts/n)
        # sequential waves; every wave gets the per-command budget.
        waves = -(-nparts // max(self.n, 1))
        deadline = time.monotonic() + self.timeout * waves + 30.0
        # versioned re-execution budget (DrVertexRecord) + exponential
        # backoff with seeded jitter between transient re-executions
        policy = RetryPolicy(max_attempts=3)
        max_attempts = policy.max_attempts
        try:
            while pending:
                self._reap_dead_workers()
                for part in sorted(pending):
                    t = tasks[part]
                    winner = next(
                        (p for p in t["procs"] if p.state is PS.COMPLETED),
                        None,
                    )
                    if winner is not None:
                        dur = time.monotonic() - run_t0.get(
                            winner.id, time.monotonic()
                        )
                        stats.record(dur)
                        if winner.computer:
                            winners[part] = int(
                                winner.computer.removeprefix("worker")
                            )
                        wfp = (winner.result or {}).get("fp")
                        if wfp:
                            part_fps[part] = wfp
                        for p in t["procs"]:
                            if p is not winner and p.state not in terminal:
                                self.scheduler.cancel(p)
                                self.events.emit(
                                    "vertex_duplicate_cancel", part=part,
                                    loser=p.computer or "queued",
                                )
                        if t["dup"]:
                            self.events.emit(
                                "vertex_duplicate_win", part=part,
                                winner=winner.computer, seconds=dur,
                            )
                        self.events.emit(
                            "vertex_complete", part=part, seconds=dur,
                            computer=winner.computer,
                        )
                        pending.discard(part)
                        continue
                    if t["procs"] and all(
                        p.state in (PS.FAILED, PS.CANCELED)
                        for p in t["procs"]
                    ):
                        # Independent re-executable vertex: a TRANSIENT
                        # failure re-runs (on a surviving worker, after
                        # a seeded backoff) up to the version budget
                        # (DrVertex.cpp:531 InstantiateVersion; failure
                        # budget DrGraph.h:42).  A DETERMINISTIC repeat
                        # — same exception class+message on a different
                        # computer — fails fast with the history.
                        if t["retry_at"] is not None:
                            if time.monotonic() >= t["retry_at"]:
                                t["retry_at"] = None
                                np_ = make_proc(part, len(t["procs"]))
                                t["procs"].append(np_)
                                self.scheduler.schedule(np_)
                            continue
                        for p in t["procs"]:
                            if (
                                p.state is PS.FAILED
                                and p.error is not None
                                and p.id not in t["seen"]
                            ):
                                t["seen"].add(p.id)
                                kind = classify(
                                    p.error, t["attempts"],
                                    computer=p.computer,
                                )
                                t["attempts"].append(Attempt(
                                    number=len(t["attempts"]) + 1,
                                    error_type=type(p.error).__name__,
                                    error=str(p.error),
                                    kind=kind.value,
                                    computer=p.computer,
                                ))
                        attempts = t["attempts"]
                        deterministic = bool(attempts) and (
                            attempts[-1].kind
                            == FailureKind.DETERMINISTIC.value
                        )
                        if deterministic or len(t["procs"]) >= max_attempts:
                            self.events.emit(
                                "vertex_job_failed", part=part,
                                failure_kind=(
                                    attempts[-1].kind if attempts
                                    else FailureKind.TRANSIENT.value
                                ),
                            )
                            why = (
                                "failed deterministically (identical "
                                "error on different computers; retrying "
                                "cannot help)"
                                if deterministic
                                and len(t["procs"]) < max_attempts
                                else f"failed on all {len(t['procs'])} "
                                "attempts"
                            )
                            errs = "; ".join(
                                str(p.error) for p in t["procs"] if p.error
                            )
                            raise JobFailedError(
                                f"vertex task {part} {why}: {errs}",
                                stage=f"part{part}", attempts=attempts,
                            )
                        backoff = policy.backoff(
                            f"part{part}", len(attempts) or 1
                        )
                        if attempts:
                            attempts[-1].backoff = backoff
                        t["retry_at"] = time.monotonic() + backoff
                        last = attempts[-1] if attempts else None
                        self.events.emit(
                            "vertex_retry", part=part,
                            attempt=len(t["procs"]) + 1,
                            backoff=round(backoff, 4),
                            computer=last.computer if last else None,
                            error=last.error if last else None,
                            failure_kind=(
                                last.kind if last
                                else FailureKind.TRANSIENT.value
                            ),
                        )
                    # Speculation: a RUNNING attempt past the outlier
                    # threshold gets one duplicate (CheckForDuplicates).
                    thr = stats.outlier_threshold()
                    if speculation and not t["dup"] and thr is not None:
                        running = [
                            p for p in t["procs"]
                            if p.state is PS.RUNNING and p.id in run_t0
                        ]
                        if running and any(
                            time.monotonic() - run_t0[p.id] > thr
                            for p in running
                        ):
                            t["dup"] = True
                            dp = make_proc(part, 1)
                            t["procs"].append(dp)
                            self.scheduler.schedule(dp)
                            self.events.emit(
                                "vertex_duplicate", part=part,
                                threshold=round(thr, 4),
                                elapsed=round(
                                    max(
                                        time.monotonic() - run_t0[p.id]
                                        for p in running
                                    ), 4,
                                ),
                            )
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"vertex job timed out with parts "
                        f"{sorted(pending)} outstanding"
                    )
                if pending:
                    time.sleep(0.05)
        finally:
            # Never leak attempts: a queued proc dispatched later would
            # clobber the worker's cmd mailbox slot (latest-value
            # semantics) and poison the next submission.
            for t in tasks.values():
                for p in t["procs"]:
                    if p.state not in terminal:
                        self.scheduler.cancel(p)
        self.events.emit("vertex_job_complete", seq=seq)
        self._collect_telemetry()
        part_rows: List[int] = []
        table = None
        snaps = None
        if (
            merge is not None
            and merge[0] == "group"
            and bool(getattr(query.ctx.config, "gang_combine_tree", False))
            and not any(op == "first" for _o, op, _p in merge[2])
        ):
            # level -1: winners pre-merge their own parts worker-side;
            # None (a worker died or refused) falls back to the flat
            # assembly below — the part files are durable on the job
            # root, so the pre-merge is an optimization, never a
            # correctness dependency
            pre = self._worker_combine(
                query, pkg_rel, result_rel, nparts, winners, part_fps,
                merge,
            )
            if pre is not None:
                table, part_rows, snaps = pre
        if table is None:
            table = self._assemble(
                query, result_rel, list(range(nparts)),
                dictionary=query.ctx.dictionary, part_rows=part_rows,
            )
        if merge is not None:
            table = self._merge_partials(
                table, merge, part_rows=part_rows,
                config=query.ctx.config, snaps=snaps,
            )
            self.events.emit(
                "vertex_partials_merged", seq=seq,
                rows=len(next(iter(table.values()), [])),
            )
        return table

    def _worker_combine(
        self, query, pkg_rel: str, result_rel: str, nparts: int,
        winners: Dict[int, int], part_fps: Dict[int, str], merge,
    ):
        """Level -1 of the combine tree (``config.gang_combine_tree``):
        each winner worker folds the un-finalized partial state of the
        parts IT completed into one ``wpart<w>.dpf``
        (``cluster.worker._combine_parts``) and ships a key-range
        snapshot, so the driver fetches one partial per WORKER instead
        of one per VERTEX — ingress drops by the per-worker fan-in and
        the existing level-0/1 driver tree starts from pre-merged
        segments.  Returns ``(table, part_rows, snaps)`` with the
        decoded premerged segments (string keys looked up from the
        driver dictionary — wparts carry raw Hash64 codes), or ``None``
        when any worker's combine fails, where the caller assembles the
        original parts flat (byte-identical either way)."""
        from dryad_tpu.columnar.schema import ColumnType
        from dryad_tpu.exec.partial import state_reductions

        _kind, keys, plan, _out_schema = merge
        by_worker: Dict[int, List[int]] = {}
        for part in range(nparts):
            w = winners.get(part)
            if w is None:
                return None  # owner unknown — keep the flat path
            by_worker.setdefault(w, []).append(part)
        self._reap_dead_workers()
        wids = sorted(by_worker)
        if not wids or any(w in self._dead for w in wids):
            return None
        config = query.ctx.config
        red = state_reductions(plan)
        ranges = int(getattr(config, "combine_tree_ranges", 64))
        cache_bytes = int(
            getattr(config, "gang_partition_cache_bytes", 0) or 0
        )
        terminal = (
            ProcessState.COMPLETED, ProcessState.FAILED,
            ProcessState.CANCELED,
        )
        procs = []
        for widx, w in enumerate(wids):
            cmd = self._stamp_trace({
                "kind": "combineparts", "package": pkg_rel,
                "result_dir": result_rel,
                "parts": [
                    {"part": p, "fp": part_fps.get(p)}
                    for p in by_worker[w]
                ],
                "keys": list(keys), "red": red, "ranges": ranges,
                "wid": widx, "cache_bytes": cache_bytes,
                "cseq": self._next_cseq(),
            })

            def fn(proc: ClusterProcess, i=w, cmd=cmd) -> Dict:
                # per-worker watch (gang=False): an unrelated death
                # must not poison every winner's combine
                return self._round_trip_body(i, cmd, proc, gang=False)

            p = ClusterProcess(
                fn, name=f"combine-w{w}",
                affinities=[Affinity(f"worker{w}", hard=True)],
            )
            self.scheduler.schedule(p)
            procs.append(p)
        statuses = []
        ok = True
        for p in procs:
            if not p.wait(self.timeout + 30.0):
                ok = False
                break
            if p.state is not ProcessState.COMPLETED:
                ok = False
                break
            statuses.append(p.result)
        if not ok:
            for p in procs:
                if p.state not in terminal:
                    self.scheduler.cancel(p)
            log.warning(
                "worker-side combine failed (%s); falling back to flat "
                "assembly — part files are durable",
                "; ".join(
                    f"{p.name}: {p.error}" for p in procs if p.error
                ) or "timeout",
            )
            return None
        # premerged assembly: wparts hold LOGICAL columns already (the
        # worker decoded before folding), so this is lookup +
        # pass-through, not the physical decode
        w0, r0 = self._client.wire_bytes, self._client.raw_bytes
        tables = []
        part_rows: List[int] = []
        snaps: List[Dict] = []
        with self.tracer.span(
            "assemble", cat="driver", parts=len(statuses)
        ):
            for st in statuses:
                host = parse_partition_bytes(
                    self._client.read_whole_file(
                        f"{result_rel}/{st['wfile']}", compress=True
                    )
                )
                tbl: Dict[str, np.ndarray] = {}
                for f in query.schema.fields:
                    if f.name not in host:
                        continue
                    col = np.asarray(host[f.name])
                    if f.ctype is ColumnType.STRING:
                        col = np.array(
                            query.ctx.dictionary.lookup_all(
                                col.astype(np.uint64)
                            ),
                            dtype=object,
                        )
                    tbl[f.name] = col
                tables.append(tbl)
                part_rows.append(len(next(iter(tbl.values()), [])))
                snaps.append(st.get("snapshot"))
        self.events.emit(
            "assemble_fetch", parts=len(statuses),
            wire_bytes=self._client.wire_bytes - w0,
            raw_bytes=self._client.raw_bytes - r0,
        )
        for widx, st in enumerate(statuses):
            self.events.emit(
                "gang_partial_combine", worker=wids[widx],
                parts=len(st.get("parts") or []),
                rows=int(st.get("rows", 0)),
                in_rows=int(st.get("in_rows", 0)),
                read_bytes=int(st.get("read_bytes", 0)),
                cache_hits=int(st.get("cache_hits", 0)),
                cache_misses=int(st.get("cache_misses", 0)),
                bytes=int(st.get("bytes", 0)),
            )
            self.events.emit(
                "combine_tree_level", level=-1, group=widx,
                fan_in=len(st.get("parts") or []),
                cap_rows=int(st.get("rows", 0)),
                bytes=int(st.get("bytes", 0)),
                ici_bytes=0, dcn_bytes=0, device=False,
            )
        table = {
            c: np.concatenate([t[c] for t in tables]) for c in tables[0]
        }
        return table, part_rows, snaps

    # -- coded k-of-n vertex execution (dryad_tpu.redundancy) ----------------
    def _submit_coded(self, query, merge, nparts, decision):
        """Run a qualifying partial aggregation as k systematic + r
        parity CODED vertices (``redundancy.coding``): ANY k of the
        k + r coded completions reconstruct the merged stage output
        (``redundancy.reconstruct`` — bit-exact for integer
        accumulators), so

        - spares launch on the coarse floor trigger
          (``exec.stats.spare_threshold``) — coding needs no straggler
          IDENTIFICATION, only a suspicion that up to r vertices are
          slow — and immediately on the first vertex failure (failure
          masking with zero re-executions);
        - at k completions the rest are canceled and completed-but-
          unused coded output is accounted as ``coded_waste_bytes``;
        - a coded vertex is relaunched ONLY if failures make k
          completions impossible (fewer than k live+done vertices) —
          the bounded fallback to re-execution semantics.
        """
        from dryad_tpu.cluster.interfaces import ProcessState as PS
        from dryad_tpu.redundancy.coding import CodedSpec
        from dryad_tpu.redundancy.reconstruct import merge_coded

        cfg = query.ctx.config
        spec = CodedSpec(int(nparts), int(decision.r))
        self._seq += 1
        seq = self._seq
        os.makedirs(
            os.path.join(self.root, self.job_id, f"r{seq}"), exist_ok=True
        )
        pkg_rel = f"{self.job_id}/r{seq}/job.pkg"
        self._register_strings(query)
        pack_query(query, os.path.join(self.root, pkg_rel))
        result_rel = f"{self.job_id}/r{seq}/result"
        self.events.emit(
            "coded_job_start", seq=seq, k=spec.k, n=spec.n, r=spec.r,
            agg=decision.kind,
        )
        t_job0 = time.monotonic()
        stats = StageStatistics(floor_ratio=cfg.straggler_floor_ratio)
        # Diagnosis-driven pre-seeding: the engine's "coded" duration
        # model accumulated coded_task_complete times from PRIOR
        # submissions, so spare_threshold() is armed from t=0 of this
        # job — a straggler can trigger parity before this job records
        # a single completion (and before any failure).
        for d in self.diagnosis.stats_for("coded").durations:
            stats.record(d)
        run_t0: Dict[int, float] = {}
        retry_policy = RetryPolicy(
            backoff_base=cfg.retry_backoff_base,
            backoff_max=cfg.retry_backoff_max,
            jitter=cfg.retry_jitter, seed=cfg.retry_seed,
        )

        def make_proc(j: int, attempt: int) -> ClusterProcess:
            cmd = {
                "kind": "runcoded", "package": pkg_rel, "coded": j,
                "parts": spec.support(j), "coeffs": spec.coeffs(j),
                "nparts": spec.k, "keys": list(decision.key_cols),
                "state": list(decision.state_cols),
                "result_dir": result_rel, "seq": seq,
                "cseq": self._next_cseq(),
            }
            affs = (
                [Affinity(f"worker{j % self.n}")]
                if not spec.is_parity(j) and attempt == 0 else []
            )
            p = ClusterProcess(
                self._placed_round_trip(cmd),
                name=f"coded{seq}-c{j}-a{attempt}", affinities=affs,
            )

            def watch(pr: ClusterProcess) -> None:
                if pr.state is PS.RUNNING:
                    run_t0[pr.id] = time.monotonic()

            p.on_state(watch)
            return p

        terminal = (PS.COMPLETED, PS.FAILED, PS.CANCELED)
        tasks: Dict[int, Dict] = {}
        for j in range(spec.k):
            tasks[j] = {
                "procs": [make_proc(j, 0)], "attempts": [], "seen": set(),
                "retry_at": None,
            }
        self.scheduler.schedule_batch([tasks[j]["procs"][0]
                                       for j in range(spec.k)])
        completed: Dict[int, ClusterProcess] = {}
        parity_launched = False
        # parity support spans all k shards, so budget parity waves at
        # full-stage cost on top of the systematic waves
        waves = -(-spec.n // max(self.n, 1)) + 1
        deadline = time.monotonic() + self.timeout * waves + 30.0

        def all_failed(t) -> bool:
            return bool(t["procs"]) and all(
                p.state in (PS.FAILED, PS.CANCELED) for p in t["procs"]
            )

        def launch_parity(trigger: str, threshold) -> None:
            nonlocal parity_launched
            parity_launched = True
            spares = []
            for j in range(spec.k, spec.n):
                tasks[j] = {
                    "procs": [make_proc(j, 0)], "attempts": [],
                    "seen": set(), "retry_at": None,
                }
                spares.append(tasks[j]["procs"][0])
            self.scheduler.schedule_batch(spares)
            self.events.emit(
                "coded_launch", seq=seq, k=spec.k, n=spec.n, r=spec.r,
                trigger=trigger,
                threshold=round(threshold, 4) if threshold else None,
            )

        try:
            while len(completed) < spec.k:
                self._reap_dead_workers()
                now = time.monotonic()
                for j in sorted(tasks):
                    t = tasks[j]
                    if j in completed:
                        continue
                    winner = next(
                        (p for p in t["procs"] if p.state is PS.COMPLETED),
                        None,
                    )
                    if winner is not None:
                        dur = now - run_t0.get(winner.id, now)
                        stats.record(dur)
                        completed[j] = winner
                        self.events.emit(
                            "coded_task_complete", seq=seq, coded=j,
                            parity=spec.is_parity(j),
                            seconds=round(dur, 4),
                            computer=winner.computer,
                        )
                        continue
                    if all_failed(t):
                        for p in t["procs"]:
                            if (
                                p.state is PS.FAILED
                                and p.error is not None
                                and p.id not in t["seen"]
                            ):
                                t["seen"].add(p.id)
                                kind = classify(
                                    p.error, t["attempts"],
                                    computer=p.computer,
                                )
                                t["attempts"].append(Attempt(
                                    number=len(t["attempts"]) + 1,
                                    error_type=type(p.error).__name__,
                                    error=str(p.error), kind=kind.value,
                                    computer=p.computer,
                                ))
                                self.events.emit(
                                    "coded_task_failed", seq=seq,
                                    coded=j, parity=spec.is_parity(j),
                                    error=str(p.error)[:200],
                                    failure_kind=kind.value,
                                )
                # failure masking: the FIRST failure launches all r
                # spares at once — parity covers ANY r losses, so
                # there is nothing to target
                failed_now = [j for j, t in tasks.items()
                              if j not in completed and all_failed(t)]
                if failed_now and not parity_launched:
                    launch_parity("failure", None)
                # straggler masking: the coarse spare trigger (no
                # per-task identification needed — see spare_threshold)
                if not parity_launched:
                    thr = stats.spare_threshold()
                    slow = None
                    if thr is not None:
                        slow = next(
                            (
                                (j, now - run_t0[p.id])
                                for j, t in tasks.items()
                                if j not in completed
                                for p in t["procs"]
                                if p.state is PS.RUNNING
                                and p.id in run_t0
                                and now - run_t0[p.id] > thr
                            ),
                            None,
                        )
                    if slow is not None:
                        # diagnose FIRST so the `diagnosis` event
                        # precedes the coded_launch it is driving
                        self.diagnosis.note_inflight(
                            "coded", slow[1], subject=f"coded{slow[0]}"
                        )
                        launch_parity("straggler", thr)
                # coverage shortfall: relaunch dead vertices only when
                # k completions are otherwise impossible
                live = sum(
                    1 for j, t in tasks.items()
                    if j not in completed and not all_failed(t)
                )
                shortfall = spec.k - len(completed) - live
                if shortfall > 0:
                    for j in failed_now:
                        if shortfall <= 0:
                            break
                        t = tasks[j]
                        if len(t["procs"]) >= retry_policy.max_attempts:
                            errs = "; ".join(
                                str(p.error)
                                for p in t["procs"] if p.error
                            )
                            raise JobFailedError(
                                f"coded vertex {j} failed on all "
                                f"{len(t['procs'])} attempts and the "
                                f"remaining coded vertices cannot reach "
                                f"k={spec.k} completions: {errs}",
                                stage=f"coded{j}", attempts=t["attempts"],
                            )
                        if t["retry_at"] is None:
                            t["retry_at"] = now + retry_policy.backoff(
                                f"coded{j}", len(t["attempts"]) or 1
                            )
                        if now >= t["retry_at"]:
                            t["retry_at"] = None
                            np_ = make_proc(j, len(t["procs"]))
                            t["procs"].append(np_)
                            self.scheduler.schedule(np_)
                            shortfall -= 1
                            self.events.emit(
                                "coded_retry", seq=seq, coded=j,
                                attempt=len(t["procs"]),
                            )
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"coded job timed out with "
                        f"{len(completed)}/{spec.k} completions"
                    )
                if len(completed) < spec.k:
                    time.sleep(0.05)
        finally:
            canceled = 0
            for t in tasks.values():
                for p in t["procs"]:
                    if p.state not in terminal:
                        self.scheduler.cancel(p)
                        canceled += 1
            if canceled:
                self.events.emit(
                    "coded_cancel", seq=seq, canceled=canceled,
                )
        # prefer systematic rows among the completions (identity
        # weights decode fastest and keep float paths exact); the
        # result is subset-independent for integer states anyway
        used = sorted(completed)[: spec.k]
        waste = 0
        unused = []
        for j in sorted(tasks):
            if j in used:
                continue
            path = os.path.join(self.root, result_rel, f"cpart{j}.dpf")
            if os.path.exists(path):
                waste += os.path.getsize(path)
                unused.append(j)
        self.events.emit(
            "coded_waste_bytes", seq=seq, bytes=waste, unused=unused,
        )
        t_rec0 = time.monotonic()
        tables = [
            parse_partition_bytes(
                self._client.read_whole_file(
                    f"{result_rel}/cpart{j}.dpf", compress=True
                )
            )
            for j in used
        ]
        merged, info = merge_coded(
            [spec.row(j) for j in used], tables,
            list(decision.key_cols), list(decision.state_cols),
            max_amplification=cfg.coded_max_amplification,
        )
        self.events.emit(
            "coded_reconstruct", seq=seq, used=used,
            parity_used=sum(1 for j in used if spec.is_parity(j)),
            exact=info["exact"],
            amplification=round(float(info["amplification"]), 4),
            seconds=round(time.monotonic() - t_rec0, 4),
        )
        self.events.emit(
            "coded_job_complete", seq=seq,
            seconds=round(time.monotonic() - t_job0, 4),
        )
        self._collect_telemetry()
        return self._finalize_coded(merged, merge)

    def _finalize_coded(self, merged, merge):
        """Produce the user-facing table from the reconstructed merged
        state columns (the coded twin of :meth:`_merge_partials`; keys
        arrive in sorted order from the union alignment, which is
        completion-subset independent)."""
        kind, keys, plan_or_dec, out_schema = merge
        result: Dict[str, np.ndarray] = {
            k: np.asarray(merged[k]) for k in keys
        }
        if kind == "group_dec":
            dec = plan_or_dec
            full = dict(result)
            # states narrow back to their declared dtypes BEFORE
            # finalize so user fns see what the uncoded path feeds them
            for name, ct in dec.state_fields:
                full[name] = np.asarray(merged[name]).astype(
                    ct.numpy_dtype
                )
            if dec.finalize is not None:
                full = {
                    k: np.asarray(v) for k, v in dec.finalize(full).items()
                }
            for name, _ct in dec.out_fields:
                dt = out_schema.field(name).ctype.numpy_dtype
                result[name] = np.asarray(full[name]).astype(dt)
            return result
        plan = plan_or_dec
        for out, op, pcols in plan:
            if op == "mean":
                s = np.asarray(merged[pcols[0]], np.float64)
                c = np.maximum(
                    np.asarray(merged[pcols[1]], np.float64), 1.0
                )
                vals = s / c
            else:  # sum / count (linear by policy)
                vals = merged[pcols[0]]
            dt = out_schema.field(out).ctype.numpy_dtype
            result[out] = np.asarray(vals).astype(dt)
        return result

    # row-local node kinds that preserve key VALUES between an input
    # binding and the routed operator (where removes rows, project
    # renames nothing it keeps) — a select could rewrite the key and
    # silently break co-partitioning, so it blocks routing
    _ROUTE_CHAIN_OPS = frozenset({"where", "project"})

    @staticmethod
    def _route_base(node, ctx):
        """Descend a where/project chain to a host input binding;
        (input_node, arrays) or None."""
        cur = node
        while cur.kind in LocalJobSubmission._ROUTE_CHAIN_OPS:
            cur = cur.inputs[0]
        if cur.kind != "input":
            return None
        b = ctx._bindings.get(cur.id)
        if not b or b[0] != "host":
            return None
        return cur, b[1]

    def _route_for_vertices(self, gate_node, ctx, nparts):
        """Driver-side routing that makes a shuffle-bearing plan
        partition-local: join inputs co-partition by key hash, sort
        inputs range-partition on driver-sampled splitters (the
        sampler + distributor pair of ``DryadLinqSampler.cs:38-42`` /
        ``DrDynamicRangeDistributor.cpp:28-100`` executed at the
        driver).  On the vertex's one-device mesh the plan's exchanges
        are identity, so each vertex computes exactly its partition of
        the answer.  Returns ``(kind, {input_node_id: host_routed
        binding})`` or None when the plan shape doesn't qualify."""
        from dryad_tpu.exec.outofcore import (
            _host_hash_buckets,
            _sample_splitters,
            _sort_key_view,
        )

        cur = gate_node
        while cur.kind in self._ROUTE_CHAIN_OPS:
            cur = cur.inputs[0]
        if cur.kind == "join":
            jp = cur.params
            sides = []
            for inp, keys in (
                (cur.inputs[0], jp["left_keys"]),
                (cur.inputs[1], jp["right_keys"]),
            ):
                base = self._route_base(inp, ctx)
                if base is None:
                    return None
                nid_node, arrays = base
                if any(k not in arrays for k in keys):
                    return None
                sides.append((nid_node.id, arrays, list(keys)))
            if sides[0][0] == sides[1][0] and sides[0][2] != sides[1][2]:
                # self-join on DIFFERENT key columns: one node cannot
                # carry two routings — a silent overwrite would drop
                # matches, so fall back to the gang submit
                return None
            overrides = {}
            for nid, arrays, keys in sides:
                buckets = _host_hash_buckets(
                    arrays, keys, nparts, salt=0,
                    dictionary=ctx.dictionary,
                )
                overrides[nid] = self._routed_binding(
                    arrays, buckets, nparts
                )
            return "join", overrides
        if cur.kind == "order_by":
            keys = cur.params["keys"]
            primary, pdesc = keys[0]
            base = self._route_base(cur.inputs[0], ctx)
            if base is None:
                return None
            nid_node, arrays = base
            if primary not in arrays:
                return None
            col = _sort_key_view(np.asarray(arrays[primary], copy=False))
            splitters = _sample_splitters(col, nparts)
            buckets = np.searchsorted(splitters, col, side="right")
            if pdesc:
                # part order must follow the sort direction: the
                # largest-value range lands on part 0
                buckets = len(splitters) - buckets
            return "order_by", {
                nid_node.id: self._routed_binding(
                    arrays, buckets, nparts
                )
            }
        return None

    @staticmethod
    def _routed_binding(arrays, buckets, nparts):
        order = np.argsort(buckets, kind="stable")
        counts = np.bincount(buckets, minlength=nparts)
        offsets = np.concatenate(
            [[0], np.cumsum(counts)]
        ).astype(np.int64)
        return (
            "host_routed",
            {k: np.asarray(v)[order] for k, v in arrays.items()},
            offsets,
        )

    # mergeable builtin aggregates for the partial-vertex rewrite
    # (shared with the streaming executor; "first" merges correctly
    # because _assemble concatenates partition results in part-id order
    # = engine order, so the first partial occurrence of a key IS the
    # engine-order first).
    _MERGEABLE_AGGS = _partial.MERGEABLE_AGGS

    _partial_plan = staticmethod(_partial.partial_plan)

    def _rewrite_partial_group(self, query):
        """Split a terminal builtin-agg group_by / scalar aggregate into
        per-vertex partials + a driver-side final merge.  Returns
        (partial_query, merge_spec, gate_node) or None when the plan
        does not qualify.  merge_spec: (kind, keys, plan, out_schema)
        where plan rows are (out_name, op, partial_col_names)."""
        from dryad_tpu.api.query import Query

        node = query.node
        dec = node.params.get("decomposable")
        if node.kind == "group_by" and dec is not None:
            return self._rewrite_partial_decomposable(query, node, dec)
        agg_list = node.params.get("aggs")
        if not agg_list or any(
            op not in self._MERGEABLE_AGGS for op, _c, _o in agg_list
        ):
            return None
        if any(op == "first" for op, _c, _o in agg_list):
            # "first" merges by part-id-concat order, which equals
            # engine order only for HOST bindings (np.array_split is
            # contiguous); slice_binding deals STORE partitions
            # round-robin, where that order diverges from
            # submit()/collect() — refuse rather than return an
            # nparts-dependent answer (code-review r4).
            from dryad_tpu.plan.nodes import walk as _walk

            for nd in _walk([node]):
                b = query.ctx._bindings.get(nd.id)
                if b and b[0] == "store":
                    return None
        if node.kind == "group_by":
            inner = Query(query.ctx, node.inputs[0])
            partial, plan = self._partial_plan(agg_list)
            pq = inner.group_by(
                list(node.params["keys"]), partial,
                dense=node.params.get("dense"),
                # salt= is the user's sort-path/skew escape hatch;
                # keep honoring it on the vertex
                salt=node.params.get("salt"),
            )
            return pq, (
                "group", list(node.params["keys"]), plan, query.schema
            ), inner.node
        if node.kind == "aggregate":
            # scalar "first" has no neutral value for an empty
            # partition's partial row (and scalar_agg doesn't implement
            # it) — the engine-order merge applies to group_by only
            if any(op == "first" for op, _c, _o in agg_list):
                return None
            inner = Query(query.ctx, node.inputs[0])
            partial, plan = self._partial_plan(agg_list)
            pq = inner.aggregate_as_query(partial)
            return pq, ("aggregate", [], plan, query.schema), inner.node
        return None

    def _rewrite_partial_decomposable(self, query, node, dec):
        """Custom-combiner vertex partials: qualify when the
        Decomposable types its state columns (``state_fields``) — each
        vertex emits per-partition state rows, the driver merges with
        the user's associative ``merge`` and runs ``finalize`` once
        (the reference's machine-level partial aggregation for custom
        combiners, ``DrDynamicAggregateManager``)."""
        import dataclasses as _dc

        from dryad_tpu.api.query import Query

        if dec.state_fields is None:
            return None
        if {n for n, _ct in dec.state_fields} != set(dec.state_cols):
            raise ValueError(
                "Decomposable.state_fields names "
                f"{[n for n, _ct in dec.state_fields]} must match "
                f"state_cols {list(dec.state_cols)}"
            )
        if any(ct.is_split for _n, ct in dec.state_fields):
            return None  # split-word states can't merge on the host
        inner = Query(query.ctx, node.inputs[0])
        partial_dec = _dc.replace(
            dec, out_fields=list(dec.state_fields), finalize=None
        )
        pq = inner.group_by(
            list(node.params["keys"]), decomposable=partial_dec
        )
        return pq, (
            "group_dec", list(node.params["keys"]), dec, query.schema
        ), inner.node

    def _merge_partials(
        self, table, merge, part_rows=None, config=None, snaps=None
    ):
        """Final merge of assembled per-vertex partial results on the
        driver (the aggregation tree's root; reference
        ``DrDynamicAggregateManager`` final vertex).

        With ``config.combine_tree`` on and per-vertex row boundaries
        from assembly, grouped partials reduce HIERARCHICALLY first:
        vertices place into merge groups by key-histogram similarity
        (``exec.combinetree.plan_groups``), each group's partial state
        merges un-finalized (level 0), and the flat pass below
        finalizes over the much smaller pre-merged rows — the driver-
        side analog of the device combine tree.  Plans carrying
        "first" skip the tree (its merge is engine-order-sensitive and
        similarity grouping reorders rows)."""
        kind, keys, plan, out_schema = merge
        if kind == "group_dec":
            return self._merge_dec_partials(table, keys, plan, out_schema)
        if (
            kind == "group"
            and part_rows
            and sum(1 for r in part_rows if r) > 2
            and bool(getattr(config, "combine_tree", False))
            and not any(op == "first" for _out, op, _p in plan)
        ):
            table = self._tree_merge_state(
                table, keys, plan, part_rows, config, snaps=snaps
            )
        cols = {k: np.asarray(v) for k, v in table.items()}
        n = len(next(iter(cols.values()), []))

        def reduce_rows(idxs):
            row = {}
            for out, op, pcols in plan:
                if op == "mean":
                    s = cols[pcols[0]][idxs].sum()
                    c = cols[pcols[1]][idxs].sum()
                    row[out] = s / max(int(c), 1)
                elif op in ("sum", "count"):
                    row[out] = cols[pcols[0]][idxs].sum()
                elif op == "min":
                    row[out] = cols[pcols[0]][idxs].min()
                elif op == "max":
                    row[out] = cols[pcols[0]][idxs].max()
                elif op == "any":
                    row[out] = bool(np.any(cols[pcols[0]][idxs]))
                elif op == "all":
                    row[out] = bool(np.all(cols[pcols[0]][idxs]))
                elif op == "first":
                    # partial rows concatenate in part-id order, so the
                    # first occurrence is the engine-order first
                    row[out] = cols[pcols[0]][np.asarray(idxs)[0]]
            return row

        out: Dict[str, list] = {}
        if kind == "aggregate":
            # scalar: one partial row per vertex; empty-partition rows
            # carry neutral sentinels (0 sums, +/-inf extrema), which
            # the reductions absorb.
            row = reduce_rows(slice(None)) if n else {}
            out = {o: [row.get(o, 0)] for o, _op, _p in plan}
        else:
            index: Dict[tuple, list] = {}
            tups = list(zip(*[cols[k].tolist() for k in keys])) if n else []
            for i, t in enumerate(tups):
                index.setdefault(t, []).append(i)
            out = {k: [] for k in keys}
            for o, _op, _p in plan:
                out[o] = []
            for t, idxs in index.items():
                for k, kv in zip(keys, t):
                    out[k].append(kv)
                row = reduce_rows(np.asarray(idxs))
                for o, _op, _p in plan:
                    out[o].append(row[o])
        result: Dict[str, np.ndarray] = {}
        for k in keys:
            result[k] = np.asarray(out[k], dtype=cols[k].dtype)
        for o, _op, _p in plan:
            dt = out_schema.field(o).ctype.numpy_dtype
            result[o] = np.asarray(out[o]).astype(dt)
        return result

    def _tree_merge_state(
        self, table, keys, plan, part_rows, config, snaps=None
    ):
        """Level-0 of the driver-side combine tree: slice the assembled
        table back into per-vertex segments, place segments into merge
        groups by key-histogram similarity, and fold each group's
        partial STATE (un-finalized, associative reductions only).
        Returns the concatenated group results — a valid partial table
        the flat finalizing pass then reduces as the tree root.
        ``snaps``: per-segment key-range snapshots already computed at
        a lower tree level (the gang workers' level-(-1) pre-merge
        ships them — same deterministic hash, same range space), which
        skip the driver-side hash + histogram pass."""
        from dryad_tpu.exec.combinetree import plan_groups
        from dryad_tpu.exec.partial import state_reductions
        from dryad_tpu.obs.metrics import KeyRangeHistogram

        cols = {k: np.asarray(v) for k, v in table.items()}
        ranges = int(getattr(config, "combine_tree_ranges", 64))
        bounds = np.cumsum([0] + list(part_rows))
        if (
            snaps is None
            or len(snaps) != len(part_rows)
            or any(s is None for s in snaps)
        ):
            h = _driver_key_hash(cols, keys)
            snaps = []
            for i in range(len(part_rows)):
                kr = KeyRangeHistogram(ranges)
                kr.observe(h[bounds[i]:bounds[i + 1]])
                snaps.append(kr.snapshot())
        g = int(getattr(config, "combine_tree_groups", 0) or 0)
        n_groups = g if g > 0 else max(2, int(len(part_rows) ** 0.5))
        groups = plan_groups(snaps, n_groups)
        red = state_reductions(plan)
        merged = []
        for gi, members in enumerate(groups):
            rows = np.concatenate(
                [np.arange(bounds[m], bounds[m + 1]) for m in members]
            )
            seg = {c: v[rows] for c, v in cols.items()}
            mseg = _merge_group_state(seg, keys, red)
            merged.append(mseg)
            self.events.emit(
                "combine_tree_level", level=0, group=gi,
                fan_in=len(members),
                cap_rows=len(next(iter(mseg.values()), [])),
                bytes=int(sum(v.nbytes for v in seg.values())),
                ici_bytes=0, dcn_bytes=0, device=False,
            )
        out = {
            c: np.concatenate([m[c] for m in merged])
            for c in merged[0]
        }
        self.events.emit(
            "combine_tree_level", level=1, fan_in=len(groups),
            cap_rows=len(next(iter(out.values()), [])),
            bytes=int(
                sum(sum(v.nbytes for v in m.values()) for m in merged)
            ),
            ici_bytes=0, dcn_bytes=0, device=False,
        )
        return out

    def _auto_fanout(self, query) -> int:
        """Data-size-driven task count (``DrDynamicRangeDistributor.cpp:
        54-110``: consumer copies = observed size / data-per-vertex):
        one task per ``config.rows_per_vertex`` input rows, at least one
        wave over the gang, capped at 8 waves."""
        from dryad_tpu.plan.nodes import walk

        rows = 0
        for n in walk([query.node]):
            b = query.ctx._bindings.get(n.id)
            if not b:
                continue
            kind, *rest = b
            if kind in ("host", "host_physical"):
                arrays = rest[0]
                rows += max(
                    (len(np.asarray(v)) for v in arrays.values()), default=0
                )
            elif kind == "store":
                parts = rest[0]
                rows += sum(
                    len(next(iter(c.values()))) if c else 0 for c in parts
                )
        per = max(query.ctx.config.rows_per_vertex, 1)
        fanout = max(self.n, -(-rows // per))
        return min(fanout, self.n * 8)

    def _register_strings(self, query) -> None:
        """Register every host-bound STRING token in the DRIVER's
        dictionary before packing.  Workers re-encode the same strings
        with the same deterministic Hash64 (``columnar/schema.py``), so
        assembly can decode results without a worker-shipped dictionary
        (the gang path ships one; vertex tasks don't)."""
        from dryad_tpu.columnar.schema import ColumnType, hash64_str
        from dryad_tpu.plan.nodes import walk

        for n in walk([query.node]):
            b = query.ctx._bindings.get(n.id)
            if not b or b[0] != "host":
                continue
            arrays = b[1]
            for f in n.schema.fields:
                if f.ctype is ColumnType.STRING and f.name in arrays:
                    for s in np.unique(np.asarray(arrays[f.name], object)):
                        query.ctx.dictionary._map[hash64_str(str(s))] = str(s)

    def _merge_dec_partials(self, table, keys, dec, out_schema):
        """Reduce assembled per-vertex STATE rows with the user's
        associative ``merge`` — vectorized across ALL groups at once,
        one round per duplicate rank (<= nparts-1 rounds, each a single
        user-merge call) — then run ``finalize`` once over the merged
        groups."""
        state_names = [n for n, _ct in dec.state_fields]
        cols = {k: np.asarray(v) for k, v in table.items()}
        n = len(next(iter(cols.values()), []))
        tups = list(zip(*[cols[k].tolist() for k in keys])) if n else []
        index: Dict[tuple, list] = {}
        for i, t in enumerate(tups):
            index.setdefault(t, []).append(i)
        groups = list(index.items())
        # Pad every group's row list to the same depth and fold rounds:
        # merge(acc, rows[j]) vectorized across ALL groups at once.
        acc = {
            c: np.asarray([cols[c][idxs[0]] for _t, idxs in groups])
            for c in state_names
        }
        depth = max((len(idxs) for _t, idxs in groups), default=1)
        for j in range(1, depth):
            rows_j = [
                idxs[j] if j < len(idxs) else idxs[0]
                for _t, idxs in groups
            ]
            nxt = {c: cols[c][rows_j] for c in state_names}
            merged = dec.merge(acc, nxt)
            has_j = np.asarray([j < len(idxs) for _t, idxs in groups])
            acc = {
                c: np.where(has_j, np.asarray(merged[c]), acc[c])
                for c in state_names
            }
        # one key-array build, preserving the assembled dtype (int32
        # keys stay int32; string keys stay object)
        key_arrays = {
            k: np.asarray([t[i] for t, _ in groups], dtype=cols[k].dtype)
            for i, k in enumerate(keys)
        }
        full = dict(key_arrays)
        full.update(acc)
        if dec.finalize is not None:
            full = {k: np.asarray(v) for k, v in dec.finalize(full).items()}
        result: Dict[str, np.ndarray] = dict(key_arrays)
        for name, _ct in dec.out_fields:
            dt = out_schema.field(name).ctype.numpy_dtype
            result[name] = np.asarray(full[name]).astype(dt)
        return result

    def inject_delay(
        self, worker: int, seconds: float, count: int = 1
    ) -> None:
        """Make the next ``count`` vertex tasks on one worker stall
        ``seconds`` — the injected-straggler knob (per-worker, unlike
        :meth:`inject_fault`'s gang broadcast)."""
        self._sync_membership()
        cmd = {
            "kind": "set_delay", "seconds": seconds, "count": count,
            "cseq": self._next_cseq(),
        }
        p = ClusterProcess(
            self._command_round_trip(worker, cmd),
            name=f"delay-w{worker}",
            affinities=[Affinity(f"worker{worker}", hard=True)],
        )
        self.scheduler.schedule(p)
        if not p.wait(30.0) or p.state is not ProcessState.COMPLETED:
            raise RuntimeError(
                f"delay injection on worker {worker} failed: {p.error}"
            )

    def _assemble(
        self, query, result_rel: str, part_ids: List[int],
        dictionary: Optional[StringDictionary] = None,
        part_rows: Optional[List[int]] = None,
    ) -> Dict[str, np.ndarray]:
        """Fetch result partitions through the file server (HTTP range
        reads via the block cache) and decode to a host table."""
        import jax.numpy as jnp

        from dryad_tpu.columnar.batch import ColumnBatch

        from concurrent.futures import ThreadPoolExecutor

        # Partitions fetch CONCURRENTLY with zlib wire compression
        # (assemble time ~ max partition, not the sum; the async
        # channel-reader role, HttpReader.cs:78 + dryadvertex.h:33-48).
        w0, r0 = self._client.wire_bytes, self._client.raw_bytes
        with self.tracer.span(
            "assemble", cat="driver", parts=len(part_ids)
        ), ThreadPoolExecutor(
            max_workers=min(8, max(len(part_ids), 1))
        ) as ex:
            cols_parts = list(
                ex.map(
                    lambda g: parse_partition_bytes(
                        self._client.read_whole_file(
                            f"{result_rel}/part{g}.dpf", compress=True
                        )
                    ),
                    part_ids,
                )
            )
        self.events.emit(
            "assemble_fetch", parts=len(part_ids),
            wire_bytes=self._client.wire_bytes - w0,
            raw_bytes=self._client.raw_bytes - r0,
        )
        if dictionary is None:
            dictionary = StringDictionary()
            dictionary._map.update(
                pickle.loads(
                    self._client.read_whole_file(
                        f"{result_rel}/dictionary.pkl"
                    )
                )
            )
        phys = query.schema.device_names()
        if not cols_parts:
            return {n: np.zeros(0) for n in query.schema.names}
        if part_rows is not None and phys:
            # per-part row boundaries of the concatenation — lets the
            # combine-tree merge slice the decoded table back into
            # per-vertex segments (decode is row-preserving)
            part_rows.extend(len(p[phys[0]]) for p in cols_parts)
        cols = {
            c: np.concatenate([p[c] for p in cols_parts]) for c in phys
        }
        nrows = len(next(iter(cols.values()), []))
        batch = ColumnBatch(
            {c: jnp.asarray(v) for c, v in cols.items()},
            jnp.ones((nrows,), jnp.bool_),  # workers wrote valid rows only
        )
        return batch.to_numpy(query.schema, dictionary)

    def inject_fault(
        self,
        stage: Optional[str],
        count: int = 1,
        plan: Optional[Dict] = None,
        workers: Optional[List[int]] = None,
    ) -> None:
        """Send a fault-injection command to workers (remote
        SetFakeVertexFailure; ``stage=None`` with no plan clears).

        ``plan``: a seeded :class:`exec.faults.FaultPlan` as a dict —
        including ``worker_kill_prob`` process kills, the gang chaos
        scenario.  ``workers``: target subset (default all).  For gang
        SPMD jobs a *stage fault* must reach EVERY member (a partial
        fault strands the rest in a collective); partial targeting is
        for vertex/coded tasks and for kill scenarios, where stranding
        the peers mid-collective is exactly the point."""
        self._sync_membership()
        cmd = {
            "kind": "set_fault", "stage": stage, "count": count,
            "cseq": self._next_cseq(),
        }
        if plan is not None:
            cmd["plan"] = plan
        targets = list(workers) if workers is not None else list(range(self.n))
        procs = []
        for i in targets:
            p = ClusterProcess(
                self._command_round_trip(i, cmd),
                name=f"fault-w{i}",
                affinities=[Affinity(f"worker{i}", hard=True)],
            )
            self.scheduler.schedule(p)
            procs.append(p)
        for i, p in zip(targets, procs):
            if not p.wait(30.0) or p.state is not ProcessState.COMPLETED:
                raise RuntimeError(f"fault injection on worker {i} failed: {p.error}")

    # -- teardown ------------------------------------------------------------
    def shutdown(self, graceful_timeout: float = 15.0) -> None:
        try:
            for i, h in self._handles.items():
                if self.launcher.poll(h) is None:
                    self.service.mailbox.set_prop(
                        self.job_id, f"cmd/{i}",
                        json.dumps(
                            {"kind": "exit", "cseq": self._next_cseq()}
                        ).encode(),
                    )
            deadline = time.monotonic() + graceful_timeout
            for h in self._handles.values():
                left = max(0.1, deadline - time.monotonic())
                try:
                    self.launcher.wait(h, timeout=left)
                except Exception:  # noqa: BLE001 — escalate to stop
                    self.launcher.stop(h)
        finally:
            self.scheduler.shutdown()
            self.service.close()
            self.events.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
