"""LocalJobSubmission — an N-process local job, end to end.

The reference's minimum distributed bar (``LinqToDryad/
LocalJobSubmission.cs:97-147``): one job-manager process plus N worker
processes on one machine, composed from the same parts a real cluster
uses.  This module is that composition for the TPU framework — it turns
the cluster layer's pieces into one working subsystem:

- ``ProcessService`` (mailbox + file server + block cache) is the
  control/data plane, hosted in the driver (C15 analog);
- ``LocalScheduler`` places the per-worker command round-trips on the
  workers' computer slots with hard affinities (C14);
- N ``cluster.worker`` OS processes join one JAX multi-controller
  runtime (``init_distributed``) so their devices form a single global
  mesh and each submitted plan executes as ONE gang-scheduled SPMD
  program spanning processes (cross-process collectives over gloo/ICI);
- ``ControlPlane`` barriers gate stage boundaries (start / durable-
  output) and carry membership, heartbeats, and failure reports;
- job packages ship the plan (``exec.jobpackage``), result partitions
  come back as partition files read through the file server's HTTP
  range reads (the managed-channel path, ``HttpReader.cs:78-110``).

Usage::

    with LocalJobSubmission(num_workers=2, devices_per_worker=4) as sub:
        table = sub.submit(query)
"""

from __future__ import annotations

import json
import os
import pickle
import socket
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Optional

import numpy as np

from dryad_tpu.cluster.interfaces import (
    Affinity,
    ClusterProcess,
    Computer,
    ProcessState,
)
from dryad_tpu.cluster.scheduler import LocalScheduler
from dryad_tpu.cluster.service import ProcessService, ServiceClient
from dryad_tpu.columnar.io import parse_partition_bytes
from dryad_tpu.columnar.schema import StringDictionary
from dryad_tpu.exec.jobpackage import pack_query
from dryad_tpu.utils.logging import get_logger

log = get_logger("dryad_tpu.cluster.localjob")


def _free_port() -> int:
    """Pick a coordinator port from a pid-derived candidate sequence so
    concurrent LocalJobSubmissions on one machine probe DIFFERENT ports
    (the bind-check-close window lasts until worker 0 rebinds it — a
    kernel-assigned port 0 can't be reserved across processes)."""
    base = 21000 + (os.getpid() * 131) % 20000
    for off in range(64):
        port = base + off
        try:
            with socket.socket() as s:
                s.bind(("127.0.0.1", port))
                return port
        except OSError:
            continue
    with socket.socket() as s:  # fall back to a kernel-assigned port
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class LocalJobSubmission:
    """Driver for N worker processes jointly executing submitted queries."""

    def __init__(
        self,
        num_workers: int = 2,
        devices_per_worker: int = 2,
        root: Optional[str] = None,
        worker_timeout: float = 300.0,
    ):
        self.n = num_workers
        self.k = devices_per_worker
        self.timeout = worker_timeout
        self.root = root or tempfile.mkdtemp(prefix="dryad-localjob-")
        self.job_id = f"job-{os.getpid()}-{int(time.time() * 1000)}"
        self.service = ProcessService(self.root)
        self.scheduler = LocalScheduler(
            [Computer(f"worker{i}", slots=1) for i in range(num_workers)]
        )
        self._client = ServiceClient("127.0.0.1", self.service.port)
        self._status_ver: Dict[int, int] = {}
        self._seq = 0
        self._cseq = 0  # unique per driver command; echoed in statuses
        self._procs: List[subprocess.Popen] = []
        self._logs: List[str] = []
        self._spawn()

    # -- worker process group (the Peloponnese "Worker" group) ---------------
    def _spawn(self) -> None:
        coord = f"127.0.0.1:{_free_port()}"
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("XLA_FLAGS", None)  # workers set their own device count
        repo = os.path.dirname(os.path.dirname(os.path.dirname(__file__)))
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        for i in range(self.n):
            log_path = os.path.join(self.root, f"worker{i}.log")
            self._logs.append(log_path)
            lf = open(log_path, "w")
            p = subprocess.Popen(
                [
                    sys.executable, "-m", "dryad_tpu.cluster.worker",
                    "--service-port", str(self.service.port),
                    "--job", self.job_id,
                    "--pid", str(i),
                    "--nproc", str(self.n),
                    "--devices-per-proc", str(self.k),
                    "--coordinator", coord,
                    "--root", self.root,
                ],
                stdout=lf, stderr=subprocess.STDOUT, env=env,
            )
            lf.close()
            self._procs.append(p)
        log.info(
            "spawned %d workers x %d devices (job %s, psvc :%d)",
            self.n, self.k, self.job_id, self.service.port,
        )

    def _worker_log_tail(self, i: int, nbytes: int = 2000) -> str:
        try:
            with open(self._logs[i], "rb") as fh:
                fh.seek(0, os.SEEK_END)
                size = fh.tell()
                fh.seek(max(0, size - nbytes))
                return fh.read().decode("utf-8", "replace")
        except OSError:
            return "<no log>"

    def _check_workers_alive(self) -> None:
        for i, p in enumerate(self._procs):
            rc = p.poll()
            if rc is not None:
                raise RuntimeError(
                    f"worker {i} exited rc={rc}; log tail:\n"
                    + self._worker_log_tail(i)
                )

    # -- submission ----------------------------------------------------------
    def _next_cseq(self) -> int:
        self._cseq += 1
        return self._cseq

    def _command_round_trip(self, i: int, cmd: Dict):
        """The GM->worker command protocol as a schedulable process fn:
        set ``cmd/<i>``, long-poll ``status/<i>`` (DVertexCommand /
        DVertexStatus, ``dvertexcommand.cpp:29-30``).  ``cmd`` must
        carry a unique ``cseq``; statuses echoing an older cseq (a run
        the driver already timed out on) are consumed and discarded so
        they can't be misattributed to this command."""

        def fn(proc: ClusterProcess) -> Dict:
            mb = self.service.mailbox
            mb.set_prop(self.job_id, f"cmd/{i}", json.dumps(cmd).encode())
            deadline = time.monotonic() + self.timeout
            while not proc.cancelled:
                after = self._status_ver.get(i, 0)
                got = mb.get_prop(self.job_id, f"status/{i}", after, timeout=1.0)
                if got is not None:
                    self._status_ver[i] = got[0]
                    st = json.loads(got[1])
                    if st.get("cseq") != cmd["cseq"]:
                        continue  # stale status from an abandoned command
                    if st.get("state") == "failed":
                        raise RuntimeError(
                            f"worker {i} failed: {st.get('error')}"
                        )
                    return st
                self._check_workers_alive()
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"worker {i}: no status after {self.timeout}s; "
                        f"log tail:\n" + self._worker_log_tail(i)
                    )
            return {"state": "canceled"}

        return fn

    def submit(self, query) -> Dict[str, np.ndarray]:
        """Pack the query, run it across the worker gang, assemble the
        result table (reference SubmitAndWait)."""
        self._check_workers_alive()
        self._seq += 1
        seq = self._seq
        job_dir = os.path.join(self.root, self.job_id, f"r{seq}")
        os.makedirs(job_dir, exist_ok=True)
        pkg_rel = f"{self.job_id}/r{seq}/job.pkg"
        pack_query(query, os.path.join(self.root, pkg_rel))
        result_rel = f"{self.job_id}/r{seq}/result"

        cmd = {
            "kind": "run", "package": pkg_rel,
            "result_dir": result_rel, "seq": seq, "cseq": self._next_cseq(),
        }
        procs = []
        for i in range(self.n):
            p = ClusterProcess(
                self._command_round_trip(i, cmd),
                name=f"run{seq}-w{i}",
                affinities=[Affinity(f"worker{i}", hard=True)],
            )
            self.scheduler.schedule(p)
            procs.append(p)
        for i, p in enumerate(procs):
            if not p.wait(self.timeout + 30.0):
                self.scheduler.cancel(p)
                raise TimeoutError(f"worker {i} command round-trip hung")
        failed = [p for p in procs if p.state is not ProcessState.COMPLETED]
        if failed:
            errs = "; ".join(f"{p.name}: {p.error}" for p in failed)
            raise RuntimeError(f"local job failed: {errs}")

        part_ids = sorted(
            {g for p in procs for g in p.result.get("parts", [])}
        )
        return self._assemble(query, result_rel, part_ids)

    def _assemble(
        self, query, result_rel: str, part_ids: List[int]
    ) -> Dict[str, np.ndarray]:
        """Fetch result partitions through the file server (HTTP range
        reads via the block cache) and decode to a host table."""
        import jax.numpy as jnp

        from dryad_tpu.columnar.batch import ColumnBatch

        cols_parts = [
            parse_partition_bytes(
                self._client.read_whole_file(f"{result_rel}/part{g}.dpf")
            )
            for g in part_ids
        ]
        dictionary = StringDictionary()
        dictionary._map.update(
            pickle.loads(
                self._client.read_whole_file(f"{result_rel}/dictionary.pkl")
            )
        )
        phys = query.schema.device_names()
        if not cols_parts:
            return {n: np.zeros(0) for n in query.schema.names}
        cols = {
            c: np.concatenate([p[c] for p in cols_parts]) for c in phys
        }
        nrows = len(next(iter(cols.values()), []))
        batch = ColumnBatch(
            {c: jnp.asarray(v) for c, v in cols.items()},
            jnp.ones((nrows,), jnp.bool_),  # workers wrote valid rows only
        )
        return batch.to_numpy(query.schema, dictionary)

    def inject_fault(self, stage: Optional[str], count: int = 1) -> None:
        """Broadcast a fault-injection command to every worker (remote
        SetFakeVertexFailure; ``stage=None`` clears).  All gang members
        must fault together — a partial fault would strand the rest in a
        collective."""
        cmd = {
            "kind": "set_fault", "stage": stage, "count": count,
            "cseq": self._next_cseq(),
        }
        procs = []
        for i in range(self.n):
            p = ClusterProcess(
                self._command_round_trip(i, cmd),
                name=f"fault-w{i}",
                affinities=[Affinity(f"worker{i}", hard=True)],
            )
            self.scheduler.schedule(p)
            procs.append(p)
        for i, p in enumerate(procs):
            if not p.wait(30.0) or p.state is not ProcessState.COMPLETED:
                raise RuntimeError(f"fault injection on worker {i} failed: {p.error}")

    # -- teardown ------------------------------------------------------------
    def shutdown(self, graceful_timeout: float = 15.0) -> None:
        try:
            for i in range(self.n):
                if self._procs[i].poll() is None:
                    self.service.mailbox.set_prop(
                        self.job_id, f"cmd/{i}",
                        json.dumps(
                            {"kind": "exit", "cseq": self._next_cseq()}
                        ).encode(),
                    )
            deadline = time.monotonic() + graceful_timeout
            for p in self._procs:
                left = max(0.1, deadline - time.monotonic())
                try:
                    p.wait(timeout=left)
                except subprocess.TimeoutExpired:
                    p.terminate()
                    try:
                        p.wait(timeout=5)
                    except subprocess.TimeoutExpired:
                        p.kill()
        finally:
            self.scheduler.shutdown()
            self.service.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
