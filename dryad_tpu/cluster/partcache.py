"""Gang-resident partition state — the worker-side partition cache.

A gang worker that just WROTE a result partition is the cheapest place
to read it back from: the level-(-1) ``combineparts`` merge (and any
later sub-command referencing the same partitions) would otherwise pay
a job-root round trip through the driver's file server for bytes this
process produced moments ago.  :class:`PartitionCache` keeps those
serialized partition blobs resident, keyed by CONTENT fingerprint (the
sha1 of the partition-file bytes — the same content-addressed keying as
``exec.operands.DeviceOperandPool``), so a reference is valid exactly
when the bytes it names still exist, regardless of which path produced
them or whether the file was since rewritten.

Eviction is LRU by a byte budget with spill-to-file (the
``cluster.service.BlockCache`` discipline): an evicted entry writes its
blob to the spill directory and stays SERVABLE — a cache "hit" that
reads the spill file is still a worker-local read, just a cold one,
counted separately so the telemetry can tell residency from mere
locality.  ``runbatch`` chains thus become worker-local dataflow: the
driver names partitions by fingerprint, the worker resolves them from
memory, spill, or (miss) the job root.
"""

from __future__ import annotations

import collections
import hashlib
import os
import threading
from typing import Dict, Optional


def content_fp(blob: bytes) -> str:
    """Content fingerprint of one serialized partition (sha1 hex)."""
    return hashlib.sha1(blob).hexdigest()


class PartitionCache:
    """Content-keyed LRU byte-budget cache of partition blobs."""

    def __init__(
        self,
        budget_bytes: int,
        spill_dir: Optional[str] = None,
    ):
        self.budget = int(budget_bytes)
        self.spill_dir = spill_dir
        self._lock = threading.Lock()
        self._mem: "collections.OrderedDict[str, bytes]" = (
            collections.OrderedDict()
        )
        self._mem_bytes = 0
        self._spilled: Dict[str, str] = {}
        self.hits = 0  # served from memory
        self.spill_hits = 0  # served from a spill file
        self.misses = 0  # caller must read the job root
        self.spills = 0  # evictions that wrote a spill file
        self.evictions = 0
        if spill_dir:
            os.makedirs(spill_dir, exist_ok=True)

    @property
    def mem_bytes(self) -> int:
        return self._mem_bytes

    def put(self, fp: str, blob: bytes) -> None:
        """Insert one blob under its content fingerprint.  A blob
        larger than the whole budget is not admitted (it would evict
        everything and then evict itself); a zero budget disables the
        cache entirely."""
        if self.budget <= 0 or len(blob) > self.budget:
            return
        with self._lock:
            old = self._mem.pop(fp, None)
            if old is not None:
                self._mem_bytes -= len(old)
            self._mem[fp] = blob
            self._mem_bytes += len(blob)
            while self._mem_bytes > self.budget and len(self._mem) > 1:
                old_fp, old = self._mem.popitem(last=False)
                self._mem_bytes -= len(old)
                self.evictions += 1
                if self.spill_dir and old_fp not in self._spilled:
                    sp = os.path.join(self.spill_dir, f"{old_fp}.part")
                    tmp = f"{sp}.tmp"
                    with open(tmp, "wb") as fh:
                        fh.write(old)
                    os.replace(tmp, sp)
                    self._spilled[old_fp] = sp
                    self.spills += 1

    def get(self, fp: str) -> Optional[bytes]:
        """Resolve a fingerprint from memory or spill; None = miss
        (the caller reads the job root and should :meth:`put` the
        bytes back so the next reference hits)."""
        with self._lock:
            blob = self._mem.get(fp)
            if blob is not None:
                self._mem.move_to_end(fp)
                self.hits += 1
                return blob
            sp = self._spilled.get(fp)
        if sp is not None and os.path.exists(sp):
            with open(sp, "rb") as fh:
                blob = fh.read()
            with self._lock:
                self.spill_hits += 1
            # re-admit: a spilled entry being referenced again is hot
            self.put(fp, blob)
            return blob
        with self._lock:
            self.misses += 1
        return None

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return {
                "hits": self.hits,
                "spill_hits": self.spill_hits,
                "misses": self.misses,
                "spills": self.spills,
                "evictions": self.evictions,
                "mem_bytes": self._mem_bytes,
                "entries": len(self._mem),
            }
