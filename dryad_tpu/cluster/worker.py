"""Worker process for N-process local jobs — the VertexHost analog.

The reference's worker node runs a long-lived daemon whose children poll
a versioned property mailbox for a ``DVertexCommand``, execute the
vertex, and post ``DVertexStatus`` back (``dvertexpncontrol.h:38-70``;
mailbox ``ProcessService.cs:42-126``).  This module is the TPU-native
worker: one OS process per mesh *slice* that

1. joins the JAX multi-controller runtime (``jax.distributed``) so the
   N workers' devices form ONE global mesh and compiled programs
   gang-launch across processes (cross-process collectives ride gloo on
   CPU, ICI/DCN on TPU),
2. announces itself on the driver's ProcessService control plane
   (membership + heartbeats, ``ControlPlane``),
3. loops on its ``cmd/<pid>`` mailbox property: a ``run`` command names
   a job package on the driver's file server; every worker executes the
   SAME SPMD plan jointly, then writes the partitions it *owns* (its
   addressable shards) as partition files for the driver to assemble —
   the persisted-channel-file egress of the reference
   (``DrPartitionFile.h:50``), and posts ``status/<pid>``.

Run as ``python -m dryad_tpu.cluster.worker --service-port P --job J
--pid I --nproc N --devices-per-proc K --coordinator H:P --root DIR``
(spawned by ``cluster.localjob.LocalJobSubmission``).
"""

from __future__ import annotations

import argparse
import json
import os
import pickle
import sys
import tempfile
import time
import traceback
from typing import Dict, List

from dryad_tpu.obs import tracectx


class _PackageCache:
    """Per-process cache of loaded job packages for vertex tasks.

    A ``runpart`` stream re-uses one loaded plan + context (and its
    compiled-stage cache) across partitions — the reference's VertexHost
    similarly keeps the vertex DLL loaded across vertex executions."""

    def __init__(self) -> None:
        self.key: str = ""
        self.query = None
        self.pristine: Dict = {}

    def load(self, rel: str, client):
        if self.key == rel and self.query is not None:
            return self.query, self.pristine
        import jax
        import numpy as np
        from jax.sharding import Mesh

        from dryad_tpu.exec.jobpackage import load_query
        from dryad_tpu.parallel.mesh import AXIS

        blob = client.read_whole_file(rel)
        with tempfile.NamedTemporaryFile(suffix=".pkg", delete=False) as fh:
            fh.write(blob)
            pkg_path = fh.name
        try:
            # Vertex tasks run on ONE local device — independent work,
            # not the gang mesh (DrStorageVertex-style per-partition
            # channels, not cohort collectives).
            local = Mesh(np.array(jax.local_devices()[:1]), (AXIS,))
            q = load_query(pkg_path, mesh=local)
        finally:
            os.unlink(pkg_path)
        self.key = rel
        self.query = q
        self.pristine = dict(q.ctx._bindings)
        return q, self.pristine


def _run_part(cmd: Dict, args, client, pkgs: _PackageCache,
              pcache=None) -> Dict:
    """Execute ONE vertex task: the plan restricted to input partition
    ``part`` of ``nparts``, on this worker's local device, writing the
    result as a partition file (the independent re-executable vertex of
    the reference, ``DrVertex.h:49`` — duplicate-safe: every attempt
    writes identical bytes and the rename is atomic)."""
    import numpy as np

    from dryad_tpu.cluster.partcache import content_fp
    from dryad_tpu.columnar.io import write_partition_file
    from dryad_tpu.exec.jobpackage import slice_binding

    q, pristine = pkgs.load(cmd["package"], client)
    part, nparts = int(cmd["part"]), int(cmd["nparts"])
    for nid, binding in pristine.items():
        q.ctx._bindings[nid] = slice_binding(binding, part, nparts)
    # rebinding invalidates cached binding fingerprints — a stale part-0
    # fingerprint would make checkpointing restore part 0 for every part
    q.ctx._binding_fp_cache.clear()
    batch = q.ctx._execute_device(q)
    valid = np.asarray(batch.valid)
    cols = {c: np.asarray(v)[valid] for c, v in batch.data.items()}
    out_dir = os.path.join(args.root, cmd["result_dir"])
    os.makedirs(out_dir, exist_ok=True)
    final = os.path.join(out_dir, f"part{part}.dpf")
    tmp = f"{final}.w{args.pid}.tmp"
    write_partition_file(tmp, cols)
    # fingerprint the serialized bytes BEFORE the rename (duplicates
    # write identical bytes, so every attempt reports the same fp) and
    # keep them gang-resident: a later sub-command naming this
    # partition by fp (level -1 combineparts) reads it from memory
    # instead of the job root
    with open(tmp, "rb") as fh:
        blob = fh.read()
    fp = content_fp(blob)
    os.replace(tmp, final)
    if pcache is not None:
        pcache.put(fp, blob)
    return {"state": "completed", "parts": [part], "fp": fp}


def _combine_parts(cmd: Dict, args, client, pkgs: _PackageCache,
                   pcache=None, wlog=None) -> Dict:
    """Level -1 of the gang combine tree: fold the un-finalized partial
    STATE of the vertex parts THIS worker won into one partial table
    (``exec.partial.merge_state_rows``) before anything ships to the
    driver — the reference's dynamic aggregation-tree rewrite
    (``DrDynamicAggregateManager.h:117-168``) pushed into the worker.
    Ships one ``wpart<w>.dpf`` plus a KeyRangeHistogram snapshot over
    DETERMINISTIC key hashes (``exec.partial.key_hash64`` — snapshots
    must mean the same ranges in every process), so driver ingress
    drops by this worker's vertex fan-in and the driver's level-0/1
    tree starts from per-worker partials.  Part bytes resolve through
    the :class:`~dryad_tpu.cluster.partcache.PartitionCache` by
    content fingerprint — this worker wrote them moments ago, so the
    common case never touches the job root."""
    import numpy as np

    from dryad_tpu.cluster.partcache import content_fp
    from dryad_tpu.columnar.batch import decode_physical_table
    from dryad_tpu.columnar.io import (
        parse_partition_bytes,
        write_partition_file,
    )
    from dryad_tpu.exec import faults
    from dryad_tpu.exec.partial import key_hash64, merge_state_rows
    from dryad_tpu.obs.metrics import KeyRangeHistogram

    faults.registry.maybe_fail("combineparts")
    if faults.registry.maybe_kill("combineparts"):
        # mid-level-(-1) chaos: the process dies between winning its
        # parts and shipping the folded partial — the driver must fall
        # back to flat assembly (the part files are durable) and the
        # blackbox must be on disk before the process vanishes
        from dryad_tpu.obs import flightrec

        if wlog is not None:
            wlog.emit(
                "worker_killed_injected", stage=-1, name="combineparts"
            )
        flightrec.dump_now("worker_killed:combineparts")
        os._exit(113)

    q, _pristine = pkgs.load(cmd["package"], client)
    keys = list(cmd["keys"])
    red = dict(cmd["red"])
    tables = []
    read_bytes = 0
    hits = misses = 0
    for spec in cmd["parts"]:
        blob = None
        fp = spec.get("fp")
        if pcache is not None and fp:
            blob = pcache.get(fp)
        if blob is None:
            misses += 1
            blob = client.read_whole_file(
                f"{cmd['result_dir']}/part{spec['part']}.dpf"
            )
            read_bytes += len(blob)
            if pcache is not None:
                pcache.put(fp or content_fp(blob), blob)
        else:
            hits += 1
        host = parse_partition_bytes(blob)
        # decode to logical columns WITHOUT the dictionary: string keys
        # stay raw Hash64 codes (cross-process deterministic), so the
        # fold groups on codes and the driver decodes once at assembly
        tables.append(
            decode_physical_table(q.schema, slice(None), host, None)
        )
    cols = {c: np.concatenate([t[c] for t in tables]) for c in tables[0]}
    in_rows = int(len(next(iter(cols.values()), [])))
    merged = merge_state_rows(cols, keys, red)
    out_rows = int(len(merged[keys[0]])) if keys else 0
    kr = KeyRangeHistogram(int(cmd.get("ranges", 64) or 64))
    if keys and out_rows:
        kr.observe(key_hash64(merged, keys))
    out_dir = os.path.join(args.root, cmd["result_dir"])
    os.makedirs(out_dir, exist_ok=True)
    wname = f"wpart{int(cmd['wid'])}.dpf"
    final = os.path.join(out_dir, wname)
    tmp = f"{final}.w{args.pid}.tmp"
    write_partition_file(tmp, merged)
    with open(tmp, "rb") as fh:
        out_blob = fh.read()
    out_fp = content_fp(out_blob)
    os.replace(tmp, final)
    if pcache is not None:
        pcache.put(out_fp, out_blob)
    snap = {
        k: (v.tolist() if hasattr(v, "tolist") else v)
        for k, v in kr.snapshot().items()
    }
    return {
        "state": "completed", "wfile": wname, "fp": out_fp,
        "parts": [int(s["part"]) for s in cmd["parts"]],
        "rows": out_rows, "in_rows": in_rows,
        "bytes": len(out_blob), "read_bytes": read_bytes,
        "cache_hits": hits, "cache_misses": misses,
        "snapshot": snap,
    }


def _run_coded(cmd: Dict, args, client, pkgs: _PackageCache) -> Dict:
    """Execute ONE CODED vertex (``dryad_tpu.redundancy``): run the
    partial plan over each shard in the vertex's support, linearly
    combine the partial tables with the generator coefficients
    (``exec.partial.coded_combine`` — exact int64 for integer states),
    and write the coded partial as ``cpart<j>.dpf``.  A systematic
    vertex (support of one shard, coefficient 1) does exactly one
    shard's work; a parity vertex pays the full-support redundancy
    work that buys any-k-of-n reconstruction."""
    from dryad_tpu.columnar.io import write_partition_file
    from dryad_tpu.exec.jobpackage import slice_binding
    from dryad_tpu.exec.partial import coded_combine

    q, pristine = pkgs.load(cmd["package"], client)
    nparts = int(cmd["nparts"])
    tables = []
    for part in cmd["parts"]:
        for nid, binding in pristine.items():
            q.ctx._bindings[nid] = slice_binding(
                binding, int(part), nparts
            )
        # stale fingerprints would restore another part's checkpoint
        q.ctx._binding_fp_cache.clear()
        batch = q.ctx._execute_device(q)
        tables.append(batch.to_numpy(q.schema, q.ctx.dictionary))
    combined = coded_combine(
        tables, [int(c) for c in cmd["coeffs"]],
        list(cmd["keys"]), list(cmd["state"]),
    )
    out_dir = os.path.join(args.root, cmd["result_dir"])
    os.makedirs(out_dir, exist_ok=True)
    j = int(cmd["coded"])
    final = os.path.join(out_dir, f"cpart{j}.dpf")
    tmp = f"{final}.w{args.pid}.tmp"
    write_partition_file(tmp, combined)
    os.replace(tmp, final)
    return {"state": "completed", "coded": [j]}


def _absorb_ctx_events(wlog, ctx) -> None:
    """Move the job context's engine events (stage spans, xla_compile,
    stream events) into the worker's telemetry log so they ship to the
    driver with the next batch."""
    if wlog is None or ctx is None:
        return
    for ev in ctx.events.drain():
        wlog.absorb(ev)


def _run_command(cmd: Dict, args, client, cp, wlog=None) -> Dict:
    """Execute one ``run`` command: fetch the package, run the plan SPMD
    over the global mesh, write owned result partitions."""
    import numpy as np

    from dryad_tpu.columnar.io import write_partition_file
    from dryad_tpu.exec.jobpackage import load_query
    from dryad_tpu.parallel.mesh import make_mesh, num_partitions

    # Fetch the package through the driver's file server (HTTP range
    # reads via the block cache — the managed-channel read path).
    blob = client.read_whole_file(cmd["package"])
    with tempfile.NamedTemporaryFile(suffix=".pkg", delete=False) as fh:
        fh.write(blob)
        pkg_path = fh.name
    try:
        mesh = make_mesh(args.nproc * args.devices_per_proc)
        q = load_query(pkg_path, mesh=mesh)
        ctx = q.ctx
        # Everyone present before tracing/ingest: a straggler joining
        # mid-collective would deadlock the gang, so gate here where the
        # failure is a clean timeout instead (DrStartClique semantics).
        cp.barrier(f"start/{cmd['seq']}", args.nproc)
        batch = ctx._execute_device(q)
        P = num_partitions(mesh)
        cap = batch.capacity // P

        out_dir = os.path.join(args.root, cmd["result_dir"])
        os.makedirs(out_dir, exist_ok=True)
        # Each addressable shard of the result IS one owned partition;
        # write its valid rows as a partition file.
        vshards = {
            int(s.index[0].start or 0): np.asarray(s.data)
            for s in batch.valid.addressable_shards
        }
        col_shards = {
            c: {
                int(s.index[0].start or 0): np.asarray(s.data)
                for s in arr.addressable_shards
            }
            for c, arr in batch.data.items()
        }
        parts: List[int] = []
        for start in sorted(vshards):
            gid = start // cap
            mask = vshards[start]
            cols = {c: col_shards[c][start][mask] for c in col_shards}
            write_partition_file(
                os.path.join(out_dir, f"part{gid}.dpf"), cols
            )
            parts.append(gid)
        if args.pid == 0:
            # The dictionary is built at ingest (identically in every
            # worker); ship one copy so the driver can decode strings.
            with open(os.path.join(out_dir, "dictionary.pkl"), "wb") as fh:
                pickle.dump(dict(ctx.dictionary._map), fh)
        # All partitions durable before anyone reports success — the
        # driver may start reading as soon as one status arrives.
        cp.barrier(f"done/{cmd['seq']}", args.nproc)
        _absorb_ctx_events(wlog, ctx)
        return {"state": "completed", "parts": parts}
    finally:
        os.unlink(pkg_path)


def _resolve_pcache(pstate: Dict, cmd: Dict, args):
    """Lazily build this worker's :class:`PartitionCache` the first time
    a command carries a ``cache_bytes`` budget (the driver forwards
    ``config.gang_partition_cache_bytes``); a zero/absent budget runs
    the command cache-less without disturbing an existing cache."""
    budget = int(cmd.get("cache_bytes", 0) or 0)
    if budget <= 0:
        return None
    pc = pstate.get("pcache")
    if pc is None:
        from dryad_tpu.cluster.partcache import PartitionCache

        pc = PartitionCache(
            budget,
            spill_dir=os.path.join(args.root, f".pcache-w{args.pid}"),
        )
        pstate["pcache"] = pc
    return pc


def _exec_one(cmd: Dict, args, client, cp, pkgs, delay, wtracer, wlog,
              pstate=None) -> Dict:
    """Execute one run/runpart/runcoded/combineparts command and return
    its status dict (no cseq — the caller stamps the mailbox echo).
    Failures are classified per command: a failed status carries the
    error, and the worker keeps serving (report-and-continue, never
    crash the loop)."""
    pstate = pstate if pstate is not None else {}
    try:
        # Re-activate the query's trace context from the mailbox
        # envelope: every span this command produces (and the engine
        # events absorbed from the job context) ships back qid-stamped
        # on the telemetry channel, joining the driver's fold.
        with tracectx.activate(
            tracectx.TraceContext.from_wire(cmd.get("trace"))
        ), wtracer.span(
            cmd["kind"], cat="worker", seq=cmd.get("seq"),
            part=cmd.get("part", cmd.get("coded")),
        ):
            if cmd["kind"] in ("runpart", "runcoded"):
                # injected straggler applies to coded vertices too, so
                # coded-vs-duplicate comparisons stall the same way
                if delay["count"] > 0:
                    delay["count"] -= 1
                    time.sleep(delay["seconds"])
                status = (
                    _run_part(cmd, args, client, pkgs,
                              pcache=_resolve_pcache(pstate, cmd, args))
                    if cmd["kind"] == "runpart"
                    else _run_coded(cmd, args, client, pkgs)
                )
                _absorb_ctx_events(
                    wlog,
                    pkgs.query.ctx if pkgs.query is not None else None,
                )
            elif cmd["kind"] == "combineparts":
                status = _combine_parts(
                    cmd, args, client, pkgs,
                    pcache=_resolve_pcache(pstate, cmd, args), wlog=wlog,
                )
            else:
                status = _run_command(cmd, args, client, cp, wlog=wlog)
    except Exception as e:  # noqa: BLE001 — report, keep serving
        traceback.print_exc()
        info = {"error": f"{type(e).__name__}: {e}", "cmd": cmd}
        cp.report_failure(info)
        status = {"state": "failed", "error": info["error"]}
    return status


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--service-host", default="127.0.0.1")
    ap.add_argument("--service-port", type=int, required=True)
    ap.add_argument("--job", required=True)
    ap.add_argument("--pid", type=int, required=True)
    ap.add_argument("--nproc", type=int, required=True)
    ap.add_argument("--devices-per-proc", type=int, default=1)
    ap.add_argument("--coordinator", required=True)
    ap.add_argument("--root", required=True)
    args = ap.parse_args(argv)

    # Backend setup MUST precede any backend query: pin CPU with K local
    # devices, select gloo for cross-process CPU collectives, then join
    # the multi-controller runtime.  (On real TPU pods the distributed
    # runtime is joined the same way with the default backend.)
    from dryad_tpu.parallel.mesh import force_cpu_backend

    force_cpu_backend(args.devices_per_proc)
    import jax

    if args.nproc > 1:
        # gloo needs the distributed client; a single-member gang never
        # initializes one (init_distributed no-ops at nproc<=1), and
        # some jaxlibs refuse gloo without it — so only select it when
        # cross-process collectives will actually exist.
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass  # older jaxlib: single CPU collective impl

    from dryad_tpu.parallel.multihost import ControlPlane, init_distributed

    init_distributed(args.coordinator, args.nproc, args.pid)

    from dryad_tpu.cluster.service import ServiceClient

    client = ServiceClient(args.service_host, args.service_port)
    cp = ControlPlane(args.job, args.pid, client=client)
    cp.announce({"devices": args.devices_per_proc, "ospid": os.getpid()})
    cp.start_heartbeat()

    # Worker-local telemetry (obs): spans around command execution plus
    # the job context's engine events, shipped back to the driver
    # through the ControlPlane mailbox after every command — the
    # reporter-inside-the-GM analog, aggregated in cluster.localjob.
    from dryad_tpu.exec.events import EventLog
    from dryad_tpu.obs.span import Tracer

    wlog = EventLog(None, mem_cap=8192)
    wtracer = Tracer(wlog)

    # Flight recorder (obs.flightrec): the worker's ring survives what
    # telemetry shipping cannot — a process death takes un-shipped
    # events with it, so the ring dumps to the SHARED job root
    # (blackbox-<ospid>.json) on any exit: atexit, SIGTERM, unhandled
    # exceptions, and the chaos os._exit path (dumped explicitly by
    # the executor before _exit).  tools/blackbox.py merges these with
    # the driver's dump into one clock-corrected timeline.
    from dryad_tpu.obs import flightrec

    flightrec.install_recorder(
        capacity=2048,
        snapshot_s=1.0,
        dump_dir=os.path.join(args.root, "blackbox"),
        role=f"worker-{args.pid}",
        worker=args.pid,
        events=wlog,
        atexit_dump=True,
        signals=True,
    )
    flightrec.get_recorder().set_info(job=args.job, nproc=args.nproc)

    after = 0
    pkgs = _PackageCache()
    pstate: Dict = {}  # lazy PartitionCache, keyed setup per job
    delay = {"seconds": 0.0, "count": 0}  # injected straggler behavior
    while True:
        got = client.get_prop(args.job, f"cmd/{args.pid}", after, timeout=2.0)
        if got is None:
            continue
        after, body = got
        cmd = json.loads(body)
        # Every status echoes the command's unique id ("cseq") so the
        # driver can discard stale statuses from a command it already
        # gave up on (e.g. a run that outlived its timeout).
        cseq = cmd.get("cseq")
        if cmd.get("ack"):
            # Windowed envelope: acknowledge the DEQUEUE itself, before
            # executing — the command mailbox is a latest-value slot,
            # and the overlapped feed may only overwrite it once this
            # envelope has provably left it.
            try:
                client.set_prop(args.job, str(cmd["ack"]), b"1")
            except Exception:  # noqa: BLE001 — driver timeout surfaces it
                pass
        if cmd["kind"] == "exit":
            client.set_prop(
                args.job, f"status/{args.pid}",
                json.dumps({"state": "exited", "cseq": cseq}).encode(),
            )
            cp.stop_heartbeat()
            return 0
        if cmd["kind"] == "set_fault":
            # Remote fault injection (SetFakeVertexFailure over the
            # command mailbox).  Stage faults must reach EVERY gang
            # member (a fault raised in only some would strand the
            # others in a collective); a seeded FaultPlan — including
            # worker_kill_prob process kills, the mid-collective-death
            # chaos scenario — may target a worker subset, where
            # stranding the peers is exactly what is under test.
            from dryad_tpu.exec import faults

            if cmd.get("plan"):
                faults.install_plan(faults.FaultPlan(**cmd["plan"]))
            elif cmd.get("stage"):
                faults.set_fake_stage_failure(
                    cmd["stage"], int(cmd.get("count", 1))
                )
            else:
                faults.clear_faults()
            client.set_prop(
                args.job, f"status/{args.pid}",
                json.dumps({"state": "fault_set", "cseq": cseq}).encode(),
            )
            continue
        if cmd["kind"] == "set_delay":
            # Injected straggler (per-worker, unlike set_fault's gang
            # broadcast): the next ``count`` vertex tasks on THIS worker
            # stall ``seconds`` before executing — the slow-machine
            # scenario speculative duplication exists for
            # (``DrStageStatistics.cpp:93`` outlier model).
            delay["seconds"] = float(cmd.get("seconds", 0.0))
            delay["count"] = int(cmd.get("count", 0))
            client.set_prop(
                args.job, f"status/{args.pid}",
                json.dumps({"state": "delay_set", "cseq": cseq}).encode(),
            )
            continue
        if cmd["kind"] == "runbatch":
            # Batched command stream: execute the sub-commands
            # back-to-back and ship ONE aggregated status — K mailbox
            # round trips become one (the cseq echo covers the batch).
            # A failed sub-command does NOT stop the batch: every gang
            # member executes the same list in the same order, keeping
            # the per-command start/done barriers aligned, and the
            # per-command statuses preserve fault classification.
            results = []
            first_error = None
            for sub in cmd["cmds"]:
                # envelope-level trace context covers sub-commands that
                # didn't carry their own
                if cmd.get("trace") and not sub.get("trace"):
                    sub["trace"] = cmd["trace"]
                sub_t0 = time.perf_counter()
                st = _exec_one(sub, args, client, cp, pkgs, delay,
                               wtracer, wlog, pstate=pstate)
                # per-sub wall clock rides in the aggregated status so
                # the driver's StageStatistics sees K real durations,
                # not one batch-wide dt smeared across K plans
                st["seconds"] = round(time.perf_counter() - sub_t0, 6)
                results.append(st)
                if st.get("state") == "failed" and first_error is None:
                    first_error = st.get("error")
            status = {
                "state": "failed" if first_error else "completed",
                "results": results,
            }
            if first_error:
                status["error"] = first_error
        elif cmd["kind"] in ("run", "runpart", "runcoded", "combineparts"):
            status = _exec_one(cmd, args, client, cp, pkgs, delay,
                               wtracer, wlog, pstate=pstate)
        else:
            continue  # unknown command kind: ignore, keep serving
        # telemetry ships BEFORE the status post: the driver drains
        # right after it sees the status, so shipping after would
        # race the batch against the drain
        try:
            cp.ship_telemetry(wlog.drain())
        except Exception:  # noqa: BLE001 — telemetry is best-effort
            pass
        status["cseq"] = cseq
        client.set_prop(
            args.job, cmd.get("skey") or f"status/{args.pid}",
            json.dumps(status).encode(),
        )


if __name__ == "__main__":
    sys.exit(main())
