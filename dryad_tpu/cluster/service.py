"""Per-node process service — mailbox + file server + block cache.

The analog of the reference's per-worker daemon (``ProcessService/``):

- **versioned property mailbox** (``ProcessService.cs:42-126``
  ``ValueVersion``/``MailboxRecord``): the control plane.  The job
  manager sets a command property; the worker long-polls for a version
  newer than the last it saw, and posts status back the same way.
- **file server** (``HttpServer.cs:498,631-667`` FileServer): serves
  channel/partition files by ``?offset=&length=`` range reads so remote
  consumers stream persisted stage outputs over HTTP/DCN.
- **block cache with spill-to-disk** (``Cache.cs:32``,
  ``SpillMachine.cs:30``): hot file blocks stay in memory under a byte
  budget; evicted blocks spill to a local directory before re-reading
  from the source.

In the TPU framework this service is the DCN-side control/data plane for
multi-host jobs; intra-slice data movement rides ICI collectives inside
compiled programs and never touches it.
"""

from __future__ import annotations

import collections
import http.client
import http.server
import os
import threading
import time
import urllib.parse
from typing import Dict, List, Optional, Tuple

from dryad_tpu.utils.logging import get_logger

log = get_logger("dryad_tpu.cluster.service")

DEFAULT_BLOCK = 2 * 1024 * 1024  # 2MB blocks, HttpServer.cs FileServer


class Mailbox:
    """Versioned key-value property store, long-poll reads."""

    def __init__(self) -> None:
        self._lock = threading.Condition()
        # (pid, name) -> (version, value)
        self._props: Dict[Tuple[str, str], Tuple[int, bytes]] = {}
        self._closed = False
        # in-process observers: fn(pid, name, version, value), called
        # after every set_prop OUTSIDE the mailbox lock (a watch that
        # re-enters the mailbox must not deadlock).  Wake signal only —
        # two racing sets may deliver out of order; observers that care
        # must re-read and compare versions.
        self._watches: List = []

    @property
    def closed(self) -> bool:
        return self._closed

    def close(self) -> None:
        """Mark closed and wake every blocked long-poll immediately.
        Subsequent ``get_prop`` calls return without waiting."""
        with self._lock:
            self._closed = True
            self._lock.notify_all()

    def add_watch(self, fn) -> None:
        with self._lock:
            self._watches.append(fn)

    def remove_watch(self, fn) -> None:
        with self._lock:
            try:
                self._watches.remove(fn)
            except ValueError:
                pass

    def set_prop(self, pid: str, name: str, value: bytes) -> int:
        with self._lock:
            ver = self._props.get((pid, name), (0, b""))[0] + 1
            self._props[(pid, name)] = (ver, value)
            self._lock.notify_all()
            watches = tuple(self._watches)
        for fn in watches:
            try:
                fn(pid, name, ver, value)
            except Exception:  # noqa: BLE001 — a watch must not poison sets
                log.exception("mailbox watch failed for %s/%s", pid, name)
        return ver

    def get_prop(
        self,
        pid: str,
        name: str,
        after_version: int = 0,
        timeout: float = 0.0,
    ) -> Optional[Tuple[int, bytes]]:
        """Return (version, value) once version > after_version, else
        None after ``timeout`` (0 = non-blocking) or as soon as the
        mailbox closes (shutdown must not wait out long-polls)."""
        deadline = time.monotonic() + timeout
        with self._lock:
            while True:
                cur = self._props.get((pid, name))
                if cur is not None and cur[0] > after_version:
                    return cur
                left = deadline - time.monotonic()
                if left <= 0 or self._closed:
                    return None
                self._lock.wait(left)

    def del_prop(self, pid: str, name: str) -> None:
        """Drop a property outright (result GC after delivery)."""
        with self._lock:
            self._props.pop((pid, name), None)

    def processes(self) -> List[str]:
        with self._lock:
            return sorted({pid for pid, _ in self._props})


class BlockCache:
    """Memory block cache with LRU spill-to-disk (Cache + SpillMachine)."""

    def __init__(
        self,
        root: str,
        spill_dir: Optional[str] = None,
        memory_budget: int = 64 * 1024 * 1024,
        block_size: int = DEFAULT_BLOCK,
    ):
        self.root = os.path.abspath(root)
        self.block_size = block_size
        self.memory_budget = memory_budget
        self.spill_dir = spill_dir
        self._lock = threading.Lock()
        self._mem: "collections.OrderedDict[Tuple[str, int], bytes]" = (
            collections.OrderedDict()
        )
        self._mem_bytes = 0
        self._spilled: Dict[Tuple[str, int], str] = {}
        self.hits = self.misses = self.spills = 0
        if spill_dir:
            os.makedirs(spill_dir, exist_ok=True)

    def _source_path(self, rel: str) -> str:
        # realpath resolves symlinks too, so a link under root pointing
        # outside cannot bypass the containment check
        path = os.path.realpath(os.path.join(self.root, rel))
        root = os.path.realpath(self.root)
        if not path.startswith(root + os.sep) and path != root:
            raise PermissionError(f"path escapes root: {rel}")
        return path

    def _load_block(self, rel: str, bi: int) -> bytes:
        key = (rel, bi)
        spath = self._spilled.get(key)
        if spath is not None and os.path.exists(spath):
            with open(spath, "rb") as fh:
                return fh.read()
        with open(self._source_path(rel), "rb") as fh:
            fh.seek(bi * self.block_size)
            return fh.read(self.block_size)

    def _put(self, key: Tuple[str, int], block: bytes) -> None:
        old = self._mem.pop(key, None)
        if old is not None:
            self._mem_bytes -= len(old)
        self._mem[key] = block
        self._mem_bytes += len(block)
        while self._mem_bytes > self.memory_budget and len(self._mem) > 1:
            old_key, old = self._mem.popitem(last=False)
            self._mem_bytes -= len(old)
            if self.spill_dir and old_key not in self._spilled:
                import hashlib

                digest = hashlib.sha1(
                    f"{old_key[0]}:{old_key[1]}".encode()
                ).hexdigest()
                sp = os.path.join(self.spill_dir, f"{digest}.blk")
                with open(sp, "wb") as fh:
                    fh.write(old)
                self._spilled[old_key] = sp
                self.spills += 1

    def invalidate(self, rel: str) -> None:
        """Drop cached blocks of one file (after an overwrite)."""
        with self._lock:
            for key in [k for k in self._mem if k[0] == rel]:
                self._mem_bytes -= len(self._mem.pop(key))
            for key in [k for k in self._spilled if k[0] == rel]:
                sp = self._spilled.pop(key)
                try:
                    os.unlink(sp)
                except OSError:
                    pass

    def read(self, rel: str, offset: int, length: int) -> bytes:
        """Range read through the cache."""
        out = bytearray()
        end = offset + length
        while offset < end:
            bi = offset // self.block_size
            key = (rel, bi)
            with self._lock:
                block = self._mem.get(key)
                if block is not None:
                    self._mem.move_to_end(key)
                    self.hits += 1
            if block is None:
                block = self._load_block(rel, bi)
                with self._lock:
                    self.misses += 1
                    # a short tail block may still be growing (reader
                    # racing a writer) — serving it from cache later
                    # would permanently truncate the file
                    if len(block) == self.block_size:
                        self._put(key, block)
            lo = offset - bi * self.block_size
            take = min(end - offset, len(block) - lo)
            if take <= 0:
                break  # EOF
            out += block[lo : lo + take]
            offset += take
        return bytes(out)

    def file_size(self, rel: str) -> int:
        return os.path.getsize(self._source_path(rel))


class _Handler(http.server.BaseHTTPRequestHandler):
    """Routes:
    GET  /prop/<pid>/<name>?after=V&timeout=T   long-poll property read
    POST /prop/<pid>/<name>                     set property (body=value)
    GET  /file/<relpath>?offset=O&length=L      range read via block cache
         (&compress=1: zlib-deflate the payload — the channel-boundary
         compression transform of the reference, ``dryadvertex.h:33-48``)
    PUT  /file/<relpath>                        write a file under root
         (X-Encoding: deflate body accepted) — the bulk-store egress
    GET  /status                                service health/stats
    """

    service: "ProcessService"

    def log_message(self, fmt, *args):  # quiet
        pass

    def _send(self, code: int, body: bytes, headers: Dict[str, str] = {}):
        self.send_response(code)
        self.send_header("Content-Length", str(len(body)))
        for k, v in headers.items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        u = urllib.parse.urlparse(self.path)
        q = urllib.parse.parse_qs(u.query)
        parts = u.path.strip("/").split("/")
        try:
            if parts[0] == "prop" and len(parts) >= 3:
                pid, name = parts[1], "/".join(parts[2:])
                after = int(q.get("after", ["0"])[0])
                timeout = float(q.get("timeout", ["0"])[0])
                got = self.service.mailbox.get_prop(pid, name, after, timeout)
                if got is None:
                    self._send(204, b"")
                else:
                    ver, val = got
                    self._send(200, val, {"X-Version": str(ver)})
            elif parts[0] == "file" and len(parts) >= 2:
                # Per-segment unquote: every shipped client percent-
                # encodes (enc=1 marks the encoding generation for
                # future format changes).  WIRE-FORMAT LOCKSTEP: a
                # client that does NOT encode must not send literal '%'
                # in paths — the decode here would corrupt them.  The
                # root realpath check below still contains any
                # reintroduced separators.
                rel = "/".join(urllib.parse.unquote(p) for p in parts[1:])
                offset = int(q.get("offset", ["0"])[0])
                length = int(
                    q.get("length", [str(self.service.cache.block_size)])[0]
                )
                data = self.service.cache.read(rel, offset, length)
                headers = {
                    "X-File-Size": str(self.service.cache.file_size(rel)),
                    "X-Raw-Length": str(len(data)),
                }
                if q.get("compress", ["0"])[0] == "1":
                    import zlib

                    data = zlib.compress(data, 1)
                    headers["X-Encoding"] = "deflate"
                self._send(200, data, headers)
            elif parts[0] == "status":
                c = self.service.cache
                body = (
                    f'{{"hits": {c.hits}, "misses": {c.misses}, '
                    f'"spills": {c.spills}}}'
                ).encode()
                self._send(200, body, {"Content-Type": "application/json"})
            else:
                self._send(404, b"not found")
        except (FileNotFoundError, PermissionError) as e:
            self._send(404, str(e).encode())
        except Exception as e:  # noqa: BLE001
            self._send(500, str(e).encode())

    def do_POST(self):
        u = urllib.parse.urlparse(self.path)
        parts = u.path.strip("/").split("/")
        try:
            if parts[0] == "prop" and len(parts) >= 3:
                n = int(self.headers.get("Content-Length", "0"))
                body = self.rfile.read(n)
                pid, name = parts[1], "/".join(parts[2:])
                ver = self.service.mailbox.set_prop(pid, name, body)
                self._send(200, b"", {"X-Version": str(ver)})
            else:
                self._send(404, b"not found")
        except Exception as e:  # noqa: BLE001
            self._send(500, str(e).encode())

    def do_PUT(self):
        u = urllib.parse.urlparse(self.path)
        parts = u.path.strip("/").split("/")
        try:
            if parts[0] == "file" and len(parts) >= 2:
                n = int(self.headers.get("Content-Length", "0"))
                body = self.rfile.read(n)
                if self.headers.get("X-Encoding") == "deflate":
                    import zlib

                    body = zlib.decompress(body)
                # same decode + lockstep rule as do_GET
                self.service.write_file(
                    "/".join(urllib.parse.unquote(p) for p in parts[1:]),
                    body,
                )
                self._send(200, b"")
            else:
                self._send(404, b"not found")
        except PermissionError as e:
            self._send(403, str(e).encode())
        except Exception as e:  # noqa: BLE001
            self._send(500, str(e).encode())


class ProcessService:
    """The per-node daemon: mailbox + file server on one HTTP port."""

    def __init__(
        self,
        root: str,
        port: int = 0,
        spill_dir: Optional[str] = None,
        memory_budget: int = 64 * 1024 * 1024,
        block_size: int = DEFAULT_BLOCK,
        host: str = "127.0.0.1",
    ):
        """``host``: bind address — loopback by default; "0.0.0.0" for
        a service remote workers must reach (multi-host jobs)."""
        self.root = os.path.abspath(root)
        self.mailbox = Mailbox()
        self.cache = BlockCache(
            self.root, spill_dir, memory_budget, block_size
        )
        handler = type("BoundHandler", (_Handler,), {"service": self})
        self._httpd = http.server.ThreadingHTTPServer((host, port), handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="dryad-psvc", daemon=True
        )
        self._thread.start()
        log.info("ProcessService on port %d root=%s", self.port, self.root)

    def write_file(self, rel: str, data: bytes) -> None:
        """Write a file under the served root (bulk-store ingest path);
        atomic replace, stale cache blocks dropped."""
        path = os.path.realpath(os.path.join(self.root, rel))
        root = os.path.realpath(self.root)
        if not path.startswith(root + os.sep) and path != root:
            raise PermissionError(f"path escapes root: {rel}")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.put.tmp"
        with open(tmp, "wb") as fh:
            fh.write(data)
        os.replace(tmp, path)
        self.cache.invalidate(rel)

    def close(self) -> None:
        # Close the mailbox FIRST: ThreadingHTTPServer.shutdown() joins
        # its handler threads, and any handler parked in a get_prop
        # long-poll would otherwise hold shutdown hostage for the full
        # poll timeout (regression: close took 30s with one 30s
        # long-poll outstanding).
        self.mailbox.close()
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class ServiceClient:
    """HTTP client for a remote ProcessService (HttpReader/ICluster side,
    ``managedchannel/HttpReader.cs:78-110``)."""

    def __init__(self, host: str, port: int):
        self.host, self.port = host, port
        self.wire_bytes = 0  # bytes on the wire (post-compression)
        self.raw_bytes = 0  # decoded payload bytes
        self._acct = threading.Lock()

    def _account(self, wire: int, raw: int) -> None:
        with self._acct:
            self.wire_bytes += wire
            self.raw_bytes += raw

    def _conn(self, timeout: float = 30.0) -> http.client.HTTPConnection:
        return http.client.HTTPConnection(self.host, self.port, timeout=timeout)

    def set_prop(self, pid: str, name: str, value: bytes) -> int:
        c = self._conn()
        try:
            c.request("POST", f"/prop/{pid}/{name}", body=value)
            r = c.getresponse()
            r.read()
            if r.status != 200:
                raise RuntimeError(f"set_prop failed: {r.status}")
            return int(r.getheader("X-Version", "0"))
        finally:
            c.close()

    def get_prop(
        self, pid: str, name: str, after_version: int = 0, timeout: float = 0.0
    ) -> Optional[Tuple[int, bytes]]:
        # socket deadline must outlast the server-side long-poll window
        c = self._conn(timeout=timeout + 30.0)
        try:
            c.request(
                "GET",
                f"/prop/{pid}/{name}?after={after_version}&timeout={timeout}",
            )
            r = c.getresponse()
            body = r.read()
            if r.status == 204:
                return None
            if r.status != 200:
                raise RuntimeError(f"get_prop failed: {r.status} {body!r}")
            return int(r.getheader("X-Version", "0")), body
        finally:
            c.close()

    def read_file(
        self,
        rel: str,
        offset: int = 0,
        length: int = DEFAULT_BLOCK,
        compress: bool = False,
    ) -> bytes:
        """One range read; ``compress`` applies the wire-compression
        transform (zlib over the DCN hop, ``dryadvertex.h:33-48``).
        ``self.wire_bytes``/``self.raw_bytes`` accumulate transfer
        accounting for observability."""
        c = self._conn()
        try:
            quoted = urllib.parse.quote(rel, safe="/")
            url = f"/file/{quoted}?offset={offset}&length={length}&enc=1"
            if compress:
                url += "&compress=1"
            c.request("GET", url)
            r = c.getresponse()
            body = r.read()
            if r.status == 404:
                raise FileNotFoundError(rel)
            if r.status != 200:
                raise RuntimeError(f"read_file failed: {r.status} {body!r}")
            wire = len(body)
            if r.getheader("X-Encoding") == "deflate":
                import zlib

                body = zlib.decompress(body)
            self._account(wire, len(body))
            return body
        finally:
            c.close()

    def read_whole_file(
        self, rel: str, chunk: int = DEFAULT_BLOCK, compress: bool = False
    ) -> bytes:
        """Stream a whole remote file by range reads."""
        out = bytearray()
        offset = 0
        while True:
            data = self.read_file(rel, offset, chunk, compress=compress)
            out += data
            offset += len(data)
            if len(data) < chunk:
                return bytes(out)

    def write_file(self, rel: str, data: bytes, compress: bool = True) -> None:
        """PUT a whole file to the remote store root (bulk egress)."""
        headers = {}
        body = data
        if compress:
            import zlib

            body = zlib.compress(data, 1)
            headers["X-Encoding"] = "deflate"
        c = self._conn()
        try:
            c.request(
                "PUT",
                f"/file/{urllib.parse.quote(rel, safe='/')}?enc=1",
                body=body, headers=headers,
            )
            r = c.getresponse()
            msg = r.read()
            if r.status != 200:
                raise RuntimeError(f"write_file failed: {r.status} {msg!r}")
            self._account(len(body), len(data))
        finally:
            c.close()
