"""Overlapped gang command streams — the mailbox dispatch window.

``submit_many`` historically issued one ``runbatch`` envelope per
worker and then BLOCKED on the whole batch before feeding the next:
the gang sat idle for a full driver round trip between batches.  This
module is the gang-scale analog of ``exec.pipeline.DispatchWindow``
with the same invariants, transplanted from device readbacks to
mailbox round trips:

- the driver thread only FEEDS: it posts each envelope to the workers'
  command mailboxes itself (the posts are cheap local HTTP writes) and
  hands the blocking half — a zero-arg ``drain`` closure that
  long-polls the envelope's per-worker status keys — to ONE background
  collector thread via :meth:`submit`;
- the collector drains drains strictly in submit order, so batch
  COMMIT order (and everything downstream of it) is exactly the serial
  loop's and results stay byte-identical;
- at most ``depth`` envelopes are in flight (submitted and not yet
  drained): :meth:`submit` blocks past that, waiting on the
  COLLECTOR's progress, never the driver's own — a full window can
  always drain itself;
- a drain exception is delivered at the drain site (never raised on
  the collector thread), where the driver re-runs the envelope's
  failed sub-commands SERIALLY at their commit position
  (:meth:`note_retry` records it);
- :meth:`close` always joins the collector, also mid-error: a
  poisoned window can never deadlock the driver's ``finally``.

Mailbox discipline (graftlint rule 17, ``mailbox-discipline``): the
property mailbox is a latest-value store, so the feed side must never
block on a status drain itself — overlapping envelopes are only safe
because each one posts its status to a distinct per-envelope key and
the collector is the single drain site.  One ``gang_window`` summary
event at close carries totals plus the peak per-worker envelope
overlap actually achieved.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from dryad_tpu.obs import flightrec, tracectx


class GangDispatchWindow:
    """Async mailbox-paced gang dispatch: the driver only feeds."""

    def __init__(self, depth: int, events=None, name: str = "gang"):
        depth = int(depth)
        if depth < 1:
            raise ValueError("gang window depth must be >= 1")
        self.depth = depth
        self.name = name
        self.events = events
        self.dispatches = 0
        self.retries = 0
        self.peak_in_flight = 0
        self._t0_wall = time.monotonic()
        self._pending: list = []  # (tag, drain) awaiting the collector
        self._done: list = []  # (tag, value, error) in submit order
        self._outstanding = 0  # submitted - consumed by the driver
        self._cv = threading.Condition()
        self._closed = False
        self._thread = threading.Thread(
            target=self._collect, name=f"dryad-gangwin-{name}", daemon=True
        )
        flightrec.probe(
            f"gangwindow:{name}",
            lambda: {
                "in_flight": len(self._pending),
                "outstanding": self._outstanding,
                "depth": self.depth,
            },
        )
        self._thread.start()

    # -- collector thread --------------------------------------------------

    def _collect(self) -> None:
        while True:
            with self._cv:
                while not self._pending and not self._closed:
                    self._cv.wait(0.1)
                if not self._pending:
                    return  # closed and drained
                tag, drain = self._pending[0]
            value, error = None, None
            try:
                value = drain()
            except BaseException as e:  # noqa: BLE001 - delivered at drain
                error = e
            with self._cv:
                if self._pending:  # close() may have dropped the queue
                    self._pending.pop(0)
                self._done.append((tag, value, error))
                self._cv.notify_all()

    # -- driver side -------------------------------------------------------

    def submit(self, tag, drain) -> None:
        """Hand one posted envelope's drain closure to the collector.
        Call immediately after posting the envelope to every worker's
        command mailbox; blocks while the window is full."""
        with self._cv:
            if self._closed:
                raise RuntimeError(f"gang window {self.name} closed")
            # flow control on UN-DRAINED work only: the collector makes
            # progress independently, so this wait always resolves (a
            # wait on driver-consumed counts would deadlock — the
            # driver is the one blocked here)
            while len(self._pending) >= self.depth and not self._closed:
                self._cv.wait(0.1)
            self._pending.append((tag, drain))
            self._outstanding += 1
            self.dispatches += 1
            self._cv.notify_all()

    def note_retry(self) -> None:
        """Record one drain-site serial re-run of a failed envelope."""
        self.retries += 1

    def note_in_flight(self, n: int) -> None:
        """Record an observed per-worker envelope-overlap sample (the
        feed side samples posted-minus-statused at each post)."""
        if n > self.peak_in_flight:
            self.peak_in_flight = n

    def ready(self):
        """Yield completed ``(tag, value, error)`` triples in submit
        order WITHOUT blocking."""
        while True:
            with self._cv:
                if not self._done:
                    return
                item = self._done.pop(0)
                self._outstanding -= 1
                self._cv.notify_all()
            yield item

    def drain(self):
        """Yield every remaining outcome in submit order, blocking
        until the collector delivers each."""
        while True:
            with self._cv:
                while not self._done:
                    if not self._pending and self._outstanding == 0:
                        return
                    self._cv.wait(0.1)
                item = self._done.pop(0)
                self._outstanding -= 1
                self._cv.notify_all()
            yield item

    def close(self, workers: Optional[int] = None) -> None:
        """Join the collector.  Safe from ``finally`` and repeatedly;
        undelivered drains are abandoned (their statuses sit harmlessly
        in per-envelope mailbox keys nobody will read)."""
        with self._cv:
            if self._closed:
                return
            self._closed = True
            self._pending.clear()
            self._cv.notify_all()
        self._thread.join(timeout=30.0)
        flightrec.unprobe(f"gangwindow:{self.name}")
        if self.events is not None:
            extra = {} if workers is None else {"workers": workers}
            self.events.emit(
                "gang_window", pipeline=self.name, depth=self.depth,
                dispatches=self.dispatches, retries=self.retries,
                peak_in_flight=self.peak_in_flight,
                qid=tracectx.current_qid(),
                wall_s=round(time.monotonic() - self._t0_wall, 6),
                **extra,
            )

    def __enter__(self) -> "GangDispatchWindow":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
