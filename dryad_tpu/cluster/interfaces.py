"""Cluster-service abstractions — the ``ClusterInterface`` analog.

Mirrors the reference's L3 contracts (``ClusterInterface/Interfaces.cs:324-545``):
``ICluster`` (computer membership, process scheduling/cancel, property
mailbox access, file-path construction) and ``IScheduler`` (queue a
process, notify run/completion), plus the resource/affinity model of the
GM kernel (``GraphManager/kernel/DrResources.h:41-137`` —
DrResource/DrUniverse/DrAffinity).

In the TPU framework the "process" is host-side work around the SPMD
compute (stage materialization, DFS ingest/egress, multi-host control),
not the compute itself — XLA gang-schedules the mesh.  The semantics
kept from the reference: locality affinities with hard/soft weights,
dynamic computer membership, versioned process state callbacks.
"""

from __future__ import annotations

import dataclasses
import enum
import itertools
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

_pids = itertools.count(1)


class ProcessState(enum.Enum):
    """Lifecycle of a scheduled process (``DrProcess.h:40-46`` DPBS_*)."""

    NOT_STARTED = "not_started"
    QUEUED = "queued"
    RUNNING = "running"
    COMPLETED = "completed"
    FAILED = "failed"
    CANCELED = "canceled"


@dataclasses.dataclass(frozen=True)
class Computer:
    """A worker host (``DrResources.h`` DrResource at machine level)."""

    name: str
    rack: str = "rack0"
    slots: int = 1


@dataclasses.dataclass(frozen=True)
class Affinity:
    """Placement preference (``DrResources.h:41-137`` DrAffinity).

    ``locality`` names a computer or a rack; ``hard`` constraints never
    relax to other locations; ``weight`` orders soft preferences.
    """

    locality: str
    hard: bool = False
    weight: float = 1.0


class ClusterProcess:
    """Handle for one scheduled unit of host work (``DrProcess`` analog).

    ``fn`` runs on the assigned computer's worker slot; raising marks
    the process FAILED.  State transitions are observed via
    ``on_state`` callbacks (the IProcessWatcher contract,
    ``Interfaces.cs:214-258``).
    """

    def __init__(
        self,
        fn: Callable[["ClusterProcess"], Any],
        name: str = "",
        affinities: Sequence[Affinity] = (),
    ):
        self.id = next(_pids)
        self.name = name or f"proc-{self.id}"
        self.fn = fn
        self.affinities = list(affinities)
        self.state = ProcessState.NOT_STARTED
        self.computer: Optional[str] = None
        self.result: Any = None
        self.error: Optional[BaseException] = None
        self._done = threading.Event()
        self._cancel = threading.Event()
        self._watchers: List[Callable[["ClusterProcess"], None]] = []
        self._lock = threading.Lock()

    # -- watcher contract ----------------------------------------------------
    def on_state(self, cb: Callable[["ClusterProcess"], None]) -> None:
        with self._lock:
            self._watchers.append(cb)

    def _transition(self, state: ProcessState) -> None:
        with self._lock:
            self.state = state
            watchers = list(self._watchers)
        for cb in watchers:
            cb(self)
        if state in (
            ProcessState.COMPLETED,
            ProcessState.FAILED,
            ProcessState.CANCELED,
        ):
            self._done.set()

    # -- caller surface ------------------------------------------------------
    @property
    def cancelled(self) -> bool:
        return self._cancel.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)


class Scheduler:
    """The IScheduler contract (``Interfaces.cs:467-545``), extended
    with the machine-level failure accounting of the reference GM
    (computers producing repeated failures are blacklisted so retries
    land elsewhere; re-admitted on probation after a cooldown)."""

    def schedule(self, process: ClusterProcess) -> None:
        raise NotImplementedError

    def cancel(self, process: ClusterProcess) -> None:
        raise NotImplementedError

    def add_computer(self, computer: Computer) -> None:
        raise NotImplementedError

    def remove_computer(self, name: str) -> None:
        raise NotImplementedError

    def computers(self) -> List[Computer]:
        raise NotImplementedError

    # -- failure accounting / quarantine (optional; default no-op) -----------
    def record_failure(self, computer: str) -> None:
        """Attribute one failure to ``computer`` (implementations keep
        a sliding window and quarantine past a threshold)."""

    def quarantined(self) -> List[str]:
        """Names of computers currently receiving no new dispatches."""
        return []
