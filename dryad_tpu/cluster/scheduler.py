"""LocalScheduler — affinity queues with delay-based locality relaxation.

The analog of the reference scheduler (``LocalScheduler/LocalScheduler.cs``):
processes queue at their preferred computer first, relax to the rack
queue after ``rack_delay`` seconds and to the cluster-wide queue after
``cluster_delay`` seconds (reference defaults 1s/2s,
``LocalScheduler.cs:52-53``); hard constraints never relax
(``:149-160``).  Computer membership is elastic
(``WaitForReasonableNumberOfComputers``, ``LocalScheduler.cs:88``).

Worker slots are threads; a "process" is host-side work (stage
materialization, ingest/egress, control) — see ``interfaces`` docstring.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from dryad_tpu.cluster.interfaces import (
    Affinity,
    ClusterProcess,
    Computer,
    ProcessState,
    Scheduler,
)
from dryad_tpu.utils.logging import get_logger

log = get_logger("dryad_tpu.cluster")


class _Entry:
    def __init__(self, process: ClusterProcess):
        self.process = process
        self.enqueued = time.monotonic()


class LocalScheduler(Scheduler):
    def __init__(
        self,
        computers: Optional[List[Computer]] = None,
        rack_delay: float = 1.0,
        cluster_delay: float = 2.0,
        poll_interval: float = 0.02,
    ):
        self.rack_delay = rack_delay
        self.cluster_delay = cluster_delay
        self.poll_interval = poll_interval
        self._lock = threading.Condition()
        self._computers: Dict[str, Computer] = {}
        self._busy: Dict[str, int] = {}  # computer -> running count
        self._queue: List[_Entry] = []  # single list; eligibility by age
        self._stop = False
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="dryad-scheduler", daemon=True
        )
        for c in computers or []:
            self._computers[c.name] = c
            self._busy[c.name] = 0
        self._dispatcher.start()

    # -- membership (elastic, Interfaces.cs:336-343) -------------------------
    def add_computer(self, computer: Computer) -> None:
        with self._lock:
            self._computers[computer.name] = computer
            self._busy.setdefault(computer.name, 0)
            self._lock.notify_all()

    def remove_computer(self, name: str) -> None:
        with self._lock:
            self._computers.pop(name, None)

    def computers(self) -> List[Computer]:
        with self._lock:
            return list(self._computers.values())

    def wait_for_computers(self, n: int, timeout: float = 30.0) -> bool:
        """Block until >= n computers joined (LocalScheduler.cs:88)."""
        deadline = time.monotonic() + timeout
        with self._lock:
            while len(self._computers) < n:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._lock.wait(left)
            return True

    # -- scheduling ----------------------------------------------------------
    def schedule(self, process: ClusterProcess) -> None:
        with self._lock:
            process._transition(ProcessState.QUEUED)
            self._queue.append(_Entry(process))
            self._lock.notify_all()

    def cancel(self, process: ClusterProcess) -> None:
        """Cancel a queued or running process (``ICluster.CancelProcess``).

        Running work observes ``process.cancelled`` cooperatively (the
        reference kills the worker process; slots here are threads)."""
        with self._lock:
            for e in list(self._queue):
                if e.process is process:
                    self._queue.remove(e)
                    process._cancel.set()
                    process._transition(ProcessState.CANCELED)
                    return
        process._cancel.set()  # running: cooperative

    # -- placement policy ----------------------------------------------------
    def _rack_of(self, locality: str) -> str:
        """A locality names a computer or a rack; resolve to a rack."""
        c = self._computers.get(locality)
        return c.rack if c is not None else locality

    def _eligible(self, entry: _Entry, comp: Computer) -> bool:
        affs = entry.process.affinities
        if not affs:
            return True
        hard = [a for a in affs if a.hard]
        if hard:
            # a hard computer constraint pins exactly that computer; a
            # hard rack constraint allows any computer in the rack
            return any(
                a.locality == comp.name
                or (
                    a.locality not in self._computers
                    and a.locality == comp.rack
                )
                for a in hard
            )
        age = time.monotonic() - entry.enqueued
        # the preferred locality itself is served immediately: an exact
        # computer match, or a rack-level affinity naming this rack —
        # delays only gate *relaxation* away from the preference
        if any(
            a.locality == comp.name
            or (a.locality not in self._computers and a.locality == comp.rack)
            for a in affs
        ):
            return True
        if age >= self.rack_delay and any(
            self._rack_of(a.locality) == comp.rack for a in affs
        ):
            return True
        return age >= self.cluster_delay

    def _pick(self) -> Optional[tuple]:
        """Find (entry, computer) to run; prefer older entries and their
        stronger (higher-weight) affinities."""
        idle = [
            c
            for c in self._computers.values()
            if self._busy.get(c.name, 0) < c.slots
        ]
        if not idle:
            return None
        for entry in self._queue:  # FIFO
            affs = sorted(
                entry.process.affinities, key=lambda a: -a.weight
            )
            # strongest preference first: exact computer, then rack
            for a in affs:
                for c in idle:
                    if c.name == a.locality and self._eligible(entry, c):
                        return entry, c
            for a in affs:
                for c in idle:
                    if c.rack == a.locality and self._eligible(entry, c):
                        return entry, c
            for c in idle:
                if self._eligible(entry, c):
                    return entry, c
        return None

    # -- dispatch loop -------------------------------------------------------
    def _dispatch_loop(self) -> None:
        while True:
            with self._lock:
                if self._stop:
                    return
                pick = self._pick()
                if pick is None:
                    self._lock.wait(self.poll_interval)
                    continue
                entry, comp = pick
                self._queue.remove(entry)
                self._busy[comp.name] += 1
            threading.Thread(
                target=self._run, args=(entry.process, comp), daemon=True
            ).start()

    def _run(self, process: ClusterProcess, comp: Computer) -> None:
        process.computer = comp.name
        process._transition(ProcessState.RUNNING)
        try:
            if process.cancelled:
                process._transition(ProcessState.CANCELED)
                return
            process.result = process.fn(process)
        except BaseException as e:  # noqa: BLE001 — report, don't die
            process.error = e
            log.warning("process %s failed on %s: %s", process.name, comp.name, e)
            process._transition(ProcessState.FAILED)
        else:
            if process.cancelled:
                process._transition(ProcessState.CANCELED)
            else:
                process._transition(ProcessState.COMPLETED)
        finally:
            with self._lock:
                if comp.name in self._busy:
                    self._busy[comp.name] -= 1
                self._lock.notify_all()

    def shutdown(self) -> None:
        with self._lock:
            self._stop = True
            drained = [e.process for e in self._queue]
            self._queue.clear()
            self._lock.notify_all()
        for p in drained:  # never-started work must still reach a terminal state
            p._cancel.set()
            p._transition(ProcessState.CANCELED)
        self._dispatcher.join(timeout=5)
