"""LocalScheduler — affinity queues with delay-based locality relaxation
and machine-level failure quarantine.

The analog of the reference scheduler (``LocalScheduler/LocalScheduler.cs``):
processes queue at their preferred computer first, relax to the rack
queue after ``rack_delay`` seconds and to the cluster-wide queue after
``cluster_delay`` seconds (reference defaults 1s/2s,
``LocalScheduler.cs:52-53``); hard constraints never relax
(``:149-160``).  Computer membership is elastic
(``WaitForReasonableNumberOfComputers``, ``LocalScheduler.cs:88``).

**Quarantine** (the Dryad machine-blacklist analog): every process
failure is attributed to the computer it ran on in a sliding window;
past ``quarantine_threshold`` failures the computer is quarantined —
no new dispatches, and queued SOFT affinities relax away from it
immediately.  A HARD affinity naming a quarantined computer still
dispatches there: hard constraints never relax, and refusing them
would deadlock gang commands that are pinned per-worker by design.
After ``quarantine_cooldown`` the computer re-admits on **probation**:
the first failure while on probation re-quarantines immediately; a
success clears probation.  ``clock`` is injectable so the whole
lifecycle is fake-time testable (no real sleeps).

Worker slots are threads; a "process" is host-side work (stage
materialization, ingest/egress, control) — see ``interfaces`` docstring.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Set

from dryad_tpu.cluster.interfaces import (
    Affinity,
    ClusterProcess,
    Computer,
    ProcessState,
    Scheduler,
)
from dryad_tpu.exec.stats import FailureWindow
from dryad_tpu.utils.logging import get_logger

log = get_logger("dryad_tpu.cluster")


class _Entry:
    def __init__(self, process: ClusterProcess, now: float):
        self.process = process
        self.enqueued = now


class LocalScheduler(Scheduler):
    def __init__(
        self,
        computers: Optional[List[Computer]] = None,
        rack_delay: float = 1.0,
        cluster_delay: float = 2.0,
        poll_interval: float = 0.02,
        quarantine_threshold: int = 3,
        quarantine_window: float = 60.0,
        quarantine_cooldown: float = 30.0,
        clock=None,
        events=None,
    ):
        self.rack_delay = rack_delay
        self.cluster_delay = cluster_delay
        self.poll_interval = poll_interval
        self.quarantine_threshold = quarantine_threshold
        self.quarantine_window = quarantine_window
        self.quarantine_cooldown = quarantine_cooldown
        self._clock = clock or time.monotonic
        self._events = events  # optional EventLog
        self._lock = threading.Condition()
        self._computers: Dict[str, Computer] = {}
        self._busy: Dict[str, int] = {}  # computer -> running count
        self._queue: List[_Entry] = []  # single list; eligibility by age
        self._failures: Dict[str, FailureWindow] = {}
        self._quarantine: Dict[str, float] = {}  # name -> cooldown end
        self._probation: Set[str] = set()
        # LOCAL failures not yet exported to peer drivers (multihost
        # shared quarantine, obs.gang.ship_failure_deltas); remote
        # absorptions never land here, so deltas can't echo.
        self._unshipped: Dict[str, int] = {}
        self._stop = False
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="dryad-scheduler", daemon=True
        )
        for c in computers or []:
            self._computers[c.name] = c
            self._busy[c.name] = 0
        self._dispatcher.start()

    def _emit(self, kind: str, **fields) -> None:
        if self._events is not None:
            self._events.emit(kind, **fields)

    # -- membership (elastic, Interfaces.cs:336-343) -------------------------
    def add_computer(self, computer: Computer) -> None:
        with self._lock:
            self._computers[computer.name] = computer
            self._busy.setdefault(computer.name, 0)
            self._lock.notify_all()

    def remove_computer(self, name: str) -> None:
        stranded: List[_Entry] = []
        with self._lock:
            self._computers.pop(name, None)
            # a re-added computer of the same name is a fresh worker:
            # its predecessor's failure history must not follow it
            # (locally or through the shared-quarantine channel)
            self._failures.pop(name, None)
            self._quarantine.pop(name, None)
            self._probation.discard(name)
            self._unshipped.pop(name, None)
            # Fail fast queued processes whose HARD affinity named the
            # removed computer and can no longer be satisfied by any
            # remaining member — _eligible would never match a missing
            # computer, leaving them queued until an external timeout.
            for e in list(self._queue):
                hard = [a for a in e.process.affinities if a.hard]
                if not hard or not any(a.locality == name for a in hard):
                    continue
                if not any(
                    self._hard_matches(a, c)
                    for a in hard
                    for c in self._computers.values()
                ):
                    self._queue.remove(e)
                    stranded.append(e)
        for e in stranded:
            p = e.process
            p.error = RuntimeError(
                f"computer {name!r} removed from the cluster; process "
                f"{p.name!r} holds a hard affinity "
                f"{[a.locality for a in p.affinities if a.hard]} no "
                f"remaining computer satisfies"
            )
            log.warning("%s", p.error)
            self._emit(
                "process_stranded", process=p.name, computer=name,
            )
            p._transition(ProcessState.FAILED)

    def _hard_matches(self, a: Affinity, comp: Computer) -> bool:
        """One hard affinity vs one computer (same rule as _eligible)."""
        return a.locality == comp.name or (
            a.locality not in self._computers and a.locality == comp.rack
        )

    # -- failure accounting / quarantine (machine blacklist analog) ----------
    def record_failure(self, computer: str) -> None:
        """Attribute one failure to ``computer``; quarantine past the
        sliding-window threshold (probation failures re-quarantine at
        once)."""
        with self._lock:
            self._note_failure_locked(computer)

    def _note_failure_locked(self, name: str, remote: bool = False) -> None:
        now = self._clock()
        count = self._failures.setdefault(
            name, FailureWindow(self.quarantine_window)
        ).record(now)
        if not remote:
            self._unshipped[name] = self._unshipped.get(name, 0) + 1
        if name in self._probation:
            # a probation failure proves the cooldown solved nothing
            self._probation.discard(name)
            self._quarantine[name] = now + self.quarantine_cooldown
            log.warning("computer %s re-quarantined on probation", name)
            self._emit(
                "computer_quarantined", computer=name, failures=count,
                cooldown=self.quarantine_cooldown, probation=True,
            )
            return
        if name not in self._quarantine and count >= self.quarantine_threshold:
            self._quarantine[name] = now + self.quarantine_cooldown
            log.warning(
                "computer %s quarantined after %d failures in %.0fs",
                name, count, self.quarantine_window,
            )
            self._emit(
                "computer_quarantined", computer=name, failures=count,
                cooldown=self.quarantine_cooldown, probation=False,
            )

    def _note_success_locked(self, name: str) -> None:
        if name in self._probation:
            self._probation.discard(name)
            self._failures.pop(name, None)
            log.info("computer %s readmitted after probation", name)
            self._emit("computer_readmitted", computer=name)

    def _quarantined_now_locked(self) -> Set[str]:
        """Names currently quarantined; expired cooldowns move the
        computer to probation as a side effect."""
        now = self._clock()
        out: Set[str] = set()
        for name, until in list(self._quarantine.items()):
            if now < until:
                out.add(name)
            else:
                del self._quarantine[name]
                self._probation.add(name)
                self._emit("computer_probation", computer=name)
        return out

    def quarantined(self) -> List[str]:
        with self._lock:
            return sorted(self._quarantined_now_locked())

    # -- multihost shared quarantine (ROADMAP; GM-global machine failure
    # counts): every driver in a multi-controller gang ships its LOCAL
    # failure deltas through the telemetry mailbox channel
    # (obs.gang.ship_failure_deltas) and folds its peers' deltas into
    # the same sliding windows, so the whole gang converges on one
    # blacklist without a central coordinator.
    def failure_delta(self) -> Dict[str, int]:
        """Drain the not-yet-shipped LOCAL failure counts (the export
        half of the shared blacklist; remote absorptions are excluded
        so a delta can never echo back and forth)."""
        with self._lock:
            out = {k: v for k, v in self._unshipped.items() if v > 0}
            self._unshipped.clear()
            return out

    def absorb_remote_failures(
        self, deltas: Dict[str, int], source=None
    ) -> None:
        """Fold a peer driver's failure deltas into this scheduler's
        windows/quarantine WITHOUT re-exporting them."""
        with self._lock:
            for name, n in deltas.items():
                for _ in range(int(n)):
                    self._note_failure_locked(name, remote=True)
        self._emit(
            "quarantine_absorbed", source=source, deltas=dict(deltas),
        )

    def computers(self) -> List[Computer]:
        with self._lock:
            return list(self._computers.values())

    def wait_for_computers(self, n: int, timeout: float = 30.0) -> bool:
        """Block until >= n computers joined (LocalScheduler.cs:88)."""
        deadline = time.monotonic() + timeout
        with self._lock:
            while len(self._computers) < n:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._lock.wait(left)
            return True

    # -- scheduling ----------------------------------------------------------
    def schedule(self, process: ClusterProcess) -> None:
        with self._lock:
            process._transition(ProcessState.QUEUED)
            self._queue.append(_Entry(process, self._clock()))
            self._lock.notify_all()

    def schedule_batch(self, processes: List[ClusterProcess]) -> None:
        """Enqueue several processes atomically (one lock round, one
        wakeup) — the coded-spare launch path enqueues all r parity
        vertices at once so they contend for slots as one decision."""
        with self._lock:
            now = self._clock()
            for p in processes:
                p._transition(ProcessState.QUEUED)
                self._queue.append(_Entry(p, now))
            self._lock.notify_all()

    def cancel(self, process: ClusterProcess) -> None:
        """Cancel a queued or running process (``ICluster.CancelProcess``).

        Running work observes ``process.cancelled`` cooperatively (the
        reference kills the worker process; slots here are threads)."""
        with self._lock:
            for e in list(self._queue):
                if e.process is process:
                    self._queue.remove(e)
                    process._cancel.set()
                    process._transition(ProcessState.CANCELED)
                    return
        process._cancel.set()  # running: cooperative

    # -- placement policy ----------------------------------------------------
    def _rack_of(self, locality: str) -> str:
        """A locality names a computer or a rack; resolve to a rack."""
        c = self._computers.get(locality)
        return c.rack if c is not None else locality

    def _eligible(self, entry: _Entry, comp: Computer, quar: Set[str]) -> bool:
        affs = entry.process.affinities
        if not affs:
            return True
        hard = [a for a in affs if a.hard]
        if hard:
            # a hard computer constraint pins exactly that computer; a
            # hard rack constraint allows any computer in the rack
            return any(self._hard_matches(a, comp) for a in hard)
        # quarantined preferred localities drop out of the preference
        # set entirely: the entry relaxes away from them IMMEDIATELY
        # (waiting out rack/cluster delays for a blacklisted machine
        # would just stall the retry the quarantine exists to re-place)
        affs = [a for a in affs if a.locality not in quar]
        if not affs:
            return True
        age = self._clock() - entry.enqueued
        # the preferred locality itself is served immediately: an exact
        # computer match, or a rack-level affinity naming this rack —
        # delays only gate *relaxation* away from the preference
        if any(
            a.locality == comp.name
            or (a.locality not in self._computers and a.locality == comp.rack)
            for a in affs
        ):
            return True
        if age >= self.rack_delay and any(
            self._rack_of(a.locality) == comp.rack for a in affs
        ):
            return True
        return age >= self.cluster_delay

    def _dispatchable(self, entry: _Entry, comp: Computer, quar: Set[str]) -> bool:
        """Quarantine gate ahead of affinity eligibility: a quarantined
        computer receives no new dispatches — except for processes whose
        HARD affinity pins them to it (hard constraints never relax;
        refusing would deadlock per-worker gang commands)."""
        if comp.name in quar and not any(
            a.hard and self._hard_matches(a, comp)
            for a in entry.process.affinities
        ):
            return False
        return self._eligible(entry, comp, quar)

    def _pick(self) -> Optional[tuple]:
        """Find (entry, computer) to run; prefer older entries and their
        stronger (higher-weight) affinities."""
        idle = [
            c
            for c in self._computers.values()
            if self._busy.get(c.name, 0) < c.slots
        ]
        if not idle:
            return None
        quar = self._quarantined_now_locked()
        for entry in self._queue:  # FIFO
            affs = sorted(
                entry.process.affinities, key=lambda a: -a.weight
            )
            # strongest preference first: exact computer, then rack
            for a in affs:
                for c in idle:
                    if c.name == a.locality and self._dispatchable(
                        entry, c, quar
                    ):
                        return entry, c
            for a in affs:
                for c in idle:
                    if c.rack == a.locality and self._dispatchable(
                        entry, c, quar
                    ):
                        return entry, c
            for c in idle:
                if self._dispatchable(entry, c, quar):
                    return entry, c
        return None

    # -- dispatch loop -------------------------------------------------------
    def _dispatch_loop(self) -> None:
        while True:
            with self._lock:
                if self._stop:
                    return
                pick = self._pick()
                if pick is None:
                    self._lock.wait(self.poll_interval)
                    continue
                entry, comp = pick
                self._queue.remove(entry)
                self._busy[comp.name] += 1
                wait = self._clock() - entry.enqueued
            # queue-wait accounting (obs): how long placement took,
            # including locality-relaxation delays — the scheduling-
            # latency half of the gang telemetry story
            self._emit(
                "process_dispatch", process=entry.process.name,
                computer=comp.name, wait_s=round(wait, 4),
            )
            threading.Thread(
                target=self._run, args=(entry.process, comp), daemon=True
            ).start()

    def _run(self, process: ClusterProcess, comp: Computer) -> None:
        process.computer = comp.name
        process._transition(ProcessState.RUNNING)
        try:
            if process.cancelled:
                process._transition(ProcessState.CANCELED)
                return
            process.result = process.fn(process)
        except BaseException as e:  # noqa: BLE001 — report, don't die
            process.error = e
            log.warning("process %s failed on %s: %s", process.name, comp.name, e)
            self._emit(
                "process_failed", process=process.name,
                computer=comp.name, error=str(e),
            )
            with self._lock:
                self._note_failure_locked(comp.name)
            process._transition(ProcessState.FAILED)
        else:
            if process.cancelled:
                process._transition(ProcessState.CANCELED)
            else:
                with self._lock:
                    self._note_success_locked(comp.name)
                process._transition(ProcessState.COMPLETED)
        finally:
            with self._lock:
                if comp.name in self._busy:
                    self._busy[comp.name] -= 1
                self._lock.notify_all()

    def shutdown(self) -> None:
        with self._lock:
            self._stop = True
            drained = [e.process for e in self._queue]
            self._queue.clear()
            self._lock.notify_all()
        for p in drained:  # never-started work must still reach a terminal state
            p._cancel.set()
            p._transition(ProcessState.CANCELED)
        self._dispatcher.join(timeout=5)
