"""ctypes bindings for the native runtime, with pure-Python fallbacks.

The shared library is built from ``runtime/native`` with the checked-in
Makefile; if it is missing we attempt one build, then fall back to
Python implementations (correct, slower).  Every native function has an
identical-semantics Python twin so the engine never *requires* the
native library.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import zlib
from typing import List, Optional, Tuple

import numpy as np

from dryad_tpu.columnar.schema import hash64_bytes
from dryad_tpu.utils.logging import get_logger

log = get_logger("dryad_tpu.runtime")

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libdryadnative.so")
_lib = None
_lib_tried = False
_lock = threading.Lock()


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_tried
    with _lock:
        if _lib is not None or _lib_tried:
            return _lib
        _lib_tried = True
        if not os.path.exists(_LIB_PATH):
            try:
                subprocess.run(
                    ["make", "-C", _NATIVE_DIR],
                    check=True, capture_output=True, timeout=120,
                )
            except Exception as e:  # no toolchain: fall back
                log.warning("native build failed (%s); using Python fallbacks", e)
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError as e:
            log.warning("native load failed (%s); using Python fallbacks", e)
            return None
        lib.dn_hash64.restype = ctypes.c_uint64
        lib.dn_hash64.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        lib.dn_token_count.restype = ctypes.c_size_t
        lib.dn_token_count.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        lib.dn_tokenize.restype = ctypes.c_size_t
        lib.dn_tokenize.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_uint32),
            ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_uint32),
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint32),
        ]
        lib.dn_channel_open.restype = ctypes.c_void_p
        lib.dn_channel_open.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_size_t,
            ctypes.c_size_t, ctypes.c_size_t,
        ]
        lib.dn_channel_next.restype = ctypes.c_int64
        lib.dn_channel_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ]
        lib.dn_channel_close.restype = None
        lib.dn_channel_close.argtypes = [ctypes.c_void_p]
        lib.dn_write_partition.restype = ctypes.c_int32
        lib.dn_write_partition.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_uint64, ctypes.c_int32,
        ]
        lib.dn_fifo_create.restype = ctypes.c_void_p
        lib.dn_fifo_create.argtypes = [ctypes.c_size_t]
        lib.dn_fifo_push.restype = ctypes.c_int32
        lib.dn_fifo_push.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
        ]
        lib.dn_fifo_pop.restype = ctypes.c_int64
        lib.dn_fifo_pop.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ]
        lib.dn_fifo_close.restype = None
        lib.dn_fifo_close.argtypes = [ctypes.c_void_p]
        lib.dn_fifo_destroy.restype = None
        lib.dn_fifo_destroy.argtypes = [ctypes.c_void_p]
        lib.dn_tlv_encode.restype = ctypes.c_size_t
        lib.dn_tlv_encode.argtypes = [
            ctypes.c_size_t, ctypes.POINTER(ctypes.c_uint16),
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_char_p, ctypes.c_size_t,
        ]
        lib.dn_tlv_encoded_size.restype = ctypes.c_size_t
        lib.dn_tlv_encoded_size.argtypes = [
            ctypes.c_size_t, ctypes.POINTER(ctypes.c_uint32),
        ]
        lib.dn_tlv_decode.restype = ctypes.c_size_t
        lib.dn_tlv_decode.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint16), ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint32),
        ]
        if hasattr(lib, "dn_decompress_batch"):  # rebuilt lib only
            lib.dn_decompress_batch.restype = ctypes.c_int32
            lib.dn_decompress_batch.argtypes = [
                ctypes.c_size_t, ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_uint64),
            ]
        _lib = lib
        log.info("native runtime loaded from %s", _LIB_PATH)
        return _lib


def native_available() -> bool:
    return _load() is not None


def decompress_batch(srcs, dsts) -> bool:
    """Inflate each zlib payload in ``srcs`` into the matching writable
    buffer in ``dsts`` (numpy arrays), all columns in parallel on native
    threads (the read half of the channel codec,
    ``channelbuffernativereader.cpp`` analog).  Returns False when the
    native runtime is unavailable (caller falls back to zlib)."""
    lib = _load()
    if lib is None or not hasattr(lib, "dn_decompress_batch") or not srcs:
        return False
    n = len(srcs)
    src_ptrs = (ctypes.c_void_p * n)()
    src_lens = (ctypes.c_uint64 * n)()
    dst_ptrs = (ctypes.c_void_p * n)()
    dst_lens = (ctypes.c_uint64 * n)()
    for i, (s, d) in enumerate(zip(srcs, dsts)):
        # c_char_p points at the bytes object's buffer (no copy); srcs
        # stays referenced by the caller for the duration of the call
        src_ptrs[i] = ctypes.cast(ctypes.c_char_p(s), ctypes.c_void_p)
        src_lens[i] = len(s)
        dst_ptrs[i] = d.ctypes.data_as(ctypes.c_void_p)
        dst_lens[i] = d.nbytes
    rc = lib.dn_decompress_batch(n, src_ptrs, src_lens, dst_ptrs, dst_lens)
    if rc != 0:
        raise ValueError("corrupt compressed column payload")
    return True


def hash64(data: bytes) -> int:
    lib = _load()
    if lib is not None:
        return int(lib.dn_hash64(data, len(data)))
    return hash64_bytes(data)


def tokenize(
    text: bytes,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Whitespace-tokenize a byte buffer into columnar token arrays.

    Returns (h0, h1, r0, r1, starts, lens): Hash64 word pairs, 8-byte
    prefix rank words, and byte offsets/lengths for dictionary
    construction.
    """
    lib = _load()
    if lib is not None:
        n = lib.dn_token_count(text, len(text))
        h0 = np.empty(n, np.uint32)
        h1 = np.empty(n, np.uint32)
        r0 = np.empty(n, np.uint32)
        r1 = np.empty(n, np.uint32)
        starts = np.empty(n, np.uint64)
        lens = np.empty(n, np.uint32)
        got = lib.dn_tokenize(
            text, len(text), n,
            h0.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            h1.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            r0.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            r1.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            starts.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            lens.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        )
        assert got == n
        return h0, h1, r0, r1, starts, lens

    # Python fallback
    from dryad_tpu.columnar.schema import string_prefix_rank

    tokens = []
    starts_l = []
    i = 0
    while i < len(text):
        while i < len(text) and text[i : i + 1].isspace():
            i += 1
        if i >= len(text):
            break
        s = i
        while i < len(text) and not text[i : i + 1].isspace():
            i += 1
        tokens.append(text[s:i])
        starts_l.append(s)
    hashes = np.array([hash64_bytes(t) for t in tokens], np.uint64)
    h0 = (hashes & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    h1 = (hashes >> np.uint64(32)).astype(np.uint32)
    sarr = np.array([t.decode("utf-8", "replace") for t in tokens], object)
    r0 = string_prefix_rank(sarr)
    r1 = string_prefix_rank(sarr, offset=4)
    return (
        h0, h1, r0, r1,
        np.array(starts_l, np.uint64),
        np.array([len(t) for t in tokens], np.uint32),
    )


def write_partition(
    path: str, cols: "dict[str, np.ndarray]", compression: Optional[str] = None
) -> None:
    """Write one ``.dpf`` partition file (format: ``columnar/io.py``).

    Native path compresses columns concurrently on a thread pool (the
    async channel-writer analog); falls back to the Python writer.
    """
    lib = _load()
    if lib is None:
        from dryad_tpu.columnar import io as cio

        cio.write_partition_file(path, cols, compression)
        return
    names = list(cols.keys())
    arrays = [np.ascontiguousarray(cols[n]) for n in names]
    rows = len(arrays[0]) if arrays else 0
    name_arr = (ctypes.c_char_p * len(names))(*[n.encode() for n in names])
    dt_arr = (ctypes.c_char_p * len(names))(
        *[str(a.dtype).encode() for a in arrays]
    )
    buf_arr = (ctypes.c_void_p * len(names))(
        *[a.ctypes.data_as(ctypes.c_void_p).value for a in arrays]
    )
    len_arr = (ctypes.c_uint64 * len(names))(*[a.nbytes for a in arrays])
    level = 6 if (compression or "none") == "zlib" else -1
    rc = lib.dn_write_partition(
        path.encode(), len(names), name_arr, dt_arr, buf_arr, len_arr,
        rows, level,
    )
    if rc != 0:
        raise IOError(f"native partition write failed rc={rc} path={path}")


class Fifo:
    """Bounded blocking byte-block queue (reference RChannelFifo,
    ``channelfifo.h:31-136``): the in-process channel between pipelined
    producer/consumer threads, with latch flow control.

    Semantics (both backends): ``push`` blocks while full, returns False
    once closed; ``pop`` blocks until a block or close, then returns
    None at end-of-stream (repeatably); ``close`` never blocks.
    """

    def __init__(self, depth: int = 4):
        self._lib = _load()
        # The native pop hands out a pointer into a buffer owned by the
        # channel that is only valid until the next pop — serialize
        # pop+copy so concurrent consumers can't invalidate it.
        self._pop_lock = threading.Lock()
        if self._lib is not None:
            self._handle = self._lib.dn_fifo_create(depth)
        else:
            self._handle = None
            self._depth = max(1, depth)
            self._deque: List[bytes] = []
            self._closed = False
            self._cv = threading.Condition()

    def push(self, data: bytes) -> bool:
        if self._handle is not None:
            return self._lib.dn_fifo_push(self._handle, data, len(data)) == 0
        with self._cv:
            while not self._closed and len(self._deque) >= self._depth:
                self._cv.wait()
            if self._closed:
                return False
            self._deque.append(data)
            self._cv.notify_all()
            return True

    def pop(self) -> Optional[bytes]:
        """Next block, or None at end of stream (writer closed + drained)."""
        if self._handle is not None:
            with self._pop_lock:
                ptr = ctypes.POINTER(ctypes.c_uint8)()
                n = self._lib.dn_fifo_pop(self._handle, ctypes.byref(ptr))
                if n < 0:
                    return None
                return ctypes.string_at(ptr, n)
        with self._cv:
            while not self._closed and not self._deque:
                self._cv.wait()
            if not self._deque:
                return None
            item = self._deque.pop(0)
            self._cv.notify_all()
            return item

    def close(self) -> None:
        if self._handle is not None:
            self._lib.dn_fifo_close(self._handle)
            return
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def destroy(self) -> None:
        if self._handle is not None:
            self._lib.dn_fifo_destroy(self._handle)
            self._handle = None


def tlv_encode(entries: List[Tuple[int, bytes]]) -> bytes:
    """Encode (tag, value) pairs as the TLV property wire format
    (reference property blocks, ``gang/DrProperty.cpp``):
    tag u16 LE + len u32 LE + value."""
    for tag, val in entries:
        if not 0 <= tag <= 0xFFFF:
            raise ValueError(f"TLV tag {tag} outside u16 range")
        if len(val) > 0xFFFFFFFF:
            raise ValueError("TLV value exceeds u32 length")
    lib = _load()
    if lib is not None and entries:
        tags = (ctypes.c_uint16 * len(entries))(*[t for t, _ in entries])
        vals = [v for _, v in entries]
        lens = (ctypes.c_uint32 * len(entries))(*[len(v) for v in vals])
        ptrs = (ctypes.c_void_p * len(entries))(
            *[ctypes.cast(ctypes.c_char_p(v), ctypes.c_void_p).value
              for v in vals]
        )
        size = lib.dn_tlv_encoded_size(len(entries), lens)
        out = ctypes.create_string_buffer(size)
        got = lib.dn_tlv_encode(len(entries), tags, ptrs, lens, out, size)
        if got != size:
            raise ValueError("tlv encode overflow")
        return out.raw
    import struct

    parts = []
    for tag, val in entries:
        parts.append(struct.pack("<HI", tag, len(val)))
        parts.append(val)
    return b"".join(parts)


def tlv_decode(buf: bytes) -> List[Tuple[int, bytes]]:
    """Decode a TLV property block; raises ValueError on malformed input."""
    lib = _load()
    if lib is not None and buf:
        max_n = max(1, len(buf) // 6)
        tags = (ctypes.c_uint16 * max_n)()
        offs = (ctypes.c_uint64 * max_n)()
        lens = (ctypes.c_uint32 * max_n)()
        n = lib.dn_tlv_decode(buf, len(buf), max_n, tags, offs, lens)
        if n == ctypes.c_size_t(-1).value:
            raise ValueError("malformed TLV block")
        return [
            (int(tags[i]), buf[offs[i] : offs[i] + lens[i]]) for i in range(n)
        ]
    import struct

    out = []
    at = 0
    while at < len(buf):
        if at + 6 > len(buf):
            raise ValueError("malformed TLV block")
        tag, ln = struct.unpack_from("<HI", buf, at)
        if at + 6 + ln > len(buf):
            raise ValueError("malformed TLV block")
        out.append((tag, buf[at + 6 : at + 6 + ln]))
        at += 6 + ln
    return out


class PrefetchChannel:
    """Ordered multi-file reader with background prefetch.

    The analog of the reference's async channel buffer readers; iterate
    to get each file's bytes in order.
    """

    def __init__(self, paths: List[str], depth: int = 4, threads: int = 2):
        self.paths = list(paths)
        self._lib = _load()
        self._handle = None
        self._fallback_iter = None
        if self._lib is not None:
            arr = (ctypes.c_char_p * len(self.paths))(
                *[p.encode() for p in self.paths]
            )
            self._handle = self._lib.dn_channel_open(
                arr, len(self.paths), depth, threads
            )

    def __iter__(self):
        if self._handle is not None:
            ptr = ctypes.POINTER(ctypes.c_uint8)()
            while True:
                n = self._lib.dn_channel_next(self._handle, ctypes.byref(ptr))
                if n == -1:
                    break
                if n == -2:
                    raise IOError("native channel read error")
                yield ctypes.string_at(ptr, n)
        else:
            for p in self.paths:
                with open(p, "rb") as fh:
                    yield fh.read()

    def close(self) -> None:
        if self._handle is not None:
            self._lib.dn_channel_close(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
