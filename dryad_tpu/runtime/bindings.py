"""ctypes bindings for the native runtime, with pure-Python fallbacks.

The shared library is built from ``runtime/native`` with the checked-in
Makefile; if it is missing we attempt one build, then fall back to
Python implementations (correct, slower).  Every native function has an
identical-semantics Python twin so the engine never *requires* the
native library.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import zlib
from typing import List, Optional, Tuple

import numpy as np

from dryad_tpu.columnar.schema import hash64_bytes
from dryad_tpu.utils.logging import get_logger

log = get_logger("dryad_tpu.runtime")

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libdryadnative.so")
_lib = None
_lib_tried = False
_lock = threading.Lock()


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_tried
    with _lock:
        if _lib is not None or _lib_tried:
            return _lib
        _lib_tried = True
        if not os.path.exists(_LIB_PATH):
            try:
                subprocess.run(
                    ["make", "-C", _NATIVE_DIR],
                    check=True, capture_output=True, timeout=120,
                )
            except Exception as e:  # no toolchain: fall back
                log.warning("native build failed (%s); using Python fallbacks", e)
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError as e:
            log.warning("native load failed (%s); using Python fallbacks", e)
            return None
        lib.dn_hash64.restype = ctypes.c_uint64
        lib.dn_hash64.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        lib.dn_token_count.restype = ctypes.c_size_t
        lib.dn_token_count.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        lib.dn_tokenize.restype = ctypes.c_size_t
        lib.dn_tokenize.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_uint32),
            ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint32),
        ]
        lib.dn_channel_open.restype = ctypes.c_void_p
        lib.dn_channel_open.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_size_t,
            ctypes.c_size_t, ctypes.c_size_t,
        ]
        lib.dn_channel_next.restype = ctypes.c_int64
        lib.dn_channel_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ]
        lib.dn_channel_close.restype = None
        lib.dn_channel_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        log.info("native runtime loaded from %s", _LIB_PATH)
        return _lib


def native_available() -> bool:
    return _load() is not None


def hash64(data: bytes) -> int:
    lib = _load()
    if lib is not None:
        return int(lib.dn_hash64(data, len(data)))
    return hash64_bytes(data)


def tokenize(
    text: bytes,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Whitespace-tokenize a byte buffer into columnar token arrays.

    Returns (h0, h1, r0, starts, lens): Hash64 word pairs, 4-byte prefix
    ranks, and byte offsets/lengths for dictionary construction.
    """
    lib = _load()
    if lib is not None:
        n = lib.dn_token_count(text, len(text))
        h0 = np.empty(n, np.uint32)
        h1 = np.empty(n, np.uint32)
        r0 = np.empty(n, np.uint32)
        starts = np.empty(n, np.uint64)
        lens = np.empty(n, np.uint32)
        got = lib.dn_tokenize(
            text, len(text), n,
            h0.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            h1.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            r0.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            starts.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            lens.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        )
        assert got == n
        return h0, h1, r0, starts, lens

    # Python fallback
    from dryad_tpu.columnar.schema import string_prefix_rank

    tokens = []
    starts_l = []
    i = 0
    while i < len(text):
        while i < len(text) and text[i : i + 1].isspace():
            i += 1
        if i >= len(text):
            break
        s = i
        while i < len(text) and not text[i : i + 1].isspace():
            i += 1
        tokens.append(text[s:i])
        starts_l.append(s)
    hashes = np.array([hash64_bytes(t) for t in tokens], np.uint64)
    h0 = (hashes & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    h1 = (hashes >> np.uint64(32)).astype(np.uint32)
    r0 = string_prefix_rank(np.array([t.decode("utf-8", "replace") for t in tokens], object))
    return (
        h0, h1, r0,
        np.array(starts_l, np.uint64),
        np.array([len(t) for t in tokens], np.uint32),
    )


class PrefetchChannel:
    """Ordered multi-file reader with background prefetch.

    The analog of the reference's async channel buffer readers; iterate
    to get each file's bytes in order.
    """

    def __init__(self, paths: List[str], depth: int = 4, threads: int = 2):
        self.paths = list(paths)
        self._lib = _load()
        self._handle = None
        self._fallback_iter = None
        if self._lib is not None:
            arr = (ctypes.c_char_p * len(self.paths))(
                *[p.encode() for p in self.paths]
            )
            self._handle = self._lib.dn_channel_open(
                arr, len(self.paths), depth, threads
            )

    def __iter__(self):
        if self._handle is not None:
            ptr = ctypes.POINTER(ctypes.c_uint8)()
            while True:
                n = self._lib.dn_channel_next(self._handle, ctypes.byref(ptr))
                if n == -1:
                    break
                if n == -2:
                    raise IOError("native channel read error")
                yield ctypes.string_at(ptr, n)
        else:
            for p in self.paths:
                with open(p, "rb") as fh:
                    yield fh.read()

    def close(self) -> None:
        if self._handle is not None:
            self._lib.dn_channel_close(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
