// dryad_tpu native runtime.
//
// TPU-native equivalents of the reference's native data-plane pieces:
//  - Hash64 (FNV-1a, identical to columnar/schema.py) — the
//    deterministic record hash (reference LinqToDryad/Hash64.cs).
//  - Whitespace tokenizer producing hash words + 4-byte prefix ranks
//    for direct columnar ingest (reference does tokenization inside
//    generated vertex code; we do it at the ingest edge).
//  - A threaded prefetch channel reader: background threads read (and
//    zlib-decompress) partition files ahead of the consumer — the
//    analog of the reference's async IOCP channel buffer readers
//    (DryadVertex/.../channelbuffernativereader.cpp) and the managed
//    record-reader prefetch thread (DryadLinqRecordReader.cs:107-124).
//
// Exposed as a C ABI for ctypes; see runtime/bindings.py.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <zlib.h>

extern "C" {

// ---------------------------------------------------------------- hash64
static const uint64_t FNV_OFFSET = 0xCBF29CE484222325ULL;
static const uint64_t FNV_PRIME = 0x100000001B3ULL;

uint64_t dn_hash64(const uint8_t* data, size_t len) {
  uint64_t h = FNV_OFFSET;
  for (size_t i = 0; i < len; ++i) {
    h ^= (uint64_t)data[i];
    h *= FNV_PRIME;
  }
  return h;
}

// ------------------------------------------------------------- tokenizer
static inline int is_space(uint8_t c) {
  return c == ' ' || c == '\t' || c == '\n' || c == '\r' || c == '\f' ||
         c == '\v';
}

// Count whitespace-separated tokens in buf.
size_t dn_token_count(const uint8_t* buf, size_t len) {
  size_t n = 0;
  size_t i = 0;
  while (i < len) {
    while (i < len && is_space(buf[i])) ++i;
    if (i >= len) break;
    ++n;
    while (i < len && !is_space(buf[i])) ++i;
  }
  return n;
}

// Tokenize: fill per-token hash (lo/hi u32 words), 4-byte prefix rank,
// and byte offsets/lengths (for host-side dictionary construction).
// Returns the number of tokens written (<= max_tokens).
size_t dn_tokenize(const uint8_t* buf, size_t len, size_t max_tokens,
                   uint32_t* h0, uint32_t* h1, uint32_t* r0,
                   uint64_t* starts, uint32_t* lens) {
  size_t n = 0;
  size_t i = 0;
  while (i < len && n < max_tokens) {
    while (i < len && is_space(buf[i])) ++i;
    if (i >= len) break;
    size_t s = i;
    uint64_t h = FNV_OFFSET;
    uint32_t rank = 0;
    while (i < len && !is_space(buf[i])) {
      uint8_t c = buf[i];
      h ^= (uint64_t)c;
      h *= FNV_PRIME;
      size_t pos = i - s;
      if (pos < 4) rank |= ((uint32_t)c) << (8 * (3 - pos));
      ++i;
    }
    h0[n] = (uint32_t)(h & 0xFFFFFFFFULL);
    h1[n] = (uint32_t)(h >> 32);
    r0[n] = rank;
    starts[n] = (uint64_t)s;
    lens[n] = (uint32_t)(i - s);
    ++n;
  }
  return n;
}

// ------------------------------------------------------ zlib transforms
// Channel compression transform (reference TransformType gzip/deflate,
// dryadvertex.h:33-48).  Returns compressed size or 0 on error.
size_t dn_compress(const uint8_t* src, size_t src_len, uint8_t* dst,
                   size_t dst_cap, int level) {
  uLongf out_len = (uLongf)dst_cap;
  int rc = compress2(dst, &out_len, src, (uLong)src_len, level);
  return rc == Z_OK ? (size_t)out_len : 0;
}

size_t dn_decompress(const uint8_t* src, size_t src_len, uint8_t* dst,
                     size_t dst_cap) {
  uLongf out_len = (uLongf)dst_cap;
  int rc = uncompress(dst, &out_len, src, (uLong)src_len);
  return rc == Z_OK ? (size_t)out_len : 0;
}

size_t dn_compress_bound(size_t src_len) { return compressBound(src_len); }

// --------------------------------------------- prefetch channel reader
// Reads whole files on background threads, keeping up to `depth` blocks
// queued.  Consumer pops blocks in file order.
struct Block {
  std::vector<uint8_t> data;
  int64_t index;
  int32_t error;  // 0 ok, nonzero errno-style
};

struct Channel {
  std::vector<std::string> paths;
  size_t next_read = 0;      // next file index to schedule
  size_t next_deliver = 0;   // next file index to hand out
  size_t depth;
  std::deque<Block> ready;
  std::mutex mu;
  std::condition_variable cv_space;
  std::condition_variable cv_data;
  std::vector<std::thread> workers;
  std::atomic<bool> stop{false};
  std::mutex sched_mu;

  // Current block handed to the consumer (kept alive until next pop).
  Block current;
};

static void read_file(const std::string& path, Block* b) {
  FILE* f = fopen(path.c_str(), "rb");
  if (!f) {
    b->error = 1;
    return;
  }
  fseek(f, 0, SEEK_END);
  long sz = ftell(f);
  fseek(f, 0, SEEK_SET);
  b->data.resize((size_t)sz);
  size_t got = fread(b->data.data(), 1, (size_t)sz, f);
  fclose(f);
  b->error = (got == (size_t)sz) ? 0 : 2;
}

static void worker_loop(Channel* ch) {
  for (;;) {
    size_t idx;
    {
      std::lock_guard<std::mutex> g(ch->sched_mu);
      if (ch->stop.load() || ch->next_read >= ch->paths.size()) return;
      idx = ch->next_read++;
    }
    Block b;
    b.index = (int64_t)idx;
    b.error = 0;
    read_file(ch->paths[idx], &b);
    {
      std::unique_lock<std::mutex> g(ch->mu);
      // Always admit the block the consumer is waiting for, even when
      // the queue is at depth — otherwise out-of-order arrivals fill
      // the queue and deadlock against the in-order consumer.
      ch->cv_space.wait(g, [ch, &b] {
        return ch->stop.load() || ch->ready.size() < ch->depth ||
               (size_t)b.index == ch->next_deliver;
      });
      if (ch->stop.load()) return;
      ch->ready.push_back(std::move(b));
      ch->cv_data.notify_all();
    }
  }
}

void* dn_channel_open(const char** paths, size_t n_paths, size_t depth,
                      size_t n_threads) {
  Channel* ch = new Channel();
  for (size_t i = 0; i < n_paths; ++i) ch->paths.emplace_back(paths[i]);
  ch->depth = depth < 1 ? 1 : depth;
  size_t nt = n_threads < 1 ? 1 : n_threads;
  if (nt > ch->paths.size() && !ch->paths.empty()) nt = ch->paths.size();
  for (size_t i = 0; i < nt; ++i)
    ch->workers.emplace_back(worker_loop, ch);
  return (void*)ch;
}

// Pop the next file (in order). Returns byte length, sets *data to an
// internally-owned buffer valid until the next call; -1 at end of
// channel; -2 on read error.
int64_t dn_channel_next(void* handle, const uint8_t** data) {
  Channel* ch = (Channel*)handle;
  if (ch->next_deliver >= ch->paths.size()) return -1;
  size_t want = ch->next_deliver;
  std::unique_lock<std::mutex> g(ch->mu);
  for (;;) {
    for (auto it = ch->ready.begin(); it != ch->ready.end(); ++it) {
      if ((size_t)it->index == want) {
        ch->current = std::move(*it);
        ch->ready.erase(it);
        ch->cv_space.notify_all();
        ch->next_deliver++;
        if (ch->current.error) return -2;
        *data = ch->current.data.data();
        return (int64_t)ch->current.data.size();
      }
    }
    ch->cv_data.wait(g);
  }
}

void dn_channel_close(void* handle) {
  Channel* ch = (Channel*)handle;
  ch->stop.store(true);
  ch->cv_space.notify_all();
  ch->cv_data.notify_all();
  for (auto& t : ch->workers) t.join();
  delete ch;
}

}  // extern "C"
