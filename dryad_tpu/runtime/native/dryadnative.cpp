// dryad_tpu native runtime.
//
// TPU-native equivalents of the reference's native data-plane pieces:
//  - Hash64 (FNV-1a, identical to columnar/schema.py) — the
//    deterministic record hash (reference LinqToDryad/Hash64.cs).
//  - Whitespace tokenizer producing hash words + 4-byte prefix ranks
//    for direct columnar ingest (reference does tokenization inside
//    generated vertex code; we do it at the ingest edge).
//  - A threaded prefetch channel reader: background threads read (and
//    zlib-decompress) partition files ahead of the consumer — the
//    analog of the reference's async IOCP channel buffer readers
//    (DryadVertex/.../channelbuffernativereader.cpp) and the managed
//    record-reader prefetch thread (DryadLinqRecordReader.cs:107-124).
//
// Exposed as a C ABI for ctypes; see runtime/bindings.py.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <zlib.h>

extern "C" {

// ---------------------------------------------------------------- hash64
static const uint64_t FNV_OFFSET = 0xCBF29CE484222325ULL;
static const uint64_t FNV_PRIME = 0x100000001B3ULL;

uint64_t dn_hash64(const uint8_t* data, size_t len) {
  uint64_t h = FNV_OFFSET;
  for (size_t i = 0; i < len; ++i) {
    h ^= (uint64_t)data[i];
    h *= FNV_PRIME;
  }
  return h;
}

// ------------------------------------------------------------- tokenizer
static inline int is_space(uint8_t c) {
  return c == ' ' || c == '\t' || c == '\n' || c == '\r' || c == '\f' ||
         c == '\v';
}

// Count whitespace-separated tokens in buf.
size_t dn_token_count(const uint8_t* buf, size_t len) {
  size_t n = 0;
  size_t i = 0;
  while (i < len) {
    while (i < len && is_space(buf[i])) ++i;
    if (i >= len) break;
    ++n;
    while (i < len && !is_space(buf[i])) ++i;
  }
  return n;
}

// Tokenize: fill per-token hash (lo/hi u32 words), 8-byte prefix rank
// words (r0 bytes 0-4, r1 bytes 4-8), and byte offsets/lengths (for
// host-side dictionary construction).
// Returns the number of tokens written (<= max_tokens).
size_t dn_tokenize(const uint8_t* buf, size_t len, size_t max_tokens,
                   uint32_t* h0, uint32_t* h1, uint32_t* r0, uint32_t* r1,
                   uint64_t* starts, uint32_t* lens) {
  size_t n = 0;
  size_t i = 0;
  while (i < len && n < max_tokens) {
    while (i < len && is_space(buf[i])) ++i;
    if (i >= len) break;
    size_t s = i;
    uint64_t h = FNV_OFFSET;
    uint32_t rank0 = 0, rank1 = 0;
    while (i < len && !is_space(buf[i])) {
      uint8_t c = buf[i];
      h ^= (uint64_t)c;
      h *= FNV_PRIME;
      size_t pos = i - s;
      if (pos < 4)
        rank0 |= ((uint32_t)c) << (8 * (3 - pos));
      else if (pos < 8)
        rank1 |= ((uint32_t)c) << (8 * (7 - pos));
      ++i;
    }
    h0[n] = (uint32_t)(h & 0xFFFFFFFFULL);
    h1[n] = (uint32_t)(h >> 32);
    r0[n] = rank0;
    r1[n] = rank1;
    starts[n] = (uint64_t)s;
    lens[n] = (uint32_t)(i - s);
    ++n;
  }
  return n;
}

// ------------------------------------------------------ zlib transforms
// Channel compression transform (reference TransformType gzip/deflate,
// dryadvertex.h:33-48).  Returns compressed size or 0 on error.
size_t dn_compress(const uint8_t* src, size_t src_len, uint8_t* dst,
                   size_t dst_cap, int level) {
  uLongf out_len = (uLongf)dst_cap;
  int rc = compress2(dst, &out_len, src, (uLong)src_len, level);
  return rc == Z_OK ? (size_t)out_len : 0;
}

size_t dn_decompress(const uint8_t* src, size_t src_len, uint8_t* dst,
                     size_t dst_cap) {
  uLongf out_len = (uLongf)dst_cap;
  int rc = uncompress(dst, &out_len, src, (uLong)src_len);
  return rc == Z_OK ? (size_t)out_len : 0;
}

size_t dn_compress_bound(size_t src_len) { return compressBound(src_len); }

// Threaded batch decompress: the read half of the channel codec
// (reference async channel readers, channelbuffernativereader.cpp) —
// every column payload of a partition file inflates in parallel into
// caller-owned buffers (numpy arrays on the Python side, zero copy).
// Returns 0 on success; 1 if any column fails to inflate to exactly
// its declared size.
int32_t dn_decompress_batch(size_t n, const uint8_t** srcs,
                            const uint64_t* src_lens, uint8_t** dsts,
                            const uint64_t* dst_lens) {
  std::vector<int> ok(n, 1);
  std::atomic<size_t> next{0};
  auto work = [&]() {
    for (;;) {
      size_t i = next.fetch_add(1);
      if (i >= n) return;
      uLongf out = (uLongf)dst_lens[i];
      int rc = uncompress(dsts[i], &out, srcs[i], (uLong)src_lens[i]);
      if (rc != Z_OK || out != (uLongf)dst_lens[i]) ok[i] = 0;
    }
  };
  size_t nt = std::thread::hardware_concurrency();
  if (nt < 1) nt = 1;
  if (nt > n) nt = n;
  if (nt > 8) nt = 8;
  std::vector<std::thread> pool;
  for (size_t t = 0; t + 1 < nt; ++t) pool.emplace_back(work);
  work();
  for (auto& t : pool) t.join();
  for (size_t i = 0; i < n; ++i)
    if (!ok[i]) return 1;
  return 0;
}

// --------------------------------------------- prefetch channel reader
// Reads whole files on background threads, keeping up to `depth` blocks
// queued.  Consumer pops blocks in file order.
struct Block {
  std::vector<uint8_t> data;
  int64_t index;
  int32_t error;  // 0 ok, nonzero errno-style
};

struct Channel {
  std::vector<std::string> paths;
  size_t next_read = 0;      // next file index to schedule
  size_t next_deliver = 0;   // next file index to hand out
  size_t depth;
  std::deque<Block> ready;
  std::mutex mu;
  std::condition_variable cv_space;
  std::condition_variable cv_data;
  std::vector<std::thread> workers;
  std::atomic<bool> stop{false};
  std::mutex sched_mu;

  // Current block handed to the consumer (kept alive until next pop).
  Block current;
};

static void read_file(const std::string& path, Block* b) {
  FILE* f = fopen(path.c_str(), "rb");
  if (!f) {
    b->error = 1;
    return;
  }
  fseek(f, 0, SEEK_END);
  long sz = ftell(f);
  fseek(f, 0, SEEK_SET);
  b->data.resize((size_t)sz);
  size_t got = fread(b->data.data(), 1, (size_t)sz, f);
  fclose(f);
  b->error = (got == (size_t)sz) ? 0 : 2;
}

static void worker_loop(Channel* ch) {
  for (;;) {
    size_t idx;
    {
      std::lock_guard<std::mutex> g(ch->sched_mu);
      if (ch->stop.load() || ch->next_read >= ch->paths.size()) return;
      idx = ch->next_read++;
    }
    Block b;
    b.index = (int64_t)idx;
    b.error = 0;
    read_file(ch->paths[idx], &b);
    {
      std::unique_lock<std::mutex> g(ch->mu);
      // Always admit the block the consumer is waiting for, even when
      // the queue is at depth — otherwise out-of-order arrivals fill
      // the queue and deadlock against the in-order consumer.
      ch->cv_space.wait(g, [ch, &b] {
        return ch->stop.load() || ch->ready.size() < ch->depth ||
               (size_t)b.index == ch->next_deliver;
      });
      if (ch->stop.load()) return;
      ch->ready.push_back(std::move(b));
      ch->cv_data.notify_all();
    }
  }
}

void* dn_channel_open(const char** paths, size_t n_paths, size_t depth,
                      size_t n_threads) {
  Channel* ch = new Channel();
  for (size_t i = 0; i < n_paths; ++i) ch->paths.emplace_back(paths[i]);
  ch->depth = depth < 1 ? 1 : depth;
  size_t nt = n_threads < 1 ? 1 : n_threads;
  if (nt > ch->paths.size() && !ch->paths.empty()) nt = ch->paths.size();
  for (size_t i = 0; i < nt; ++i)
    ch->workers.emplace_back(worker_loop, ch);
  return (void*)ch;
}

// Pop the next file (in order). Returns byte length, sets *data to an
// internally-owned buffer valid until the next call; -1 at end of
// channel; -2 on read error.
int64_t dn_channel_next(void* handle, const uint8_t** data) {
  Channel* ch = (Channel*)handle;
  if (ch->next_deliver >= ch->paths.size()) return -1;
  size_t want = ch->next_deliver;
  std::unique_lock<std::mutex> g(ch->mu);
  for (;;) {
    for (auto it = ch->ready.begin(); it != ch->ready.end(); ++it) {
      if ((size_t)it->index == want) {
        ch->current = std::move(*it);
        ch->ready.erase(it);
        ch->cv_space.notify_all();
        ch->next_deliver++;
        if (ch->current.error) return -2;
        *data = ch->current.data.data();
        return (int64_t)ch->current.data.size();
      }
    }
    ch->cv_data.wait(g);
  }
}

void dn_channel_close(void* handle) {
  Channel* ch = (Channel*)handle;
  ch->stop.store(true);
  ch->cv_space.notify_all();
  ch->cv_data.notify_all();
  for (auto& t : ch->workers) t.join();
  delete ch;
}

// ------------------------------------------------- partition file writer
// Native twin of columnar/io.py write_partition_file (format doc there):
// JSON header line + per-column payloads, zlib-compressed per column
// when level >= 0.  Columns are compressed concurrently on a small
// thread pool — the analog of the reference's double-buffered async
// channel writer (channelbuffernativewriter.cpp) plus its WorkQueue
// compute pool (workqueue.h).  Returns 0 on success.
int32_t dn_write_partition(const char* path, size_t n_cols,
                           const char** names, const char** dtypes,
                           const uint8_t** bufs, const uint64_t* lens,
                           uint64_t rows, int32_t level) {
  std::vector<std::vector<uint8_t>> payload(n_cols);
  std::vector<int> ok(n_cols, 1);
  std::atomic<size_t> next{0};
  auto work = [&]() {
    for (;;) {
      size_t i = next.fetch_add(1);
      if (i >= n_cols) return;
      if (level >= 0) {
        uLongf cap = compressBound((uLong)lens[i]);
        payload[i].resize((size_t)cap);
        int rc = compress2(payload[i].data(), &cap, bufs[i], (uLong)lens[i],
                           level);
        if (rc != Z_OK) {
          ok[i] = 0;
          return;
        }
        payload[i].resize((size_t)cap);
      } else {
        payload[i].assign(bufs[i], bufs[i] + lens[i]);
      }
    }
  };
  size_t nt = std::thread::hardware_concurrency();
  if (nt < 1) nt = 1;
  if (nt > n_cols) nt = n_cols;
  if (nt > 8) nt = 8;
  std::vector<std::thread> pool;
  for (size_t t = 0; t + 1 < nt; ++t) pool.emplace_back(work);
  work();
  for (auto& t : pool) t.join();
  for (size_t i = 0; i < n_cols; ++i)
    if (!ok[i]) return 1;

  auto json_escape = [](const char* s) {
    std::string out;
    for (const char* p = s; *p; ++p) {
      unsigned char c = (unsigned char)*p;
      if (c == '"' || c == '\\') {
        out += '\\';
        out += (char)c;
      } else if (c < 0x20) {
        char buf[8];
        snprintf(buf, sizeof(buf), "\\u%04x", c);
        out += buf;
      } else {
        out += (char)c;
      }
    }
    return out;
  };
  std::string header = "{\"rows\": " + std::to_string(rows) +
                       ", \"columns\": [";
  for (size_t i = 0; i < n_cols; ++i) {
    if (i) header += ", ";
    header += "{\"name\": \"" + json_escape(names[i]) + "\", \"dtype\": \"" +
              json_escape(dtypes[i]) + "\", \"rows\": " +
              std::to_string(rows) + ", \"comp\": \"" +
              (level >= 0 ? "zlib" : "none") + "\", \"nbytes\": " +
              std::to_string(payload[i].size()) + "}";
  }
  header += "]}\n";

  FILE* f = fopen(path, "wb");
  if (!f) return 2;
  if (fwrite(header.data(), 1, header.size(), f) != header.size()) {
    fclose(f);
    return 3;
  }
  for (size_t i = 0; i < n_cols; ++i) {
    if (!payload[i].empty() &&
        fwrite(payload[i].data(), 1, payload[i].size(), f) !=
            payload[i].size()) {
      fclose(f);
      return 3;
    }
  }
  fclose(f);
  return 0;
}

// ----------------------------------------------------- in-memory FIFO
// Bounded blocking byte-block queue: the in-process pipelined-stage
// channel (reference RChannelFifo, channelfifo.h:31-136) with latch
// flow control — push blocks when the queue holds `depth` blocks, pop
// blocks until a block or writer close arrives.
struct Fifo {
  std::deque<std::vector<uint8_t>> q;
  size_t depth;
  bool closed = false;
  std::mutex mu;
  std::condition_variable cv_space, cv_data;
  std::vector<uint8_t> current;  // block owned for the consumer
};

void* dn_fifo_create(size_t depth) {
  Fifo* f = new Fifo();
  f->depth = depth < 1 ? 1 : depth;
  return (void*)f;
}

// Returns 0 on success, -1 if the FIFO was already closed.
int32_t dn_fifo_push(void* handle, const uint8_t* data, size_t len) {
  Fifo* f = (Fifo*)handle;
  std::unique_lock<std::mutex> g(f->mu);
  f->cv_space.wait(g, [f] { return f->closed || f->q.size() < f->depth; });
  if (f->closed) return -1;
  f->q.emplace_back(data, data + len);
  f->cv_data.notify_one();
  return 0;
}

// Returns block length (>= 0) with *data set, or -1 at end of stream.
int64_t dn_fifo_pop(void* handle, const uint8_t** data) {
  Fifo* f = (Fifo*)handle;
  std::unique_lock<std::mutex> g(f->mu);
  f->cv_data.wait(g, [f] { return f->closed || !f->q.empty(); });
  if (f->q.empty()) return -1;
  f->current = std::move(f->q.front());
  f->q.pop_front();
  f->cv_space.notify_one();
  *data = f->current.data();
  return (int64_t)f->current.size();
}

void dn_fifo_close(void* handle) {
  Fifo* f = (Fifo*)handle;
  std::lock_guard<std::mutex> g(f->mu);
  f->closed = true;
  f->cv_space.notify_all();
  f->cv_data.notify_all();
}

void dn_fifo_destroy(void* handle) { delete (Fifo*)handle; }

// -------------------------------------------- TLV property wire format
// The reference's tag-length-value property block (GM property/metadata
// serialization, gang/DrProperty.cpp; vertex twin dryadmetadata.cpp):
// each entry is tag(u16 LE) + len(u32 LE) + value bytes.  Used for
// binary mailbox payloads (vertex command/status analogs).
size_t dn_tlv_encoded_size(size_t n, const uint32_t* lens) {
  size_t total = 0;
  for (size_t i = 0; i < n; ++i) total += 6 + (size_t)lens[i];
  return total;
}

size_t dn_tlv_encode(size_t n, const uint16_t* tags, const uint8_t** vals,
                     const uint32_t* lens, uint8_t* out, size_t out_cap) {
  size_t at = 0;
  for (size_t i = 0; i < n; ++i) {
    size_t need = 6 + (size_t)lens[i];
    if (at + need > out_cap) return 0;
    out[at] = (uint8_t)(tags[i] & 0xFF);
    out[at + 1] = (uint8_t)(tags[i] >> 8);
    uint32_t l = lens[i];
    out[at + 2] = (uint8_t)(l & 0xFF);
    out[at + 3] = (uint8_t)((l >> 8) & 0xFF);
    out[at + 4] = (uint8_t)((l >> 16) & 0xFF);
    out[at + 5] = (uint8_t)((l >> 24) & 0xFF);
    memcpy(out + at + 6, vals[i], l);
    at += need;
  }
  return at;
}

// Walk a TLV buffer: fills tags/offsets/lens up to max entries; returns
// the entry count, or (size_t)-1 on malformed input.
size_t dn_tlv_decode(const uint8_t* buf, size_t len, size_t max,
                     uint16_t* tags, uint64_t* offs, uint32_t* lens) {
  size_t at = 0, n = 0;
  while (at < len) {
    if (at + 6 > len || n >= max) return (size_t)-1;
    uint16_t tag = (uint16_t)(buf[at] | (buf[at + 1] << 8));
    uint32_t l = (uint32_t)buf[at + 2] | ((uint32_t)buf[at + 3] << 8) |
                 ((uint32_t)buf[at + 4] << 16) | ((uint32_t)buf[at + 5] << 24);
    if (at + 6 + (size_t)l > len) return (size_t)-1;
    tags[n] = tag;
    offs[n] = (uint64_t)(at + 6);
    lens[n] = l;
    ++n;
    at += 6 + (size_t)l;
  }
  return n;
}

}  // extern "C"
