"""User-type codecs — the custom-serializer hook.

The reference lets a user type define its own record serialization
(``IDryadLinqSerializer<T>``, ``DryadLinqSerialization.cs:41``) and
auto-generates serializers for composite types.  Device columns are
fixed-width here, so the TPU-native form of "custom serializer" is a
**codec**: how one logical host column of arbitrary Python objects
expands into typed device columns at ingest, and how those columns fold
back into objects at egress.

A codec declares ``fields()`` (suffix -> ColumnType) and implements
``encode`` (object array -> suffix-keyed typed arrays) / ``decode``
(the inverse).  Ingest expands column ``c`` into ``c.<suffix>`` columns;
egress re-packs when every suffix column survived the query (renaming
or dropping any of them leaves the raw columns in the result).

Built-ins: ``ComplexCodec`` (re/im float32), ``DatetimeCodec``
(microseconds since epoch, INT64), ``PairCodec`` (2-tuples of numbers).
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from dryad_tpu.columnar.schema import ColumnType


class TypeCodec:
    def fields(self) -> List[Tuple[str, ColumnType]]:
        """(suffix, ColumnType) per generated column."""
        raise NotImplementedError

    def encode(self, values: np.ndarray) -> Dict[str, np.ndarray]:
        """Object array -> {suffix: typed array} (all same length)."""
        raise NotImplementedError

    def decode(self, cols: Dict[str, np.ndarray]) -> np.ndarray:
        """{suffix: typed array} -> object array."""
        raise NotImplementedError


def expanded_name(col: str, suffix: str) -> str:
    return f"{col}.{suffix}"


class ComplexCodec(TypeCodec):
    """complex -> (re, im) float32 columns."""

    def fields(self):
        return [("re", ColumnType.FLOAT32), ("im", ColumnType.FLOAT32)]

    def encode(self, values):
        a = np.asarray(values, np.complex64)
        return {"re": a.real.astype(np.float32), "im": a.imag.astype(np.float32)}

    def decode(self, cols):
        return (
            cols["re"].astype(np.float32)
            + 1j * cols["im"].astype(np.float32)
        ).astype(np.complex64)


class DatetimeCodec(TypeCodec):
    """numpy datetime64 -> INT64 microseconds since epoch."""

    def fields(self):
        return [("us", ColumnType.INT64)]

    def encode(self, values):
        a = np.asarray(values, "datetime64[us]")
        return {"us": a.astype(np.int64)}

    def decode(self, cols):
        return cols["us"].astype(np.int64).astype("datetime64[us]")


class PairCodec(TypeCodec):
    """2-tuples of numbers -> two float32 columns (a composite-type
    auto-serializer example, reference ``DryadLinqSerialization.cs``
    Pair/Tuple serializers)."""

    def fields(self):
        return [("a", ColumnType.FLOAT32), ("b", ColumnType.FLOAT32)]

    def encode(self, values):
        a = np.array([v[0] for v in values], np.float32)
        b = np.array([v[1] for v in values], np.float32)
        return {"a": a, "b": b}

    def decode(self, cols):
        out = np.empty(len(cols["a"]), object)
        for i, (x, y) in enumerate(zip(cols["a"], cols["b"])):
            out[i] = (float(x), float(y))
        return out


def expand_arrays(
    arrays: Dict[str, np.ndarray], codecs: Dict[str, TypeCodec]
) -> Dict[str, np.ndarray]:
    """Apply codecs at ingest: replace each coded column with its
    expanded typed columns."""
    out: Dict[str, np.ndarray] = {}
    for name, arr in arrays.items():
        codec = codecs.get(name)
        if codec is None:
            out[name] = arr
            continue
        enc = codec.encode(np.asarray(arr, object))
        declared = {s for s, _t in codec.fields()}
        if set(enc) != declared:
            raise ValueError(
                f"codec for {name!r} produced {sorted(enc)} but declared "
                f"{sorted(declared)}"
            )
        for suffix, col in enc.items():
            out[expanded_name(name, suffix)] = col
    return out


def collapse_table(
    table: Dict[str, np.ndarray], codecs: Dict[str, TypeCodec]
) -> Dict[str, np.ndarray]:
    """Apply codecs at egress: fold suffix columns back into object
    columns where the full set survived."""
    out = dict(table)
    for name, codec in codecs.items():
        suffixes = [s for s, _t in codec.fields()]
        names = [expanded_name(name, s) for s in suffixes]
        if not all(n in out for n in names):
            continue
        packed = codec.decode(
            {s: out[n] for s, n in zip(suffixes, names)}
        )
        for n in names:
            del out[n]
        out[name] = packed
    return out
