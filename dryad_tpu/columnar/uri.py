"""Data-provider URI registry — the DataProvider/DataPath analog.

The reference maps URI schemes to pluggable storage providers
(``LinqToDryad/DataProvider.cs:682`` scheme registry, ``DataPath.cs``:
``partfile://``, ``hdfs://``, ``azureblob://``).  Here:

- ``partfile://<dir>`` (or a bare path) — local partitioned columnar
  store (``columnar/io.py``).
- ``file://<path>``   — raw text file (one STRING ``line`` column).
- ``mem://<name>``    — in-process named table registry (the
  LocalDebug-style test provider).
- ``http://host:port/<rel>`` — a store served by a remote node's
  ProcessService file server (``cluster/service.py``): 2MB range
  reads like the reference's HTTP channel readers
  (``managedchannel/HttpReader.cs:78-110``), PUT writes, zlib wire
  compression.
- ``hdfs://namenode:port/<path>`` — REAL WebHDFS REST
  (``columnar/webhdfs.py``: ranged OPEN with the namenode->datanode
  redirect, two-step CREATE — ``DrHdfsClient.cpp:32-69``,
  ``channelbufferhdfs.cpp``); set ``DRYAD_TPU_DFS_GATEWAY`` to route
  through a framework file gateway instead (secured clusters).
- ``wasb://``, ``abfs://`` — Azure schemes routed through the file
  gateway (``DRYAD_TPU_DFS_GATEWAY``, or the URI authority itself)
  speaking the framework file-plane protocol — the REST-bridge
  pattern of ``DrAzureBlobClient.h:25``.

Register custom providers with ``register_provider``.
"""

from __future__ import annotations

import io as _io
import json
import os
import urllib.parse
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from dryad_tpu.columnar import io as CIO
from dryad_tpu.columnar.schema import ColumnType, Schema, StringDictionary

ReadResult = Tuple[Schema, List[Dict[str, np.ndarray]], StringDictionary]


class DataProvider:
    """Provider interface: read a URI into (schema, partitions,
    dictionary); optionally write a store to a URI."""

    def read(self, uri: str) -> ReadResult:
        raise NotImplementedError

    def write(
        self,
        uri: str,
        partitions: List[Dict[str, np.ndarray]],
        schema: Schema,
        dictionary: Optional[StringDictionary],
        compression: Optional[str],
    ) -> None:
        raise NotImplementedError(f"provider for {uri!r} is read-only")


_PROVIDERS: Dict[str, DataProvider] = {}


def register_provider(scheme: str, provider: DataProvider) -> None:
    _PROVIDERS[scheme] = provider


def split_uri(uri: str) -> Tuple[str, str]:
    """(scheme, rest); bare paths map to 'partfile'."""
    if "://" not in uri:
        return "partfile", uri
    scheme, rest = uri.split("://", 1)
    return scheme.lower(), rest


def get_provider(uri: str) -> Tuple[DataProvider, str]:
    scheme, rest = split_uri(uri)
    p = _PROVIDERS.get(scheme)
    if p is None:
        raise ValueError(
            f"no data provider for scheme {scheme!r} "
            f"(registered: {sorted(_PROVIDERS)})"
        )
    return p, rest


def read_store_uri(uri: str) -> ReadResult:
    p, rest = get_provider(uri)
    return p.read(rest)


def write_store_uri(
    uri: str,
    partitions: List[Dict[str, np.ndarray]],
    schema: Schema,
    dictionary: Optional[StringDictionary],
    compression: Optional[str],
) -> None:
    p, rest = get_provider(uri)
    p.write(rest, partitions, schema, dictionary, compression)


# -- built-in providers ----------------------------------------------------

class PartfileProvider(DataProvider):
    def read(self, path: str) -> ReadResult:
        return CIO.read_store(path)

    def write(self, path, partitions, schema, dictionary, compression,
              threads: int = 4):
        CIO.write_store(
            path, partitions, schema, dictionary, compression, threads
        )


class TextFileProvider(DataProvider):
    """Raw text: one partition, one STRING column ``line``."""

    def read(self, path: str) -> ReadResult:
        from dryad_tpu.columnar.schema import hash64_str, string_prefix_rank

        with open(path, "r", encoding="utf-8", errors="replace") as fh:
            lines = [ln.rstrip("\n") for ln in fh]
        arr = np.array(lines, object)
        schema = Schema([("line", ColumnType.STRING)])
        dictionary = StringDictionary()
        h = np.array([hash64_str(s) for s in lines], np.uint64)
        for hv, s in zip(h, lines):
            dictionary._map[int(hv)] = s
        cols = {
            "line#h0": (h & np.uint64(0xFFFFFFFF)).astype(np.uint32),
            "line#h1": (h >> np.uint64(32)).astype(np.uint32),
            "line#r0": string_prefix_rank(arr),
            "line#r1": string_prefix_rank(arr, offset=4),
        }
        return schema, [cols], dictionary


class MemProvider(DataProvider):
    """In-process named stores (testing / LocalDebug analog)."""

    def __init__(self) -> None:
        self._tables: Dict[str, Tuple] = {}

    def read(self, name: str) -> ReadResult:
        if name not in self._tables:
            raise FileNotFoundError(f"mem://{name}")
        schema, parts, dictionary = self._tables[name]
        return schema, [dict(p) for p in parts], dictionary

    def write(self, name, partitions, schema, dictionary, compression):
        self._tables[name] = (
            schema,
            [dict(p) for p in partitions],
            dictionary or StringDictionary(),
        )


def _read_store_via(fetch: Callable[[str], bytes], threads: int) -> ReadResult:
    """Store read parameterized over a byte transport: manifest ->
    schema, optional dictionary, parallel part-file fan-in."""
    from concurrent.futures import ThreadPoolExecutor

    manifest = json.loads(fetch(CIO.MANIFEST).decode("utf-8"))
    schema = Schema([(n, ColumnType(t)) for n, t in manifest["schema"]])
    dictionary = StringDictionary()
    try:
        dmap = json.loads(fetch(CIO.DICTFILE).decode("utf-8"))
        for h, s in dmap.items():
            dictionary._map[int(h, 16)] = s
    except FileNotFoundError:
        pass
    n = manifest["partitions"]
    with ThreadPoolExecutor(max_workers=min(threads, max(n, 1))) as ex:
        parts = list(
            ex.map(
                lambda i: CIO.parse_partition_bytes(
                    fetch(f"part-{i:05d}.dpf"), copy=False
                ),
                range(n),
            )
        )
    return schema, parts, dictionary


def _write_store_via(
    ship: Callable[[str, bytes], None],
    partitions, schema, dictionary, compression, threads: int,
) -> None:
    """Store write parameterized over a byte transport: stage the exact
    on-disk layout locally, then ship each file in parallel (the
    reference stages partitions to the DFS the same way,
    ``DrPartitionFile.h:50``)."""
    import shutil
    import tempfile
    from concurrent.futures import ThreadPoolExecutor

    tmp = tempfile.mkdtemp(prefix="dryad-store-stage-")
    try:
        CIO.write_store(tmp, partitions, schema, dictionary, compression)
        names = sorted(os.listdir(tmp))

        def one(name: str) -> None:
            with open(os.path.join(tmp, name), "rb") as fh:
                ship(name, fh.read())

        with ThreadPoolExecutor(
            max_workers=min(threads, max(len(names), 1))
        ) as ex:
            list(ex.map(one, names))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


class HttpStoreProvider(DataProvider):
    """A partitioned store on a remote ProcessService FileServer:
    ``http://host:port/<relative store dir>`` — the bulk remote-store
    scheme (the reference's HDFS/Azure stream role,
    ``GraphManager/filesystem/DrHdfsClient.h:29,63``,
    ``channelbufferhdfs.cpp``).  Reads are 2MB HTTP range reads with
    zlib wire compression (``managedchannel/HttpReader.cs:78-110``;
    transform ``dryadvertex.h:33-48``); writes PUT each store file,
    compressed, so TB-scale ingest/egress rides the DCN file plane.
    Partition fetches run on a small thread pool (the async
    channel-reader analog)."""

    THREADS = 4

    def _client(self, rest: str):
        from dryad_tpu.cluster.service import ServiceClient

        netloc, _, rel = rest.partition("/")
        host, _, port = netloc.partition(":")
        return ServiceClient(host, int(port or 80)), rel.strip("/")

    def read(self, rest: str) -> ReadResult:
        client, prefix = self._client(rest)
        return _read_store_via(
            lambda name: client.read_whole_file(
                f"{prefix}/{name}" if prefix else name, compress=True
            ),
            self.THREADS,
        )

    def write(self, rest, partitions, schema, dictionary, compression):
        client, prefix = self._client(rest)
        _write_store_via(
            lambda name, data: client.write_file(
                f"{prefix}/{name}" if prefix else name, data, compress=True
            ),
            partitions, schema, dictionary, compression, self.THREADS,
        )


class DfsGatewayProvider(DataProvider):
    """Cloud-DFS scheme adapter: ``hdfs://``, ``wasb://``, ``abfs://``
    URIs route through a cluster file gateway speaking the
    ProcessService file-plane protocol (2MB range reads + zlib wire
    compression).  The reference reads these schemes through a managed
    WebHDFS/Azure REST bridge (``DrHdfsClient.cpp:32-69``,
    ``DrAzureBlobClient.h:25``) — the same gateway-REST pattern; here
    the gateway is any ProcessService-compatible file server.

    Routing: with ``DRYAD_TPU_DFS_GATEWAY=host:port`` set, the store
    lives under ``<gateway>/<scheme>/<authority>/<path>`` (one gateway
    fronts many DFS namespaces); without it, the URI authority itself
    must be a reachable ``host:port`` file server (an "HDFS namenode"
    that IS the gateway)."""

    def __init__(self, scheme: str, via: "HttpStoreProvider"):
        self.scheme = scheme
        self.via = via

    def _route(self, rest: str) -> str:
        gw = os.environ.get("DRYAD_TPU_DFS_GATEWAY")
        if not gw:
            return rest
        netloc, _, rel = rest.partition("/")
        path = f"{self.scheme}/{netloc}/{rel}".rstrip("/")
        return f"{gw}/{path}"

    def read(self, rest: str) -> ReadResult:
        return self.via.read(self._route(rest))

    def write(self, rest, partitions, schema, dictionary, compression):
        self.via.write(
            self._route(rest), partitions, schema, dictionary, compression
        )


class WebHdfsProvider(DataProvider):
    """``hdfs://namenode:port/path`` speaking REAL WebHDFS REST
    (``columnar/webhdfs.py``): ranged OPEN with the namenode->datanode
    307 redirect, two-step CREATE, LISTSTATUS — the protocol the
    reference's ``DrHdfsClient.cpp:32-69`` and ``channelbufferhdfs.cpp``
    speak.  Part files fetch in parallel, each chunked-parallel through
    the native Fifo pipeline.

    With ``DRYAD_TPU_DFS_GATEWAY`` set the scheme instead routes
    through the framework file gateway (``DfsGatewayProvider``) — the
    escape hatch for secured (Kerberos) clusters the plain client
    can't talk to."""

    THREADS = 4

    def _gateway(self) -> Optional["DfsGatewayProvider"]:
        if os.environ.get("DRYAD_TPU_DFS_GATEWAY"):
            return DfsGatewayProvider("hdfs", _HTTP)
        return None

    def _client(self, rest: str):
        from dryad_tpu.columnar.webhdfs import (
            WebHdfsClient, parse_hdfs_netloc,
        )

        host, port, path = parse_hdfs_netloc(rest)
        return WebHdfsClient(host, port), path

    def read(self, rest: str) -> ReadResult:
        gw = self._gateway()
        if gw is not None:
            return gw.read(rest)
        client, base = self._client(rest)
        return _read_store_via(
            lambda name: client.read_file(f"{base}/{name}"), self.THREADS
        )

    def write(self, rest, partitions, schema, dictionary, compression):
        gw = self._gateway()
        if gw is not None:
            return gw.write(rest, partitions, schema, dictionary, compression)
        client, base = self._client(rest)
        client.mkdirs(base)
        _write_store_via(
            lambda name, data: client.create(f"{base}/{name}", data),
            partitions, schema, dictionary, compression, self.THREADS,
        )


class AzureBlobProvider(DataProvider):
    """``wasb://container@host[:port]/path`` (and ``abfs://``) speaking
    REAL Azure Blob REST (``columnar/azblob.py``: ranged Get Blob,
    BlockBlob Put, XML List Blobs — the surface of
    ``DrAzureBlobClient.h:25,42``).  SAS auth via
    ``DRYAD_TPU_AZURE_SAS``.

    URIs WITHOUT the ``container@`` authority, or any URI when
    ``DRYAD_TPU_DFS_GATEWAY`` is set, keep the legacy framework
    file-gateway route (``DfsGatewayProvider``) — the secured-cluster /
    Shared-Key escape hatch."""

    THREADS = 4

    def __init__(self, scheme: str, gateway: "DfsGatewayProvider"):
        self.scheme = scheme
        self.gateway = gateway

    def _route(self, rest: str):
        from dryad_tpu.columnar.azblob import (
            AzureBlobClient, parse_wasb_netloc,
        )

        if os.environ.get("DRYAD_TPU_DFS_GATEWAY"):
            return None
        try:
            container, host, port, base = parse_wasb_netloc(rest)
        except ValueError:
            return None  # no container@ authority: legacy gateway form
        return AzureBlobClient(host, port), container, base

    def read(self, rest: str) -> ReadResult:
        routed = self._route(rest)
        if routed is None:
            return self.gateway.read(rest)
        client, container, base = routed
        return _read_store_via(
            lambda name: client.get_blob(
                container, f"{base}/{name}" if base else name
            ),
            self.THREADS,
        )

    def write(self, rest, partitions, schema, dictionary, compression):
        routed = self._route(rest)
        if routed is None:
            return self.gateway.write(
                rest, partitions, schema, dictionary, compression
            )
        client, container, base = routed
        client.create_container(container)
        _write_store_via(
            lambda name, data: client.put_blob(
                container, f"{base}/{name}" if base else name, data
            ),
            partitions, schema, dictionary, compression, self.THREADS,
        )


_HTTP = HttpStoreProvider()
register_provider("partfile", PartfileProvider())
register_provider("file", TextFileProvider())
register_provider("mem", MemProvider())
register_provider("http", _HTTP)
register_provider("hdfs", WebHdfsProvider())
for _scheme in ("wasb", "abfs"):
    register_provider(
        _scheme, AzureBlobProvider(_scheme, DfsGatewayProvider(_scheme, _HTTP))
    )
