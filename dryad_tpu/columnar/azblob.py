"""Azure Blob Storage REST client — the ``wasb://`` / ``abfs://``
ingest/egress path.

The reference reads/writes Azure blobs natively
(``GraphManager/filesystem/DrAzureBlobClient.h:25,42``; the managed
``AzureCollectionPartition`` streams).  This module speaks the actual
Blob service REST surface:

- ``Get Blob`` with an ``x-ms-range`` header (206 partial content) —
  chunk-parallel through the shared read-ahead pipeline
  (``columnar/chunked.py``);
- ``Put Blob`` (``x-ms-blob-type: BlockBlob``);
- ``Get Blob Properties`` (HEAD), ``List Blobs``
  (``restype=container&comp=list``, XML), ``Create Container``,
  ``Delete Blob``.

Auth: a SAS token appended to every request's query string
(``DRYAD_TPU_AZURE_SAS`` or ``sas=``) — the standard
shared-access-signature scheme; anonymous works against public
containers, Azurite, and the in-tree stub (``tools/azblob_stub.py``).
Shared-Key signing is out of scope — use SAS or route through the
framework file gateway (``uri.DfsGatewayProvider``).
"""

from __future__ import annotations

import http.client
import os
import urllib.parse
import xml.etree.ElementTree as ET
from typing import Dict, List, Optional, Tuple

DEFAULT_CHUNK = 4 * 1024 * 1024


class AzureBlobError(IOError):
    def __init__(self, status: int, body: bytes, context: str):
        self.status = status
        detail = body[:300].decode("utf-8", "replace")
        # Azure error bodies are XML: <Error><Code>..</Code><Message>..
        try:
            root = ET.fromstring(body.decode("utf-8"))
            code = root.findtext("Code") or ""
            msg = (root.findtext("Message") or "").splitlines()[0]
            detail = f"{code}: {msg}"
        except Exception:  # noqa: BLE001 - non-XML body
            pass
        super().__init__(f"azure blob {context}: HTTP {status}: {detail}")


class AzureBlobClient:
    """Minimal Blob service client over ``http.client`` (stdlib only).

    ``host``/``port`` address the blob endpoint (the account host in
    real Azure, e.g. ``acct.blob.core.windows.net:443``; a local
    Azurite/stub otherwise)."""

    def __init__(
        self,
        host: str,
        port: int = 443,
        sas: Optional[str] = None,
        https: Optional[bool] = None,
        chunk: int = DEFAULT_CHUNK,
        threads: int = 4,
        depth: int = 4,
        timeout: float = 30.0,
    ):
        self.host = host
        self.port = int(port)
        self.sas = (sas or os.environ.get("DRYAD_TPU_AZURE_SAS") or "").lstrip("?")
        self.https = bool(port == 443) if https is None else https
        self.chunk = int(chunk)
        self.threads = int(threads)
        self.depth = int(depth)
        self.timeout = timeout

    # -- plumbing ----------------------------------------------------------
    def _url(self, container: str, blob: str = "", **params) -> str:
        path = f"/{urllib.parse.quote(container)}"
        if blob:
            path += f"/{urllib.parse.quote(blob, safe='/')}"
        q = [(k, str(v)) for k, v in params.items() if v is not None]
        query = urllib.parse.urlencode(q)
        if self.sas:
            query = f"{query}&{self.sas}" if query else self.sas
        return f"{path}?{query}" if query else path

    def _request(
        self,
        method: str,
        url: str,
        body: Optional[bytes] = None,
        headers: Optional[Dict[str, str]] = None,
        context: str = "",
    ) -> Tuple[int, Dict[str, str], bytes]:
        cls = (
            http.client.HTTPSConnection if self.https
            else http.client.HTTPConnection
        )
        c = cls(self.host, self.port, timeout=self.timeout)
        try:
            hs = {"x-ms-version": "2021-08-06", **(headers or {})}
            c.request(method, url, body=body, headers=hs)
            r = c.getresponse()
            data = r.read()
            return r.status, {k.lower(): v for k, v in r.getheaders()}, data
        finally:
            c.close()

    # -- container / metadata ---------------------------------------------
    def create_container(self, container: str) -> None:
        st, _h, body = self._request(
            "PUT", self._url(container, restype="container"),
            context=f"create container {container}",
        )
        if st not in (201, 409):  # 409 = already exists
            raise AzureBlobError(st, body, f"create container {container}")

    def blob_size(self, container: str, blob: str) -> int:
        st, h, body = self._request(
            "HEAD", self._url(container, blob),
            context=f"head {container}/{blob}",
        )
        if st == 404:
            raise FileNotFoundError(f"{container}/{blob}")
        if st != 200:
            raise AzureBlobError(st, body, f"head {container}/{blob}")
        return int(h.get("content-length", "0"))

    def list_blobs(self, container: str, prefix: str = "") -> List[str]:
        """List Blobs (flat): names under ``prefix``."""
        st, _h, body = self._request(
            "GET",
            self._url(
                container, restype="container", comp="list",
                prefix=prefix or None,
            ),
            context=f"list {container}",
        )
        if st != 200:
            raise AzureBlobError(st, body, f"list {container}")
        root = ET.fromstring(body.decode("utf-8"))
        return [
            el.text or ""
            for el in root.findall("./Blobs/Blob/Name")
        ]

    def delete_blob(self, container: str, blob: str) -> bool:
        st, _h, body = self._request(
            "DELETE", self._url(container, blob),
            context=f"delete {container}/{blob}",
        )
        if st == 404:
            return False
        if st != 202:
            raise AzureBlobError(st, body, f"delete {container}/{blob}")
        return True

    # -- data --------------------------------------------------------------
    def get_range(
        self, container: str, blob: str, offset: int, length: int
    ) -> bytes:
        st, _h, data = self._request(
            "GET", self._url(container, blob),
            headers={"x-ms-range": f"bytes={offset}-{offset + length - 1}"},
            context=f"get {container}/{blob}",
        )
        if st == 404:
            raise FileNotFoundError(f"{container}/{blob}")
        if st not in (200, 206):
            raise AzureBlobError(st, data, f"get {container}/{blob}")
        return data

    def get_blob(self, container: str, blob: str) -> bytes:
        """Whole blob via the shared chunk-parallel read-ahead."""
        from dryad_tpu.columnar.chunked import chunked_read

        size = self.blob_size(container, blob)
        return chunked_read(
            size,
            lambda off, ln: self.get_range(container, blob, off, ln),
            self.chunk, self.threads, self.depth,
        )

    def put_blob(self, container: str, blob: str, data: bytes) -> None:
        st, _h, body = self._request(
            "PUT", self._url(container, blob), body=data,
            headers={
                "x-ms-blob-type": "BlockBlob",
                "Content-Length": str(len(data)),
            },
            context=f"put {container}/{blob}",
        )
        if st != 201:
            raise AzureBlobError(st, body, f"put {container}/{blob}")


def parse_wasb_netloc(rest: str) -> Tuple[str, str, int, str]:
    """Split the non-scheme part of
    ``wasb://container@host[:port]/path`` -> (container, host, port,
    path).  Raises ValueError when no ``container@`` authority is
    present (those URIs route through the legacy file gateway)."""
    netloc, _, rel = rest.partition("/")
    if "@" not in netloc:
        raise ValueError(f"no container@account authority in {rest!r}")
    container, _, hostport = netloc.partition("@")
    host, _, port = hostport.partition(":")
    return container, host, int(port or 443), rel.strip("/")
