"""Real WebHDFS REST client — the cloud-DFS ingest/egress path.

The reference reads and writes HDFS natively through libhdfs/WebHDFS
(``GraphManager/filesystem/DrHdfsClient.cpp:32-69``; the vertex-side
stream reader ``DryadVertex/VertexHost/system/channel/channelbufferhdfs.cpp``).
This module speaks the actual WebHDFS HTTP protocol:

- ``OPEN`` with ``offset``/``length`` range params, following the
  namenode's 307 redirect to the datanode (the two-hop read dance);
- ``CREATE`` via the two-step redirect PUT (namenode allocates, the
  data body goes to the redirect target);
- ``MKDIRS``, ``GETFILESTATUS``, ``LISTSTATUS``, ``DELETE``.

Large files are fetched **chunked-parallel**: a window of ranged OPEN
reads runs on a thread pool, and completed chunks flow to the consumer
in order through the native ``Fifo`` (``runtime/native/
dryadnative.cpp`` — the async channel-buffer reader pattern of
``channelbufferhdfs.cpp``'s read-ahead), so memory stays bounded at
``depth`` chunks while the network pipe stays full.

Simple (user.name) authentication only; set ``DRYAD_TPU_HDFS_USER`` or
pass ``user=``.  Kerberos/delegation tokens are out of scope — gate via
a fronting gateway for secured clusters (``uri.DfsGatewayProvider``).
"""

from __future__ import annotations

import http.client
import json
import os
import urllib.parse
from typing import Dict, List, Optional, Tuple

DEFAULT_CHUNK = 4 * 1024 * 1024
DEFAULT_THREADS = 4
DEFAULT_DEPTH = 4


class WebHdfsError(IOError):
    def __init__(self, status: int, body: bytes, context: str):
        self.status = status
        try:
            msg = json.loads(body.decode("utf-8", "replace"))
            exc = msg.get("RemoteException", {})
            kind = exc.get("exception", "")
            detail = ": ".join(
                p for p in (kind, exc.get("message")) if p
            ) or str(msg)
        except Exception:  # noqa: BLE001 - body may be html/plain
            detail = body[:200].decode("utf-8", "replace")
        super().__init__(f"webhdfs {context}: HTTP {status}: {detail}")


class WebHdfsClient:
    """Minimal WebHDFS v1 client over ``http.client`` (stdlib only)."""

    def __init__(
        self,
        host: str,
        port: int,
        user: Optional[str] = None,
        chunk: int = DEFAULT_CHUNK,
        threads: int = DEFAULT_THREADS,
        depth: int = DEFAULT_DEPTH,
        timeout: float = 30.0,
    ):
        self.host = host
        self.port = int(port)
        self.user = user or os.environ.get("DRYAD_TPU_HDFS_USER")
        self.chunk = int(chunk)
        self.threads = int(threads)
        self.depth = int(depth)
        self.timeout = timeout

    # -- low-level request with one-hop redirect following -----------------
    def _url(self, path: str, op: str, **params) -> str:
        if not path.startswith("/"):
            path = "/" + path
        q = {"op": op}
        if self.user:
            q["user.name"] = self.user
        for k, v in params.items():
            if v is not None:
                q[k] = str(v).lower() if isinstance(v, bool) else str(v)
        quoted = urllib.parse.quote(path, safe="/")
        return f"/webhdfs/v1{quoted}?{urllib.parse.urlencode(q)}"

    def _request(
        self,
        method: str,
        url: str,
        body: Optional[bytes] = None,
        host: Optional[str] = None,
        port: Optional[int] = None,
        follow: bool = True,
        context: str = "",
    ) -> Tuple[int, Dict[str, str], bytes]:
        c = http.client.HTTPConnection(
            host or self.host, port or self.port, timeout=self.timeout
        )
        try:
            c.request(method, url, body=body)
            r = c.getresponse()
            data = r.read()
            headers = {k.lower(): v for k, v in r.getheaders()}
            if follow and r.status in (301, 302, 307) and "location" in headers:
                # The namenode redirects data operations to a datanode
                # (DrHdfsClient.cpp follows the same two-hop protocol).
                loc = urllib.parse.urlsplit(headers["location"])
                path = loc.path + (f"?{loc.query}" if loc.query else "")
                return self._request(
                    method, path, body=body,
                    host=loc.hostname or self.host,
                    port=loc.port or self.port,
                    follow=False, context=context,
                )
            return r.status, headers, data
        finally:
            c.close()

    def _json(self, method: str, url: str, context: str, ok=(200,)) -> dict:
        status, _h, data = self._request(method, url, context=context)
        if status not in ok:
            raise WebHdfsError(status, data, context)
        return json.loads(data.decode("utf-8")) if data else {}

    # -- metadata ----------------------------------------------------------
    def status(self, path: str) -> dict:
        """GETFILESTATUS -> the FileStatus dict (raises FileNotFoundError)."""
        url = self._url(path, "GETFILESTATUS")
        st, _h, data = self._request("GET", url, context=f"status {path}")
        if st == 404:
            raise FileNotFoundError(path)
        if st != 200:
            raise WebHdfsError(st, data, f"status {path}")
        return json.loads(data.decode("utf-8"))["FileStatus"]

    def list_dir(self, path: str) -> List[dict]:
        """LISTSTATUS -> FileStatus list."""
        out = self._json(
            "GET", self._url(path, "LISTSTATUS"), f"list {path}"
        )
        return out["FileStatuses"]["FileStatus"]

    def mkdirs(self, path: str) -> None:
        self._json("PUT", self._url(path, "MKDIRS"), f"mkdirs {path}")

    def delete(self, path: str, recursive: bool = False) -> bool:
        out = self._json(
            "DELETE",
            self._url(path, "DELETE", recursive=recursive),
            f"delete {path}",
        )
        return bool(out.get("boolean"))

    # -- data --------------------------------------------------------------
    def open_range(
        self, path: str, offset: int = 0, length: Optional[int] = None
    ) -> bytes:
        """One ranged OPEN read (namenode 307 -> datanode GET)."""
        url = self._url(path, "OPEN", offset=offset, length=length)
        st, _h, data = self._request("GET", url, context=f"open {path}")
        if st == 404:
            raise FileNotFoundError(path)
        if st != 200:
            raise WebHdfsError(st, data, f"open {path}")
        return data

    def read_file(self, path: str) -> bytes:
        """Whole file, chunked-parallel: a ``depth``-deep window of
        ranged reads on a thread pool, re-ordered through the native
        Fifo so the consumer sees bytes in order with bounded
        memory (the channelbufferhdfs read-ahead pipeline,
        ``columnar/chunked.py``)."""
        from dryad_tpu.columnar.chunked import chunked_read

        size = int(self.status(path)["length"])
        return chunked_read(
            size,
            lambda off, ln: self.open_range(path, off, ln),
            self.chunk, self.threads, self.depth,
        )

    def create(self, path: str, data: bytes, overwrite: bool = True) -> None:
        """Two-step CREATE: PUT to the namenode with no body -> 307
        Location -> PUT the bytes to the redirect target (201)."""
        url = self._url(path, "CREATE", overwrite=overwrite)
        st, headers, body = self._request(
            "PUT", url, follow=False, context=f"create {path}"
        )
        if st in (301, 302, 307) and "location" in headers:
            loc = urllib.parse.urlsplit(headers["location"])
            st, _h, body = self._request(
                "PUT",
                loc.path + (f"?{loc.query}" if loc.query else ""),
                body=data,
                host=loc.hostname or self.host,
                port=loc.port or self.port,
                follow=False,
                context=f"create {path}",
            )
        elif st in (200, 201):
            # server accepted the body-less PUT directly (noredirect
            # mode); re-send with the body
            st, _h, body = self._request(
                "PUT", url, body=data, follow=False,
                context=f"create {path}",
            )
        if st not in (200, 201):
            raise WebHdfsError(st, body, f"create {path}")


def parse_hdfs_netloc(rest: str) -> Tuple[str, int, str]:
    """Split the non-scheme part of hdfs://host:port/path."""
    netloc, _, rel = rest.partition("/")
    host, _, port = netloc.partition(":")
    return host, int(port or 9870), "/" + rel.strip("/")
