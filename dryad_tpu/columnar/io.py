"""Partitioned columnar stores on the filesystem.

The analog of the reference's partitioned-table data providers
(``LinqToDryad/DataProvider.cs``, partfile scheme ``DataPath.cs``;
metadata ``DryadLinqMetaData.cs``): a store is a directory with a JSON
manifest (logical schema, partition count, compression), one ``.dpf``
columnar partition file per partition, and the string dictionary.

``.dpf`` format (implemented natively in ``runtime/native`` too):
one JSON header line (column name, dtype, row count, compressed byte
length per column) terminated by ``\\n``, then each column's payload —
little-endian raw array bytes, zlib-compressed when ``comp='zlib'``.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from dryad_tpu.columnar.schema import ColumnType, Schema, StringDictionary

MANIFEST = "manifest.json"
DICTFILE = "dictionary.json"


def _part_name(i: int) -> str:
    return f"part-{i:05d}.dpf"


def write_partition_file(
    path: str, cols: Dict[str, np.ndarray], compression: Optional[str] = None
) -> None:
    names = list(cols.keys())
    rows = len(cols[names[0]]) if names else 0
    payloads: List[bytes] = []
    header = {"rows": rows, "columns": []}
    for n in names:
        a = np.ascontiguousarray(cols[n])
        raw = a.tobytes()
        comp = compression or "none"
        data = zlib.compress(raw) if comp == "zlib" else raw
        header["columns"].append(
            {"name": n, "dtype": str(a.dtype), "rows": rows,
             "comp": comp, "nbytes": len(data)}
        )
        payloads.append(data)
    with open(path, "wb") as fh:
        fh.write((json.dumps(header) + "\n").encode("utf-8"))
        for p in payloads:
            fh.write(p)


def parse_partition_bytes(
    buf: bytes, copy: bool = True
) -> Dict[str, np.ndarray]:
    """``copy=False`` returns zero-copy (read-only) views over ``buf``
    for uncompressed columns — callers that immediately repack into a
    device layout (the ``store`` ingest binding) skip one full memcpy
    of the dataset."""
    nl = buf.index(b"\n")
    header = json.loads(buf[:nl].decode("utf-8"))
    out: Dict[str, np.ndarray] = {}
    at = nl + 1
    # compressed columns inflate in parallel on native threads when the
    # runtime is available (channelbuffernativereader analog)
    comp_srcs: List[bytes] = []
    comp_dsts: List[np.ndarray] = []
    for c in header["columns"]:
        data = buf[at : at + c["nbytes"]]
        at += c["nbytes"]
        if c["comp"] == "zlib":
            dt = np.dtype(c["dtype"])
            arr = np.empty(c["rows"], dt)
            out[c["name"]] = arr
            comp_srcs.append(data)
            comp_dsts.append(arr)
        else:
            view = np.frombuffer(data, dtype=np.dtype(c["dtype"]))
            out[c["name"]] = view if not copy else view.copy()
    if comp_srcs:
        from dryad_tpu.runtime.bindings import decompress_batch

        if not decompress_batch(comp_srcs, comp_dsts):
            for src, dst in zip(comp_srcs, comp_dsts):
                dst[:] = np.frombuffer(zlib.decompress(src), dst.dtype)
    return out


def read_partition_file(path: str) -> Dict[str, np.ndarray]:
    with open(path, "rb") as fh:
        return parse_partition_bytes(fh.read())


def write_store_meta(
    path: str,
    n_partitions: int,
    schema: Schema,
    dictionary: Optional[StringDictionary] = None,
    compression: Optional[str] = None,
) -> None:
    """Store manifest + dictionary files — the single writer of the
    store metadata format (shared with the streaming store writer)."""
    os.makedirs(path, exist_ok=True)
    manifest = {
        "version": 1,
        "partitions": n_partitions,
        "compression": compression or "none",
        "schema": [[f.name, f.ctype.value] for f in schema.fields],
    }
    with open(os.path.join(path, MANIFEST), "w") as fh:
        json.dump(manifest, fh, indent=1)
    if dictionary is not None:
        with open(os.path.join(path, DICTFILE), "w") as fh:
            json.dump({format(h, "016x"): s for h, s in dictionary.items()}, fh)


def load_store_meta(path: str):
    """(manifest, schema, hash->string map) — the single reader of the
    store metadata format."""
    with open(os.path.join(path, MANIFEST)) as fh:
        manifest = json.load(fh)
    schema = Schema([(n, ColumnType(t)) for n, t in manifest["schema"]])
    dict_map: Dict[int, str] = {}
    dpath = os.path.join(path, DICTFILE)
    if os.path.exists(dpath):
        with open(dpath) as fh:
            for h, s in json.load(fh).items():
                dict_map[int(h, 16)] = s
    return manifest, schema, dict_map


def write_store(
    path: str,
    partitions: List[Dict[str, np.ndarray]],
    schema: Schema,
    dictionary: Optional[StringDictionary] = None,
    compression: Optional[str] = None,
    threads: int = 4,
) -> None:
    write_store_meta(path, len(partitions), schema, dictionary, compression)
    # Native writer compresses columns on a thread pool when available
    # (falls back to write_partition_file); partitions additionally
    # write concurrently — the async channel-writer analog
    # (channelbuffernativewriter.cpp), GIL released inside ctypes.
    from concurrent.futures import ThreadPoolExecutor

    from dryad_tpu.runtime.bindings import write_partition

    if threads <= 1 or len(partitions) <= 1:
        for i, cols in enumerate(partitions):
            write_partition(
                os.path.join(path, _part_name(i)), cols, compression
            )
        return
    with ThreadPoolExecutor(max_workers=min(threads, len(partitions))) as ex:
        futs = [
            ex.submit(
                write_partition,
                os.path.join(path, _part_name(i)), cols, compression,
            )
            for i, cols in enumerate(partitions)
        ]
        for f in futs:
            f.result()


def read_store(
    path: str,
) -> Tuple[Schema, List[Dict[str, np.ndarray]], StringDictionary]:
    manifest, schema, dict_map = load_store_meta(path)
    dictionary = StringDictionary()
    dictionary._map.update(dict_map)
    # Background-prefetched ordered reads via the native channel reader
    # (Python fallback inside PrefetchChannel when the lib is absent).
    from dryad_tpu.runtime.bindings import PrefetchChannel

    paths = [
        os.path.join(path, _part_name(i)) for i in range(manifest["partitions"])
    ]
    with PrefetchChannel(paths, depth=4, threads=2) as ch:
        # zero-copy views: the store binding repacks into the (P x cap)
        # device layout anyway, so that repack is THE copy
        parts = [parse_partition_bytes(buf, copy=False) for buf in ch]
    return schema, parts, dictionary
