"""Schema / type system for columnar batches.

The reference ships a row-oriented binary record format with per-type
(de)serializers (``LinqToDryad/DryadLinqBinaryReader.cs``,
``DryadLinqSerialization.cs``).  The TPU-native design is columnar
(struct-of-arrays in HBM): a ``Schema`` is an ordered list of named,
typed columns; records are rows across those columns.

Strings cannot live on a TPU, so STRING columns are dictionary-encoded at
ingest: each string becomes a 64-bit hash carried as TWO uint32 device
columns (``name#h0``/``name#h1`` — avoids requiring jax x64 mode), with a
host-side :class:`StringDictionary` mapping hashes back to strings at
egress.  This follows the reference's own precedent of hashing record
keys with a deterministic 64-bit hash (``LinqToDryad/Hash64.cs``).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np


class ColumnType(enum.Enum):
    INT32 = "int32"
    INT64 = "int64"  # stored on device as two uint32 words (#h0 low, #h1 high)
    FLOAT32 = "float32"
    # Stored on device as the ORDER-PRESERVING signed-int64 image of the
    # IEEE-754 bits (two uint32 words): exact round-trip, and every
    # int64 comparison/sort/min/max kernel applies unchanged.  No f64
    # arithmetic on device (x64 stays off): sum/mean are rejected with
    # a cast-to-f32 suggestion.
    FLOAT64 = "float64"
    BOOL = "bool"
    UINT32 = "uint32"
    STRING = "string"  # dictionary-encoded: two uint32 hash words + host dict

    @property
    def is_split(self) -> bool:
        """True when the logical column maps to multiple uint32 device columns."""
        return self in (ColumnType.INT64, ColumnType.FLOAT64, ColumnType.STRING)

    @property
    def numpy_dtype(self) -> np.dtype:
        return {
            ColumnType.INT32: np.dtype(np.int32),
            ColumnType.INT64: np.dtype(np.int64),
            ColumnType.FLOAT32: np.dtype(np.float32),
            ColumnType.FLOAT64: np.dtype(np.float64),
            ColumnType.BOOL: np.dtype(np.bool_),
            ColumnType.UINT32: np.dtype(np.uint32),
            ColumnType.STRING: np.dtype(object),
        }[self]


def device_column_names(name: str, ctype: ColumnType) -> List[str]:
    """Physical device-column names backing one logical column.

    INT64  -> ``#h0`` (low word), ``#h1`` (high word).
    STRING -> ``#h0``/``#h1`` (Hash64 words, the identity) plus ``#r0``/``#r1``,
    an order-preserving uint32 rank of the first 4 UTF-8 bytes
    (big-endian), so range partitioning / OrderBy on strings is exact on
    4-byte prefixes with hash-order tie-breaking beyond that.
    """
    if ctype == ColumnType.STRING:
        return [f"{name}#h0", f"{name}#h1", f"{name}#r0", f"{name}#r1"]
    if ctype in (ColumnType.INT64, ColumnType.FLOAT64):
        return [f"{name}#h0", f"{name}#h1"]
    return [name]


def string_prefix_rank(strings: "np.ndarray", offset: int = 0) -> "np.ndarray":
    """uint32 big-endian rank of UTF-8 bytes [offset, offset+4) of each
    string — memcomparable prefix words (``#r0`` offset 0, ``#r1``
    offset 4: exact ordering for 8-byte prefixes, hash-order beyond)."""
    out = np.zeros(len(strings), np.uint32)
    for i, s in enumerate(strings):
        b = str(s).encode("utf-8")[offset : offset + 4]
        r = 0
        for j in range(4):
            r = (r << 8) | (b[j] if j < len(b) else 0)
        out[i] = r
    return out


FNV_OFFSET = 0xCBF29CE484222325
FNV_PRIME = 0x100000001B3
_MASK64 = (1 << 64) - 1


def hash64_bytes(data: bytes) -> int:
    """Deterministic 64-bit FNV-1a hash.

    The framework-wide string hash, the analog of the reference's
    deterministic ``Hash64`` (``LinqToDryad/Hash64.cs``) used so every
    machine partitions identically.  Implemented identically in the
    native runtime (``runtime/native/dryadnative.cpp``).
    """
    h = FNV_OFFSET
    for b in data:
        h ^= b
        h = (h * FNV_PRIME) & _MASK64
    return h


def hash64_str(s: str) -> int:
    return hash64_bytes(s.encode("utf-8"))


def split64(values: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Split uint64/int64 array into (low, high) uint32 words."""
    v = values.astype(np.uint64)
    lo = (v & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    hi = (v >> np.uint64(32)).astype(np.uint32)
    return lo, hi


def join64(lo: np.ndarray, hi: np.ndarray, signed: bool = False) -> np.ndarray:
    v = lo.astype(np.uint64) | (hi.astype(np.uint64) << np.uint64(32))
    return v.view(np.int64) if signed else v


_SIGN64 = np.uint64(1 << 63)


def f64_to_ordered_i64(values: np.ndarray) -> np.ndarray:
    """Order-preserving signed-int64 image of float64 values.

    The classic memcomparable-double transform (negatives: ~bits;
    non-negatives: bits | signbit) shifted into the signed domain
    (xor signbit), so signed-int64 comparisons order exactly like the
    doubles under IEEE-754 totalOrder semantics: -0.0 orders below
    +0.0, sign-negative NaNs below -inf, sign-positive NaNs above +inf
    (the documented engine semantic for float64 ordering).
    """
    bits = np.ascontiguousarray(values, np.float64).view(np.uint64)
    neg = (bits & _SIGN64) != 0
    t = np.where(neg, ~bits ^ _SIGN64, bits)
    return t.view(np.int64)


def ordered_i64_to_f64(vals: np.ndarray) -> np.ndarray:
    """Inverse of :func:`f64_to_ordered_i64`."""
    s = np.ascontiguousarray(vals, np.int64).view(np.uint64)
    neg = (s & _SIGN64) != 0  # negatives map to signed-negative images
    bits = np.where(neg, ~(s ^ _SIGN64), s)
    return bits.view(np.float64)


class StringDictionary:
    """Host-side hash -> string mapping for dictionary-encoded columns.

    Built at ingest, consulted only at egress (the reference keeps string
    payloads in channel bytes; we keep them on the host and ship hashes).
    """

    def __init__(self) -> None:
        self._map: Dict[int, str] = {}

    def __len__(self) -> int:
        return len(self._map)

    def add(self, s: str) -> int:
        h = hash64_str(s)
        existing = self._map.get(h)
        if existing is not None and existing != s:
            # 64-bit collision between distinct strings: astronomically
            # unlikely; surface loudly rather than silently merging keys.
            raise ValueError(f"hash64 collision: {existing!r} vs {s!r}")
        self._map[h] = s
        return h

    def add_all(self, strings: Iterable[str]) -> np.ndarray:
        return np.array([self.add(s) for s in strings], dtype=np.uint64)

    def lookup(self, h: int) -> str:
        return self._map[int(h)]

    def lookup_all(self, hashes: np.ndarray) -> List[str]:
        return [self._map[int(h)] for h in np.asarray(hashes).ravel()]

    def merge(self, other: "StringDictionary") -> "StringDictionary":
        out = StringDictionary()
        out._map.update(self._map)
        for h, s in other._map.items():
            if h in out._map and out._map[h] != s:
                raise ValueError(f"hash64 collision merging dictionaries: {s!r}")
            out._map[h] = s
        return out

    def items(self):
        # Snapshot: a streaming prefetch thread may register tokens
        # concurrently with a consumer iterating the dictionary (e.g.
        # build_tables during lowering) — a live view would raise
        # "dict changed size during iteration".
        return list(self._map.items())


@dataclasses.dataclass(frozen=True)
class Field:
    name: str
    ctype: ColumnType

    @property
    def device_names(self) -> List[str]:
        return device_column_names(self.name, self.ctype)


class Schema:
    """Ordered, named, typed columns of a dataset."""

    def __init__(self, fields: Sequence[Tuple[str, ColumnType]]):
        self.fields: List[Field] = [Field(n, t) for n, t in fields]
        names = [f.name for f in self.fields]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate column names in schema: {names}")
        self._by_name = {f.name: f for f in self.fields}

    @property
    def names(self) -> List[str]:
        return [f.name for f in self.fields]

    def field(self, name: str) -> Field:
        return self._by_name[name]

    def __contains__(self, name: str) -> bool:
        return name in self._by_name

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Schema) and self.fields == other.fields

    def __repr__(self) -> str:
        cols = ", ".join(f"{f.name}:{f.ctype.value}" for f in self.fields)
        return f"Schema({cols})"

    def device_names(self) -> List[str]:
        out: List[str] = []
        for f in self.fields:
            out.extend(f.device_names)
        return out

    def with_field(self, name: str, ctype: ColumnType) -> "Schema":
        return Schema([(f.name, f.ctype) for f in self.fields] + [(name, ctype)])

    def select(self, names: Sequence[str]) -> "Schema":
        return Schema([(n, self._by_name[n].ctype) for n in names])
