"""ColumnBatch — the HBM-resident record container.

The TPU-native replacement for the reference's streamed row records
(``DryadLinqBinaryReader/Writer``, ``RChannelItem`` arrays): a fixed
*capacity* struct-of-arrays with a boolean validity mask.  Static shapes
keep every stage jit-compilable; deletion/filtering clears mask bits,
and compaction happens on-device when a shuffle or sort needs dense rows.

A ColumnBatch is a registered pytree, so it flows through ``jit``,
``shard_map`` and collectives directly.  Device columns are *physical*
columns: logical INT64/STRING columns are two uint32 word columns (see
``columnar.schema.device_column_names``).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dryad_tpu.columnar.schema import (
    ColumnType,
    Schema,
    StringDictionary,
    join64,
    split64,
)


def encode_physical(
    field, a: np.ndarray, dictionary: Optional[StringDictionary]
) -> Dict[str, np.ndarray]:
    """One logical host column -> its physical device/store columns
    (STRING: Hash64 words + memcomparable prefix ranks; INT64/FLOAT64:
    order-preserving split words).  Shared by device ingest and the
    streaming store writer, so ``.dpf`` parts written out-of-core read
    back through the same ``store`` binding path."""
    if field.ctype == ColumnType.STRING:
        if dictionary is None:
            raise ValueError(f"STRING column {field.name} needs a dictionary")
        from dryad_tpu.columnar.schema import string_prefix_rank

        strs = [str(s) for s in a]
        hashes = dictionary.add_all(strs)
        lo, hi = split64(hashes)
        sarr = np.array(strs, object)
        return {
            f"{field.name}#h0": lo,
            f"{field.name}#h1": hi,
            f"{field.name}#r0": string_prefix_rank(sarr),
            f"{field.name}#r1": string_prefix_rank(sarr, offset=4),
        }
    if field.ctype == ColumnType.INT64:
        lo, hi = split64(a.astype(np.int64))
        return {f"{field.name}#h0": lo, f"{field.name}#h1": hi}
    if field.ctype == ColumnType.FLOAT64:
        from dryad_tpu.columnar.schema import f64_to_ordered_i64

        lo, hi = split64(f64_to_ordered_i64(a))
        return {f"{field.name}#h0": lo, f"{field.name}#h1": hi}
    return {field.name: a.astype(field.ctype.numpy_dtype)}


@jax.tree_util.register_pytree_node_class
class ColumnBatch:
    """Fixed-capacity columnar batch with a validity mask.

    ``data`` maps physical column name -> array of shape ``(capacity,)``
    (or ``(n_partitions * capacity,)`` for a global view of a sharded
    batch — the container is shape-agnostic beyond requiring all columns
    and the mask to share their leading dimension).
    """

    def __init__(self, data: Dict[str, jax.Array], valid: jax.Array):
        self.data = dict(data)
        self.valid = valid

    # -- pytree protocol ---------------------------------------------------
    def tree_flatten(self):
        names = sorted(self.data.keys())
        children = [self.data[n] for n in names] + [self.valid]
        return children, tuple(names)

    @classmethod
    def tree_unflatten(cls, names, children):
        data = dict(zip(names, children[:-1]))
        return cls(data, children[-1])

    # -- basic properties --------------------------------------------------
    @property
    def capacity(self) -> int:
        return int(self.valid.shape[0])

    @property
    def columns(self) -> List[str]:
        return sorted(self.data.keys())

    def count(self) -> jax.Array:
        """Number of valid rows (traced value)."""
        return jnp.sum(self.valid.astype(jnp.int32))

    def __getitem__(self, name: str) -> jax.Array:
        return self.data[name]

    # -- jit-safe transforms ----------------------------------------------
    def with_column(self, name: str, values: jax.Array) -> "ColumnBatch":
        new = dict(self.data)
        new[name] = values
        return ColumnBatch(new, self.valid)

    def select(self, names: Sequence[str]) -> "ColumnBatch":
        return ColumnBatch({n: self.data[n] for n in names}, self.valid)

    def drop(self, names: Sequence[str]) -> "ColumnBatch":
        keep = {n: v for n, v in self.data.items() if n not in set(names)}
        return ColumnBatch(keep, self.valid)

    def rename(self, mapping: Dict[str, str]) -> "ColumnBatch":
        new = {mapping.get(n, n): v for n, v in self.data.items()}
        return ColumnBatch(new, self.valid)

    def filter(self, keep_mask: jax.Array) -> "ColumnBatch":
        """Row filter: AND a predicate into the validity mask (Where)."""
        return ColumnBatch(self.data, jnp.logical_and(self.valid, keep_mask))

    def compact(self) -> "ColumnBatch":
        """Move valid rows to the front (stable).

        Sort-based compaction: key = !valid, stable, so valid rows keep
        their order at the front.  Invalid slots retain stale values but
        their mask bits are off.
        """
        order = jnp.argsort(jnp.logical_not(self.valid), stable=True)
        data = {n: v[order] for n, v in self.data.items()}
        return ColumnBatch(data, self.valid[order])

    def take(self, order: jax.Array) -> "ColumnBatch":
        """Row gather by index array (caller manages mask semantics)."""
        data = {n: v[order] for n, v in self.data.items()}
        return ColumnBatch(data, self.valid[order])

    def pad_to(self, capacity: int) -> "ColumnBatch":
        cur = self.capacity
        if capacity == cur:
            return self
        if capacity < cur:
            raise ValueError(f"pad_to({capacity}) below current capacity {cur}")
        extra = capacity - cur
        data = {
            n: jnp.concatenate([v, jnp.zeros((extra,) + v.shape[1:], v.dtype)])
            for n, v in self.data.items()
        }
        valid = jnp.concatenate([self.valid, jnp.zeros((extra,), jnp.bool_)])
        return ColumnBatch(data, valid)

    @staticmethod
    def concatenate(batches: Sequence["ColumnBatch"]) -> "ColumnBatch":
        """Static concat along rows (the Concat operator's device step)."""
        names = batches[0].columns
        for b in batches[1:]:
            if b.columns != names:
                raise ValueError("concat of batches with differing columns")
        data = {n: jnp.concatenate([b.data[n] for b in batches]) for n in names}
        valid = jnp.concatenate([b.valid for b in batches])
        return ColumnBatch(data, valid)

    @staticmethod
    def empty(col_dtypes: Dict[str, jnp.dtype], capacity: int) -> "ColumnBatch":
        data = {n: jnp.zeros((capacity,), dt) for n, dt in col_dtypes.items()}
        return ColumnBatch(data, jnp.zeros((capacity,), jnp.bool_))

    # -- host conversion ---------------------------------------------------
    @staticmethod
    def from_numpy(
        schema: Schema,
        arrays: Dict[str, np.ndarray],
        capacity: Optional[int] = None,
        dictionary: Optional[StringDictionary] = None,
    ) -> "ColumnBatch":
        """Encode host arrays (logical columns) into a device batch.

        STRING columns require ``dictionary`` and are hashed via the
        framework Hash64 (``columnar.schema.hash64_str``); INT64 columns
        are split into uint32 word pairs.  Rows are padded to
        ``capacity`` with mask bits off.
        """
        n = None
        for name in schema.names:
            a = np.asarray(arrays[name])
            if n is None:
                n = len(a)
            elif len(a) != n:
                raise ValueError("ragged input columns")
        n = n or 0
        cap = capacity if capacity is not None else n
        if cap < n:
            raise ValueError(f"capacity {cap} < row count {n}")

        data: Dict[str, jnp.ndarray] = {}
        for f in schema.fields:
            for pname, pvals in encode_physical(
                f, np.asarray(arrays[f.name]), dictionary
            ).items():
                padded = np.zeros((cap,), pvals.dtype)
                padded[:n] = pvals
                data[pname] = jnp.asarray(padded)
        valid = np.zeros((cap,), np.bool_)
        valid[:n] = True
        return ColumnBatch(data, jnp.asarray(valid))

    def fetch_host(self, extra: Sequence[jax.Array] = ()):
        """(valid, columns, extras) on the host, via ONE
        ``jax.device_get`` so PJRT overlaps all the device->host copies
        (copy_to_host_async then a single block).  A per-column
        ``np.asarray`` loop pays one synchronous transfer round-trip
        per column, which dominates egress through a high-latency link
        (BASELINE.md round-4: ~70 ms/round-trip through the tunnel x
        4-5 columns per rep).  ``extra`` arrays (e.g. deferred
        dict-miss counters) ride the same transfer; ``extras`` is empty
        when none were passed."""
        assert "#valid" not in self.data, "'#valid' is a reserved name"
        host, extras = jax.device_get(
            ({"#valid": self.valid, **self.data}, list(extra))
        )
        valid = host.pop("#valid")
        return valid, host, extras

    def to_numpy(
        self,
        schema: Schema,
        dictionary: Optional[StringDictionary] = None,
        _host: Optional[Tuple[np.ndarray, Dict[str, np.ndarray]]] = None,
    ) -> Dict[str, np.ndarray]:
        """Decode valid rows back to host logical columns.  ``_host``:
        already-fetched ``(valid, columns)`` from :meth:`fetch_host`
        (callers that batched the transfer with extra arrays)."""
        valid, host = _host if _host is not None else self.fetch_host()[:2]
        return decode_physical_table(schema, valid, host, dictionary)


def decode_physical_table(
    schema: Schema,
    valid,
    host: Dict[str, np.ndarray],
    dictionary: Optional[StringDictionary] = None,
) -> Dict[str, np.ndarray]:
    """Physical host columns -> logical table (``valid`` is a bool mask
    or a full slice).  The inverse of :func:`encode_physical`."""
    out: Dict[str, np.ndarray] = {}
    for f in schema.fields:
        if f.ctype == ColumnType.STRING:
            lo = host[f"{f.name}#h0"][valid]
            hi = host[f"{f.name}#h1"][valid]
            hashes = join64(lo, hi)
            if dictionary is None:
                out[f.name] = hashes  # fall back to raw hashes
            else:
                out[f.name] = np.array(
                    dictionary.lookup_all(hashes), dtype=object
                )
        elif f.ctype == ColumnType.INT64:
            lo = host[f"{f.name}#h0"][valid]
            hi = host[f"{f.name}#h1"][valid]
            out[f.name] = join64(lo, hi, signed=True)
        elif f.ctype == ColumnType.FLOAT64:
            from dryad_tpu.columnar.schema import ordered_i64_to_f64

            lo = host[f"{f.name}#h0"][valid]
            hi = host[f"{f.name}#h1"][valid]
            out[f.name] = ordered_i64_to_f64(join64(lo, hi, signed=True))
        else:
            out[f.name] = np.asarray(host[f.name])[valid]
    return out
