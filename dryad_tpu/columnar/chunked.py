"""Windowed chunk-parallel ranged reads through the native Fifo.

The async channel-buffer read-ahead pipeline of the reference's DFS
stream readers (``channelbufferhdfs.cpp``; Azure page reads in
``DrAzureBlobClient.h``) factored once for every ranged-byte client:
a thread pool fetches ``chunk``-sized ranges ahead, completed chunks
flow to the consumer IN ORDER through the native ``Fifo``
(``runtime/native/dryadnative.cpp``), and memory stays bounded at
``depth`` chunks while the pipe stays full.
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, List


def chunked_read(
    size: int,
    fetch_range: Callable[[int, int], bytes],
    chunk: int,
    threads: int = 4,
    depth: int = 4,
) -> bytes:
    """Read ``size`` bytes as parallel ranged fetches, reassembled in
    order.  ``fetch_range(offset, length) -> bytes``."""
    if size <= chunk:
        return fetch_range(0, size) if size else b""
    from dryad_tpu.runtime.bindings import Fifo

    nchunks = -(-size // chunk)
    fifo = Fifo(depth=depth)
    err: List[BaseException] = []

    def feed() -> None:
        try:
            with ThreadPoolExecutor(max_workers=threads) as ex:
                futs = [
                    ex.submit(
                        fetch_range,
                        i * chunk,
                        min(chunk, size - i * chunk),
                    )
                    for i in range(nchunks)
                ]
                # in-order push; the pool keeps later chunks fetching
                for f in futs:
                    if not fifo.push(f.result()):
                        for g in futs:
                            g.cancel()
                        return
        except BaseException as e:  # noqa: BLE001 - surfaced below
            err.append(e)
        finally:
            fifo.close()

    t = threading.Thread(target=feed, daemon=True)
    t.start()
    out = bytearray()
    try:
        while True:
            block = fifo.pop()
            if block is None:
                break
            out += block
    finally:
        fifo.close()
        t.join()
        fifo.destroy()
    if err:
        raise err[0]
    if len(out) != size:
        raise IOError(f"chunked read: got {len(out)} of {size} bytes")
    return bytes(out)
