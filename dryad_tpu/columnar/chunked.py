"""Windowed chunk-parallel ranged reads through the native Fifo.

The async channel-buffer read-ahead pipeline of the reference's DFS
stream readers (``channelbufferhdfs.cpp``; Azure page reads in
``DrAzureBlobClient.h``) factored once for every ranged-byte client:
a thread pool fetches ``chunk``-sized ranges ahead, completed chunks
flow to the consumer IN ORDER through the native ``Fifo``
(``runtime/native/dryadnative.cpp``), and memory stays bounded at
``depth`` chunks while the pipe stays full.
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterator, List


class _ReadCancelled(Exception):
    """Internal: a queued fetch noticed the consumer went away."""


def chunked_read_iter(
    size: int,
    fetch_range: Callable[[int, int], bytes],
    chunk: int,
    threads: int = 4,
    depth: int = 4,
) -> Iterator[bytes]:
    """Yield ``size`` bytes as in-order blocks from parallel ranged
    fetches.  Closing the generator early (a consumer that stops after
    a partial read) propagates PROMPTLY to the fetch side: queued
    range fetches are cancelled, fetches that have not yet issued
    their request notice the stop flag and return without fetching,
    and the feed thread exits without waiting out the remaining
    window — bytes for ranges the consumer will never see are not
    silently fetched and dropped."""
    if size <= 0:
        return
    if size <= chunk:
        yield fetch_range(0, size)
        return
    from dryad_tpu.runtime.bindings import Fifo

    nchunks = -(-size // chunk)
    fifo = Fifo(depth=depth)
    err: List[BaseException] = []
    stop = threading.Event()

    def guarded(offset: int, length: int) -> bytes:
        # checked at dequeue time: a cancelled consumer stops NEW
        # fetches immediately, not after the pool drains the window
        if stop.is_set():
            raise _ReadCancelled()
        return fetch_range(offset, length)

    def feed() -> None:
        try:
            with ThreadPoolExecutor(max_workers=threads) as ex:
                futs = [
                    ex.submit(
                        guarded,
                        i * chunk,
                        min(chunk, size - i * chunk),
                    )
                    for i in range(nchunks)
                ]
                # in-order push; the pool keeps later chunks fetching
                for f in futs:
                    if stop.is_set() or not fifo.push(f.result()):
                        stop.set()
                        for g in futs:
                            g.cancel()
                        return
        except _ReadCancelled:
            pass
        except BaseException as e:  # noqa: BLE001 - surfaced below
            err.append(e)
            stop.set()
        finally:
            fifo.close()

    t = threading.Thread(target=feed, daemon=True)
    t.start()
    got = 0
    try:
        while True:
            block = fifo.pop()
            if block is None:
                break
            got += len(block)
            yield block
    finally:
        stop.set()
        fifo.close()
        t.join()
        fifo.destroy()
    if err:
        raise err[0]
    if got != size:
        raise IOError(f"chunked read: got {got} of {size} bytes")


def chunked_read(
    size: int,
    fetch_range: Callable[[int, int], bytes],
    chunk: int,
    threads: int = 4,
    depth: int = 4,
) -> bytes:
    """Read ``size`` bytes as parallel ranged fetches, reassembled in
    order.  ``fetch_range(offset, length) -> bytes``."""
    if size <= 0:
        return b""
    out = bytearray()
    for block in chunked_read_iter(size, fetch_range, chunk, threads, depth):
        out += block
    return bytes(out)
