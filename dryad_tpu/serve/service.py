"""QueryService — one resident engine, many concurrent tenants.

The reference Dryad's GraphManager multiplexed vertices from many
stages onto one shared cluster; this is the same move one level up:
many tenants' PLANS multiplexed onto one resident
:class:`~dryad_tpu.api.context.DryadContext` (mesh, gang, compile
cache, operand pool all shared).

Threading model — the executor is driver-owned and NOT thread-safe, so
the service owns exactly ONE driver thread and everything device-
facing happens there:

- client threads build plans, pass admission (quota check + enqueue,
  under the service lock), and block on :class:`QueryFuture`;
- the driver thread picks the next query fair-share (weighted deficit
  round robin over the tenant ring), computes its result-cache
  fingerprint, and either resolves it from the cache (zero dispatches)
  or dispatches it through the ONE shared
  :class:`~dryad_tpu.exec.pipeline.DispatchWindow` — whose collector
  drains fetches strictly in submit order, so interleaved tenants
  still commit deterministically and results stay byte-identical to
  serial one-at-a-time execution;
- session ingest (which mutates the shared StringDictionary and
  binding table) serializes against driver-side lowering on
  ``_ctx_lock``, never held while blocked on the window.

Fair share is classic weighted deficit round robin: each visit to a
tenant with queued work earns ``weight`` quantum units, a query costs
``1 + input_bytes // config.serve_drr_quantum_bytes`` units, and an
idle tenant forfeits its credit — so a heavy tenant cannot starve a
light one, and a returning tenant cannot burst on banked idle time.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import deque
from typing import Any, Dict, Optional, Tuple

from dryad_tpu.exec.pipeline import DispatchWindow
from dryad_tpu.obs import critpath, flightrec, tracectx
from dryad_tpu.obs.span import Tracer
from dryad_tpu.obs.telemetry import RollingStore
from dryad_tpu.serve.admission import (
    DEFAULT_TIER,
    TIERS,
    QueryRejected,
    TenantQuota,
    check_tier,
)
from dryad_tpu.serve.cache import ResultCache
from dryad_tpu.serve.router import canonical_fingerprint
from dryad_tpu.utils.logging import get_logger
from dryad_tpu.views import ViewRegistry, finalize_query

log = get_logger("dryad_tpu.serve")


class QueryFuture:
    """Resolution handle for one admitted query.  ``result()`` blocks
    until the driver resolves it — with the host table, the execution
    error, or a :class:`QueryRejected` if the service closed first."""

    def __init__(self, tenant: str, qid: str):
        self.tenant = tenant
        self.qid = qid
        self.cached = False  # set at resolve: served from the result cache
        self._ev = threading.Event()
        self._result: Optional[Dict] = None
        self._error: Optional[BaseException] = None

    def done(self) -> bool:
        return self._ev.is_set()

    def result(self, timeout: Optional[float] = None) -> Dict:
        if not self._ev.wait(timeout):
            raise TimeoutError(
                f"query {self.qid} unresolved after {timeout}s"
            )
        if self._error is not None:
            raise self._error
        return self._result

    def _resolve(self, result=None, error=None) -> None:
        self._result = result
        self._error = error
        self._ev.set()


class _Queued:
    """One admitted query riding the tenant queue."""

    __slots__ = (
        "state", "qid", "query", "future", "cost_bytes", "cost_units",
        "epoch", "t_submit", "tctx", "view",
    )

    def __init__(self, state, qid, query, future, cost_bytes, cost_units,
                 epoch, t_submit, tctx=None):
        self.state = state
        self.qid = qid
        self.query = query
        self.future = future
        self.cost_bytes = cost_bytes
        self.cost_units = cost_units
        self.epoch = epoch  # tenant ingest epoch at ADMISSION
        self.t_submit = t_submit
        self.view = None  # MaterializedView when a stale read finalizes
        # trace identity, minted at admission — or ADOPTED when the
        # query crossed a process boundary (fleet router mints the qid
        # at the front door) so every span/event on this side still
        # carries the end-to-end qid and the critical path sums to e2e
        self.tctx = tctx or tracectx.mint(tenant=state.name, qid=qid)


class _TenantState:
    """Service-internal per-tenant record (queues, quota, counters).
    All mutation under the service lock."""

    def __init__(self, name: str, weight: int, quota: TenantQuota,
                 tier: str = DEFAULT_TIER):
        self.name = name
        self.weight = weight
        self.quota = quota
        self.tier = check_tier(tier)
        self.queue: "deque[_Queued]" = deque()
        self.deficit = 0
        self.visited = False  # earned this visit's refill already
        self.epoch = 0  # ingest epoch: result-cache invalidation signal
        self.saturated = False
        self.inflight = 0  # admitted and not yet resolved
        self.inflight_bytes = 0
        self.seq = 0
        self.admitted = 0
        self.completed = 0
        self.rejected = 0
        self.cache_hits = 0
        self.failed = 0


class TenantSession:
    """A tenant's handle on the service: submit plans, ingest data,
    bump the ingest epoch.  Cheap — open one per logical client."""

    def __init__(self, service: "QueryService", state: _TenantState):
        self._service = service
        self._state = state

    @property
    def name(self) -> str:
        return self._state.name

    @property
    def epoch(self) -> int:
        return self._state.epoch

    def submit(self, query, qid: Optional[str] = None,
               tctx=None) -> QueryFuture:
        """Admit ``query`` (raises :class:`QueryRejected` past quota)
        and return its future.  Never blocks on device work.

        ``qid``/``tctx`` adopt an externally minted query identity —
        the fleet replica path, where the front door minted the qid and
        the wire TraceContext must keep flowing through this engine's
        spans and events."""
        return self._service._submit(self._state, query, qid=qid, tctx=tctx)

    def run(self, query, timeout: Optional[float] = None) -> Dict:
        """Submit and block for the result."""
        return self.submit(query).result(timeout)

    def ingest(self, arrays, **kw):
        """Bind a host table through the shared context.  Streaming —
        no epoch bump: a NEW binding fingerprints differently from
        anything cached, so existing results cannot alias it and stay
        valid.  Invalidation work happens only on :meth:`append`, and
        only for the entries the append actually staled."""
        svc = self._service
        with svc._ctx_lock:
            return svc.ctx.from_arrays(arrays, **kw)

    def append(self, query, arrays) -> int:
        """Append rows to an ingested table WITHOUT stopping the world:
        rewrites the binding in place, drops exactly the cached results
        computed over the table's old bytes (any tenant — the binding
        is shared engine state), and folds the rows as a delta into
        every registered view over it.  Returns the number of cache
        entries invalidated."""
        svc = self._service
        with svc._ctx_lock:
            old_fp = svc.ctx.append_arrays(query, arrays)
            dropped = svc._cache.invalidate_binding(None, old_fp)
            svc.views.apply_delta(query.node.id, arrays)
        return dropped

    def register_view(self, query, name=None, window_col=None,
                      window_count=None, max_staleness_s: float = 0.0):
        """Admit ``query`` as a resident materialized view: reads of
        this exact Query serve a bounded-staleness snapshot (zero
        dispatches fresh, one finalize dispatch stale) and appends to
        its table fold in as deltas.  The default name is the plan's
        process-portable canonical fingerprint, so fleet replicas
        agree on identity.  Raises
        :class:`~dryad_tpu.views.ViewIneligible` (after emitting the
        structured ``view_fallback`` event) for plans with no
        incremental maintenance path."""
        svc = self._service
        with svc._ctx_lock:
            if name is None:
                fp = svc.ctx.query_fingerprint(query)
                cfp = canonical_fingerprint(fp) if fp is not None else None
                if cfp is not None:
                    name = f"view-{cfp[:16]}"
            return svc.views.register(
                self.name, query, name=name, window_col=window_col,
                window_count=window_count,
                max_staleness_s=max_staleness_s,
            )

    def bump_epoch(self) -> None:
        """Advance the ingest epoch: every cached result this tenant
        inserted before now is invalid (epoch-mismatch miss)."""
        with self._service._lock:
            self._state.epoch += 1


class QueryService:
    """Long-lived multiplexing front end over one DryadContext."""

    def __init__(self, ctx, start: bool = True):
        self.ctx = ctx
        self.config = ctx.config
        self.events = ctx.events
        self._cache = ResultCache(
            self.config.serve_result_cache_bytes,
            admission=getattr(
                self.config, "serve_cache_admission", "all"
            ),
            min_sec_per_gb=getattr(
                self.config, "serve_cache_min_sec_per_gb", 0.5
            ),
        )
        # resident materialized views: registered plans whose reads
        # serve snapshots and whose appends fold in as deltas
        self.views = ViewRegistry(ctx, events=self.events)
        self._window = DispatchWindow(
            depth=self.config.dispatch_depth, events=self.events,
            name="serve", headroom=getattr(ctx, "headroom", None),
        )
        # per-tenant SLO plane: admission->completion latency
        # percentiles and windowed admission/completion/rejection
        # counters over the telemetry rolling window — the metricsd
        # scrape surface and the ``stats()["slo"]`` block
        self.slo = RollingStore(
            window_s=getattr(self.config, "telemetry_window_s", 60.0)
        )
        # driver-side serve spans (cache_probe etc) for the per-query
        # critical-path fold
        self.tracer = Tracer(self.events)
        # per-query trace buffers: an EventLog tap routes each
        # qid-stamped event (worker telemetry included — absorb() runs
        # taps too) into its query's buffer between admission and
        # completion, so the critical-path fold at _finish reads one
        # small list instead of refolding the whole ring
        self._trace_buf: Dict[str, list] = {}
        if self.events is not None:
            self.events.add_tap(self._trace_tap)
        # cumulative per-tenant critical-path phase seconds (stats())
        self._phase_totals: Dict[str, Dict[str, float]] = {}
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        # ingest (client threads) vs lowering/dispatch (driver thread)
        # both touch the shared dictionary and binding table; RLock so
        # the driver's fingerprint+dispatch pair stays one critical
        # section.  NEVER held while blocked on the window.
        self._ctx_lock = threading.RLock()
        self._tenants: Dict[str, _TenantState] = {}
        # per-tier deficit-round-robin ring pointers (strict priority
        # across tiers, DRR within)
        self._rr: Dict[str, int] = {}
        self._queued = 0  # total across tenant queues
        self._inflight_items: Dict[str, Tuple[_Queued, Any]] = {}
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        # queue-depth health probe: ONE shared-registry entry feeds
        # both the blackbox microsnapshots and the ResourceMonitor
        flightrec.probe(
            "serve:queue",
            lambda: {
                "queued": self._queued,
                "in_flight": len(self._inflight_items),
                "depth": self._window.depth,
            },
        )
        if start:
            self.start()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "QueryService":
        """Spawn the driver thread (idempotent).  A service built with
        ``start=False`` queues admissions until started — the fairness
        tests preload competing tenants this way."""
        with self._lock:
            if self._thread is not None or self._closed:
                return self
            self._thread = threading.Thread(
                target=self._drive, name="dryad-serve", daemon=True
            )
            self._thread.start()
        return self

    def close(self, timeout: float = 60.0) -> None:
        """Stop admitting, drain everything already admitted, join the
        driver, close the window.  Safe to call repeatedly."""
        with self._lock:
            already = self._closed
            self._closed = True
            self._work.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
        elif not already:
            # never started: unblock queued clients with a structured
            # rejection instead of letting them wait forever
            self._cancel_queued()
        self._window.close()
        if self.events is not None:
            self.events.remove_tap(self._trace_tap)
        self._trace_buf.clear()
        flightrec.unprobe("serve:queue")

    def __enter__(self) -> "QueryService":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- tenants -----------------------------------------------------------

    def session(self, tenant: str, weight: int = 1,
                quota: Optional[TenantQuota] = None,
                tier: Optional[str] = None) -> TenantSession:
        """Open (or re-open) a tenant session.  ``weight`` is the DRR
        share WITHIN the tenant's priority ``tier`` ("latency" tenants
        are always served before "batch" tenants with runnable work);
        ``quota`` defaults to the config budgets."""
        if weight < 1:
            raise ValueError("tenant weight must be >= 1")
        if tier is not None:
            check_tier(tier)
        with self._lock:
            st = self._tenants.get(tenant)
            if st is None:
                st = _TenantState(
                    tenant, weight,
                    quota or TenantQuota(
                        max_inflight=self.config.serve_max_inflight,
                        max_bytes=self.config.serve_max_bytes,
                    ),
                    tier=tier or DEFAULT_TIER,
                )
                self._tenants[tenant] = st
            else:
                st.weight = weight
                if quota is not None:
                    st.quota = quota
                if tier is not None:
                    st.tier = tier
        return TenantSession(self, st)

    # -- admission (client threads) ----------------------------------------

    def _submit(self, st: _TenantState, query, qid: Optional[str] = None,
                tctx=None) -> QueryFuture:
        with self._ctx_lock:
            cost = self.ctx.query_input_bytes(query)
        rejection = None
        quota_event = None
        with self._lock:
            if self._closed:
                rejection = QueryRejected(st.name, "closed", 0, 0)
                st.rejected += 1
                rej_id = f"{st.name}:rej{st.rejected}"
            else:
                try:
                    st.quota.check(
                        st.name, st.inflight, st.inflight_bytes, cost
                    )
                except QueryRejected as e:
                    rejection = e
                    st.rejected += 1
                    rej_id = f"{st.name}:rej{st.rejected}"
            if rejection is None:
                if qid is None:
                    qid = f"{st.name}:{st.seq}"
                st.seq += 1
                item = _Queued(
                    st, qid, query, QueryFuture(st.name, qid), cost,
                    1 + cost // self.config.serve_drr_quantum_bytes,
                    st.epoch, time.monotonic(), tctx=tctx,
                )
                st.inflight += 1
                st.inflight_bytes += cost
                st.admitted += 1
                st.queue.append(item)
                # open the trace buffer BEFORE query_admitted fires so
                # the lifecycle event itself lands in it
                self._trace_buf[qid] = []
                self._queued += 1
                queued = len(st.queue)
                if (not st.saturated
                        and st.inflight >= st.quota.max_inflight):
                    st.saturated = True
                    quota_event = dict(
                        tenant=st.name, state="saturated",
                        inflight=st.inflight,
                        limit=st.quota.max_inflight,
                        bytes=st.inflight_bytes,
                    )
                self._work.notify_all()
        if rejection is not None:
            self.slo.incr("queries_rejected", tenant=st.name)
            self.events.emit(
                "query_rejected", tenant=st.name, query=rej_id,
                reason=rejection.reason, limit=rejection.limit,
                current=rejection.current,
            )
            raise rejection
        self.slo.incr("queries_admitted", tenant=st.name)
        self.slo.set_gauge("serve_queue_depth", self._queued)
        self.events.emit(
            "query_admitted", tenant=st.name, query=qid,
            cost_bytes=cost, queued=queued,
        )
        if quota_event is not None:
            self.events.emit(
                "tenant_quota", tenant=quota_event["tenant"],
                state=quota_event["state"],
                inflight=quota_event["inflight"],
                limit=quota_event["limit"], bytes=quota_event["bytes"],
            )
        return item.future

    # -- fair-share scheduling (driver thread) -----------------------------

    def _pick_locked(self) -> Optional[_Queued]:
        """Strict priority across tiers, weighted deficit round robin
        within each tier.  A runnable latency-tier tenant always goes
        before any batch-tier tenant; weights keep their DRR meaning
        among same-tier peers.  None when nothing is runnable (all
        queues empty, or the window is at depth — dispatching more
        would block the driver)."""
        if len(self._inflight_items) >= self._window.depth:
            return None
        for tier in TIERS:
            ring = [
                st for st in self._tenants.values() if st.tier == tier
            ]
            if not ring or not any(st.queue for st in ring):
                continue
            rr = self._rr.get(tier, 0)
            while True:
                st = ring[rr % len(ring)]
                if not st.queue:
                    # idle tenants forfeit credit: no bursting on
                    # banked idle time when they return
                    st.deficit = 0
                    st.visited = False
                    rr += 1
                    continue
                if not st.visited:
                    st.deficit += st.weight
                    st.visited = True
                head = st.queue[0]
                if st.deficit >= head.cost_units:
                    st.deficit -= head.cost_units
                    st.queue.popleft()
                    self._queued -= 1
                    if not st.queue:
                        st.visited = False
                    self._rr[tier] = rr
                    return head
                # deficit exhausted: next tenant (credit carries over,
                # so an expensive head eventually accumulates its cost)
                st.visited = False
                rr += 1
        return None

    # -- driver loop -------------------------------------------------------

    def _drive(self) -> None:
        try:
            while True:
                with self._lock:
                    item = self._pick_locked()
                    if (item is None and self._closed
                            and self._queued == 0
                            and not self._inflight_items):
                        break
                if item is not None:
                    self._dispatch(item)
                for out in self._window.ready():
                    self._commit(out)
                if item is None:
                    # park: wakes immediately on a window outcome, and
                    # within one short tick of a new submission (two
                    # wait targets, one thread — bounded poll)
                    if not self._window.wait(0.02):
                        with self._work:
                            if self._queued == 0 and not self._closed:
                                self._work.wait(0.02)
        except BaseException as e:  # noqa: BLE001 - fail every future
            log.exception("serve driver died: %r", e)
            self._abort(e)

    def _dispatch(self, item: _Queued) -> None:
        """Resolve ``item`` from the cache, or dispatch it.  Any
        lowering/compile error resolves the future — the loop never
        dies on one tenant's bad plan.  Runs under the query's trace
        context: lowering/compile spans, the window handoff, and the
        gang envelopes all inherit its qid."""
        with tracectx.activate(item.tctx):
            self._dispatch_traced(item)

    def _dispatch_traced(self, item: _Queued) -> None:
        st = item.state
        key = None
        run_query = item.query
        try:
            with self._ctx_lock:
                if self.ctx.is_stream_query(item.query):
                    # stream plans route through the StreamExecutor —
                    # no async fetch to window; run inline (rare on a
                    # serving path, still correct)
                    table = self.ctx.run_to_host(item.query)
                    self._finish(item, table=table)
                    return
                view = self.views.lookup(st.name, item.query)
                if view is not None:
                    now = time.monotonic()
                    if view.fresh(now):
                        # fresh snapshot: zero dispatches, zero probes
                        table = view.read_snapshot()
                        rows = (
                            len(next(iter(table.values())))
                            if table else 0
                        )
                        self.slo.incr(
                            "view_snapshots_fresh", tenant=st.name
                        )
                        self.events.emit(
                            "view_snapshot", tenant=st.name,
                            view=view.name, fresh=True, qid=item.qid,
                            rows=rows,
                            staleness_s=round(view.staleness_s(now), 6),
                        )
                        self._finish(item, table=table, cached=True)
                        return
                    # stale: ONE dispatch of the finalize plan over the
                    # resident partial state (the snapshot IS this
                    # plan's cache — skip the result-cache probe)
                    self.events.emit(
                        "view_snapshot", tenant=st.name, view=view.name,
                        fresh=False, qid=item.qid,
                        staleness_s=round(view.staleness_s(now), 6),
                    )
                    item.view = view
                    run_query = finalize_query(view, self.ctx)
                elif self._cache.budget > 0:
                    with self.tracer.span(
                        "cache_probe", cat="serve", query=item.qid,
                    ):
                        fp = self.ctx.query_fingerprint(item.query)
                        table = None
                        if fp is not None:
                            # sha-based trace label, never builtin
                            # hash(): stable across processes so fleet
                            # traces correlate (graftlint routing-hash)
                            cfp = canonical_fingerprint(fp)
                            if cfp is None:
                                # reference-keyed plan: label is
                                # process-local by construction
                                cfp = hashlib.sha256(
                                    repr(fp).encode()
                                ).hexdigest()
                            item.tctx.fingerprint = cfp[:16]
                            key = (st.name, fp)
                            table = self._cache.get(key, item.epoch)
                    if table is not None:
                        rows = (
                            len(next(iter(table.values())))
                            if table else 0
                        )
                        self.slo.incr(
                            "result_cache_hits", tenant=st.name
                        )
                        self.events.emit(
                            "result_cache_hit", tenant=st.name,
                            query=item.qid, rows=rows,
                        )
                        self._finish(item, table=table, cached=True)
                        return
                fetch = self.ctx.run_to_host_async(run_query)
        except Exception as e:
            self._finish(item, error=e)
            return
        with self._lock:
            self._inflight_items[item.qid] = (item, key)
        self._window.submit(item.qid, fetch)

    def _commit(self, out) -> None:
        tag, value, error = out
        with self._lock:
            item, key = self._inflight_items.pop(tag)
        if error is None and item.view is not None:
            # store the finalized snapshot: the next read of this view
            # is zero dispatches until an append folds a newer delta
            with self._ctx_lock:
                item.view.commit_snapshot(value, self.ctx)
        if error is None and key is not None:
            # observed compute seconds drive cost-aware admission: a
            # cheap-to-recompute result must not displace expensive ones
            self._cache.put(
                key, value, item.epoch,
                cost_s=time.monotonic() - item.t_submit,
            )
        if isinstance(error, BaseException) and not isinstance(
            error, Exception
        ):
            raise error  # KeyboardInterrupt etc: don't swallow
        self._finish(item, table=value, error=error)

    def _finish(self, item: _Queued, table=None, cached: bool = False,
                error: Optional[BaseException] = None) -> None:
        st = item.state
        ok = error is None
        quota_event = None
        with self._lock:
            st.inflight -= 1
            st.inflight_bytes -= item.cost_bytes
            st.completed += 1
            if cached:
                st.cache_hits += 1
            if not ok:
                st.failed += 1
            if st.saturated and st.inflight < st.quota.max_inflight:
                st.saturated = False
                quota_event = dict(
                    tenant=st.name, inflight=st.inflight,
                    limit=st.quota.max_inflight, bytes=st.inflight_bytes,
                )
        seconds = round(time.monotonic() - item.t_submit, 6)
        self.slo.incr("queries_completed", tenant=st.name)
        self.slo.observe_latency("query_latency_s", seconds, tenant=st.name)
        self.slo.set_gauge("serve_queue_depth", self._queued)
        if ok:
            self.events.emit(
                "query_complete", tenant=st.name, query=item.qid,
                ok=True, seconds=seconds, cached=cached,
            )
        else:
            self.events.emit(
                "query_complete", tenant=st.name, query=item.qid,
                ok=False, seconds=seconds, cached=False,
                error=repr(error),
            )
        if quota_event is not None:
            self.events.emit(
                "tenant_quota", tenant=quota_event["tenant"], state="ok",
                inflight=quota_event["inflight"],
                limit=quota_event["limit"], bytes=quota_event["bytes"],
            )
        # critical-path fold: pop the trace buffer (query_complete just
        # landed in it via the tap) and sweep it into per-phase seconds
        # for the tenant's SLO plane.  Attribution failure must never
        # fail the query.
        trace = self._trace_buf.pop(item.qid, None)
        if trace is not None:
            try:
                bd = critpath.fold_query(trace, item.qid)
            except Exception:
                bd = None
            if bd is not None and bd.phases:
                with self._lock:
                    tot = self._phase_totals.setdefault(st.name, {})
                    for ph, secs in bd.phases.items():
                        tot[ph] = tot.get(ph, 0.0) + secs
                for ph, secs in bd.phases.items():
                    if secs > 0.0:
                        self.slo.observe_latency(
                            "query_phase_s", secs,
                            tenant=st.name, phase=ph,
                        )
        item.future.cached = cached
        item.future._resolve(result=table, error=error)

    def _trace_tap(self, ev: Dict[str, Any]) -> None:
        """EventLog tap: route qid-stamped events (and ``query=``-keyed
        lifecycle events) into the per-query trace buffer, if one is
        open.  Runs on every emit AND every absorbed worker telemetry
        event; must stay cheap and never raise."""
        q = ev.get("qid")
        if q is None and ev.get("kind") in (
            "query_admitted", "query_complete", "result_cache_hit",
        ):
            q = ev.get("query")
        if q is None:
            return
        buf = self._trace_buf.get(q)
        if buf is not None:
            buf.append(ev)

    # -- failure teardown --------------------------------------------------

    def _cancel_queued(self) -> None:
        with self._lock:
            items = []
            for st in self._tenants.values():
                items.extend(st.queue)
                st.queue.clear()
            self._queued = 0
            for it in items:
                it.state.inflight -= 1
                it.state.inflight_bytes -= it.cost_bytes
        for it in items:
            it.future._resolve(
                error=QueryRejected(it.state.name, "closed", 0, 0)
            )

    def _abort(self, exc: BaseException) -> None:
        """Driver-death last resort: every unresolved future gets the
        error instead of a hang."""
        self._cancel_queued()
        with self._lock:
            inflight = list(self._inflight_items.values())
            self._inflight_items.clear()
        for item, _key in inflight:
            item.future._resolve(error=exc)

    # -- observability -----------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        """Point-in-time counters for benchmarks and panels."""
        with self._lock:
            tenants = {
                st.name: {
                    "admitted": st.admitted,
                    "completed": st.completed,
                    "rejected": st.rejected,
                    "cache_hits": st.cache_hits,
                    "failed": st.failed,
                    "in_flight": st.inflight,
                    "queued": len(st.queue),
                    "epoch": st.epoch,
                    "saturated": st.saturated,
                    "tier": st.tier,
                }
                for st in self._tenants.values()
            }
        # rolling-window SLO readout: admission->completion latency
        # percentiles per tenant (None until a query completes inside
        # the window), plus cumulative critical-path phase seconds once
        # any query has been folded
        with self._lock:
            phase_totals = {
                t: dict(ph) for t, ph in self._phase_totals.items()
            }
        slo: Dict[str, Any] = {}
        for name in tenants:
            pct = self.slo.percentiles("query_latency_s", tenant=name)
            phases = phase_totals.get(name)
            if phases:
                pct = dict(pct or {})
                pct["phases"] = {
                    p: round(v, 6) for p, v in sorted(phases.items())
                }
            slo[name] = pct
        return {
            "tenants": tenants,
            "slo": slo,
            "cache": self._cache.stats(),
            "views": self.views.stats(),
            "dispatches": self._window.dispatches,
        }
