"""Plan-fingerprint result cache — repeat queries without re-dispatch.

Keys are ``(tenant, DryadContext.query_fingerprint(query))``: plan
structure via the executor's ``graph_key`` (the compile-cache
machinery), output position, and the content SHA-1 of every ingest
binding.  A query whose fingerprint is None (local_debug, stream
inputs, device-resident bindings) is simply uncacheable.

Invalidation is two-tier.  PER-BINDING (the continuous-ingest path):
``invalidate_binding`` drops exactly the entries whose fingerprint
covers the rewritten ingest binding — an append to table T touches
only results computed over T's old bytes, everything else keeps
hitting.  EPOCH-based (the blunt manual hammer): every entry records
the tenant's ingest epoch at insert, and a lookup whose epoch has
moved on misses (stale entries are dropped on contact, so a bumped
epoch also reclaims their bytes); ``TenantSession.bump_epoch`` remains
for whole-tenant resets.  Content changes need no invalidation at all:
a new binding fingerprints differently and misses cleanly (likewise a
vocabulary widening that moves the plan to a new operand tier changes
the graph key — a recompute, never a stale hit).

Eviction is LRU by byte budget.  ADMISSION is cost-aware (config
``serve_cache_admission="cost"``): an insert carrying its observed
compute seconds is rejected when the query is cheaper to recompute
than its bytes are worth keeping — ``cost_s < min_sec_per_gb *
nbytes/1e9`` — so a burst of big cheap scans cannot evict small
expensive aggregates.  ``admission="all"`` restores unconditional
insert (the differential baseline); inserts without a cost always
admit.  Hits hand back per-client array copies so a caller mutating
its result cannot poison the cached master.  NOT thread-safe on its
own: the service driver thread is the only caller (lookups, inserts,
and eviction all happen between dispatches).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, Optional, Tuple

import numpy as np


def table_nbytes(table: Dict[str, np.ndarray]) -> int:
    """Budget accounting for one host result table.  Object (string)
    columns count pointer width only — an approximation, but a stable
    one, and string-heavy results still evict in insertion order."""
    return sum(np.asarray(v).nbytes for v in table.values())


def _copy_table(table: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    return {k: np.asarray(v).copy() for k, v in table.items()}


class ResultCache:
    """LRU-by-bytes map: (tenant, fingerprint) -> host result table."""

    def __init__(
        self,
        budget_bytes: int,
        admission: str = "all",
        min_sec_per_gb: float = 0.5,
    ):
        self.budget = int(budget_bytes)
        self.admission = str(admission)
        self.min_sec_per_gb = float(min_sec_per_gb)
        # key -> (master table, nbytes, tenant epoch at insert)
        self._entries: "OrderedDict[Tuple, Tuple[Dict, int, int]]" = (
            OrderedDict()
        )
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.rejected = 0
        self.invalidations = 0
        self.bytes = 0

    def __len__(self) -> int:
        return len(self._entries)

    def get(self, key, epoch: int) -> Optional[Dict[str, np.ndarray]]:
        """The cached table (a fresh copy) when ``key`` is live at
        ``epoch``; None otherwise.  A stale-epoch entry is dropped on
        contact — the bump already invalidated it, this reclaims it."""
        if self.budget <= 0 or key is None:
            return None
        ent = self._entries.get(key)
        if ent is not None and ent[2] != epoch:
            self._drop(key)
            ent = None
        if ent is None:
            self.misses += 1
            return None
        self._entries.move_to_end(key)
        self.hits += 1
        return _copy_table(ent[0])

    def put(
        self,
        key,
        table: Dict[str, np.ndarray],
        epoch: int,
        cost_s: Optional[float] = None,
    ) -> None:
        """Insert; ``cost_s`` is the observed compute seconds the entry
        would save on a hit (the ``query_complete`` wall time).  Under
        cost admission an entry must be worth its bytes to enter."""
        if self.budget <= 0 or key is None:
            return
        nbytes = table_nbytes(table)
        if nbytes > self.budget:
            return  # would evict everything and still not fit
        if (
            self.admission == "cost"
            and cost_s is not None
            and cost_s < self.min_sec_per_gb * (nbytes / 1e9)
        ):
            self.rejected += 1
            return
        if key in self._entries:
            self._drop(key)
        self._entries[key] = (_copy_table(table), nbytes, epoch)
        self.bytes += nbytes
        while self.bytes > self.budget:
            _, (_t, nb, _e) = self._entries.popitem(last=False)
            self.bytes -= nb
            self.evictions += 1

    def invalidate_binding(self, tenant, binding_fp: str) -> int:
        """Drop exactly the entries computed over a rewritten ingest
        binding: a key's fingerprint carries the content SHA of every
        plan input (``query_fingerprint`` index [2]), so an entry is
        stale iff it covers the binding's PRE-append fingerprint.
        ``tenant=None`` sweeps every tenant — an ingest binding is
        shared engine state, so any tenant's result over it is stale.
        Returns the number of entries dropped."""
        if binding_fp is None:
            return 0
        stale = [
            k for k in self._entries
            if (tenant is None or k[0] == tenant)
            and isinstance(k[1], tuple)
            and len(k[1]) > 2
            and binding_fp in k[1][2]
        ]
        for k in stale:
            self._drop(k)
        self.invalidations += len(stale)
        return len(stale)

    def _drop(self, key) -> None:
        _t, nb, _e = self._entries.pop(key)
        self.bytes -= nb

    def stats(self) -> Dict[str, int]:
        return {
            "entries": len(self._entries),
            "bytes": self.bytes,
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "rejected": self.rejected,
            "invalidations": self.invalidations,
        }
