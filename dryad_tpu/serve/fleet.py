"""Serving fleet — multi-process front door + engine replicas.

The reference Dryad scales by putting a per-node ProcessService daemon
in front of every machine; this module is that move for the serving
tier.  ONE front-door :class:`~dryad_tpu.cluster.service.ProcessService`
(mailbox + HTTP) faces the clients, N engine replicas (each a
:class:`~dryad_tpu.serve.service.QueryService` wrapping its OWN
:class:`~dryad_tpu.api.context.DryadContext`) sit behind it, and a
plan-fingerprint-affine router keeps repeat plans landing on the
replica that already holds their compiled program, operand-pool
residency, and result-cache entries.

Wire protocol — everything is mailbox props under the ``fleet`` pid,
so the transport is exactly the gang envelope plane:

- ``rq/<qid>``    client -> router: pickled submit envelope (tenant,
                  tier, weight, routing fingerprint, packed query
                  blob, TraceContext wire form).  The mailbox itself
                  is the SUBMIT LOG: replay after a replica death
                  re-reads the envelope from this prop.
- ``cmd/<rid>/<seq>`` router -> replica: pickled list of envelopes.
                  Sequential per-replica props (never overwritten), so
                  the replica reads seq 0,1,2,... and a batch can
                  never be lost to latest-value semantics; batching is
                  natural back-pressure — whatever queued while the
                  replica was busy ships as one prop.
- ``res/<qid>``   replica -> everyone: framed result (header + table).
                  The CLIENT long-polls this prop directly — result
                  delivery costs no router hop — while the router's
                  in-process mailbox watch observes the same set to
                  retire the in-flight entry, feed the negative quota
                  memo, and emit ``fleet_result``.
- ``hb/<rid>``    replica heartbeat; the prop VERSION is the liveness
                  signal (:class:`~dryad_tpu.serve.router.ReplicaSet`
                  only counts an advancing version).
- ``stats/<rid>`` periodic ``QueryService.stats()`` + rolling SLO
                  snapshot, the metricsd scrape surface
                  (``merge_snapshots`` folds N of these).

The router runs IN the front-door process and touches the mailbox
object directly (a mailbox watch wakes it; routing decisions cost zero
HTTP).  Failure path: a replica whose heartbeat version stops
advancing is reaped, the routing generation bumps, and every in-flight
query it held replays from the submit log onto the rendezvous failover
replica — byte-identical results, because the engine is deterministic
and the replayed envelope is the original bytes.
"""

from __future__ import annotations

import itertools
import os
import pickle
import struct
import subprocess
import sys
import tempfile
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from dryad_tpu.cluster.service import ProcessService, ServiceClient
from dryad_tpu.exec.events import EventLog
from dryad_tpu.obs import tracectx
from dryad_tpu.serve.admission import (
    DEFAULT_TIER,
    QueryRejected,
    check_tier,
    tier_rank,
)
from dryad_tpu.serve.router import (
    NegativeQuotaMemo,
    ReplicaSet,
    canonical_fingerprint,
    package_fingerprint,
    rendezvous_rank,
)
from dryad_tpu.utils.logging import get_logger

log = get_logger("dryad_tpu.serve.fleet")

FLEET_PID = "fleet"  # mailbox pid namespace for every fleet prop

_MAGIC = b"F1"


# -- result framing ---------------------------------------------------------
# header and table pickle separately so the router (which only needs
# the header to retire an in-flight entry) never deserializes payload
# tables on the hot path.


def encode_result(header: Dict[str, Any], table) -> bytes:
    h = pickle.dumps(header, protocol=pickle.HIGHEST_PROTOCOL)
    t = pickle.dumps(table, protocol=pickle.HIGHEST_PROTOCOL)
    return _MAGIC + struct.pack("<II", len(h), len(t)) + h + t


def decode_result_header(blob: bytes) -> Dict[str, Any]:
    if blob[:2] != _MAGIC:
        raise ValueError("bad result frame")
    hlen, _tlen = struct.unpack("<II", blob[2:10])
    return pickle.loads(blob[10 : 10 + hlen])


def decode_result(blob: bytes) -> Tuple[Dict[str, Any], Any]:
    if blob[:2] != _MAGIC:
        raise ValueError("bad result frame")
    hlen, tlen = struct.unpack("<II", blob[2:10])
    header = pickle.loads(blob[10 : 10 + hlen])
    table = pickle.loads(blob[10 + hlen : 10 + hlen + tlen])
    return header, table


def raise_for_result(header: Dict[str, Any]) -> None:
    """Map a failed result header onto the structured exceptions the
    single-process serving tier raises."""
    rej = header.get("rejected")
    if rej is not None:
        raise QueryRejected(
            header.get("tenant", "?"), rej.get("reason", "?"),
            int(rej.get("limit", 0)), int(rej.get("current", 0)),
        )
    if not header.get("ok", False):
        raise RuntimeError(
            f"fleet query {header.get('qid')} failed: "
            f"{header.get('error')}"
        )


def pack_for_fleet(query) -> Tuple[bytes, str]:
    """Serialize *query* into a fleet envelope payload: the job-package
    bytes plus the routing fingerprint — the canonical sha of the serve
    cache's ``(graph_key, output, binding_SHAs)`` tuple when the plan
    is value-portable, else the package-bytes sha (same client
    resubmitting the same blob still routes affine)."""
    from dryad_tpu.exec import jobpackage

    with tempfile.TemporaryDirectory(prefix="dryad-pack-") as td:
        path = os.path.join(td, "query.qpkg")
        jobpackage.pack_query(query, path)
        with open(path, "rb") as fh:
            blob = fh.read()
    fp = canonical_fingerprint(query.ctx.query_fingerprint(query))
    return blob, (fp or package_fingerprint(blob))


def make_envelope(
    *,
    qid: str,
    tenant: str,
    package: bytes,
    fingerprint: Optional[str] = None,
    tier: str = DEFAULT_TIER,
    weight: int = 1,
    trace: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    check_tier(tier)
    return {
        "qid": qid,
        "tenant": tenant,
        "tier": tier,
        "weight": int(weight),
        "package": package,
        "fingerprint": fingerprint or package_fingerprint(package),
        "trace": trace or {"qid": qid, "tenant": tenant},
    }


# -- replica side -----------------------------------------------------------


class ReplicaRunner:
    """One engine replica: its own DryadContext + QueryService, fed by
    the front door's ``cmd/<rid>/<seq>`` prop stream over real HTTP
    (same wire whether the runner lives in a thread or its own
    process — ``dryad_tpu.serve.replica`` is this class as a main).

    Threads: the SERVE loop long-polls command props in sequence,
    loads/looks-up the prepared query per package sha, and submits to
    the local QueryService; the RESULT loop posts each future's
    outcome as it resolves (so the serve loop keeps reading the next
    batch while earlier queries execute); the HEARTBEAT loop versions
    ``hb/<rid>`` and refreshes ``stats/<rid>``.

    ``kill()`` is the chaos hook: a simulated SIGKILL — every loop
    stops posting IMMEDIATELY (no result flush, no farewell heartbeat),
    exactly what the router's staleness detector must recover from.
    """

    def __init__(
        self,
        rid: str,
        host: str,
        port: int,
        ctx_factory: Callable[[], Any],
        hb_interval: float = 0.25,
        poll_s: float = 1.0,
        allow_process_exit: bool = False,
    ):
        self.rid = rid
        self.host, self.port = host, port
        self._ctx_factory = ctx_factory
        self.hb_interval = hb_interval
        self.poll_s = poll_s
        # only a replica that OWNS its process may honor a FaultPlan
        # kill (os._exit) — a thread-mode runner must never take the
        # test runner down with it
        self._allow_process_exit = allow_process_exit
        self._killed = False
        self._stopping = False
        self._ready = threading.Event()
        self._drained = threading.Event()
        self._results: "deque" = deque()
        self._res_cv = threading.Condition()
        self.svc = None
        self.ctx = None
        self._threads: List[threading.Thread] = []

    # -- lifecycle --

    def start(self) -> "ReplicaRunner":
        t = threading.Thread(
            target=self._serve_loop, daemon=True,
            name=f"dryad-replica-{self.rid}",
        )
        self._threads.append(t)
        t.start()
        return self

    def run_forever(self) -> None:
        """Process-mode entry: serve on the calling thread until the
        exit envelope arrives (``dryad_tpu.serve.replica`` main)."""
        self._serve_loop()

    def kill(self) -> None:
        """Chaos: die mid-query.  Nothing further is posted — pending
        results, heartbeats, and stats all stop on the spot."""
        self._killed = True
        with self._res_cv:
            self._res_cv.notify_all()

    def stop(self, timeout: float = 30.0) -> None:
        """Graceful local stop (normally driven by the exit envelope)."""
        self._stopping = True
        with self._res_cv:
            self._res_cv.notify_all()
        for t in self._threads:
            t.join(timeout)
        self._cleanup()

    def _cleanup(self) -> None:
        if self.svc is not None:
            try:
                self.svc.close(timeout=10.0)
            except Exception:  # noqa: BLE001
                pass

    # -- loops --

    def _serve_loop(self) -> None:
        from dryad_tpu.serve.service import QueryService

        client = ServiceClient(self.host, self.port)
        try:
            self.ctx = self._ctx_factory()
            self.svc = QueryService(self.ctx)
        except Exception:  # noqa: BLE001 — a replica that can't build
            log.exception("replica %s failed to build its engine", self.rid)
            return
        self._prepared: Dict[str, Any] = {}
        self._ready.set()
        for target, name in (
            (self._hb_loop, f"dryad-replica-{self.rid}-hb"),
            (self._result_loop, f"dryad-replica-{self.rid}-res"),
        ):
            t = threading.Thread(target=target, daemon=True, name=name)
            self._threads.append(t)
            t.start()
        seq = 0
        while not self._killed and not self._stopping:
            try:
                got = client.get_prop(
                    FLEET_PID, f"cmd/{self.rid}/{seq}", 0, self.poll_s
                )
            except Exception:  # noqa: BLE001 — front door gone
                if self._stopping or self._killed:
                    break
                time.sleep(min(self.poll_s, 0.2))
                continue
            if got is None:
                continue
            seq += 1
            if self._maybe_chaos_exit(seq):
                return
            try:
                envelopes = pickle.loads(got[1])
            except Exception:  # noqa: BLE001
                log.exception("replica %s: bad command batch", self.rid)
                continue
            for env in envelopes:
                if env.get("exit"):
                    self._graceful_exit(client)
                    return
                self._submit_one(client, env)

    def _maybe_chaos_exit(self, seq: int) -> bool:
        """Seeded FaultPlan kill at a batch boundary — process-mode
        replicas reuse the gang chaos machinery (``worker_kill_prob``),
        dying the way a machine dies: no cleanup, no farewell."""
        if not self._allow_process_exit:
            return False
        from dryad_tpu.exec import faults
        from dryad_tpu.obs import flightrec

        if faults.registry.maybe_kill(f"replica:{self.rid}"):
            try:
                self.svc.events.emit(
                    "worker_killed_injected",
                    name=f"replica:{self.rid}", stage=f"batch{seq}",
                )
                flightrec.dump(reason="replica_chaos_kill")
            except Exception:  # noqa: BLE001
                pass
            os._exit(113)
        return False

    def _graceful_exit(self, client: ServiceClient) -> None:
        # drain: wait for the result loop to post everything in flight
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            with self._res_cv:
                if not self._results:
                    break
            time.sleep(0.01)
        self._stopping = True
        with self._res_cv:
            self._res_cv.notify_all()
        try:
            self._post_stats(client)
        except Exception:  # noqa: BLE001
            pass
        self._cleanup()

    def _submit_one(self, client: ServiceClient, env: Dict) -> None:
        qid, tenant = env["qid"], env["tenant"]
        t0 = time.monotonic()
        try:
            query = self._prepared_query(env)
            sess = self.svc.session(
                tenant, weight=max(1, int(env.get("weight", 1))),
                tier=env.get("tier") or DEFAULT_TIER,
            )
            tctx = tracectx.TraceContext.from_wire(env.get("trace"))
            fut = sess.submit(query, qid=qid, tctx=tctx)
        except QueryRejected as e:
            self._post_result(client, env, t0, rejected=e)
            return
        except Exception as e:  # noqa: BLE001 — bad package, etc.
            self._post_result(client, env, t0, error=e)
            return
        with self._res_cv:
            self._results.append((env, fut, t0))
            self._res_cv.notify_all()

    def _prepared_query(self, env: Dict):
        """Prepared-statement cache: the FIRST envelope carrying a
        package sha pays the load (bindings ingest into the resident
        context); every repeat reuses the loaded Query OBJECT — so the
        compile cache and the result cache hit even for plans whose
        graph key holds closures by reference."""
        import hashlib

        from dryad_tpu.exec import jobpackage

        blob = env["package"]
        sha = hashlib.sha256(blob).hexdigest()
        query = self._prepared.get(sha)
        if query is None:
            path = os.path.join(
                tempfile.gettempdir(),
                f"dryad-replica-{os.getpid()}-{self.rid}-{sha[:16]}.qpkg",
            )
            with open(path, "wb") as fh:
                fh.write(blob)
            try:
                query = jobpackage.load_query(path, ctx=self.ctx)
            finally:
                try:
                    os.unlink(path)
                except OSError:
                    pass
            self._prepared[sha] = query
        return query

    def _result_loop(self) -> None:
        client = ServiceClient(self.host, self.port)
        while not self._killed:
            with self._res_cv:
                while not self._results and not (
                    self._killed or self._stopping
                ):
                    self._res_cv.wait(0.5)
                if not self._results:
                    if self._killed or self._stopping:
                        return
                    continue
                env, fut, t0 = self._results.popleft()
            try:
                table = fut.result(timeout=600.0)
            except QueryRejected as e:
                self._post_result(client, env, t0, rejected=e)
                continue
            except BaseException as e:  # noqa: BLE001
                self._post_result(client, env, t0, error=e)
                continue
            self._post_result(
                client, env, t0, table=table, cached=fut.cached
            )

    def _post_result(
        self, client: ServiceClient, env: Dict, t0: float,
        table=None, cached: bool = False, error=None, rejected=None,
    ) -> None:
        if self._killed:
            return  # a dead replica posts nothing
        header: Dict[str, Any] = {
            "qid": env["qid"],
            "tenant": env["tenant"],
            "ok": error is None and rejected is None,
            "cached": cached,
            "seconds": round(time.monotonic() - t0, 6),
            "replica": self.rid,
            "generation": env.get("generation", 0),
            "error": repr(error) if error is not None else None,
            "rejected": (
                {
                    "reason": rejected.reason,
                    "limit": rejected.limit,
                    "current": rejected.current,
                }
                if rejected is not None
                else None
            ),
        }
        try:
            client.set_prop(
                FLEET_PID, f"res/{env['qid']}", encode_result(header, table)
            )
        except Exception:  # noqa: BLE001 — front door gone mid-close
            if not self._stopping:
                log.exception(
                    "replica %s: result post failed for %s",
                    self.rid, env["qid"],
                )

    def _hb_loop(self) -> None:
        client = ServiceClient(self.host, self.port)
        last_stats = 0.0
        while not self._killed and not self._stopping:
            try:
                client.set_prop(
                    FLEET_PID, f"hb/{self.rid}",
                    pickle.dumps({"pid": os.getpid(), "ts": time.time()}),
                )
                now = time.monotonic()
                if now - last_stats >= max(self.hb_interval, 0.5):
                    self._post_stats(client)
                    last_stats = now
            except Exception:  # noqa: BLE001
                if self._stopping or self._killed:
                    return
            time.sleep(self.hb_interval)

    def _post_stats(self, client: ServiceClient) -> None:
        if self.svc is None or self._killed:
            return
        payload = {
            "stats": self.svc.stats(),
            "snapshot": self.svc.slo.snapshot(),
            "pid": os.getpid(),
            "ts": time.time(),
        }
        client.set_prop(
            FLEET_PID, f"stats/{self.rid}", pickle.dumps(payload)
        )


# -- router / supervisor ----------------------------------------------------


class _InFlight:
    __slots__ = ("qid", "rid", "tenant", "tier", "fingerprint", "t0",
                 "replays", "cmd_key")

    def __init__(self, qid, rid, tenant, tier, fingerprint, t0):
        self.qid = qid
        self.rid = rid
        self.tenant = tenant
        self.tier = tier
        self.fingerprint = fingerprint
        self.t0 = t0
        self.replays = 0
        self.cmd_key = None  # (rid, seq) of the batch that carried it


class ServeFleet:
    """Fleet supervisor: the front-door service, the affinity router,
    and replica lifecycle (spawn / attach / chaos-kill / reap)."""

    def __init__(
        self,
        root: Optional[str] = None,
        port: int = 0,
        events: Optional[EventLog] = None,
        hb_interval: float = 0.25,
        stale_after: float = 2.0,
        memo_ttl: float = 0.25,
        res_gc_s: float = 20.0,
    ):
        self._own_root = root is None
        self.root = root or tempfile.mkdtemp(prefix="dryad-fleet-")
        self.service = ProcessService(self.root, port=port)
        self.host, self.port = "127.0.0.1", self.service.port
        self.mailbox = self.service.mailbox
        self.events = events if events is not None else EventLog()
        self.hb_interval = hb_interval
        self.replicas = ReplicaSet(stale_after=stale_after)
        self.memo = NegativeQuotaMemo(ttl=memo_ttl)
        self.res_gc_s = res_gc_s
        self._runners: Dict[str, ReplicaRunner] = {}
        self._procs: Dict[str, subprocess.Popen] = {}
        self._modes: Dict[str, str] = {}
        self._inflight: Dict[str, _InFlight] = {}
        self._cmd_seq: Dict[str, int] = {}
        # (rid, seq) -> unresolved qids riding that cmd prop (GC)
        self._cmd_members: Dict[Tuple[str, int], set] = {}
        self._done_gc: "deque" = deque()
        self._queue: "deque" = deque()
        self._cv = threading.Condition()
        self._closing = False
        self._seq = itertools.count(1)
        self.routed = 0
        self.delivered = 0
        self.replayed = 0
        self.failed = 0
        self.stale_results = 0
        self.mailbox.add_watch(self._on_prop)
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="dryad-fleet-router"
        )
        self._thread.start()

    # -- replica lifecycle --

    def spawn_thread(
        self, rid: str, ctx_factory: Callable[[], Any],
        timeout: float = 120.0,
    ) -> ReplicaRunner:
        """In-process replica (its own DryadContext + QueryService on
        daemon threads, same HTTP wire as a subprocess replica)."""
        runner = ReplicaRunner(
            rid, self.host, self.port, ctx_factory,
            hb_interval=self.hb_interval,
        )
        self._runners[rid] = runner
        self._modes[rid] = "thread"
        runner.start()
        self.attach(rid, timeout=timeout, mode="thread")
        return runner

    def spawn_process(
        self,
        rid: str,
        bootstrap: str,
        fault: Optional[str] = None,
        env: Optional[Dict[str, str]] = None,
        timeout: float = 180.0,
    ) -> subprocess.Popen:
        """Subprocess replica: ``python -m dryad_tpu.serve.replica``
        with *bootstrap* (a python file defining ``build_context()``)
        and an optional FaultPlan JSON for seeded chaos."""
        argv = [
            sys.executable, "-m", "dryad_tpu.serve.replica",
            "--host", self.host, "--port", str(self.port),
            "--rid", rid, "--bootstrap", bootstrap,
            "--hb-interval", str(self.hb_interval),
        ]
        if fault:
            argv += ["--fault", fault]
        p = subprocess.Popen(argv, env=env)
        self._procs[rid] = p
        self._modes[rid] = "process"
        self.attach(rid, timeout=timeout, mode="process")
        return p

    def attach(
        self, rid: str, timeout: float = 120.0, mode: str = "external"
    ) -> None:
        """Wait for the replica's first heartbeat, then add it to the
        routing set."""
        got = self.mailbox.get_prop(FLEET_PID, f"hb/{rid}", 0, timeout)
        if got is None:
            raise TimeoutError(
                f"replica {rid} posted no heartbeat in {timeout}s"
            )
        info = {}
        try:
            info = pickle.loads(got[1])
        except Exception:  # noqa: BLE001
            pass
        mode = self._modes.setdefault(rid, mode)
        with self._cv:
            self.replicas.add(rid)
            self.replicas.observe(rid, got[0])
        self.events.emit(
            "replica_started", replica=rid, mode=mode,
            pid=info.get("pid"),
        )

    def kill_replica(self, rid: str) -> None:
        """Chaos: make *rid* die mid-query.  Thread replicas get the
        simulated SIGKILL (stop posting instantly); process replicas
        get the real one."""
        runner = self._runners.get(rid)
        if runner is not None:
            runner.kill()
        p = self._procs.get(rid)
        if p is not None and p.poll() is None:
            p.kill()

    # -- client surface (in-process; FleetClient is the HTTP twin) --

    def submit(
        self,
        *,
        tenant: str,
        package: bytes,
        fingerprint: Optional[str] = None,
        tier: str = DEFAULT_TIER,
        weight: int = 1,
        qid: Optional[str] = None,
    ) -> str:
        qid = qid or f"f-{os.getpid()}-{next(self._seq)}"
        env = make_envelope(
            qid=qid, tenant=tenant, package=package,
            fingerprint=fingerprint, tier=tier, weight=weight,
        )
        self.mailbox.set_prop(
            FLEET_PID, f"rq/{qid}",
            pickle.dumps(env, protocol=pickle.HIGHEST_PROTOCOL),
        )
        return qid

    def result(self, qid: str, timeout: float = 60.0):
        got = self.mailbox.get_prop(FLEET_PID, f"res/{qid}", 0, timeout)
        if got is None:
            raise TimeoutError(f"fleet query {qid} unresolved in {timeout}s")
        header, table = decode_result(got[1])
        raise_for_result(header)
        return table

    def run(self, query, tenant: str, tier: str = DEFAULT_TIER,
            weight: int = 1, timeout: float = 60.0):
        """Pack, route, execute, and fetch — the one-call local path."""
        blob, fp = pack_for_fleet(query)
        qid = self.submit(
            tenant=tenant, package=blob, fingerprint=fp, tier=tier,
            weight=weight,
        )
        return self.result(qid, timeout=timeout)

    # -- observability --

    def stats(self) -> Dict[str, Any]:
        with self._cv:
            inflight = len(self._inflight)
        return {
            "replicas": {
                rid: self._replica_stats(rid)
                for rid in self.replicas.alive()
            },
            "router": {
                "routed": self.routed,
                "delivered": self.delivered,
                "replayed": self.replayed,
                "failed": self.failed,
                "fast_rejects": self.memo.fast_rejects,
                "stale_results": self.stale_results,
                "in_flight": inflight,
                "generation": self.replicas.generation,
                "dead": self.replicas.dead(),
            },
        }

    def _replica_stats(self, rid: str) -> Optional[Dict[str, Any]]:
        got = self.mailbox.get_prop(FLEET_PID, f"stats/{rid}")
        if got is None:
            return None
        try:
            return pickle.loads(got[1])["stats"]
        except Exception:  # noqa: BLE001
            return None

    def replica_snapshots(self) -> List[Dict[str, Any]]:
        """The latest rolling-SLO snapshot each replica posted —
        ``tools.metricsd.merge_snapshots`` folds these into fleet
        percentiles (bucket-for-bucket, the only commutative fold)."""
        out = []
        for rid in self.replicas.alive() + self.replicas.dead():
            got = self.mailbox.get_prop(FLEET_PID, f"stats/{rid}")
            if got is None:
                continue
            try:
                out.append(pickle.loads(got[1])["snapshot"])
            except Exception:  # noqa: BLE001
                continue
        return out

    # -- shutdown --

    def close(self, timeout: float = 60.0) -> None:
        with self._cv:
            if self._closing:
                return
            self._closing = True
            self._cv.notify_all()
        # exit envelopes ride the same sequential cmd stream, so they
        # land AFTER everything already routed
        for rid in self.replicas.alive():
            try:
                self._post_cmd(rid, [{"exit": True}])
            except Exception:  # noqa: BLE001
                pass
        for rid, runner in self._runners.items():
            runner.stop(timeout=timeout)
        for rid, p in self._procs.items():
            try:
                p.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(timeout=10)
        self._thread.join(timeout=10.0)
        self.mailbox.remove_watch(self._on_prop)
        self.service.close()

    def __enter__(self) -> "ServeFleet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- router internals --

    def _on_prop(self, pid: str, name: str, ver: int, value: bytes) -> None:
        """Mailbox watch — the router's wake signal.  Runs on whatever
        thread called set_prop (HTTP handler, replica thread, router
        itself); must only enqueue."""
        if pid != FLEET_PID:
            return
        if name.startswith("rq/"):
            item = ("rq", name[3:], value)
        elif name.startswith("res/"):
            item = ("res", name[4:], value)
        elif name.startswith("hb/"):
            item = ("hb", name[3:], ver)
        else:
            return
        with self._cv:
            self._queue.append(item)
            self._cv.notify_all()

    def _run(self) -> None:
        tick = max(0.05, self.hb_interval / 2.0)
        while True:
            with self._cv:
                if not self._queue:
                    if self._closing:
                        return
                    self._cv.wait(tick)
                drained = list(self._queue)
                self._queue.clear()
            batches: Dict[str, List[Dict]] = {}
            for kind, key, val in drained:
                try:
                    if kind == "rq":
                        self._route_one(key, val, batches)
                    elif kind == "res":
                        self._on_result(key, val)
                    else:
                        self.replicas.observe(key, val)
                except Exception:  # noqa: BLE001 — router must survive
                    log.exception("fleet router: %s/%s failed", kind, key)
            try:
                self._sweep_stale(batches)
            except Exception:  # noqa: BLE001
                log.exception("fleet router: staleness sweep failed")
            for rid, envs in batches.items():
                self._post_cmd(rid, envs)
            self._gc()

    def _fail(self, qid: str, tenant: str, message: str) -> None:
        self.failed += 1
        self.mailbox.set_prop(
            FLEET_PID, f"res/{qid}",
            encode_result(
                {
                    "qid": qid, "tenant": tenant, "ok": False,
                    "cached": False, "seconds": 0.0, "replica": None,
                    "generation": self.replicas.generation,
                    "error": message, "rejected": None,
                },
                None,
            ),
        )
        self._done_gc.append((time.monotonic(), qid))

    def _reject_fast(self, qid: str, tenant: str, memo: Dict) -> None:
        self.mailbox.set_prop(
            FLEET_PID, f"res/{qid}",
            encode_result(
                {
                    "qid": qid, "tenant": tenant, "ok": False,
                    "cached": False, "seconds": 0.0, "replica": None,
                    "generation": self.replicas.generation,
                    "error": None,
                    "rejected": {
                        "reason": memo.get("reason", "inflight"),
                        "limit": memo.get("limit", 0),
                        "current": memo.get("current", 0),
                    },
                },
                None,
            ),
        )
        self.events.emit(
            "fleet_rejected", tenant=tenant, query=qid,
            reason=memo.get("reason", "inflight"),
            limit=memo.get("limit"), current=memo.get("current"),
        )
        self._done_gc.append((time.monotonic(), qid))

    def _route_one(
        self, qid: str, blob: bytes, batches: Dict[str, List[Dict]]
    ) -> None:
        try:
            env = pickle.loads(blob)
            tenant = env["tenant"]
            check_tier(env.get("tier") or DEFAULT_TIER)
        except Exception as e:  # noqa: BLE001
            self._fail(qid, "?", f"malformed envelope: {e!r}")
            return
        memo = self.memo.check(tenant)
        if memo is not None:
            # negative-result memo: the tenant is hard-quota'd; fail
            # fast at the front door, no replica round trip
            self._reject_fast(qid, tenant, memo)
            return
        alive = self.replicas.alive()
        if not alive:
            self._fail(qid, tenant, "no replicas in the fleet")
            return
        fp = env.get("fingerprint") or package_fingerprint(env["package"])
        rid = rendezvous_rank(fp, alive)[0]
        env["generation"] = self.replicas.generation
        info = _InFlight(
            qid, rid, tenant, env.get("tier") or DEFAULT_TIER, fp,
            time.monotonic(),
        )
        self._inflight[qid] = info
        batches.setdefault(rid, []).append(env)
        self.routed += 1
        self.events.emit(
            "fleet_submit", tenant=tenant, query=qid, replica=rid,
            tier=info.tier, fingerprint=fp[:16],
        )

    def _post_cmd(self, rid: str, envs: List[Dict]) -> None:
        # latency-tier envelopes lead the batch: the replica submits in
        # batch order, so the front door's tier ordering is preserved
        # end to end (the replica's own scheduler then keeps it)
        envs.sort(
            key=lambda e: tier_rank(e.get("tier") or DEFAULT_TIER)
            if not e.get("exit") else len("zz")
        )
        seq = self._cmd_seq.get(rid, 0)
        self._cmd_seq[rid] = seq + 1
        members = {e["qid"] for e in envs if "qid" in e}
        if members:
            self._cmd_members[(rid, seq)] = members
            for e in envs:
                if "qid" in e and e["qid"] in self._inflight:
                    self._inflight[e["qid"]].cmd_key = (rid, seq)
        self.mailbox.set_prop(
            FLEET_PID, f"cmd/{rid}/{seq}",
            pickle.dumps(envs, protocol=pickle.HIGHEST_PROTOCOL),
        )

    def _retire_cmd(self, info: _InFlight) -> None:
        key = info.cmd_key
        if key is None:
            return
        members = self._cmd_members.get(key)
        if members is None:
            return
        members.discard(info.qid)
        if not members:
            del self._cmd_members[key]
            self.mailbox.del_prop(FLEET_PID, f"cmd/{key[0]}/{key[1]}")

    def _on_result(self, qid: str, blob: bytes) -> None:
        info = self._inflight.pop(qid, None)
        if info is None:
            # late post from a reaped replica after replay delivered —
            # harmless (deterministic engine: same bytes), just counted
            self.stale_results += 1
            return
        try:
            header = decode_result_header(blob)
        except Exception:  # noqa: BLE001
            header = {"ok": False, "error": "undecodable result"}
        rej = header.get("rejected")
        if rej is not None:
            self.memo.note_rejection(
                info.tenant, rej.get("reason", ""), dict(rej),
            )
        else:
            self.memo.note_completion(info.tenant)
        self.delivered += 1
        self._retire_cmd(info)
        self._done_gc.append((time.monotonic(), qid))
        self.events.emit(
            "fleet_result", tenant=info.tenant, query=qid,
            ok=bool(header.get("ok")),
            seconds=round(time.monotonic() - info.t0, 6),
            cached=bool(header.get("cached")),
            replica=header.get("replica"),
        )

    def _sweep_stale(self, batches: Dict[str, List[Dict]]) -> None:
        for rid in self.replicas.stale():
            victims = [
                info for info in self._inflight.values() if info.rid == rid
            ]
            gen = self.replicas.reap(rid)
            age = self.replicas.stale_after
            self.events.emit(
                "replica_dead", replica=rid, generation=gen,
                inflight=len(victims), stale_s=round(age, 3),
            )
            log.warning(
                "fleet: replica %s heartbeat stale; reaped (gen %d), "
                "replaying %d in-flight queries", rid, gen, len(victims),
            )
            alive = self.replicas.alive()
            for info in victims:
                self._retire_cmd(info)
                # the submit log IS the mailbox: replay the original
                # envelope bytes, so the rerun is bit-for-bit the same
                # submission
                got = self.mailbox.get_prop(FLEET_PID, f"rq/{info.qid}")
                if got is None or not alive:
                    del self._inflight[info.qid]
                    self._fail(
                        info.qid, info.tenant,
                        f"replica {rid} died"
                        + ("; no submit log" if got is None
                           else "; no replicas left"),
                    )
                    continue
                env = pickle.loads(got[1])
                env["generation"] = gen
                new_rid = rendezvous_rank(info.fingerprint, alive)[0]
                info.rid = new_rid
                info.replays += 1
                self.replayed += 1
                batches.setdefault(new_rid, []).append(env)
                self.events.emit(
                    "fleet_reroute", tenant=info.tenant, query=info.qid,
                    from_replica=rid, to_replica=new_rid,
                )

    def _gc(self) -> None:
        now = time.monotonic()
        while self._done_gc and now - self._done_gc[0][0] > self.res_gc_s:
            _, qid = self._done_gc.popleft()
            self.mailbox.del_prop(FLEET_PID, f"res/{qid}")
            self.mailbox.del_prop(FLEET_PID, f"rq/{qid}")


# -- HTTP client ------------------------------------------------------------


class FleetClient:
    """A tenant's HTTP handle on the fleet front door.  Import-light by
    design (stdlib + cluster transport only): closed-loop bench client
    processes submit pre-packed envelopes without paying an engine
    import."""

    def __init__(
        self,
        host: str,
        port: int,
        tenant: str,
        tier: str = DEFAULT_TIER,
        weight: int = 1,
    ):
        self.tenant = tenant
        self.tier = check_tier(tier)
        self.weight = weight
        self._sc = ServiceClient(host, port)
        # sha-derived client nonce — qids must be unique fleet-wide and
        # PYTHONHASHSEED-independent
        self._nonce = os.urandom(6).hex()
        self._seq = itertools.count(1)

    def submit_package(
        self,
        package: bytes,
        fingerprint: Optional[str] = None,
        qid: Optional[str] = None,
    ) -> str:
        qid = qid or f"{self.tenant}-{self._nonce}-{next(self._seq)}"
        env = make_envelope(
            qid=qid, tenant=self.tenant, package=package,
            fingerprint=fingerprint, tier=self.tier, weight=self.weight,
        )
        self._sc.set_prop(
            FLEET_PID, f"rq/{qid}",
            pickle.dumps(env, protocol=pickle.HIGHEST_PROTOCOL),
        )
        return qid

    def submit_query(self, query, qid: Optional[str] = None) -> str:
        blob, fp = pack_for_fleet(query)
        return self.submit_package(blob, fingerprint=fp, qid=qid)

    def result(self, qid: str, timeout: float = 60.0):
        got = self._sc.get_prop(FLEET_PID, f"res/{qid}", 0, timeout)
        if got is None:
            raise TimeoutError(f"fleet query {qid} unresolved in {timeout}s")
        header, table = decode_result(got[1])
        raise_for_result(header)
        return table

    def result_header(self, qid: str, timeout: float = 60.0) -> Dict:
        """Latency-probe variant: wait for the result but decode only
        the header (no table deserialization on the client)."""
        got = self._sc.get_prop(FLEET_PID, f"res/{qid}", 0, timeout)
        if got is None:
            raise TimeoutError(f"fleet query {qid} unresolved in {timeout}s")
        return decode_result_header(got[1])

    def run(self, query, timeout: float = 60.0):
        return self.result(self.submit_query(query), timeout=timeout)
