"""Serving tier — multiplex concurrent tenant queries on one resident
engine.

Everything below ``serve/`` is one-driver-one-job; this package is the
long-lived front end that turns the engine into a service.  A
:class:`QueryService` owns ONE driver thread (the executor is
driver-owned and not thread-safe), admits queries from many logical
tenants under per-tenant quotas, schedules them fair-share
(weighted deficit round robin) onto a single shared
:class:`~dryad_tpu.exec.pipeline.DispatchWindow`, and serves repeat
queries from a plan-fingerprint result cache.  Client threads only
build plans, submit, and block on :class:`QueryFuture` — they never
touch devices.

The FLEET layer (:mod:`~dryad_tpu.serve.fleet`) scales the same
service past one process: a multi-process front door on the cluster
mailbox/HTTP plane, N engine replicas (each a QueryService wrapping
its own context), and a plan-fingerprint-affine rendezvous router
(:mod:`~dryad_tpu.serve.router`) that keeps repeat plans landing on
the replica already holding their compiled programs and caches.

Layering: ``serve/`` reaches devices exclusively through the ``api``
and ``exec`` public entry points (``cluster`` is allowed for the fleet
transport only); engine layers never import ``serve/`` (enforced by
graftlint's ``serve-layering`` rule).
"""

from dryad_tpu.serve.admission import (
    DEFAULT_TIER,
    TIERS,
    QueryRejected,
    TenantQuota,
)
from dryad_tpu.serve.cache import ResultCache
from dryad_tpu.serve.fleet import FleetClient, ReplicaRunner, ServeFleet
from dryad_tpu.serve.router import (
    NegativeQuotaMemo,
    ReplicaSet,
    canonical_fingerprint,
    package_fingerprint,
    rendezvous_rank,
    route,
)
from dryad_tpu.serve.service import QueryFuture, QueryService, TenantSession

__all__ = [
    "DEFAULT_TIER",
    "FleetClient",
    "NegativeQuotaMemo",
    "QueryFuture",
    "QueryRejected",
    "QueryService",
    "ReplicaRunner",
    "ReplicaSet",
    "ResultCache",
    "ServeFleet",
    "TIERS",
    "TenantSession",
    "TenantQuota",
    "canonical_fingerprint",
    "package_fingerprint",
    "rendezvous_rank",
    "route",
]
