"""Serving tier — multiplex concurrent tenant queries on one resident
engine.

Everything below ``serve/`` is one-driver-one-job; this package is the
long-lived front end that turns the engine into a service.  A
:class:`QueryService` owns ONE driver thread (the executor is
driver-owned and not thread-safe), admits queries from many logical
tenants under per-tenant quotas, schedules them fair-share
(weighted deficit round robin) onto a single shared
:class:`~dryad_tpu.exec.pipeline.DispatchWindow`, and serves repeat
queries from a plan-fingerprint result cache.  Client threads only
build plans, submit, and block on :class:`QueryFuture` — they never
touch devices.

Layering: ``serve/`` reaches devices exclusively through the ``api``
and ``exec`` public entry points; engine layers never import
``serve/`` (enforced by graftlint's ``serve-layering`` rule).
"""

from dryad_tpu.serve.admission import QueryRejected, TenantQuota
from dryad_tpu.serve.cache import ResultCache
from dryad_tpu.serve.service import QueryFuture, QueryService, TenantSession

__all__ = [
    "QueryFuture",
    "QueryRejected",
    "QueryService",
    "ResultCache",
    "TenantSession",
    "TenantQuota",
]
