"""Admission control — per-tenant budgets and the structured rejection.

Admission runs on the CLIENT thread at submit time, before a query
ever reaches the shared dispatch window: an over-quota tenant fails
fast with :class:`QueryRejected` and can never wedge the window (the
acceptance invariant of the serving tier).  Budgets are per tenant —
in-flight query count and admitted host-input bytes — so one tenant
saturating its own quota leaves every other tenant's admission
untouched.

Priority tiers layer ON TOP of the DRR weights: every tenant belongs
to one of :data:`TIERS` ("latency" before "batch"), the scheduler
serves any runnable latency-tier tenant before touching the batch
tier, and weights keep their meaning WITHIN a tier.  The fleet router
enforces the same ordering at the front door, so a batch backlog can
neither starve latency tenants at a replica nor queue ahead of them
in the fleet dispatch queues.
"""

from __future__ import annotations

import dataclasses

# Scheduling order: every runnable tenant of TIERS[i] is served before
# any tenant of TIERS[i+1].  DRR weights apply within a tier only.
TIERS = ("latency", "batch")
DEFAULT_TIER = "latency"


def check_tier(tier: str) -> str:
    """Validate a tier name (returns it, for assignment chaining)."""
    if tier not in TIERS:
        raise ValueError(
            f"unknown priority tier {tier!r}; expected one of {TIERS}"
        )
    return tier


def tier_rank(tier: str) -> int:
    """Position of *tier* in the strict-priority order (0 = first)."""
    return TIERS.index(check_tier(tier))


class QueryRejected(RuntimeError):
    """Admission refused — structured so callers can shed load
    programmatically instead of parsing a message.

    ``reason`` is one of ``"inflight"`` (per-tenant in-flight query
    cap), ``"bytes"`` (per-tenant admitted host-input byte budget), or
    ``"closed"`` (service shut down with the query still queued).
    ``limit``/``current`` are the budget and the value that tripped it.
    """

    def __init__(self, tenant: str, reason: str, limit: int, current: int):
        self.tenant = tenant
        self.reason = reason
        self.limit = limit
        self.current = current
        super().__init__(
            f"tenant {tenant!r} rejected: {reason} at {current} "
            f"against limit {limit}"
        )


@dataclasses.dataclass(frozen=True)
class TenantQuota:
    """One tenant's admission budget.

    ``max_inflight``: admitted-and-unresolved query cap.
    ``max_bytes``: summed host-input bytes of admitted queries
    (``DryadContext.query_input_bytes``); 0 disables the byte check.
    Defaults come from ``config.serve_max_inflight`` /
    ``config.serve_max_bytes`` when the session is opened without an
    explicit quota.
    """

    max_inflight: int = 32
    max_bytes: int = 1 << 30

    def check(
        self, tenant: str, inflight: int, inflight_bytes: int,
        cost_bytes: int,
    ) -> None:
        """Raise :class:`QueryRejected` when admitting one more query
        of ``cost_bytes`` would exceed either budget."""
        if inflight >= self.max_inflight:
            raise QueryRejected(
                tenant, "inflight", self.max_inflight, inflight
            )
        if self.max_bytes and inflight_bytes + cost_bytes > self.max_bytes:
            raise QueryRejected(
                tenant, "bytes", self.max_bytes,
                inflight_bytes + cost_bytes,
            )
