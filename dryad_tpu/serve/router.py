"""Plan-fingerprint-affine routing for the serving fleet.

Pure logic — no sockets, no threads — so every property the fleet
depends on is testable in isolation:

- **canonical fingerprints** (:func:`canonical_fingerprint`): a
  process-portable SHA-256 of the serve cache's
  ``(graph_key, (pos, oidx), binding_SHAs)`` tuple.  The compile-cache
  key holds fn-valued params BY REFERENCE (``executor._stage_key``), so
  a fingerprint is only portable when every leaf is a value — the
  encoder refuses reference-keyed leaves and the caller falls back to
  :func:`package_fingerprint` (SHA-256 of the packed query blob), which
  is deterministic for a client resubmitting the same bytes.
- **rendezvous (HRW) hashing** (:func:`rendezvous_rank`): each replica
  scores ``sha256(fingerprint | replica_id)``; the query goes to the
  max.  Removing one replica remaps only that replica's shard (~1/N of
  fingerprints) — every other query keeps its warm compile cache,
  operand-pool residency, and result-cache entries.  Built on sha256,
  never the builtin ``hash()`` — routing keys must agree across
  processes and ``PYTHONHASHSEED`` values (graftlint ``routing-hash``).
- **negative quota memos** (:class:`NegativeQuotaMemo`): a hard-quota'd
  tenant fails fast at the front door instead of paying an RPC round
  trip per rejection.
- **replica liveness** (:class:`ReplicaSet`): heartbeat-versioned
  membership with a routing generation that bumps on every death, so
  stale results from a removed replica are recognizably stale.
"""

from __future__ import annotations

import dataclasses
import enum
import hashlib
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "canonical_fingerprint",
    "package_fingerprint",
    "rendezvous_rank",
    "route",
    "NegativeQuotaMemo",
    "ReplicaSet",
]


class _Unportable(Exception):
    """A fingerprint leaf keyed by reference — not stable across
    processes, so the canonical encoding refuses it."""


def _is_np_dtype(obj) -> bool:
    """Duck-typed numpy dtype check (no numpy import here).  Modern
    numpy hands out instances of per-type subclasses
    (``numpy.dtypes.Int64DType``), so a name check on ``type(obj)``
    misses — walk the MRO instead."""
    mod = getattr(type(obj), "__module__", "")
    if not (mod == "numpy" or mod.startswith("numpy.")):
        return False
    return any(c.__name__ == "dtype" for c in type(obj).__mro__)


def _encode(obj, out: List[bytes]) -> None:
    """Append a canonical, self-delimiting encoding of *obj*.

    Only VALUE leaves are admitted: two processes that built the same
    logical plan must produce identical bytes, and any leaf whose repr
    or identity is address-dependent (functions, lambdas, arbitrary
    objects) raises :class:`_Unportable` instead of silently encoding
    an unstable key.
    """
    if obj is None:
        out.append(b"n;")
    elif obj is True:
        out.append(b"T;")
    elif obj is False:
        out.append(b"F;")
    elif isinstance(obj, int):
        out.append(b"i%d;" % obj)
    elif isinstance(obj, float):
        out.append(f"f{obj!r};".encode())
    elif isinstance(obj, str):
        b = obj.encode()
        out.append(b"s%d:" % len(b))
        out.append(b)
    elif isinstance(obj, bytes):
        out.append(b"b%d:" % len(obj))
        out.append(obj)
    elif isinstance(obj, enum.Enum):
        _encode(("enum", type(obj).__qualname__, obj.name), out)
    elif isinstance(obj, (tuple, list)):
        out.append(b"t%d:" % len(obj))
        for item in obj:
            _encode(item, out)
    elif isinstance(obj, dict):
        items = sorted(obj.items(), key=lambda kv: repr(kv[0]))
        out.append(b"d%d:" % len(items))
        for k, v in items:
            _encode(k, out)
            _encode(v, out)
    elif isinstance(obj, frozenset):
        enc: List[bytes] = []
        for item in obj:
            one: List[bytes] = []
            _encode(item, one)
            enc.append(b"".join(one))
        enc.sort()
        out.append(b"S%d:" % len(enc))
        out.extend(enc)
    elif _is_np_dtype(obj):
        _encode(("dtype", str(obj)), out)
    elif hasattr(obj, "dtype") and hasattr(obj, "item") and not hasattr(
        obj, "__len__"
    ):
        # numpy scalar: value + dtype pin it down
        _encode(("npscalar", str(obj.dtype), obj.item()), out)
    elif dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        fields = tuple(
            (f.name, getattr(obj, f.name)) for f in dataclasses.fields(obj)
        )
        _encode(("dc", type(obj).__qualname__, fields), out)
    else:
        raise _Unportable(type(obj).__qualname__)


def canonical_fingerprint(fp) -> Optional[str]:
    """SHA-256 hex of the canonical encoding of a
    ``DryadContext.query_fingerprint`` tuple, or None when the tuple
    contains reference-keyed leaves (closure-bearing plans) or the
    query was uncacheable (``fp is None``).  Identical logical plans
    produce identical digests in every process regardless of
    ``PYTHONHASHSEED``."""
    if fp is None:
        return None
    out: List[bytes] = []
    try:
        _encode(fp, out)
    except _Unportable:
        return None
    return hashlib.sha256(b"".join(out)).hexdigest()


def package_fingerprint(blob: bytes) -> str:
    """Routing fallback for non-portable plans: SHA-256 of the packed
    query bytes.  A client resubmitting the same package routes to the
    same replica (prepared-statement affinity survives), while two
    clients that independently pickled equal plans may land apart —
    correct, just colder."""
    return "pkg:" + hashlib.sha256(blob).hexdigest()


def rendezvous_rank(fingerprint: str, replicas: Sequence[str]) -> List[str]:
    """Highest-random-weight order of *replicas* for *fingerprint*:
    element 0 is the owner, element 1 the failover target, and so on.
    Deterministic across processes (sha256-scored), and removing a
    replica leaves the relative order of the survivors unchanged — the
    rendezvous property that bounds remapping to ~1/N."""
    key = fingerprint.encode()
    scored = [
        (hashlib.sha256(key + b"|" + rid.encode()).digest(), rid)
        for rid in replicas
    ]
    scored.sort(key=lambda pair: (pair[0], pair[1]), reverse=True)
    return [rid for _, rid in scored]


def route(fingerprint: str, replicas: Sequence[str]) -> str:
    """The rendezvous owner of *fingerprint* among *replicas*."""
    if not replicas:
        raise ValueError("no replicas to route to")
    return rendezvous_rank(fingerprint, replicas)[0]


class NegativeQuotaMemo:
    """Front-door memo of per-tenant hard-quota rejections.

    When a replica rejects tenant T (reason ``inflight``/``bytes``),
    the router records it; further submissions from T fail fast at the
    front door — no envelope post, no replica round trip — until the
    memo expires (``ttl`` seconds) or any completion for T frees
    capacity.  Only *load*-shaped rejections memoize: a ``closed``
    rejection means the replica is going away, which is the liveness
    plane's problem, not the tenant's.
    """

    MEMOABLE = ("inflight", "bytes")

    def __init__(self, ttl: float = 0.25, clock=time.monotonic):
        self.ttl = ttl
        self._clock = clock
        self._memo: Dict[str, Tuple[float, Dict[str, object]]] = {}
        self.fast_rejects = 0

    def note_rejection(self, tenant: str, reason: str, detail: Dict) -> None:
        if reason in self.MEMOABLE:
            self._memo[tenant] = (
                self._clock(),
                dict(detail, reason=reason),
            )

    def note_completion(self, tenant: str) -> None:
        # capacity freed: the next submission deserves a real attempt
        self._memo.pop(tenant, None)

    def check(self, tenant: str) -> Optional[Dict[str, object]]:
        """The memoized rejection detail when fresh, else None."""
        got = self._memo.get(tenant)
        if got is None:
            return None
        stamped, detail = got
        if self._clock() - stamped > self.ttl:
            del self._memo[tenant]
            return None
        self.fast_rejects += 1
        return detail


class ReplicaSet:
    """Heartbeat-versioned fleet membership.

    Each replica posts a monotonically versioned heartbeat prop; the
    router feeds ``observe`` with the (version, now) it read.  A
    replica whose heartbeat version stops advancing for
    ``stale_after`` seconds is dead: ``reap`` removes it and bumps the
    routing ``generation``, which every subsequently routed envelope
    carries — a result stamped with an older generation by a removed
    replica is recognizably stale and gets dropped instead of
    delivered.
    """

    def __init__(self, stale_after: float = 3.0, clock=time.monotonic):
        self.stale_after = stale_after
        self._clock = clock
        # rid -> (last heartbeat version, monotonic time it advanced)
        self._hb: Dict[str, Tuple[int, float]] = {}
        self._dead: Dict[str, float] = {}
        self.generation = 0

    def add(self, rid: str) -> None:
        self._hb.setdefault(rid, (0, self._clock()))

    def alive(self) -> List[str]:
        return sorted(self._hb)

    def is_alive(self, rid: str) -> bool:
        return rid in self._hb

    def observe(self, rid: str, version: int) -> None:
        """Record a heartbeat read; only an ADVANCING version counts as
        liveness (a wedged replica's last value re-read forever must
        still go stale)."""
        if rid not in self._hb:
            return
        last_ver, last_t = self._hb[rid]
        if version > last_ver:
            self._hb[rid] = (version, self._clock())

    def stale(self) -> List[str]:
        now = self._clock()
        return sorted(
            rid
            for rid, (_, t) in self._hb.items()
            if now - t > self.stale_after
        )

    def reap(self, rid: str) -> int:
        """Remove a dead replica; returns the new routing generation."""
        if rid in self._hb:
            del self._hb[rid]
            self._dead[rid] = self._clock()
            self.generation += 1
        return self.generation

    def dead(self) -> List[str]:
        return sorted(self._dead)


def remap_fraction(
    fingerprints: Iterable[str], before: Sequence[str], after: Sequence[str]
) -> float:
    """Fraction of *fingerprints* whose rendezvous owner changes going
    from replica set *before* to *after* (test/diagnostic helper)."""
    fps = list(fingerprints)
    if not fps:
        return 0.0
    moved = sum(
        1 for fp in fps if route(fp, before) != route(fp, after)
    )
    return moved / len(fps)
