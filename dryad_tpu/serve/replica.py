"""Engine-replica subprocess entry — ``python -m dryad_tpu.serve.replica``.

One fleet replica as its own OS process: builds a DryadContext from a
bootstrap file (so the parent decides mesh shape, backend, and data
loading without this module knowing), wraps it in a QueryService, and
serves the front door's ``cmd/<rid>/<seq>`` prop stream until the exit
envelope arrives.

The bootstrap file is plain python defining ``build_context() ->
DryadContext`` (and optionally ``prepare(ctx)`` for table ingest).  It
runs INSIDE the replica process — the whole point of process replicas
is that each one owns its runtime, its compile cache, and its operand
pools, so nothing jax-shaped crosses the process boundary.

``--fault`` takes a FaultPlan JSON (see :mod:`dryad_tpu.exec.faults`)
and arms the seeded chaos hook: the replica may ``os._exit`` at a
command-batch boundary, mid-query, with no cleanup — the way a machine
dies — which is what the router's heartbeat reaping and submit-log
replay exist to absorb.
"""

from __future__ import annotations

import argparse
import json
import runpy
import sys

from dryad_tpu.serve.fleet import ReplicaRunner
from dryad_tpu.utils.logging import get_logger

log = get_logger("dryad_tpu.serve.replica")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="dryad_tpu.serve.replica")
    ap.add_argument("--host", required=True)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--rid", required=True, help="replica id")
    ap.add_argument(
        "--bootstrap", required=True,
        help="python file defining build_context() (and optionally "
        "prepare(ctx))",
    )
    ap.add_argument("--hb-interval", type=float, default=0.25)
    ap.add_argument(
        "--fault", default=None,
        help="FaultPlan JSON for seeded chaos kills",
    )
    args = ap.parse_args(argv)

    if args.fault:
        from dryad_tpu.exec import faults

        plan = json.loads(args.fault)
        faults.install_plan(faults.FaultPlan(**plan))

    ns = runpy.run_path(args.bootstrap)
    build_context = ns.get("build_context")
    if build_context is None:
        log.error("bootstrap %s defines no build_context()", args.bootstrap)
        return 2
    prepare = ns.get("prepare")

    def factory():
        ctx = build_context()
        if prepare is not None:
            prepare(ctx)
        return ctx

    runner = ReplicaRunner(
        args.rid, args.host, args.port, factory,
        hb_interval=args.hb_interval, allow_process_exit=True,
    )
    log.info("replica %s serving %s:%d", args.rid, args.host, args.port)
    runner.run_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())
